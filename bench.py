"""Benchmark: single-token decode throughput on real TPU hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N}

Mirrors the reference's benchmark mode (`dllama inference`,
dllama.cpp:45-93): average per-token generation time over nSamples decode
steps after prefill.  Baseline for comparison is the reference's best
published single-node Llama-2-7B number — 101.81 ms/token (9.82 tok/s) on a
c3d-highcpu-30 VM (README.md:126, BASELINE.md) — since multi-chip hardware
is not reachable from this harness (one v5e chip via the axon tunnel).

Weights are zero-initialized on device: dense decode timing is
value-independent, and materializing 7B random f32 weights on host would
need ~27 GB RAM.  Falls back to TinyLlama-1.1B shapes if the 7B working set
does not fit the chip.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def model_cfgs():
    from dllama_tpu.models.config import tiny_config
    # llama-2-7b shapes (README.md:102 measurement target), short KV budget
    llama7b = tiny_config(dim=4096, hidden_dim=11008, n_layers=32, n_heads=32,
                          n_kv_heads=32, vocab_size=32000, seq_len=1024,
                          dtype=jnp.bfloat16)
    # tinyllama-1.1b (launch.py:7)
    tiny11 = tiny_config(dim=2048, hidden_dim=5632, n_layers=22, n_heads=32,
                         n_kv_heads=4, vocab_size=32000, seq_len=2048,
                         dtype=jnp.bfloat16)
    return [("llama2-7b", llama7b, 9.82), ("tinyllama-1.1b", tiny11, None)]


def bench_decode(cfg, chunk=32, n_chunks=4):
    """Times the production path: the on-device K-step generation loop
    (runtime/decode_loop.py) — sampling included, only token ids fetched."""
    from dllama_tpu.models.params import param_shapes
    from dllama_tpu.models.transformer import init_kv_cache
    from dllama_tpu.runtime.decode_loop import decode_chunk

    params = {k: jnp.zeros(s, jnp.float32 if k.startswith("rms") else cfg.dtype)
              for k, s in param_shapes(cfg).items()}
    cache = init_kv_cache(cfg, batch=1)

    fn = jax.jit(
        lambda p, c, tok, pos, k: decode_chunk(
            p, cfg, c, tok, pos, k, steps=chunk, temperature=0.8, topp=0.9),
        donate_argnums=(1,))

    tok = jnp.zeros((1,), jnp.int32)
    key = jax.random.PRNGKey(0)
    toks, cache, tok, _, _ = fn(params, cache, tok, jnp.int32(0), key)  # warmup/compile
    np.asarray(toks)

    times = []
    for i in range(n_chunks):
        t0 = time.perf_counter()
        toks, cache, tok, _, _ = fn(params, cache, tok, jnp.int32((i + 1) * chunk), key)
        np.asarray(toks)  # only K int32 ids cross the host boundary
        times.append((time.perf_counter() - t0) * 1000 / chunk)
    return float(np.mean(times))


def main():
    last_err = None
    for name, cfg, baseline_toks in model_cfgs():
        try:
            ms = bench_decode(cfg)
            toks = 1000.0 / ms
            # only compare against a published reference number for the same
            # model; the fallback has none, so its vs_baseline is null
            vs = round(toks / baseline_toks, 2) if baseline_toks else None
            print(json.dumps({
                "metric": f"{name} bf16 decode tok/s (1 TPU v5e chip)",
                "value": round(toks, 2),
                "unit": "tok/s",
                "vs_baseline": vs,
            }))
            return
        except Exception as e:  # OOM etc. — try the smaller model
            last_err = e
            print(f"bench: {name} failed ({type(e).__name__}: {str(e)[:120]}), "
                  "falling back", file=sys.stderr)
    raise SystemExit(f"all bench configs failed: {last_err}")


if __name__ == "__main__":
    main()
