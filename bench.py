"""Benchmark: single-token decode throughput on real TPU hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N}

Mirrors the reference's benchmark mode (`dllama inference`,
dllama.cpp:45-93): average per-token generation time over a decode loop.
Baseline for comparison is the reference's best published single-node
Llama-2-7B Q40 number — 101.81 ms/token (9.82 tok/s) on a c3d-highcpu-30
VM (README.md:126, BASELINE.md) — since multi-chip hardware is not
reachable from this harness (one v5e chip via the axon tunnel).

The benched path is the production one: packed-Q40 weights in HBM, the
fused Pallas dequant-matmul (ops/q40.py), and the on-device K-step
generation loop (runtime/decode_loop.py) — sampling included, only token
ids cross to the host.  Weights are zero-valued (built directly as packed
buffers): decode timing is value-independent, and materializing 7B f32
weights on host would need ~27 GB RAM.  Falls back to TinyLlama-1.1B
shapes if the 7B working set does not fit the chip.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def model_cfgs():
    from dllama_tpu.models.config import tiny_config
    # llama-2-7b shapes (README.md:102/126 measurement target)
    llama7b = tiny_config(dim=4096, hidden_dim=11008, n_layers=32, n_heads=32,
                          n_kv_heads=32, vocab_size=32000, seq_len=1024,
                          dtype=jnp.bfloat16)
    # tinyllama-1.1b (launch.py:7)
    tiny11 = tiny_config(dim=2048, hidden_dim=5632, n_layers=22, n_heads=32,
                         n_kv_heads=4, vocab_size=32000, seq_len=2048,
                         dtype=jnp.bfloat16)
    return [("llama2-7b", llama7b, 9.82), ("tinyllama-1.1b", tiny11, None)]


def zero_q40_params(cfg):
    """Params with packed-Q40 matmul weights, built as zero device buffers
    (no host-side f32 materialization)."""
    from dllama_tpu.models.params import param_shapes
    from dllama_tpu.ops.q40 import QTensor, padded_n

    shapes = dict(param_shapes(cfg))
    L, D = cfg.n_layers, cfg.dim
    # fused projection layout, as the quantized loader produces
    shapes["wqkv"] = (L, D, (cfg.n_heads + 2 * cfg.n_kv_heads) * cfg.head_size)
    shapes["w13"] = (L, D, 2 * cfg.hidden_dim)
    for k in ("wq", "wk", "wv", "w1", "w3"):
        del shapes[k]

    qkeys = {"wqkv", "wo", "w13", "w2", "wcls"}
    params = {}
    for k, shape in shapes.items():
        if k in qkeys:
            *lead, n, d = shape
            np_ = padded_n(n)
            params[k] = QTensor(
                jnp.zeros((*lead, np_ // 2, d), jnp.uint8),
                jnp.zeros((*lead, np_ // 32, d), jnp.float32), (n, d))
        else:
            params[k] = jnp.zeros(shape, jnp.float32 if k.startswith("rms") else cfg.dtype)
    return params


def bench_decode(cfg, chunk=64, n_chunks=4):
    from dllama_tpu.models.transformer import init_kv_cache
    from dllama_tpu.runtime.decode_loop import decode_chunk

    params = zero_q40_params(cfg)
    cache = init_kv_cache(cfg, batch=1)

    fn = jax.jit(
        lambda p, c, tok, pos, k: decode_chunk(
            p, cfg, c, tok, pos, k, steps=chunk, temperature=0.8, topp=0.9),
        donate_argnums=(1,))

    tok = jnp.zeros((1,), jnp.int32)
    key = jax.random.PRNGKey(0)
    toks, cache, tok, _, _ = fn(params, cache, tok, jnp.int32(0), key)  # warmup/compile
    np.asarray(toks)

    times = []
    for i in range(n_chunks):
        t0 = time.perf_counter()
        toks, cache, tok, _, _ = fn(params, cache, tok, jnp.int32((i + 1) * chunk), key)
        np.asarray(toks)  # forces execution; only K int32 ids cross the boundary
        times.append((time.perf_counter() - t0) * 1000 / chunk)
    return float(np.mean(times))


def main():
    last_err = None
    for name, cfg, baseline_toks in model_cfgs():
        try:
            ms = bench_decode(cfg)
            toks = 1000.0 / ms
            # only compare against a published reference number for the same
            # model; the fallback has none, so its vs_baseline is null
            vs = round(toks / baseline_toks, 2) if baseline_toks else None
            print(json.dumps({
                "metric": f"{name} q40 decode tok/s (1 TPU v5e chip, fused pallas)",
                "value": round(toks, 2),
                "unit": "tok/s",
                "vs_baseline": vs,
            }))
            return
        except Exception as e:  # OOM etc. — try the smaller model
            last_err = e
            print(f"bench: {name} failed ({type(e).__name__}: {str(e)[:120]}), "
                  "falling back", file=sys.stderr)
    raise SystemExit(f"all bench configs failed: {last_err}")


if __name__ == "__main__":
    main()
