"""Benchmark: single-token decode throughput on real TPU hardware.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N}

Mirrors the reference's benchmark mode (`dllama inference`,
dllama.cpp:45-93): average per-token generation time over a greedy decode
loop.  Baseline is the reference's best published single-node Llama-2-7B
Q40 number — 101.81 ms/token = 9.82 tok/s on a c3d-highcpu-30 VM
(README.md:126, BASELINE.md) — since multi-chip hardware is not reachable
from this harness (one v5e chip via the axon tunnel).

Architecture (hardened after r01, where a hanging backend init burned the
whole window and produced no JSON at all; re-hardened after r03, where the
axon relay was dead at round end, the single 420 s probe burned its whole
timeout, and the round recorded only a degraded CPU number): a parent
orchestrator spawns each stage as a subprocess with a hard timeout under a
global wall-clock budget (env BENCH_BUDGET_S, default 1500 s) —

  0. relay watch: a dead relay makes `jax.devices()` block forever inside
     the PJRT claim, so a full JAX probe is only paid for when a 2 s TCP
     connect to the relay port (127.0.0.1:8093) succeeds.  The orchestrator
     polls the port across the run window and probes at the FIRST sign of
     life — a flaky tunnel that comes up mid-window still gets benched;
  1. backend probe: `jax.devices()` only; bounded and repeatable (short
     timeouts, multiple attempts), so a wedged TPU tunnel costs minutes,
     not the session;
  2. llama2-7b Q40 greedy decode on the TPU (the config with a published
     reference number), preceded by an in-process pallas-vs-XLA hardware
     equality check on the fused kernel;
  3. llama3-8b immediately after — the BASELINE.json north-star metric
     gets an early slot so late-window tunnel loss cannot starve it;
  4. tinyllama-1.1b fallback if the 7B working set fails;
  5. degraded CPU fallback (tiny shapes, vs_baseline null) so the driver
     always records a parsed line even with the TPU unreachable.

Secondary hardware numbers (llama3-8b, 16k long-context) are logged to
stderr AND embedded in the final JSON line under "extras" so they survive
into BENCH_r{N}.json either way.

The timing loop is greedy (temperature 0 → on-device argmax): sampling
cost is not the metric the baseline measures (the reference samples on
host between steps; its published ms/token is dominated by the matmuls).
Weights are zero-valued packed buffers: decode timing is value-independent
and 7B f32 host materialization (~27 GB) is avoided.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1500"))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "240"))
BASELINE_7B_TOKS = 9.82  # README.md:126 — 101.81 ms/token, 1× c3d-highcpu-30
BASELINE_13B_TOKS = 5.43  # README.md:127 — 184.19 ms/token, 1× c3d-highcpu-30
# the axon relay's remote-compile HTTP endpoint; when this port is not even
# listening, the PJRT claim inside jax.devices() blocks forever (observed
# r03) — so the TCP check below is the cheap gate in front of every probe
RELAY_PORT = int(os.environ.get("BENCH_RELAY_PORT", "8093"))
RELAY_HOST = (os.environ.get("PALLAS_AXON_POOL_IPS", "").split(",")[0].strip()
              or "127.0.0.1")


def _vs_baseline(toks, baseline):
    """The one headline-vs-reference helper: tok/s over the published
    reference tok/s for EVERY stage (ms/token stages convert to tok/s
    before calling).  ``None`` — never a crash — when the stage has no
    baseline to compare against."""
    if not baseline or not isinstance(toks, (int, float)):
        return None
    return round(toks / baseline, 2)


def current_round() -> int | None:
    """The driver's round number from PROGRESS.jsonl's last line — the ONE
    shared parser for the in-session artifact's freshness gate (bench, the
    capture tool, and the regression test all import this)."""
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "PROGRESS.jsonl")
        with open(path) as f:
            return int(json.loads(f.read().strip().splitlines()[-1])["round"])
    except Exception:
        return None


def _relay_listening(timeout_s: float = 2.0) -> bool:
    """True when the axon relay port accepts a TCP connect — a cheap
    (≤2 s) necessary condition for the TPU tunnel being alive."""
    import socket
    try:
        with socket.create_connection((RELAY_HOST, RELAY_PORT), timeout=timeout_s):
            return True
    except OSError:
        return False


# ---------------------------------------------------------------------------
# Child attempts (run in a subprocess; last stdout line is a JSON object)
# ---------------------------------------------------------------------------

def _model_cfg(name):
    import jax.numpy as jnp
    from dllama_tpu.models.config import tiny_config
    if name == "llama2-7b":
        # README.md:102/126 measurement target shapes
        return tiny_config(dim=4096, hidden_dim=11008, n_layers=32, n_heads=32,
                           n_kv_heads=32, vocab_size=32000, seq_len=1024,
                           dtype=jnp.bfloat16)
    if name == "llama2-7b-long":
        # long-context variant: a 16k cache (2×4.3 GB bf16) next to the
        # ~4 GB packed weights — decode stays fast only because attention
        # reads the live prefix, not the whole cache (ops/attention.py
        # decode_gqa_attention); logged as evidence, not the headline
        return _model_cfg("llama2-7b").with_(seq_len=16384)
    if name == "llama3-8b":
        # the BASELINE.json north-star config (≥80 tok/s/chip on v5e-8):
        # GQA (8 kv heads) + 128k vocab — the wcls matmul alone is ~295 MB
        # packed, so this also exercises the kernel's widest output shape
        return tiny_config(dim=4096, hidden_dim=14336, n_layers=32, n_heads=32,
                           n_kv_heads=8, vocab_size=128256, seq_len=2048,
                           rope_theta=500000.0, dtype=jnp.bfloat16)
    if name == "llama2-13b":
        # README.md:127 row (184.19 ms/token on the reference's best VM);
        # 13B Q40 packs to ~7.3 GB — fits one v5e chip's 16 GB HBM next
        # to its bf16 cache, so the reference's 13B row gets a same-chip
        # comparison too
        return tiny_config(dim=5120, hidden_dim=13824, n_layers=40, n_heads=40,
                           n_kv_heads=40, vocab_size=32000, seq_len=1024,
                           dtype=jnp.bfloat16)
    if name == "tinyllama-1.1b":  # launch.py:7
        return tiny_config(dim=2048, hidden_dim=5632, n_layers=22, n_heads=32,
                           n_kv_heads=4, vocab_size=32000, seq_len=2048,
                           dtype=jnp.bfloat16)
    if name == "cpu-tiny":
        return tiny_config(dim=512, hidden_dim=1408, n_layers=4, n_heads=8,
                           n_kv_heads=8, vocab_size=4096, seq_len=256,
                           dtype=jnp.float32)
    raise ValueError(name)


def _zero_q40_params(cfg, codec="q40"):
    """Params with packed quantized matmul weights (``codec`` "q40" or
    "q80"), built as zero device buffers
    (no host-side f32 materialization).  Matches the quantized loader's
    single-chip layout (load_params fuse=True): fused wqkv everywhere,
    fused w13 for dense FFNs, packed expert stacks for MoE — shared by
    the bench and tools/moe_hw_check.py."""
    import jax.numpy as jnp
    from dllama_tpu.models.params import param_shapes
    from dllama_tpu.ops.q40 import QTensor, padded_n

    shapes = dict(param_shapes(cfg))
    L, D = cfg.n_layers, cfg.dim
    # fused wqkv, as the quantized loader produces (load_params fuse=True)
    shapes["wqkv"] = (L, D, (cfg.n_heads + 2 * cfg.n_kv_heads) * cfg.head_size)
    for k in ("wq", "wk", "wv"):
        del shapes[k]
    qkeys = {"wqkv", "wo", "wcls"}
    if cfg.is_moe:
        qkeys |= {"up", "gate", "down"}
    else:
        shapes["w13"] = (L, D, 2 * cfg.hidden_dim)
        for k in ("w1", "w3"):
            del shapes[k]
        qkeys |= {"w13", "w2"}

    params = {}
    for k, shape in shapes.items():
        if k in qkeys:
            *lead, n, d = shape
            np_ = padded_n(n)
            if codec == "q80":
                from dllama_tpu.ops.q8 import Q8Tensor
                params[k] = Q8Tensor(
                    jnp.zeros((*lead, np_, d), jnp.int8),
                    jnp.zeros((*lead, np_ // 32, d), jnp.uint16), (n, d))
            else:
                params[k] = QTensor(
                    jnp.zeros((*lead, np_ // 2, d), jnp.uint8),
                    jnp.zeros((*lead, np_ // 32, d), jnp.uint16), (n, d))
        else:
            params[k] = jnp.zeros(shape, jnp.float32 if k.startswith("rms") else cfg.dtype)
    return params


def _synth_model_files(name, dirpath):
    """Synthesize a full-size Q40 `.m` (+ matching `.t`) at packed size —
    random nibble blocks with a constant small f16 scale, written via
    MFileWriter.write_raw with no f32 transit (VERDICT r02 Next #3: bench
    the operator surface, loader included, not a zero-buffer bypass)."""
    import numpy as np
    from dllama_tpu import quants
    from dllama_tpu.io import mfile
    from tests.fixtures import write_tiny_tokenizer

    cfg = _model_cfg(name)
    spec = mfile.ModelSpec(
        arch=mfile.ARCH_LLAMA, dim=cfg.dim, hidden_dim=cfg.hidden_dim,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads,
        n_experts=0, n_active_experts=0, vocab_size=cfg.vocab_size,
        seq_len=cfg.seq_len, hidden_act=mfile.ACT_SILU, rope_theta=10000.0,
        weights_ftype=quants.Q40)
    mpath = os.path.join(dirpath, f"{name}-synth.m")
    tpath = os.path.join(dirpath, f"{name}-synth.t")
    if not os.path.exists(tpath):
        write_tiny_tokenizer(tpath, vocab_size=cfg.vocab_size)
    if os.path.exists(mpath):
        return mpath, tpath
    rng = np.random.RandomState(0)
    scale = np.frombuffer(np.float16(0.008).tobytes(), np.uint8)
    nib_pool = rng.randint(0, 256, 1 << 22, dtype=np.uint8)  # 4 MB pattern
    t0 = time.time()
    with mfile.MFileWriter(mpath + ".part", spec) as w:
        for tinfo in w.plan:
            n = int(np.prod(tinfo.shape))
            if tinfo.ftype == quants.Q40:
                blocks = n // 32
                arr = np.empty((blocks, quants.Q40_BLOCK_BYTES), np.uint8)
                arr[:, :2] = scale
                arr[:, 2:] = np.resize(nib_pool, (blocks, 16))
                w.write_raw(tinfo.name, arr)
            else:  # f32 norms/embedding in non-Q40 plans
                w.write_tensor(tinfo.name,
                               (rng.randn(*tinfo.shape) * 0.02).astype(np.float32))
    os.replace(mpath + ".part", mpath)
    print(f"bench: synthesized {mpath} "
          f"({os.path.getsize(mpath) / 1e9:.2f} GB in {time.time() - t0:.0f}s)",
          file=sys.stderr)
    return mpath, tpath


def _run_cli_bench(name, steps=320, chunk=32):
    """Drive `dllama inference` end-to-end (loader → Engine →
    generate_stream → G/I/T print) and parse its run averages."""
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    mpath, tpath = _synth_model_files(name, os.environ.get("BENCH_TMP", "/tmp"))
    cmd = [sys.executable, "-m", "dllama_tpu", "inference", "--model", mpath,
           "--tokenizer", tpath, "--prompt", "hello hello hello", "--steps",
           # warmup == steps: the warmup pass replays the exact chunk-size
           # sequence of the timed pass, so every program is compiled before
           # timing starts
           str(steps), "--chunk", str(chunk), "--warmup", str(steps),
           "--temperature", "0", "--seed", "0"]
    # the grandchild's timeout comes from an absolute deadline so model
    # synthesis time above cannot push the kill past the attempt timeout
    # (which would orphan the CLI process on the TPU)
    deadline = float(os.environ.get("BENCH_CLI_DEADLINE", time.time() + 780))
    try:
        r = subprocess.run(cmd, cwd=here, stdout=subprocess.PIPE, text=True,
                           env=_child_env({"DLLAMA_AUTO_PROFILE": "0"}),
                           timeout=max(deadline - time.time(), 60))
        out, rc = r.stdout, r.returncode
    except subprocess.TimeoutExpired as e:
        # the stats print before any trailing profile work — salvage them
        # from a killed child rather than discarding a finished measurement
        out = (e.stdout.decode() if isinstance(e.stdout, bytes)
               else e.stdout) or ""
        rc = None
    sys.stderr.write("\n".join(out.splitlines()[-8:]) + "\n")
    # rc None = deadline kill (salvage is legitimate: stats print before any
    # trailing work); any OTHER non-zero exit means the run itself is
    # suspect, stats line or not
    if rc not in (0, None):
        raise RuntimeError(f"CLI bench rc={rc}")
    m = re.search(r"Avg generation time:\s+([0-9.]+) ms", out)
    if not m:
        raise RuntimeError("CLI bench timed out (child killed)" if rc is None
                           else "CLI bench output had no 'Avg generation time'")
    return float(m.group(1))


def _child_env(extra: dict | None = None) -> dict:
    """Subprocess env with the repo importable (shared by every stage that
    launches a helper script)."""
    here = os.path.dirname(os.path.abspath(__file__))
    env = _with_compile_cache(dict(os.environ))
    env.update(extra or {})
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _profile_split_stderr(run_once, chunk):
    """Trace one decode chunk and log the compute/collective split — the
    reference's I/T attribution on a real TPU xplane (VERDICT r02 Next #4) —
    plus the top per-op device times, so every driver-captured bench run
    records where the step time actually goes."""
    try:
        from dllama_tpu.runtime.profiling import split_op_times, traced_op_times

        times = traced_op_times(run_once, steps=1)
        if not times:
            print("bench: profile split unavailable (no xplane tooling/trace)",
                  file=sys.stderr)
            return
        comp, coll = split_op_times(times)
        verdict = ("T≈0 contract holds" if coll < 1.0
                   else f"collectives are {100 * coll / (comp + coll):.1f}% — inspect")
        print(f"bench: profile split over {chunk}-token chunk: "
              f"compute {comp:.1f} ms, collectives {coll:.1f} ms "
              f"({comp / chunk:.2f} ms/token compute; {verdict})", file=sys.stderr)
        top = sorted(times.items(), key=lambda kv: -kv[1])[:6]
        for op, ms in top:
            print(f"bench:   top op {ms:8.2f} ms  {op}", file=sys.stderr)
    except Exception as e:
        print(f"bench: profile split failed ({type(e).__name__}: {str(e)[:120]})",
              file=sys.stderr)


def _pallas_hw_check(codec="q40"):
    """Non-interpret fused-kernel equality check on the real backend
    (VERDICT r01: Mosaic breakage must be visible in the artifact), for
    the codec the stage will actually bench — a q40 verdict says nothing
    about the Q80 kernel's lowering and vice versa.
    Returns 'pallas' if the fused kernel is usable, else 'xla'."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dllama_tpu.ops import q40, q8

    if jax.default_backend() == "cpu":
        return "xla"
    mod = q40 if codec == "q40" else q8
    try:
        rng = np.random.RandomState(0)
        w = (rng.randn(2048, 512) * 0.1).astype(np.float32)
        x = jnp.asarray(rng.randn(1, 2048).astype(np.float32), jnp.bfloat16)
        qt = mod.quantize(w)
        out_p = np.asarray(mod.matmul(x, qt, impl="pallas"))
        out_x = np.asarray(mod.matmul(x, qt, impl="xla"))
        err = float(np.max(np.abs(out_p - out_x)) / (np.max(np.abs(out_x)) + 1e-9))
        if err > 2e-2:
            raise AssertionError(f"pallas/xla mismatch, rel err {err:.3g}")
        if codec == "q40" and os.environ.get("DLLAMA_Q40_LAYOUT", "") == "blocked":
            # probe the blocked kernel's Mosaic lowering too: the static
            # tile predicate (_blocked_tiles_ok) cannot prove lowerability
            # at real shapes, and a compile failure must downgrade the run
            # here — not crash the first decode step
            import jax.numpy as jnp2
            w3 = (rng.randn(2, 2048, 512) * 0.1).astype(np.float32)
            bqt = q40.to_blocked(q40.quantize(w3))
            view = q40.QLayerView(bqt, jnp2.int32(1))
            out_b = np.asarray(q40.matmul(x, view, impl="pallas"))
            ref_b = np.asarray(q40.matmul(x, view, impl="xla"))
            err_b = float(np.max(np.abs(out_b - ref_b))
                          / (np.max(np.abs(ref_b)) + 1e-9))
            if err_b > 2e-2:
                raise AssertionError(f"blocked mismatch, rel err {err_b:.3g}")
            print(f"pallas hardware check: blocked layout OK "
                  f"(max rel err {err_b:.2e})", file=sys.stderr)
        print(f"pallas hardware check ({codec}): OK (max rel err {err:.2e})",
              file=sys.stderr)
        return "pallas"
    except Exception as e:
        print(f"pallas hardware check ({codec}) FAILED ({type(e).__name__}: "
              f"{str(e)[:160]}); benching the XLA dequant path", file=sys.stderr)
        return "xla"


def _bench_decode(cfg, chunk=32, n_chunks=10, profile=False, start_pos=0,
                  batch=1, kv_quant=False, codec="q40"):
    """Greedy on-device decode loop; returns avg ms/token over the timed
    chunks (compile + warmup excluded).  ``start_pos`` places the decode
    deep into the cache so long-context runs time attention over a long
    *live* prefix, not an empty one.  ``batch`` > 1 times the lockstep
    multi-stream decode (Engine.generate_batch's hot loop): decode is
    weight-bandwidth-bound at batch 1, so the per-STEP time should stay
    near the batch-1 cost while every step yields ``batch`` tokens —
    returned ms is still per step, so aggregate tok/s = batch·1000/ms."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dllama_tpu.models.transformer import init_kv_cache
    from dllama_tpu.runtime.decode_loop import decode_chunk

    params = maybe_blocked(_zero_q40_params(cfg, codec), codec)
    cache = init_kv_cache(cfg, batch=batch, quant=kv_quant)

    fn = jax.jit(
        lambda p, c, tok, pos, k: decode_chunk(
            p, cfg, c, tok, pos, k, steps=chunk, temperature=0.0, topp=0.9),
        donate_argnums=(1,))

    tok = jnp.zeros((batch,), jnp.int32)
    key = jax.random.PRNGKey(0)
    t0 = time.perf_counter()
    toks, cache, tok, _, _ = fn(params, cache, tok, jnp.int32(start_pos), key)
    np.asarray(toks)  # compile+warmup
    print(f"compile+warmup: {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # depth-1 pipelined schedule — the one Engine.generate_stream ships:
    # chunk i+1 is enqueued (device-carried token) before chunk i's ids
    # are fetched, so the timed rate includes the dispatch overlap a real
    # serving loop gets; per-chunk time is fetch-boundary to
    # fetch-boundary (chunk 0 from its dispatch)
    times = []
    boundary = time.perf_counter()
    toks, cache, tok, _, _ = fn(params, cache, tok,
                                jnp.int32(start_pos + chunk), key)
    for i in range(n_chunks):
        nxt = None
        if i + 1 < n_chunks:
            nxt = fn(params, cache, tok,
                     jnp.int32(start_pos + (i + 2) * chunk), key)
            cache, tok = nxt[1], nxt[2]
        np.asarray(toks)  # forces execution; only K int32 ids cross the boundary
        now = time.perf_counter()
        times.append((now - boundary) * 1000 / chunk)
        boundary = now
        if nxt is not None:
            toks = nxt[0]

    if profile:
        state = {"cache": cache, "tok": tok}

        def run_once():
            toks, state["cache"], state["tok"], _, _ = fn(
                params, state["cache"], state["tok"],
                jnp.int32(start_pos + (n_chunks + 1) * chunk), key)
            np.asarray(toks)

        _profile_split_stderr(run_once, chunk)

    # feed the timed chunks into the obs step-latency histogram and log
    # the distribution (stderr) — same buckets the serving layer exports,
    # so a bench number and a /metrics scrape are directly comparable
    from dllama_tpu.obs import dispatch as obs_dispatch, \
        metrics as obs_metrics
    for t in times:
        obs_metrics.ENGINE_GENERATION_MS.observe(t)
    h = obs_metrics.ENGINE_GENERATION_MS.json_value()
    print(f"bench: per-token ms distribution: count={h['count']} "
          f"avg={h['avg']:.3f} (dllama_engine_generation_ms)", file=sys.stderr)
    # per-device HBM residency next to the timing number (the gauge readers
    # are bound at runtime.engine import; {} on backends without allocator
    # stats — absent, not zero)
    from dllama_tpu.runtime import engine as _engine  # noqa: F401
    hbm = obs_metrics.HBM_BYTES_IN_USE.values()
    if hbm:
        peak = obs_metrics.HBM_BYTES_PEAK.values()
        print(f"bench: HBM in use "
              f"{sum(hbm.values()) / 2**30:.2f} GiB over {len(hbm)} "
              f"device(s), peak {sum(peak.values()) / 2**30:.2f} GiB "
              f"(dllama_hbm_bytes_in_use)", file=sys.stderr)
    # and the dispatch ledger: a decode number that fell off the fused
    # Pallas path must say so next to the number it degrades
    print(f"bench: {obs_dispatch.summary_line()}", file=sys.stderr)
    coll = obs_dispatch.collective_line()
    if coll:
        print(f"bench: {coll}", file=sys.stderr)
    return float(np.mean(times))


def maybe_blocked(params, codec="q40"):
    """Apply the tile-contiguous layout lever when the env asks for it —
    the ONE shared recipe (bench decode/prefill, tools/profile_decode.py).
    Q40 only: blocked_params is a no-op on Q8 planes, and claiming the
    layout for a q80 run would mislabel the measurement."""
    if os.environ.get("DLLAMA_Q40_LAYOUT", "") == "blocked" and codec == "q40":
        from dllama_tpu.ops import q40 as _q40
        params = _q40.blocked_params(params)
        print("bench: blocked (tile-contiguous) Q40 layout", file=sys.stderr)
    return params


def _bench_prefill(cfg, T=512, reps=6):
    """Avg ms/token over ``reps`` bucketed prefill forwards (compile +
    warmup excluded).  The cache is NOT donated — each rep rewrites the
    same pos-0 window, and the extra cache copy is noise next to the
    T-token matmul volume."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dllama_tpu.models.transformer import forward_last, init_kv_cache

    params = maybe_blocked(_zero_q40_params(cfg))
    cache = init_kv_cache(cfg, batch=1)
    fn = jax.jit(lambda p, c, t: forward_last(p, cfg, t, c, jnp.int32(0),
                                              jnp.int32(T - 1)))
    toks = jnp.zeros((1, T), jnp.int32)
    t0 = time.perf_counter()
    logits, _ = fn(params, cache, toks)
    np.asarray(logits)
    print(f"compile+warmup: {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    t0 = time.perf_counter()
    for _ in range(reps):
        logits, _ = fn(params, cache, toks)
        np.asarray(logits)
    return (time.perf_counter() - t0) * 1000 / reps / T


def _bench_sched(cfg, slots=4, max_new=96, tp=1):
    """Continuous-batching aggregate decode throughput (the serving path
    behind ``--batch-slots``, runtime/scheduler.py): ``slots`` staggered
    greedy requests admitted at decode-step granularity over one
    slot-addressable engine, timed first-submit to last-retire.  Contrast
    with the lockstep ``-b8`` attempt: there the batch starts in lockstep;
    here requests JOIN while their neighbors are mid-decode, which is what
    /v1/completions traffic actually looks like.  Returns aggregate
    tok/s (completion tokens only — prefill is inside the window, as it is
    for a real request).

    ``tp`` > 1 runs the same workload on a tensor-parallel mesh (PR-12):
    the scheduler's step loop samples the mesh's all-reduce latency into
    ``engine_collective_ms`` as it serves, and the dispatch ledger
    records whether decode collectives took the fused ring or psum."""
    import threading

    import jax
    import numpy as np
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.runtime.scheduler import SlotScheduler

    params = maybe_blocked(_zero_q40_params(cfg))
    eng = Engine(cfg, params,
                 mesh=make_mesh(tp=tp, devices=jax.devices()[:tp]),
                 batch=slots)
    sched = SlotScheduler(eng, prefill_chunk=16, max_wait_ms=20.0)
    rng = np.random.RandomState(7)
    prompts = [[int(t) for t in rng.randint(1, cfg.vocab_size, 8 + 4 * i)]
               for i in range(slots)]
    counts = [0] * slots

    def run(i, delay):
        time.sleep(delay)
        t = sched.submit(prompts[i], max_new)
        counts[i] = sum(1 for _ in t.tokens())

    def wave(stagger):
        ths = [threading.Thread(target=run, args=(i, stagger * i))
               for i in range(slots)]
        t0 = time.perf_counter()
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        return time.perf_counter() - t0

    t0 = time.perf_counter()
    wave(0.05)  # compile + warmup: same stagger, so the same shape set
    print(f"compile+warmup: {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    elapsed = wave(0.05)
    sched.close()
    total = sum(counts)
    print(f"bench: sched {total} tokens over {slots} staggered requests "
          f"in {elapsed:.2f}s", file=sys.stderr)
    # goodput decomposition (obs/flight.py SlotTimeline + scheduler
    # accounting): where the wall time of the measured wave actually went
    from dllama_tpu.obs import metrics as obs_metrics
    comp = obs_metrics.SCHED_STEP_TIME_MS.json_value()
    if comp:
        split = " ".join(f"{k}={v:.0f}ms" for k, v in sorted(comp.items()))
        print(f"bench: sched goodput "
              f"{obs_metrics.SCHED_GOODPUT_RATIO.value:.3f} ({split})",
              file=sys.stderr)
    # roofline utilization (obs/cost.py): achieved FLOP/s and HBM bytes/s
    # over the backend's peaks — the per-stage economics line
    from dllama_tpu.obs import cost as obs_cost
    perf = obs_cost.summary()
    if perf.get("mfu") is not None or perf.get("mbu") is not None:
        mfu = perf.get("mfu")
        mbu = perf.get("mbu")
        print(f"bench: sched mfu={mfu:.4f}" if mfu is not None
              else "bench: sched mfu=n/a", file=sys.stderr, end="")
        print(f" mbu={mbu:.4f}" if mbu is not None else " mbu=n/a",
              file=sys.stderr, end="")
        print(f" ({perf['peaks'].get('source', '?')} peaks, "
              f"{perf['flops_total'] / 1e9:.2f} GFLOP, "
              f"{perf['hbm_bytes_total'] / 1e9:.3f} GB moved)",
              file=sys.stderr)
    return total / elapsed


def _bench_sched_prefix(cfg, slots=4, max_new=96):
    """Prefix-sharing serving throughput (the paged-KV radix cache,
    runtime/pagepool.py): ``slots`` staggered greedy requests that share
    one long synthetic "system prompt" (128 tokens) ahead of a short
    unique suffix, over a paged engine sized at the same cache-length
    budget as ``_bench_sched``.  The first request prefills the shared
    block; the rest match it in the radix tree at admission, bind the
    cached pages copy-free and prefill only their suffix — the serving
    win ``prefix_tokens_reused_total`` quantifies.  Returns (aggregate
    tok/s, prefix tokens reused)."""
    import threading

    import jax
    import numpy as np
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.runtime.scheduler import SlotScheduler

    params = maybe_blocked(_zero_q40_params(cfg))
    page_size = 16
    eng = Engine(cfg, params,
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                 batch=slots,
                 kv_pages=slots * (-(-cfg.seq_len // page_size)) + 1,
                 kv_page_size=page_size)
    sched = SlotScheduler(eng, prefill_chunk=16, max_wait_ms=20.0)
    rng = np.random.RandomState(7)
    system = [int(t) for t in rng.randint(1, cfg.vocab_size, 128)]
    prompts = [system + [int(t) for t in rng.randint(1, cfg.vocab_size, 8)]
               for _ in range(slots)]
    counts = [0] * slots

    def run(i, delay):
        time.sleep(delay)
        t = sched.submit(prompts[i], max_new)
        counts[i] = sum(1 for _ in t.tokens())

    def wave(stagger):
        ths = [threading.Thread(target=run, args=(i, stagger * i))
               for i in range(slots)]
        t0 = time.perf_counter()
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        return time.perf_counter() - t0

    from dllama_tpu.obs import metrics as obs_metrics
    t0 = time.perf_counter()
    wave(0.05)  # compile + warmup: same stagger, so the same shape set
    print(f"compile+warmup: {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    reused0 = obs_metrics.PREFIX_TOKENS_REUSED.value
    elapsed = wave(0.05)
    reused = obs_metrics.PREFIX_TOKENS_REUSED.value - reused0
    sched.close()
    total = sum(counts)
    print(f"bench: sched-prefix {total} tokens over {slots} staggered "
          f"requests sharing a 128-token prefix in {elapsed:.2f}s "
          f"({reused} prompt tokens bound from cache)", file=sys.stderr)
    return total / elapsed, reused


def _bench_sched_pressure(cfg, slots=4, max_new=96):
    """KV-tiering serving throughput under page pressure (runtime/
    kvtier.py + scheduler grow ladder): the ``-sched4`` staggered
    workload on a paged pool deliberately sized at ~40% of what full
    reservation would demand, with ``--kv-reserve optimistic`` so every
    request seats on prompt-sized pages and grows page-by-page at
    decode.  The pool cannot hold all four requests resident, so the
    grow ladder spills idle-longest victims to the host pool and pages
    them back in as neighbors retire — the run measures what that
    thrash costs relative to an uncontended pool (``-sched4``), while
    greedy decode stays byte-identical.  A full-reservation scheduler
    on this pool could not even admit the workload concurrently.
    Returns (aggregate tok/s, pages spilled, pages paged back in)."""
    import threading

    import jax
    import numpy as np
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.runtime.scheduler import SlotScheduler

    params = maybe_blocked(_zero_q40_params(cfg))
    page_size = 16
    rng = np.random.RandomState(7)
    prompts = [[int(t) for t in rng.randint(1, cfg.vocab_size, 8 + 4 * i)]
               for i in range(slots)]
    # full-reservation demand for this workload, then size the pool at
    # 40% of it (+1 for the scratch page): optimistic reservation must
    # serve out of a pool that full reservation could not seat
    full_pages = sum(-(-min(len(p) + max_new, cfg.seq_len) // page_size)
                     for p in prompts)
    worst = max(-(-min(len(p) + max_new, cfg.seq_len) // page_size)
                for p in prompts)
    kv_pages = max(int(0.4 * full_pages), worst) + 1
    eng = Engine(cfg, params,
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                 batch=slots,
                 kv_pages=kv_pages, kv_page_size=page_size)
    sched = SlotScheduler(eng, prefill_chunk=16, max_wait_ms=20.0,
                          kv_reserve="optimistic", spill_headroom=16,
                          host_pool_mb=64.0)
    counts = [0] * slots

    def run(i, delay):
        time.sleep(delay)
        t = sched.submit(prompts[i], max_new)
        counts[i] = sum(1 for _ in t.tokens())

    def wave(stagger):
        ths = [threading.Thread(target=run, args=(i, stagger * i))
               for i in range(slots)]
        t0 = time.perf_counter()
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        return time.perf_counter() - t0

    from dllama_tpu.obs import metrics as obs_metrics
    t0 = time.perf_counter()
    wave(0.05)  # compile + warmup: same stagger, so the same shape set
    print(f"compile+warmup: {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    spilled0 = obs_metrics.KV_PAGES_SPILLED.value
    paged_in0 = obs_metrics.KV_PAGES_PAGED_IN.value
    elapsed = wave(0.05)
    spilled = obs_metrics.KV_PAGES_SPILLED.value - spilled0
    paged_in = obs_metrics.KV_PAGES_PAGED_IN.value - paged_in0
    sched.pool.check()
    sched.close()
    total = sum(counts)
    print(f"bench: sched-pressure {total} tokens over {slots} staggered "
          f"requests on a {kv_pages - 1}-page pool ({full_pages} pages of "
          f"full-reservation demand) in {elapsed:.2f}s "
          f"({spilled} pages spilled, {paged_in} paged back in)",
          file=sys.stderr)
    return total / elapsed, int(spilled), int(paged_in)


def _bench_sched_overlap(cfg, slots=4, max_new=96):
    """Overlapped-dispatch A/B (the two-deep pipeline in
    runtime/scheduler.py): ``slots`` short prompts submitted together so
    the workload is pure-decode steady state — the regime where the
    speculative feed-fed dispatch keeps the device busy while the host
    fans out the previous burst.  Runs the identical workload twice,
    overlap off then on, each on a fresh engine + scheduler, and
    decomposes where the wall time went via the scheduler's goodput
    accounting.  Greedy decode is byte-identical in both modes, so the
    tok/s delta is pure dispatch-pipeline effect.  Returns a dict with
    tok/s, goodput ratio and exposed host_gap share per mode."""
    import threading

    import jax
    import numpy as np
    from dllama_tpu.obs import metrics as obs_metrics
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.runtime.scheduler import SlotScheduler

    params = maybe_blocked(_zero_q40_params(cfg))
    rng = np.random.RandomState(7)
    prompts = [[int(t) for t in rng.randint(1, cfg.vocab_size, 8)]
               for _ in range(slots)]

    def run_mode(overlap):
        eng = Engine(cfg, params,
                     mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                     batch=slots)
        sched = SlotScheduler(eng, prefill_chunk=16, max_wait_ms=20.0,
                              overlap=overlap)
        counts = [0] * slots

        def run(i):
            t = sched.submit(prompts[i], max_new)
            counts[i] = sum(1 for _ in t.tokens())

        def wave():
            ths = [threading.Thread(target=run, args=(i,))
                   for i in range(slots)]
            t0 = time.perf_counter()
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            return time.perf_counter() - t0

        t0 = time.perf_counter()
        wave()  # compile + warmup: identical shape set
        print(f"compile+warmup ({'overlap' if overlap else 'sync'}): "
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
        comp0 = dict(obs_metrics.SCHED_STEP_TIME_MS.json_value() or {})
        hidden0 = obs_metrics.SCHED_HOST_GAP_HIDDEN_MS.value
        elapsed = wave()
        comp1 = obs_metrics.SCHED_STEP_TIME_MS.json_value() or {}
        hidden = obs_metrics.SCHED_HOST_GAP_HIDDEN_MS.value - hidden0
        sched.close()
        delta = {k: comp1.get(k, 0.0) - comp0.get(k, 0.0) for k in comp1}
        wall = sum(delta.values()) or 1.0
        mode = {
            "toks": sum(counts) / elapsed,
            "goodput": (delta.get("prefill", 0.0)
                        + delta.get("decode", 0.0)) / wall,
            "host_gap_share": delta.get("host_gap", 0.0) / wall,
            "hidden_host_ms": hidden,
        }
        split = " ".join(f"{k}={v:.0f}ms" for k, v in sorted(delta.items()))
        print(f"bench: sched-overlap {'on' if overlap else 'off'}: "
              f"{mode['toks']:.1f} tok/s, goodput {mode['goodput']:.3f}, "
              f"exposed host_gap {mode['host_gap_share']:.3f} "
              f"(hidden {hidden:.0f}ms; {split})", file=sys.stderr)
        return mode

    return {"sync": run_mode(False), "overlap": run_mode(True)}


def _bench_sched_fused(cfg, slots=4, max_new=96):
    """One-dispatch-decode A/B (the fused page-walk attention kernel in
    ops/attention.py + on-device sampling): the ``-sched4`` pure-decode
    workload on a paged pool, run twice — fused attention off, then
    forced on (``DLLAMA_FUSED_ATTN=on`` on TPU, ``interp`` elsewhere so
    the kernel logic still executes) — each on a fresh engine +
    scheduler, because the env ladder is read lazily at trace time and
    the engine's compile keys include it.  Greedy decode must be
    byte-identical across modes (checked on the emitted streams), so
    the tok/s delta is pure kernel-fusion effect.  The headline signal
    is the dispatch-family count per steady pure-decode step, taken
    from a trace-time ledger probe: reset the ledger on the fresh
    engine, trace one t=1 slot_step, and count the distinct matmul
    (``q40/``/``q8/``) + attention (``kv_``) families it recorded —
    the fused contract is ≤ 2 (one matmul family + ``paged-fused``),
    the unfused gather arm records 3–4.  Returns per-mode dicts plus
    the cross-mode parity verdict."""
    import threading

    import jax
    import numpy as np
    from dllama_tpu.obs import dispatch as obs_dispatch
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.runtime.scheduler import SlotScheduler

    params = maybe_blocked(_zero_q40_params(cfg))
    page_size = 16
    rng = np.random.RandomState(7)
    prompts = [[int(t) for t in rng.randint(1, cfg.vocab_size, 8)]
               for _ in range(slots)]
    kv_pages = sum(-(-min(len(p) + max_new, cfg.seq_len) // page_size)
                   for p in prompts) + 1
    fused_env = "on" if jax.default_backend() == "tpu" else "interp"

    def run_mode(fused):
        os.environ["DLLAMA_FUSED_ATTN"] = fused_env if fused else "off"
        tag = f"fused={fused_env}" if fused else "fused=off"
        eng = Engine(cfg, params,
                     mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                     batch=slots,
                     kv_pages=kv_pages, kv_page_size=page_size)
        # dispatch-family probe first, on the fresh engine: the ledger
        # records once per compiled call site (trace time), so reset and
        # trace exactly one steady pure-decode executable — a t=1 greedy
        # slot_step over a small page table — and count what it recorded
        obs_dispatch.reset()
        maxp = 2
        ptab = 1 + np.arange(slots * maxp, dtype=np.int32).reshape(
            slots, maxp)
        eng.slot_step(np.ones((slots, 1), np.int32),
                      np.full((slots,), page_size + 1, np.int32),
                      np.ones((slots,), np.int32),
                      temps_np=np.zeros((slots,), np.float32),
                      topps_np=np.full((slots,), 0.9, np.float32),
                      page_tables_np=ptab)
        fams = sorted(k for k in obs_dispatch.dispatches()
                      if k.startswith(("q40/", "q80/", "q8/", "kv_")))
        print(f"bench: sched-fused {tag} steady-decode dispatch "
              f"families ({len(fams)}): {' '.join(fams)}", file=sys.stderr)

        sched = SlotScheduler(eng, prefill_chunk=16, max_wait_ms=20.0)
        streams = [None] * slots

        def run(i):
            t = sched.submit(prompts[i], max_new)
            streams[i] = list(t.tokens())

        def wave():
            ths = [threading.Thread(target=run, args=(i,))
                   for i in range(slots)]
            t0 = time.perf_counter()
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            return time.perf_counter() - t0

        t0 = time.perf_counter()
        wave()  # compile + warmup: identical shape set
        print(f"compile+warmup ({tag}): {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        elapsed = wave()
        sched.close()
        mode = {
            "toks": sum(len(s) for s in streams) / elapsed,
            "dispatches_per_step": len(fams),
            "families": fams,
            "streams": streams,
        }
        print(f"bench: sched-fused {tag}: {mode['toks']:.1f} tok/s, "
              f"{len(fams)} dispatch families/step", file=sys.stderr)
        return mode

    prev = os.environ.get("DLLAMA_FUSED_ATTN")
    try:
        off = run_mode(False)
        on = run_mode(True)
    finally:
        if prev is None:
            os.environ.pop("DLLAMA_FUSED_ATTN", None)
        else:
            os.environ["DLLAMA_FUSED_ATTN"] = prev
    parity = on.pop("streams") == off.pop("streams")
    if not parity:
        print("bench: sched-fused GREEDY STREAM MISMATCH between modes",
              file=sys.stderr)
    return {"fused": on, "unfused": off, "parity": parity}


def _bench_sched_spec(cfg, slots=4, max_new=96, spec_k=4):
    """Speculative-decoding A/B (runtime/spec.py + the slot-verify
    dispatch): the ``-sched4`` staggered workload run twice, speculation
    off then on with the prompt-lookup proposer.  Greedy output is
    byte-identical in both modes (the emitted stream is always the
    model's own argmax); the tok/s delta is what the verify window's
    multi-token yield buys when drafts are accepted.  Returns a dict
    with tok/s per mode plus the cumulative accept ratio."""
    import threading

    import jax
    import numpy as np
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.runtime.scheduler import SlotScheduler
    from dllama_tpu.runtime.spec import PromptLookupProposer

    params = maybe_blocked(_zero_q40_params(cfg))
    rng = np.random.RandomState(7)
    prompts = [[int(t) for t in rng.randint(1, cfg.vocab_size, 8 + 4 * i)]
               for i in range(slots)]

    def run_mode(spec_on):
        eng = Engine(cfg, params,
                     mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                     batch=slots)
        spec = PromptLookupProposer(vocab=cfg.vocab_size) if spec_on else None
        sched = SlotScheduler(eng, prefill_chunk=16, max_wait_ms=20.0,
                              spec=spec, spec_k=spec_k)
        counts = [0] * slots

        def run(i, delay):
            time.sleep(delay)
            t = sched.submit(prompts[i], max_new)
            counts[i] = sum(1 for _ in t.tokens())

        def wave(stagger):
            ths = [threading.Thread(target=run, args=(i, stagger * i))
                   for i in range(slots)]
            t0 = time.perf_counter()
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            return time.perf_counter() - t0

        t0 = time.perf_counter()
        wave(0.05)  # compile + warmup: same stagger, so the same shape set
        print(f"compile+warmup (spec {'pld' if spec_on else 'off'}): "
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
        elapsed = wave(0.05)
        proposed = sched._spec_proposed
        accepted = sched._spec_accepted
        sched.close()
        mode = {
            "toks": sum(counts) / elapsed,
            "accept_ratio": accepted / proposed if proposed else None,
            "proposed": proposed, "accepted": accepted,
        }
        ratio = (f"{mode['accept_ratio']:.3f}"
                 if mode["accept_ratio"] is not None else "n/a")
        print(f"bench: sched-spec {'pld' if spec_on else 'off'}: "
              f"{mode['toks']:.1f} tok/s, accept ratio {ratio} "
              f"({accepted}/{proposed} drafts)", file=sys.stderr)
        return mode

    return {"off": run_mode(False), "spec": run_mode(True)}


def _bank_stage_metrics(name):
    """Append this stage's final metrics-registry snapshot (obs/metrics
    .py, the same families /metrics serves) to the BENCH_METRICS_BANK
    JSONL artifact — stdout stays the one-JSON-line result contract, so
    the observability evidence rides in a side file next to
    BENCH_r{N}.json instead."""
    path = os.environ.get("BENCH_METRICS_BANK")
    if not path:
        return
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from dllama_tpu.obs import metrics as obs_metrics
        snap = obs_metrics.snapshot_json()
        # provenance stamp: which bench run and which tree produced this
        # row, plus the registry schema it speaks — so perf_sentinel.py
        # can pair rows across rounds without guessing
        line = json.dumps({"stage": name, "ts": round(time.time(), 3),
                           "schema_version": snap.get("schema_version"),
                           "bench_run_id": os.environ.get("BENCH_RUN_ID"),
                           "git_sha": os.environ.get("BENCH_GIT_SHA"),
                           "metrics": snap})
        with open(path, "a") as f:
            f.write(line + "\n")
    except Exception as e:  # noqa: BLE001 — evidence, never the number
        print(f"bench: metrics bank failed for {name}: {e}",
              file=sys.stderr)


def run_attempt(name):
    try:
        _attempt_body(name)
    finally:
        _bank_stage_metrics(name)


def _attempt_body(name):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    # bench children log like the server does (DLLAMA_LOG honored); all
    # dllama logging goes to stderr, so the one-JSON-line stdout contract
    # is untouched
    from dllama_tpu.obs.log import configure as _configure_logging
    _configure_logging()
    import jax

    if name == "probe":
        devs = jax.devices()
        print(json.dumps({"platform": jax.default_backend(),
                          "devices": [str(d) for d in devs]}))
        return

    if name == "llama2-7b-cli":
        ms = _run_cli_bench("llama2-7b")
        print(json.dumps({
            "metric": "llama2-7b q40 greedy decode tok/s "
                      "(1 TPU chip, dllama inference CLI end-to-end)",
            "value": round(1000.0 / ms, 2), "unit": "tok/s",
            "vs_baseline": _vs_baseline(1000.0 / ms, BASELINE_7B_TOKS),
            "backend": jax.default_backend()}))
        return

    if name == "llama2-7b-prefill":
        # prompt-evaluation throughput (the reference's "evaluation" stat,
        # dllama.cpp:45-93; no published number to compare): one bucketed
        # forward over T tokens through the REAL dispatch (quant_impl
        # "auto": prefill rows beyond PALLAS_MAX_ROWS take the XLA dequant
        # path, which pipelines the unpack into the MXU dots)
        ms = _bench_prefill(_model_cfg("llama2-7b"))
        print(json.dumps({
            "metric": "llama2-7b q40 prefill tok/s (1 TPU chip, T=512)",
            "value": round(1000.0 / ms, 1), "unit": "tok/s",
            "vs_baseline": None, "backend": jax.default_backend()}))
        return

    if name.endswith("-tp4sched4"):
        # tensor-parallel serving (parallel/mesh.py + ops/q40.py): the
        # -sched4 staggered workload on a tp=4 mesh — on CPU 4 of the 8
        # forced virtual devices (psum fallback, ledger-recorded), on TPU
        # 4 real chips with the fused collective-matmul ring.  Must be
        # checked before -sched4: the suffix contains it.
        base = name[:-10]
        cfg = _model_cfg(base)
        if base == "cpu-tiny":
            impl = "xla"
        else:
            print(f"bench: {base}: claiming backend...", file=sys.stderr)
            print(f"bench: {base}: backend {jax.default_backend()}",
                  file=sys.stderr)
            impl = _pallas_hw_check("q40")
        if len(jax.devices()) < 4:
            print(f"bench: {name}: needs 4 devices, have "
                  f"{len(jax.devices())}", file=sys.stderr)
            raise SystemExit(3)
        toks = _bench_sched(cfg.with_(quant_impl=impl), tp=4)
        from dllama_tpu.obs import metrics as obs_metrics
        coll = obs_metrics.ENGINE_COLLECTIVE_MS
        print(json.dumps({
            "metric": f"{base} q40 tensor-parallel tp=4 continuous-batching "
                      f"slots=4 aggregate decode tok/s "
                      f"(staggered arrivals, {impl})",
            "value": round(toks, 2), "unit": "tok/s",
            "vs_baseline": _vs_baseline(
                toks, BASELINE_7B_TOKS if base == "llama2-7b" else None),
            "collective_ms_avg": round(coll.sum / coll.count, 3)
            if coll.count else None,
            "backend": jax.default_backend()}))
        return

    if name.endswith("-spec4"):
        # speculative decoding (runtime/spec.py): the -sched4 staggered
        # workload with the prompt-lookup proposer off vs on — the accept
        # ratio says how often drafts verified, the tok/s delta what the
        # multi-token verify yield bought.  Checked before -sched4 with
        # the other sched-suffix stages.
        base = name[:-6]
        cfg = _model_cfg(base)
        if base == "cpu-tiny":
            impl = "xla"
        else:
            print(f"bench: {base}: claiming backend...", file=sys.stderr)
            print(f"bench: {base}: backend {jax.default_backend()}",
                  file=sys.stderr)
            impl = _pallas_hw_check("q40")
        ab = _bench_sched_spec(cfg.with_(quant_impl=impl))
        on, off = ab["spec"], ab["off"]
        print(json.dumps({
            "metric": f"{base} q40 speculative-decoding slots=4 aggregate "
                      f"decode tok/s (prompt-lookup drafts, spec_k=4, "
                      f"{impl})",
            "value": round(on["toks"], 2), "unit": "tok/s",
            "vs_baseline": _vs_baseline(
                on["toks"], BASELINE_7B_TOKS if base == "llama2-7b" else None),
            "spec_off_toks": round(off["toks"], 2),
            "spec_speedup": round(on["toks"] / off["toks"], 3)
            if off["toks"] else None,
            "accept_ratio": round(on["accept_ratio"], 3)
            if on["accept_ratio"] is not None else None,
            "drafts_proposed": on["proposed"],
            "drafts_accepted": on["accepted"],
            "backend": jax.default_backend()}))
        return

    if name.endswith("-fused4"):
        # one-dispatch decode (ops/attention.py fused page-walk kernel +
        # runtime/decode_loop.py on-device sampling): the -sched4
        # pure-decode workload on a paged pool, fused attention off vs
        # forced on — greedy streams must be byte-identical, so the
        # tok/s delta is pure fusion; the trace-time ledger probe counts
        # matmul+attention dispatch families per steady decode step
        # (fused contract: ≤ 2, the unfused gather arm records 3–4)
        base = name[:-7]
        cfg = _model_cfg(base)
        if base == "cpu-tiny":
            impl = "xla"
        else:
            print(f"bench: {base}: claiming backend...", file=sys.stderr)
            print(f"bench: {base}: backend {jax.default_backend()}",
                  file=sys.stderr)
            impl = _pallas_hw_check("q40")
        ab = _bench_sched_fused(cfg.with_(quant_impl=impl))
        on, off = ab["fused"], ab["unfused"]
        print(json.dumps({
            "metric": f"{base} q40 fused-attention one-dispatch decode "
                      f"slots=4 pure-decode aggregate tok/s (paged pool, "
                      f"{impl})",
            "value": round(on["toks"], 2), "unit": "tok/s",
            "vs_baseline": _vs_baseline(
                on["toks"], BASELINE_7B_TOKS if base == "llama2-7b" else None),
            "unfused_toks": round(off["toks"], 2),
            "fused_speedup": round(on["toks"] / off["toks"], 3)
            if off["toks"] else None,
            "dispatches_per_step": on["dispatches_per_step"],
            "unfused_dispatches_per_step": off["dispatches_per_step"],
            "dispatch_families": on["families"],
            "greedy_parity": ab["parity"],
            "backend": jax.default_backend()}))
        return

    if name.endswith("-sched4"):
        # the continuous-batching serving lever (runtime/scheduler.py):
        # cross-request slot scheduler over the batch engine, staggered
        # arrivals — the number the --batch-slots serving path delivers
        base = name[:-7]
        cfg = _model_cfg(base)
        if base == "cpu-tiny":
            impl = "xla"
        else:
            print(f"bench: {base}: claiming backend...", file=sys.stderr)
            print(f"bench: {base}: backend {jax.default_backend()}",
                  file=sys.stderr)
            impl = _pallas_hw_check("q40")
        toks = _bench_sched(cfg.with_(quant_impl=impl))
        print(json.dumps({
            "metric": f"{base} q40 continuous-batching slots=4 aggregate "
                      f"decode tok/s (staggered arrivals, {impl})",
            "value": round(toks, 2), "unit": "tok/s",
            "vs_baseline": _vs_baseline(
                toks, BASELINE_7B_TOKS if base == "llama2-7b" else None),
            "backend": jax.default_backend()}))
        return

    if name.endswith("-overlap4"):
        # overlapped dispatch pipeline (runtime/scheduler.py): the -sched4
        # engine in pure-decode steady state, run twice with the two-deep
        # pipeline off then on — the tok/s delta and the exposed-host_gap
        # drop are what the speculative feed-fed dispatch buys
        base = name[:-9]
        cfg = _model_cfg(base)
        if base == "cpu-tiny":
            impl = "xla"
        else:
            print(f"bench: {base}: claiming backend...", file=sys.stderr)
            print(f"bench: {base}: backend {jax.default_backend()}",
                  file=sys.stderr)
            impl = _pallas_hw_check("q40")
        ab = _bench_sched_overlap(cfg.with_(quant_impl=impl))
        on, off = ab["overlap"], ab["sync"]
        print(json.dumps({
            "metric": f"{base} q40 overlapped-dispatch slots=4 pure-decode "
                      f"aggregate tok/s (two-deep pipeline on, {impl})",
            "value": round(on["toks"], 2), "unit": "tok/s",
            "vs_baseline": _vs_baseline(
                on["toks"], BASELINE_7B_TOKS if base == "llama2-7b" else None),
            "sync_toks": round(off["toks"], 2),
            "overlap_speedup": round(on["toks"] / off["toks"], 3)
            if off["toks"] else None,
            "goodput_on": round(on["goodput"], 3),
            "goodput_off": round(off["goodput"], 3),
            "host_gap_share_on": round(on["host_gap_share"], 4),
            "host_gap_share_off": round(off["host_gap_share"], 4),
            "hidden_host_ms_on": round(on["hidden_host_ms"], 1),
            "backend": jax.default_backend()}))
        return

    if name.endswith("-pressure4"):
        # KV tiering under page pressure (runtime/kvtier.py): the -sched4
        # workload on a pool at ~40% of full-reservation demand, served
        # with optimistic reservation + host spill — the tok/s gap vs
        # -sched4 is what over-commit thrash costs; full reservation
        # could not run this workload concurrently at all
        base = name[:-10]
        cfg = _model_cfg(base)
        if base == "cpu-tiny":
            impl = "xla"
        else:
            print(f"bench: {base}: claiming backend...", file=sys.stderr)
            print(f"bench: {base}: backend {jax.default_backend()}",
                  file=sys.stderr)
            impl = _pallas_hw_check("q40")
        toks, spilled, paged_in = _bench_sched_pressure(
            cfg.with_(quant_impl=impl))
        print(json.dumps({
            "metric": f"{base} q40 KV-tiering slots=4 aggregate decode "
                      f"tok/s (optimistic reservation, pool at 40% of "
                      f"full demand, {impl})",
            "value": round(toks, 2), "unit": "tok/s",
            "vs_baseline": _vs_baseline(
                toks, BASELINE_7B_TOKS if base == "llama2-7b" else None),
            "spill_pages": spilled,
            "pagein_pages": paged_in,
            "backend": jax.default_backend()}))
        return

    if name.endswith("-prefix4"):
        # paged KV + radix prefix cache (runtime/pagepool.py): the -sched4
        # workload but with a 128-token shared system prompt — the tok/s
        # delta over -sched4 is the prefill the radix tree avoided
        base = name[:-8]
        cfg = _model_cfg(base)
        if base == "cpu-tiny":
            impl = "xla"
        else:
            print(f"bench: {base}: claiming backend...", file=sys.stderr)
            print(f"bench: {base}: backend {jax.default_backend()}",
                  file=sys.stderr)
            impl = _pallas_hw_check("q40")
        toks, reused = _bench_sched_prefix(cfg.with_(quant_impl=impl))
        print(json.dumps({
            "metric": f"{base} q40 paged-KV prefix-sharing slots=4 "
                      f"aggregate decode tok/s (128-token shared system "
                      f"prompt, {impl})",
            "value": round(toks, 2), "unit": "tok/s",
            "vs_baseline": _vs_baseline(
                toks, BASELINE_7B_TOKS if base == "llama2-7b" else None),
            "prefix_tokens_reused": int(reused),
            "backend": jax.default_backend()}))
        return

    batch = 1
    kv_quant = False
    profile = False
    if name.endswith("-b8"):
        name, batch = name[:-3], 8
    if name.endswith("-q8kv"):
        # int8 KV cache: at a 16k live prefix the cache read dominates the
        # step, so this should show ~2× less attention time than the bf16
        # run (beyond-reference capability, models/transformer.py)
        name, kv_quant = name[:-5], True
    codec = "q40"  # codec_label below keeps every metric string honest
    if name.endswith("-q8w"):
        # Q80 weight files (the reference's fallback codec): the fused Q80
        # kernel's first hardware number — ~1.9x the Q40 weight bytes but
        # cheaper per-weight unpack, so where it lands vs Q40 is empirical
        name, codec = name[:-4], "q80"
    if name.endswith("-profile"):
        # xplane profiling rides its OWN attempt, run as the LAST hardware
        # stage: in the r05 window the in-stage profiler left the tunneled
        # chip's exclusive claim wedged — every later client (including a
        # bare jax.devices()) hung until the relay died.  Isolating it
        # means a wedge costs only the optional diagnostics, never a
        # headline or extras stage.
        name, profile = name[:-8], True
    chunk_override = None
    if "-c" in name and name.rsplit("-c", 1)[-1].isdigit():
        # decode chunk-size probe: per-token wall cost = compute + (per-
        # chunk dispatch overhead)/chunk, and the r05 window measured that
        # overhead at ~75 ms/chunk over the tunnel — a larger K amortizes
        # it (runtime/decode_loop.py K-step chunk; --chunk on the CLI)
        name, c = name.rsplit("-c", 1)
        chunk_override = int(c)
    codec_label = "q40" if codec == "q40" else "q80-weights"
    cfg = _model_cfg(name)
    if name == "cpu-tiny":
        impl, chunk, n_chunks = "xla", 16, 2
    else:
        # the claim marker makes a wedged tunnel diagnosable: if the next
        # line never appears, the child hung acquiring the chip, not in
        # compile or decode (the r05 post-profile failure signature)
        print(f"bench: {name}: claiming backend...", file=sys.stderr)
        print(f"bench: {name}: backend {jax.default_backend()}", file=sys.stderr)
        impl = _pallas_hw_check(codec)
        chunk, n_chunks = 32, 10  # ≥10 timed chunks (ADVICE r02)
    if profile:
        n_chunks = 2  # the split needs one traced chunk, not a full rerun
    if chunk_override:
        # keep the ≥10-timed-chunks evidence standard (ADVICE r02) even for
        # probes: a promoted chunk-size headline must rest on the same
        # sample count as the number it replaces
        chunk, n_chunks = chunk_override, 10
    cfg = cfg.with_(quant_impl=impl)
    # long-context evidence decodes deep in the cache (live prefix ~15.7k),
    # otherwise the "16k" number would really measure a ~350-token prefix
    start = cfg.seq_len - 64 - (n_chunks + 2) * chunk if name.endswith("-long") else 0
    ms = _bench_decode(cfg, chunk=chunk, n_chunks=n_chunks, profile=profile,
                       start_pos=start, batch=batch, kv_quant=kv_quant,
                       codec=codec)
    toks = batch * 1000.0 / ms
    backend = jax.default_backend()
    if kv_quant:
        print(json.dumps({
            "metric": f"{name} {codec_label} greedy decode tok/s with int8 KV cache"
                      + (f" at seq_len {cfg.seq_len}, live prefix ≥{start}"
                         if start else "")
                      + f" (1 TPU chip, {impl})",
            "value": round(toks, 2), "unit": "tok/s", "vs_baseline": None,
            "backend": backend}))
        return
    if batch > 1:
        # the distinct-stream serving lever (Engine.generate_batch): decode
        # is weight-bandwidth-bound, so aggregate tok/s should approach
        # batch× the single-stream rate — the reference cannot batch at all
        # (tasks.cpp:199-210)
        print(json.dumps({
            "metric": f"{name} {codec_label} lockstep batch={batch} aggregate decode "
                      f"tok/s (1 TPU chip, {impl})",
            "value": round(toks, 2), "unit": "tok/s",
            "vs_baseline": _vs_baseline(
                toks, BASELINE_7B_TOKS if name == "llama2-7b" else None),
            "backend": backend}))
        return
    if name == "llama2-7b-long":
        metric = (f"llama2-7b {codec_label} greedy decode tok/s at seq_len 16384, "
                  f"live prefix ≥{start} (1 TPU chip, {impl})")
        vs = None  # reference has no long-context capability to compare
    elif name == "llama3-8b":
        metric = f"llama3-8b {codec_label} greedy decode tok/s (1 TPU chip, {impl})"
        vs = None  # BASELINE.json target is 80 tok/s/chip on v5e-8; the
        # reference's only published Llama-3 numbers are RasPi multi-node
    elif name == "llama2-7b":
        metric = f"llama2-7b {codec_label} greedy decode tok/s (1 TPU chip, {impl})"
        if chunk_override:
            metric += f" [chunk={chunk}]"
        vs = _vs_baseline(toks, BASELINE_7B_TOKS)
    elif name == "llama2-13b":
        metric = f"llama2-13b {codec_label} greedy decode tok/s (1 TPU chip, {impl})"
        vs = _vs_baseline(toks, BASELINE_13B_TOKS)
    elif name == "tinyllama-1.1b":
        metric = f"tinyllama-1.1b {codec_label} greedy decode tok/s (1 TPU chip, {impl})"
        vs = None  # no published reference number for this config
    else:
        metric = "DEGRADED cpu-fallback tiny-llama decode tok/s (TPU unreachable)"
        vs = None
    print(json.dumps({"metric": metric, "value": round(toks, 2),
                      "unit": "tok/s", "vs_baseline": vs, "backend": backend}))


# ---------------------------------------------------------------------------
# Parent orchestrator
# ---------------------------------------------------------------------------

def _with_compile_cache(env: dict) -> dict:
    """Point a child at the persistent XLA compilation cache under the repo
    (VERDICT r04 Next #8): remote compiles over the tunnel cost 30-90 s
    each, so when a relay window opens every second must go to measurement,
    not recompiles — and the on-disk cache survives into the next round's
    bench.  setdefault so an operator override wins."""
    here = os.path.dirname(os.path.abspath(__file__))
    cache = os.path.join(here, "build", "xla_cache")
    try:
        os.makedirs(cache, exist_ok=True)
    except OSError:
        return env
    env.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
    # remote axon compiles are tens of seconds; 2 s keeps tiny CPU-test
    # programs from churning the cache while catching everything that hurts
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    return env


def _spawn(name, timeout_s, env_extra=None):
    """Run one attempt in a subprocess; returns its parsed JSON or None.
    Stderr is inherited so progress lands in the driver log."""
    env = _with_compile_cache(dict(os.environ))
    env.update(env_extra or {})
    t0 = time.time()
    print(f"bench: attempt {name} (timeout {timeout_s:.0f}s)", file=sys.stderr)
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--attempt", name],
            stdout=subprocess.PIPE, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
    except subprocess.TimeoutExpired:
        print(f"bench: {name} timed out after {time.time() - t0:.0f}s", file=sys.stderr)
        return None
    if r.returncode != 0:
        print(f"bench: {name} exited rc={r.returncode}", file=sys.stderr)
        return None
    try:
        line = r.stdout.decode().strip().splitlines()[-1]
        out = json.loads(line)
        print(f"bench: {name} ok in {time.time() - t0:.0f}s: {line}", file=sys.stderr)
        return out
    except Exception as e:
        print(f"bench: {name} unparseable output ({e})", file=sys.stderr)
        return None


_EMITTED = False


def _sentinel_verdict(result, extras):
    """Compare this run's result against the newest banked round with
    tools/perf_sentinel.py and ride the verdict in ``extras`` — evidence
    for the round notes, never a gate (any failure here is logged and
    swallowed; the bench number always lands)."""
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        rounds = sorted(f for f in os.listdir(here)
                        if f.startswith("BENCH_r") and f.endswith(".json"))
        if not rounds:
            return extras
        sys.path.insert(0, os.path.join(here, "tools"))
        import perf_sentinel
        base = perf_sentinel.load_any(os.path.join(here, rounds[-1]))
        cur = perf_sentinel.normalize_result(
            dict(result, extras=extras or {}))
        rep = perf_sentinel.compare(base, cur)
        extras = dict(extras or {})
        extras["perf_sentinel"] = {
            "vs": rounds[-1], "verdict": rep["verdict"],
            "compared": rep["compared"],
            "regressions": rep["regressions"]}
        print(f"bench: perf sentinel vs {rounds[-1]}: {rep['verdict']} "
              f"({rep['compared']} comparable)", file=sys.stderr)
        return extras
    except Exception as e:  # noqa: BLE001 — evidence, never the number
        print(f"bench: perf sentinel skipped ({type(e).__name__}: {e})",
              file=sys.stderr)
        return extras


def _emit(result, extras=None):
    """Write the result line with SIGTERM blocked: one atomic os.write of
    the full payload, flag set under the mask — no window in which a kill
    can truncate the line, suppress the fallback, or append a second line."""
    global _EMITTED
    import signal
    result.pop("backend", None)
    extras = _sentinel_verdict(result, extras)
    if extras:
        result["extras"] = extras
    payload = (json.dumps(result) + "\n").encode()
    signal.pthread_sigmask(signal.SIG_BLOCK, {signal.SIGTERM})
    try:
        sys.stdout.flush()
        os.write(1, payload)
        _EMITTED = True
    finally:
        signal.pthread_sigmask(signal.SIG_UNBLOCK, {signal.SIGTERM})


_TERM_PAYLOAD = (json.dumps(
    {"metric": "bench interrupted before a number was produced",
     "value": 0.0, "unit": "tok/s", "vs_baseline": None}) + "\n").encode()


def _bank_term_result(result: dict) -> None:
    """Pre-serialize a real measurement for the SIGTERM handler: if the
    driver's outer timeout is shorter than BENCH_BUDGET_S and kills the
    bench mid-poll, the banked number is emitted instead of the 0.0
    'interrupted' line."""
    global _TERM_PAYLOAD
    r = dict(result)
    r.pop("backend", None)
    _TERM_PAYLOAD = (json.dumps(r) + "\n").encode()


def _install_term_handler():
    """If the driver tears the bench down (SIGTERM) before a number was
    emitted, still print a parseable last-resort line — a killed bench must
    never leave BENCH_r{N}.json without JSON (r03 lesson, generalized).
    The handler uses os.write, not print(): stdout's BufferedWriter is not
    reentrant, and the signal can land inside _emit's own print."""
    import signal

    def _on_term(signum, frame):
        if not _EMITTED:
            os.write(1, _TERM_PAYLOAD)
        os._exit(1)

    signal.signal(signal.SIGTERM, _on_term)


def _relay_up(attempts: int = 3, delay_s: float = 5.0) -> bool:
    """Relay liveness with retries, for mid-run stage gates: one dropped SYN
    right after a successful probe must not abort the whole hardware run
    (the probe phase retries for minutes; stages deserve more than one shot)."""
    for i in range(attempts):
        if _relay_listening(5.0):
            return True
        if i < attempts - 1:
            time.sleep(delay_s)
    return False


def main():
    t_start = time.time()
    _install_term_handler()

    # per-stage metrics bank: every attempt child appends its final
    # registry snapshot (one JSON line per stage) here — the federated
    # observability artifact that lands next to BENCH_r{N}.json
    bank = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_metrics.jsonl")
    try:
        os.unlink(bank)
    except OSError:
        pass
    os.environ["BENCH_METRICS_BANK"] = bank
    # provenance for every banked row: one run id for the whole bench
    # invocation (children inherit it) and the tree it measured
    os.environ.setdefault(
        "BENCH_RUN_ID", f"{int(t_start)}-{os.getpid()}")
    if "BENCH_GIT_SHA" not in os.environ:
        try:
            sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
                timeout=10).stdout.decode().strip()
            if sha:
                os.environ["BENCH_GIT_SHA"] = sha
        except Exception:
            pass

    def remaining():
        return BUDGET_S - (time.time() - t_start)

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from dllama_tpu.hostenv import forced_cpu_env
    cpu_env = forced_cpu_env(1)

    # --- probe phase (r03 postmortem): poll the relay port across the
    # window and pay for a JAX probe only at the first sign of life, so a
    # tunnel that is down now but comes back mid-window still gets benched.
    # RESERVE keeps enough tail for the degraded CPU fallback either way.
    RESERVE = 180.0

    def _hw(p):
        return p is not None and p.get("platform") != "cpu"

    probe = None
    probes_attempted = 0
    blind_probe_done = False
    waiting_logged = False
    banked = None
    bank_proc = None
    bank_attempted = False

    def _bank_reap(wait_s: float = 0.0):
        """Collect the background CPU-banking child if it has finished (or
        within ``wait_s``); runs concurrently with the relay poll so a
        tunnel coming up during the ~2 min banking stage loses nothing
        (ADVICE r04 #4 — the inline version blinded the poll for 150 s)."""
        nonlocal banked, bank_proc
        if bank_proc is None:
            return
        if wait_s <= 0 and bank_proc.poll() is None:
            return  # still running; communicate(timeout=0) would raise
        try:
            out, _ = bank_proc.communicate(timeout=wait_s if wait_s > 0 else None)
        except subprocess.TimeoutExpired:
            return
        bank_proc = None
        try:
            banked = json.loads(out.decode().strip().splitlines()[-1])
            _bank_term_result(banked)
            print(f"bench: banked cpu fallback: "
                  f"{json.dumps(banked)}", file=sys.stderr)
        except Exception:
            print("bench: cpu banking child produced no JSON", file=sys.stderr)

    while remaining() > RESERVE + 240:
        # ~4 minutes in with no TPU yet (either degraded branch), bank the
        # CPU fallback ONCE — in the background, so the relay poll keeps
        # running — so a driver whose OUTER timeout is shorter than
        # BENCH_BUDGET_S still gets a real number via the SIGTERM handler
        # instead of the 0.0 line
        if not bank_attempted and BUDGET_S - remaining() > 240:
            bank_attempted = True
            print("bench: attempt cpu-tiny banking (background)", file=sys.stderr)
            bank_proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--attempt", "cpu-tiny"],
                stdout=subprocess.PIPE, env=cpu_env,
                cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
        _bank_reap()
        if _relay_listening():
            probe = _spawn("probe",
                           min(PROBE_TIMEOUT_S, remaining() - RESERVE - 60))
            probes_attempted += 1
            if _hw(probe):
                break
            # port open but the claim failed, hung, or fell to CPU — a
            # half-up relay; back off briefly and re-try while the window
            # allows (each probe subprocess re-registers the backend, so a
            # later attempt can still find the TPU)
            print("bench: relay port open but no TPU probe yet; retrying",
                  file=sys.stderr)
            time.sleep(20)
        else:
            if not waiting_logged:
                print(f"bench: relay {RELAY_HOST}:{RELAY_PORT} not listening; "
                      "polling for tunnel across the run window", file=sys.stderr)
                waiting_logged = True
            # one blind probe mid-window guards against the port heuristic
            # itself being wrong (e.g. relay moved ports but tunnel alive)
            if not blind_probe_done and remaining() < BUDGET_S * 0.55:
                blind_probe_done = True
                probe = _spawn("probe", min(90, remaining() - RESERVE - 60))
                probes_attempted += 1
                if _hw(probe):
                    break
            time.sleep(15)
    _bank_reap()
    if bank_proc is not None and _hw(probe):
        # TPU found while the CPU banking child is still compiling — it is
        # pure fallback insurance, not worth contending for cores with the
        # hardware stages
        bank_proc.kill()
        bank_proc.wait()
        bank_proc = None
    if not _hw(probe) and probes_attempted == 0:
        # small budgets skip the poll loop entirely — still probe once so a
        # healthy TPU is never bypassed (pre-r04 behavior, ≥45 s timeout)
        probe = _spawn("probe", min(PROBE_TIMEOUT_S,
                                    max(remaining() - 120, 45)))
    on_hw = _hw(probe)

    extras = {}
    if on_hw:
        # kernel variant/tile choice is settled offline (tools/sweep_q40.py
        # + the xplane profile, docs/PERF.md) — an in-bench sweep at
        # jit-scan fidelity would cost several minutes of compile per
        # config, which this budget spends on the headline stages instead
        chunk_out = None
        for name in ("llama2-7b", "tinyllama-1.1b"):
            budget = remaining() - RESERVE  # keep room for the CPU fallback
            if budget < 180:
                print("bench: budget exhausted, skipping to fallback", file=sys.stderr)
                break
            if not _relay_up():
                print("bench: relay died before headline stage", file=sys.stderr)
                break
            chunk_out = _spawn(name, min(budget, 900))
            if chunk_out:
                # bank the hardware number immediately: a driver SIGTERM
                # during any later stage must emit THIS, not a stale CPU
                # line or 0.0
                _bank_term_result(chunk_out)
                break
        got_7b = bool(chunk_out) and "llama2-7b" in chunk_out.get("metric", "")
        # BASELINE.json north-star (Llama-3-8B, target ≥80 tok/s/chip) gets
        # the EARLY slot right after the headline (VERDICT r03 Next #3): a
        # tunnel that dies late in the window must not starve the one metric
        # BASELINE actually names.  Recorded in the final JSON's "extras".
        if got_7b and remaining() > RESERVE + 200 and _relay_up():
            l3_out = _spawn("llama3-8b",
                            min(remaining() - RESERVE - 60, 480))
            if l3_out:
                extras["llama3-8b_toks"] = l3_out["value"]
                print(f"bench: north-star config: {json.dumps(l3_out)}",
                      file=sys.stderr)
        # --- tile probe + auto-tune (docs/PERF.md lever #1): time the w13
        # shape at three tile configs; if a wider-td config clearly beats
        # the default, re-run the headline with the width rule applied and
        # keep whichever number is better.  This lets the round-end bench
        # close the tile_d/DMA lever without a builder in the loop. ---
        # ``winning_env`` is set ONLY when the tuned re-run actually ran
        # and beat the default end-to-end — the CLI stage must never apply
        # a rule validated only by the w13 microbench.
        winning_env = None
        # the whole auto-tune block lives inside its own sub-deadline so it
        # can never starve the operator-surface CLI stage (which needs
        # ~RESERVE+420 s of tail); with a short window it simply skips
        tune_deadline = time.time() + (remaining() - (RESERVE + 420))
        if got_7b and tune_deadline - time.time() > 280 and _relay_up():
            here = os.path.dirname(os.path.abspath(__file__))
            probe_ms = {}
            for tn, td in ((1024, 1024), (512, 2048), (512, 4096)):
                left = tune_deadline - time.time()
                if left < 80:
                    break
                try:
                    r = subprocess.run(
                        [sys.executable, os.path.join(here, "tools", "sweep_q40.py"),
                         "--one", "classic", str(tn), str(td), "--shapes", "w13"],
                        stdout=subprocess.PIPE, env=_child_env(), cwd=here,
                        timeout=min(left - 10, 180))
                    line = r.stdout.decode().strip().splitlines()[-1] if r.stdout else ""
                    print(f"bench: tile probe ({tn},{td}): {line}", file=sys.stderr)
                    ms = json.loads(line).get("shapes", {}).get("w13", {}).get("ms")
                    if ms:
                        probe_ms[(tn, td)] = float(ms)
                except Exception as e:
                    print(f"bench: tile probe ({tn},{td}) failed "
                          f"({type(e).__name__})", file=sys.stderr)
            base = probe_ms.get((1024, 1024))
            best = min(probe_ms, key=probe_ms.get) if probe_ms else None
            if base and best and best != (1024, 1024) \
                    and probe_ms[best] < 0.95 * base \
                    and tune_deadline - time.time() > 120 and chunk_out:
                rule = json.dumps([[8192, best[0], best[1]]])
                print(f"bench: width rule wins on w13 "
                      f"({best}: {probe_ms[best]:.3f} ms vs {base:.3f} ms); "
                      f"re-running headline with {rule}", file=sys.stderr)
                tuned_out = _spawn(
                    "llama2-7b", min(tune_deadline - time.time(), 300),
                    env_extra={"DLLAMA_Q40_TILES_JSON": rule})
                if tuned_out:
                    extras["llama2-7b_default_tiles_toks"] = chunk_out["value"]
                    if tuned_out["value"] > chunk_out["value"]:
                        extras["tile_rule"] = rule
                        tuned_out["metric"] += f" [width-rule tiles {rule}]"
                        chunk_out = tuned_out
                        _bank_term_result(chunk_out)
                        winning_env = {"DLLAMA_Q40_TILES_JSON": rule}
                    else:
                        extras["llama2-7b_tuned_tiles_toks"] = tuned_out["value"]
        # the operator-surface run (synth .m → loader → Engine → CLI stats)
        # is the headline number when it completes (VERDICT r02 Next #3);
        # the decode_chunk number above remains the recorded cross-check.
        # Only attempted when the 7B shape itself just worked — a tinyllama
        # fallback means 7B failed and re-running it would burn the budget.
        cli_out = None
        if got_7b and remaining() > RESERVE + 300 and _relay_up():
            # the grandchild CLI process is killed at an absolute deadline
            # strictly inside the attempt timeout, so a hang can never
            # orphan it on the TPU (synthesis time is inside the deadline)
            cli_env = dict(winning_env or {})  # only an end-to-end-winning rule
            cli_env["BENCH_CLI_DEADLINE"] = str(time.time() + remaining() - 240)
            cli_out = _spawn("llama2-7b-cli", remaining() - 150, env_extra=cli_env)
            if cli_out:
                _bank_term_result(cli_out)  # survives a kill in later stages
        # packed-MoE decode on hardware once (VERDICT r02 Next #5): the
        # QLayerView scalar-prefetch expert select must lower under Mosaic.
        # Runs after the headline stages (a hang here costs diagnostics, not
        # the number) but before the optional long-context stage, which must
        # not starve it of budget.
        if chunk_out and remaining() > RESERVE + 120 and _relay_up():
            here = os.path.dirname(os.path.abspath(__file__))
            try:
                r = subprocess.run(
                    [sys.executable, os.path.join(here, "tools", "moe_hw_check.py"),
                     "--layers", "2", "--steps", "8"],
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    env=_child_env(), cwd=here,
                    timeout=min(remaining() - 60, 240))
                tail = r.stdout.decode().strip().splitlines()[-1] if r.stdout else ""
                print(f"bench: moe hw check rc={r.returncode}: {tail}",
                      file=sys.stderr)
            except Exception as e:
                print(f"bench: moe hw check failed ({type(e).__name__})",
                      file=sys.stderr)
        # long-context decode evidence: 16k cache, decode deep in a live
        # prefix stays usable because attention reads O(pos) — the flagship
        # beyond-reference capability; recorded in "extras".  Runs BEFORE
        # the batch stage so a tight tail starves the newer evidence, not
        # this one.
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            long_out = _spawn("llama2-7b-long", 300)
            if long_out:
                extras["llama2-7b_16k_toks"] = long_out["value"]
                print(f"bench: long-context: {json.dumps(long_out)}",
                      file=sys.stderr)
        # batched-serving evidence: lockstep batch=8 aggregate tok/s — the
        # distinct-stream throughput lever (Engine.generate_batch; the
        # reference is batch=1).  Decode is weight-bandwidth-bound, so this
        # should approach 8× the single-stream rate on the same chip.
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            b8_out = _spawn("llama2-7b-b8", 300)
            if b8_out:
                extras["llama2-7b_batch8_agg_toks"] = b8_out["value"]
                print(f"bench: batched serving: {json.dumps(b8_out)}",
                      file=sys.stderr)
        # continuous-batching evidence: the same chip serving 4 STAGGERED
        # requests through the slot scheduler (the --batch-slots path) —
        # unlike the lockstep b8 row, requests join mid-decode here
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            sc_out = _spawn("llama2-7b-sched4", 300)
            if sc_out:
                extras["llama2-7b_sched4_agg_toks"] = sc_out["value"]
                print(f"bench: continuous batching: {json.dumps(sc_out)}",
                      file=sys.stderr)
        # overlapped-dispatch evidence: the sched4 engine in pure-decode
        # steady state, two-deep pipeline off vs on — on hardware the
        # enqueue is truly async, so the hidden host fanout converts
        # directly into aggregate tok/s
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            ov_out = _spawn("llama2-7b-overlap4", 300)
            if ov_out:
                extras["llama2-7b_overlap4_agg_toks"] = ov_out["value"]
                extras["llama2-7b_overlap4_sync_toks"] = ov_out.get("sync_toks")
                extras["llama2-7b_overlap4_speedup"] = \
                    ov_out.get("overlap_speedup")
                extras["llama2-7b_overlap4_host_gap_share_on"] = \
                    ov_out.get("host_gap_share_on")
                extras["llama2-7b_overlap4_host_gap_share_off"] = \
                    ov_out.get("host_gap_share_off")
                print(f"bench: overlapped dispatch: {json.dumps(ov_out)}",
                      file=sys.stderr)
        # one-dispatch-decode evidence: the sched4 pure-decode workload
        # on a paged pool with the fused page-walk attention kernel off
        # vs on — on hardware the gather arm's extra dispatches are real
        # HBM round trips, so the family-count drop converts to tok/s
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            fu_out = _spawn("llama2-7b-fused4", 300)
            if fu_out:
                extras["llama2-7b_fused4_agg_toks"] = fu_out["value"]
                extras["llama2-7b_fused4_unfused_toks"] = \
                    fu_out.get("unfused_toks")
                extras["llama2-7b_fused4_speedup"] = \
                    fu_out.get("fused_speedup")
                extras["llama2-7b_fused4_dispatches_per_step"] = \
                    fu_out.get("dispatches_per_step")
                extras["llama2-7b_fused4_greedy_parity"] = \
                    fu_out.get("greedy_parity")
                print(f"bench: one-dispatch decode: {json.dumps(fu_out)}",
                      file=sys.stderr)
        # speculative-decoding evidence: the sched4 workload with
        # prompt-lookup drafts off vs on — on hardware each accepted
        # draft saves a whole dispatch round trip, so the accept ratio
        # converts directly into aggregate tok/s
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            sp_out = _spawn("llama2-7b-spec4", 300)
            if sp_out:
                extras["llama2-7b_spec4_agg_toks"] = sp_out["value"]
                extras["llama2-7b_spec4_accept_ratio"] = \
                    sp_out.get("accept_ratio")
                extras["llama2-7b_spec4_speedup"] = \
                    sp_out.get("spec_speedup")
                print(f"bench: speculative decoding: {json.dumps(sp_out)}",
                      file=sys.stderr)
        # prefix-sharing evidence: the sched4 workload with a shared
        # 128-token system prompt over the paged pool + radix cache — the
        # delta vs the sched4 row is the prefill the tree avoided
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            px_out = _spawn("llama2-7b-prefix4", 300)
            if px_out:
                extras["llama2-7b_prefix4_agg_toks"] = px_out["value"]
                extras["llama2-7b_prefix4_tokens_reused"] = \
                    px_out.get("prefix_tokens_reused")
                print(f"bench: prefix sharing: {json.dumps(px_out)}",
                      file=sys.stderr)
        # KV-tiering evidence: the sched4 workload on a pool at 40% of
        # full-reservation demand, optimistic reservation + host spill —
        # the ratio vs the sched4 row is what over-commit thrash costs
        # on a pool full reservation could not serve concurrently
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            pr_out = _spawn("llama2-7b-pressure4", 300)
            if pr_out:
                extras["llama2-7b_pressure4_agg_toks"] = pr_out["value"]
                extras["llama2-7b_pressure4_spill_pages"] = \
                    pr_out.get("spill_pages")
                sc_toks = extras.get("llama2-7b_sched4_agg_toks")
                if sc_toks:
                    extras["llama2-7b_pressure4_vs_sched4"] = round(
                        pr_out["value"] / sc_toks, 3)
                print(f"bench: KV tiering: {json.dumps(pr_out)}",
                      file=sys.stderr)
        # tensor-parallel serving evidence: the sched4 workload on a tp=4
        # mesh (4 chips) with the fused collective-matmul decode — the
        # dispatch ledger in the attempt's stderr says whether the ring
        # or the psum fallback actually ran
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            tp4_out = _spawn("llama2-7b-tp4sched4", 300)
            if tp4_out:
                extras["llama2-7b_tp4sched4_agg_toks"] = tp4_out["value"]
                extras["llama2-7b_tp4sched4_collective_ms"] = \
                    tp4_out.get("collective_ms_avg")
                print(f"bench: tp serving: {json.dumps(tp4_out)}",
                      file=sys.stderr)
        # int8-KV-cache long-context evidence: the 16k live-prefix decode
        # rerun with the quantized cache — the cache read dominates there,
        # so the delta vs llama2-7b_16k_toks measures the ~2× traffic cut
        if got_7b and remaining() > RESERVE + 280 and _relay_up():
            q8kv_out = _spawn("llama2-7b-long-q8kv", 300)
            if q8kv_out:
                extras["llama2-7b_16k_q8kv_toks"] = q8kv_out["value"]
                print(f"bench: int8-KV long-context: {json.dumps(q8kv_out)}",
                      file=sys.stderr)
        # prompt-evaluation throughput + the reference's 13B row — cheap
        # extras once the headline is in hand
        if got_7b and remaining() > RESERVE + 200 and _relay_up():
            pf_out = _spawn("llama2-7b-prefill",
                            min(remaining() - RESERVE - 60, 240))
            if pf_out:
                extras["llama2-7b_prefill_toks"] = pf_out["value"]
        if got_7b and remaining() > RESERVE + 400 and _relay_up():
            out13 = _spawn("llama2-13b", min(remaining() - RESERVE - 60, 600))
            if out13:
                extras["llama2-13b_toks"] = out13["value"]
                print(f"bench: 13B row: {json.dumps(out13)}", file=sys.stderr)
        # xplane I/T-split diagnostics run DEAD LAST: the r05 window showed
        # the tunnel profiler can wedge the chip's exclusive claim, hanging
        # every subsequent client — after this stage there is nothing left
        # to lose (the emit below uses results already in hand)
        if got_7b and remaining() > RESERVE + 120 and _relay_up():
            _spawn("llama2-7b-profile", min(remaining() - RESERVE, 300))
        if cli_out:
            print(f"bench: decode_chunk cross-check: {json.dumps(chunk_out)}",
                  file=sys.stderr)
            _emit(cli_out, extras)
            return
        if chunk_out:
            _emit(chunk_out, extras)
            return
    else:
        print("bench: TPU backend unreachable — degraded CPU mode", file=sys.stderr)

    # the in-session watcher (tools/tunnel_watch.sh + tools/hw_capture.py)
    # may have banked driver-grade hardware numbers during a relay window
    # earlier in the round — a dead relay at round end must surface THAT
    # evidence, clearly labeled, not only a degraded CPU line
    insession = None
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_insession.json")
        with open(path) as f:
            cand = json.loads(f.read().strip())
        # freshness gate: a capture from THIS round only (the artifact is
        # committed, so a later dead-relay round must not replay it as
        # current evidence).  Primary check: the round stamp vs the
        # driver's PROGRESS.jsonl (exact).  A ROUND-STAMPED capture whose
        # current round is unreadable is STALE — the stamp was written to
        # be compared, and "can't read the round" must not widen into the
        # time window (a replayed checkout always has a fresh mtime and
        # often a recent clock).  The 14 h captured_unix window applies
        # only to artifacts that never carried a round stamp.
        cur_round = current_round()
        if cand.get("round") is not None:
            fresh = (cur_round is not None
                     and int(cand["round"]) == cur_round)
        else:
            fresh = (time.time() - float(cand.get("captured_unix") or 0)
                     < 14 * 3600)
        if cand.get("metric") and cand.get("value", 0) > 0 \
                and "DEGRADED" not in cand["metric"] and fresh:
            insession = cand
    except Exception:
        pass

    if banked is None and bank_proc is not None:
        # the background banking child may still be mid-compile — give it
        # the time a fresh spawn would have gotten rather than starting over
        _bank_reap(wait_s=max(min(remaining() - 30, 300), 60))
        if bank_proc is not None:
            bank_proc.kill()
            bank_proc.wait()
            bank_proc = None
    if insession is not None and not on_hw:
        # only when the relay is genuinely unreachable: an on-hw run whose
        # stages all failed keeps the honest degraded path (and its label)
        print("bench: emitting the committed in-session TPU capture "
              "(relay down at round end)", file=sys.stderr)
        insession.pop("captured_unix", None)
        insession.pop("round", None)
        insession["metric"] += " [in-session capture; relay down at round end]"
        extras = insession.pop("extras", None) or {}
        _bank_term_result(dict(insession, **({"extras": extras} if extras else {})))
        cpu_out = banked or _spawn(
            "cpu-tiny", max(min(remaining() - 30, 300), 120),
            env_extra=cpu_env)
        if cpu_out and cpu_out.get("value"):
            extras["degraded_cpu_toks"] = cpu_out["value"]
            # re-bank so a kill after this point carries the cross-check too
            _bank_term_result(dict(insession, extras=extras))
        _emit(insession, extras or None)
        return
    out = banked or _spawn("cpu-tiny", max(min(remaining() - 30, 420), 120),
                           env_extra=cpu_env)
    if out:
        # even with the TPU unreachable, record the batching lever's
        # SCALING quantitatively: lockstep batch=8 aggregate vs the
        # single-stream rate on the same CPU backend (architecture-level
        # evidence that the distinct-stream batch amortizes the weight
        # read; r04 lesson — a dead relay must not mean zero evidence)
        extras = None
        if remaining() > 200:
            _bank_term_result(out)  # a kill mid-b8 must emit THIS number
            b8 = _spawn("cpu-tiny-b8", min(remaining() - 60, 300),
                        env_extra=cpu_env)
            if b8 and b8.get("value") and out.get("value"):
                extras = {"cpu_batch8_agg_toks": b8["value"],
                          "cpu_batch8_vs_single": round(
                              b8["value"] / out["value"], 2)}
        if remaining() > 140:
            # one-dispatch-decode A/B on the same CPU backend (fused
            # kernel forced via interpret mode): tok/s parity is the
            # expected result here — the signal is the dispatch-family
            # count per steady decode step (fused contract: ≤ 2 vs the
            # gather arm's 3–4) and byte-identical greedy streams.
            # Runs FIRST among the scheduler stages: it is this round's
            # new evidence, so a tight tail starves the older rows.
            fu = _spawn("cpu-tiny-fused4", min(remaining() - 60, 360),
                        env_extra=cpu_env)
            if fu and fu.get("value"):
                extras = extras or {}
                extras["cpu_fused4_agg_toks"] = fu["value"]
                extras["cpu_fused4_unfused_toks"] = fu.get("unfused_toks")
                extras["cpu_fused4_dispatches_per_step"] = \
                    fu.get("dispatches_per_step")
                extras["cpu_fused4_unfused_dispatches_per_step"] = \
                    fu.get("unfused_dispatches_per_step")
                extras["cpu_fused4_greedy_parity"] = \
                    fu.get("greedy_parity")
        if remaining() > 140:
            # overlapped-dispatch A/B on the same CPU backend: pure-decode
            # steady state with the two-deep pipeline off vs on.
            # (The CPU client executes at enqueue time, so tok/s parity
            # is the expected result here; the exposed-host_gap drop is
            # the pipeline signal.)
            ov = _spawn("cpu-tiny-overlap4", min(remaining() - 60, 360),
                        env_extra=cpu_env)
            if ov and ov.get("value"):
                extras = extras or {}
                extras["cpu_overlap4_agg_toks"] = ov["value"]
                extras["cpu_overlap4_sync_toks"] = ov.get("sync_toks")
                extras["cpu_overlap4_speedup"] = ov.get("overlap_speedup")
                extras["cpu_overlap4_host_gap_share_on"] = \
                    ov.get("host_gap_share_on")
                extras["cpu_overlap4_host_gap_share_off"] = \
                    ov.get("host_gap_share_off")
        if remaining() > 140:
            # continuous batching on the same CPU backend: 4 staggered
            # requests through the slot scheduler vs the single-stream rate
            sc = _spawn("cpu-tiny-sched4", min(remaining() - 60, 300),
                        env_extra=cpu_env)
            if sc and sc.get("value") and out.get("value"):
                extras = extras or {}
                extras["cpu_sched4_agg_toks"] = sc["value"]
                extras["cpu_sched4_vs_single"] = round(
                    sc["value"] / out["value"], 2)
        if remaining() > 140:
            # speculative decoding on the same CPU backend: the sched4
            # workload with prompt-lookup drafts off vs on — the accept
            # ratio is the real signal here (CPU step cost barely
            # changes with window width, so tok/s parity is expected)
            sp = _spawn("cpu-tiny-spec4", min(remaining() - 60, 300),
                        env_extra=cpu_env)
            if sp and sp.get("value"):
                extras = extras or {}
                extras["cpu_spec4_agg_toks"] = sp["value"]
                extras["cpu_spec4_accept_ratio"] = sp.get("accept_ratio")
                if sp.get("spec_off_toks"):
                    extras["cpu_spec4_vs_sched4"] = round(
                        sp["value"] / sp["spec_off_toks"], 2)
        if remaining() > 140:
            # paged KV + radix prefix sharing on the same CPU backend:
            # the sched4 workload with a shared 128-token system prompt
            px = _spawn("cpu-tiny-prefix4", min(remaining() - 60, 300),
                        env_extra=cpu_env)
            if px and px.get("value"):
                extras = extras or {}
                extras["cpu_prefix4_agg_toks"] = px["value"]
                extras["cpu_prefix4_tokens_reused"] = \
                    px.get("prefix_tokens_reused")
        if remaining() > 140:
            # KV tiering on the same CPU backend: the sched4 workload on
            # a pool at 40% of full-reservation demand — optimistic
            # reservation + host spill keep it serving (byte-identical
            # greedy decode); the ratio vs sched4 is the thrash cost
            pr = _spawn("cpu-tiny-pressure4", min(remaining() - 60, 360),
                        env_extra=cpu_env)
            if pr and pr.get("value"):
                extras = extras or {}
                extras["cpu_pressure4_agg_toks"] = pr["value"]
                extras["cpu_pressure4_spill_pages"] = pr.get("spill_pages")
                if extras.get("cpu_sched4_agg_toks"):
                    extras["cpu_pressure4_vs_sched4"] = round(
                        pr["value"] / extras["cpu_sched4_agg_toks"], 2)
        if remaining() > 140:
            # tensor-parallel serving on the same host: the sched4
            # workload on a tp=4 mesh over 8 forced virtual devices —
            # end-to-end through the sharded program (CPU takes the psum
            # fallback; the fused ring is TPU-only and ledger-recorded)
            tp4 = _spawn("cpu-tiny-tp4sched4", min(remaining() - 60, 360),
                         env_extra=forced_cpu_env(8))
            if tp4 and tp4.get("value"):
                extras = extras or {}
                extras["cpu_tp4sched4_agg_toks"] = tp4["value"]
                extras["cpu_tp4sched4_collective_ms"] = \
                    tp4.get("collective_ms_avg")
                if extras.get("cpu_sched4_agg_toks"):
                    extras["cpu_tp4sched4_vs_sched4"] = round(
                        tp4["value"] / extras["cpu_sched4_agg_toks"], 2)
        _emit(out, extras)
        return
    # absolute last resort: still print a parseable line
    _emit({"metric": "bench failed (no backend produced a number)",
           "value": 0.0, "unit": "tok/s", "vs_baseline": None})


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--attempt":
        run_attempt(sys.argv[2])
    else:
        main()
