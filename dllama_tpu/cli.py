"""`dllama` command-line app: inference | generate | chat | worker |
batch | router | serve-pod.

Re-implements the reference app layer (`src/apps/dllama/dllama.cpp` +
`src/app.cpp`) with the same flag surface (`AppArgs::parse`, app.cpp:19-93),
the reference's four modes (dllama.cpp:221-252) plus a beyond-reference
``batch`` mode:

* ``inference`` — benchmark mode: per-token ``G/I/T`` ms line + run
  averages (dllama.cpp:45-93 output contract).
* ``generate``  — stream text for ``--steps`` tokens.
* ``chat``      — REPL with system prompt, chat template, streaming EOS
  detection, KV position persisting across turns (dllama.cpp:111-203).
* ``worker``    — in the reference, a TCP worker process (dllama.cpp:205-
  219).  Within one host the "workers" are mesh devices inside one
  process; across hosts, ``worker`` joins the multi-host process group
  (``--coordinator host:port --nproc N --proc-id K``, parallel/
  distributed.py) and runs the same SPMD program as the root with stdout
  suppressed.
* ``batch``     — beyond reference: decode DISTINCT prompts
  (``--prompts-file``) as one lockstep ragged batch
  (Engine.generate_batch); aggregate tok/s scales with batch while the
  per-step cost stays near one stream's.
* ``router``    — beyond reference: fleet router fronting N dllama-api
  replicas (router/service.py; pure HTTP, no jax in-process).
* ``serve-pod`` — beyond reference: partition the local devices into
  ``--dp`` tensor-parallel serving replicas of ``--workers tpu:N``
  chips each and front them with the fleet router on one public port
  (router/pod.py).

``--workers`` keeps its name but takes ``tpu:N`` (a mesh degree) instead of
host:port pairs — the transport is XLA collectives, not sockets.  ``--sp``/
``--dp`` add sequence-parallel (long context) and data-parallel (batch)
mesh axes, capability the reference does not have.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

from . import quants
from .io import mfile, tfile
from .models.config import ModelConfig
from .models.params import load_params
from .parallel.mesh import parse_workers
from .runtime.engine import Engine, RunStats
from .runtime.stream import drain_generation
from .tokenizer.bpe import Tokenizer
from .tokenizer.chat import ChatItem, ChatTemplate, TokenizerChatStops
from .tokenizer.eos import EosDetector

DTYPES = {"f32": "float32", "bf16": "bfloat16", "f16": "float16"}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dllama", description=__doc__)
    p.add_argument("mode", choices=["inference", "generate", "chat", "worker",
                                    "batch", "router", "serve-pod"])
    p.add_argument("--model", help="path to .m model file")
    p.add_argument("--tokenizer", help="path to .t tokenizer file")
    p.add_argument("--prompt", default=None)
    p.add_argument("--prompts-file", default=None,
                   help="batch mode: file with one prompt per line; each "
                        "line decodes as its own distinct stream in one "
                        "lockstep batch (beyond-reference capability — the "
                        "reference is batch=1, tasks.cpp:199-210)")
    p.add_argument("--steps", type=int, default=0)
    p.add_argument("--temperature", type=float, default=0.8)  # app.cpp:31
    p.add_argument("--topp", type=float, default=0.9)         # app.cpp:32
    p.add_argument("--seed", type=int, default=None)          # time-based default (app.cpp:33)
    p.add_argument("--weights-float-type", choices=list(quants.FLOAT_TYPE_BY_NAME),
                   default=None, help="required for legacy .m files without a header key")
    p.add_argument("--buffer-float-type", choices=list(DTYPES) + ["q80"], default="bf16",
                   help="compute dtype (the reference's wire/buffer quantization "
                        "analogue); 'q80' is accepted for reference-command parity "
                        "and maps to bf16 (Q80's purpose is wire compression, "
                        "tasks.cpp:124-163 — the 'wire' here is ICI inside the "
                        "XLA program)")
    p.add_argument("--workers", default=None, help="tpu:N mesh degree")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel degree: shards the KV cache's "
                        "sequence axis over the mesh for long context "
                        "(beyond-reference capability; see ops/sp_attention.py)")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel degree: shards the batch axis over a "
                        "dp mesh axis (beyond-reference capability). In "
                        "batch mode the dp shards carry DISTINCT prompts; "
                        "in the single-prompt modes the dp rows are "
                        "replicas and only stream 0 is printed")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree for MoE models: expert "
                        "stacks — dense AND packed Q40 — shard over experts "
                        "instead of replicating (beyond-reference; the "
                        "reference TP-slices all experts everywhere, "
                        "transformer.cpp:299-317; packed path: ops/q40.py "
                        "_sharded_matmul_ep)")
    p.add_argument("--coordinator", default=None,
                   help="multi-host: process-0 host:port for "
                        "jax.distributed.initialize (parallel/distributed.py); "
                        "every process runs the same command with the same "
                        "model flags")
    p.add_argument("--nproc", type=int, default=None,
                   help="multi-host: total process count")
    p.add_argument("--proc-id", type=int, default=None,
                   help="multi-host: this process's id (0 = root)")
    p.add_argument("--program", choices=list(WORKER_PROGRAMS),
                   default="generate",
                   help="worker mode: which root program this worker mirrors "
                        "(multi-host SPMD runs the same program on every process)")
    p.add_argument("--max-seq-len", type=int, default=None)
    p.add_argument("--kv-cache-dtype", choices=list(DTYPES) + ["q8"],
                   default=None,
                   help="cache dtype (default bf16; reference parity is "
                        "f32).  'q8' stores int8 values + per-position "
                        "scales: ~2x less cache HBM traffic/residency, so "
                        "max context per chip nearly doubles "
                        "(beyond-reference)")
    p.add_argument("--chunk", type=int, default=16, help="on-device decode chunk size")
    p.add_argument("--pld", type=int, default=0, metavar="K",
                   help="generate mode, temperature 0: prompt-lookup "
                        "speculative decoding — propose K tokens from the "
                        "latest matching n-gram in the context and verify "
                        "them in ONE forward (beyond-reference; a valid "
                        "greedy stream — bit-identical to plain greedy up "
                        "to argmax near-ties between the T=1 and T=K+1 "
                        "forwards' reduction orders)")
    p.add_argument("--dequantize", action="store_true",
                   help="load Q40 weights as dense bf16 instead of the packed "
                        "fused-kernel path (debugging / numerics comparison)")
    p.add_argument("--warmup", type=int, default=0,
                   help="inference mode: generate this many throwaway tokens "
                        "first (compiles the prefill bucket and decode chunks) "
                        "so the timed stats measure steady state, not XLA "
                        "compilation; 0 = reference parity (it has no compile)")
    p.add_argument("--profile-split", action="store_true",
                   help="inference mode: after the run, trace a few decode steps "
                        "with the XLA profiler and report compute vs collective "
                        "time (the reference's I/T split, SURVEY §5-tracing)")
    p.add_argument("--profile-ops", action="store_true",
                   help="inference mode: like --profile-split but also lists "
                        "the top per-op device times (where did the decode "
                        "step's milliseconds actually go); same xplane trace, "
                        "deeper report")
    p.add_argument("--nthreads", type=int, default=0, help="accepted for reference CLI parity; unused on TPU")
    p.add_argument("--port", type=int, default=9990,
                   help="accepted for reference CLI parity; only the API server "
                        "(python -m dllama_tpu.server.api) listens on it")
    p.add_argument("--batch-slots", type=int, default=0,
                   help="api server: serve /v1/completions list-prompts as one "
                        "lockstep batch with this many slots (a second KV "
                        "cache; weights are shared); also enables the "
                        "continuous-batching slot scheduler for single-"
                        "stream requests (runtime/scheduler.py)")
    p.add_argument("--sched-prefill-chunk", type=int, default=16,
                   help="continuous batching: prompt tokens fed per mixed "
                        "prefill step when a request joins mid-decode; "
                        "smaller chunks bound the extra inter-token latency "
                        "a join adds to running streams")
    p.add_argument("--sched-max-wait-ms", type=float, default=50.0,
                   help="continuous batching: with requests queued for a "
                        "slot, clamp on-device decode bursts so a finishing "
                        "stream frees its slot within about this many "
                        "milliseconds")
    p.add_argument("--sched-max-queue", type=int, default=32,
                   help="continuous batching: max requests waiting for a "
                        "slot (beyond free slots); excess submissions get "
                        "429 + Retry-After")
    p.add_argument("--kv-pages", type=int, default=0,
                   help="slot scheduler: back the slot KV cache with a paged "
                        "pool of this many pages instead of per-slot "
                        "contiguous rows (page 0 is reserved scratch).  "
                        "Pages are allocated per request at admission and "
                        "shared across requests with identical prompt "
                        "prefixes (radix prefix cache), so the pool can be "
                        "sized well below slots x max-seq-len "
                        "(docs/PERF.md).  0 = contiguous (default)")
    p.add_argument("--kv-page-size", type=int, default=16,
                   help="paged KV: tokens per page; prefix sharing works in "
                        "whole pages, so smaller pages share more of a "
                        "common prompt but make longer page tables")
    p.add_argument("--kv-reserve", choices=("full", "optimistic"),
                   default="full",
                   help="paged KV: page reservation policy.  'full' "
                        "reserves every page a request can ever touch at "
                        "admission (exhaustion = queueing, spill never "
                        "engages); 'optimistic' admits with only "
                        "ceil((prompt + --spill-headroom)/page) pages and "
                        "grows slots page-by-page at decode, reclaiming "
                        "through radix eviction and host-RAM spill under "
                        "pressure (docs/PERF.md KV tiering)")
    p.add_argument("--spill-headroom", type=int, default=16,
                   help="optimistic KV reservation: decode tokens of "
                        "slack reserved beyond the prompt at admission "
                        "(and at preempt-resume); larger values grow "
                        "less often, smaller ones admit more "
                        "concurrently")
    p.add_argument("--kv-host-pool-mb", type=float, default=64.0,
                   help="KV tiering: pinned host-RAM budget (MiB) for "
                        "spilled KV pages; a spill that would not fit "
                        "falls back to preempt/park (0 disables "
                        "spilling entirely)")
    p.add_argument("--kv-quant", choices=("off", "int8"), default="off",
                   help="paged KV: store pages quantized int8 with "
                        "per-page scales (~half the pool bytes of bf16); "
                        "attention dequantizes fused at read "
                        "(dispatch ledger codec kv_int8).  Snapshots "
                        "and DLREQ01 hand-off records carry the codec; "
                        "geometry-compatible peers with a different "
                        "codec reject cleanly")
    p.add_argument("--no-prefix-reuse", action="store_true",
                   help="paged KV: disable the radix prefix cache (pages "
                        "are still pooled; nothing is shared or retained "
                        "across requests) — A/B baseline for "
                        "prefix_tokens_reused metrics")
    p.add_argument("--no-sched-overlap", action="store_true",
                   help="slot scheduler: disable the two-deep overlapped "
                        "dispatch pipeline (device-fed pipelined decode "
                        "bursts) and dispatch fully synchronously — debug "
                        "switch and A/B baseline; greedy output is "
                        "byte-identical either way (docs/PERF.md)")
    p.add_argument("--spec", choices=("off", "pld", "draft"), default="off",
                   help="slot scheduler: per-slot speculative decoding "
                        "(runtime/spec.py).  'pld' drafts from a per-slot "
                        "prompt-lookup n-gram index (zero extra model "
                        "cost), 'draft' from a second smaller model "
                        "(--draft-model).  Greedy output stays "
                        "byte-identical to 'off'; sampled (temperature>0) "
                        "requests decode normally (docs/PERF.md)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="speculative decoding: max draft tokens proposed "
                        "per slot per verify window (window width is "
                        "spec-k+1 and rides the compile key, so changing "
                        "it mints one new executable)")
    p.add_argument("--draft-model", default=None,
                   help="--spec draft: path to the draft model (same "
                        "format as the target; loaded like --model onto "
                        "the same mesh with a slot-aligned contiguous KV "
                        "cache)")
    p.add_argument("--no-preempt", action="store_true",
                   help="QoS: disable priority preemption (paged scheduler "
                        "only); admission stays priority-ordered but a "
                        "higher-priority arrival never evicts a running "
                        "lower-priority slot (docs/SERVING.md QoS)")
    p.add_argument("--preempt-age-ms", type=float, default=5000.0,
                   help="QoS: a queued request climbs one priority class "
                        "per this many ms waited, bounding starvation of "
                        "batch traffic behind interactive load (0 = no "
                        "aging; aged rank affects admission order only, "
                        "never eviction)")
    p.add_argument("--preempt-cap", type=int, default=3,
                   help="QoS: max times one request may be preempted and "
                        "parked; past the cap it finishes honestly with "
                        "finish_reason=\"preempted\" and whatever tokens "
                        "it produced")
    p.add_argument("--preempt-spill-dir", default=None,
                   help="QoS: spill parked DLREQ01 records of preempted "
                        "requests to this directory instead of holding "
                        "them in RAM (the parked count stays bounded by "
                        "--sched-max-queue either way)")
    # ---- serving robustness (api server; docs/ROBUSTNESS.md) ----
    p.add_argument("--host", default="0.0.0.0",
                   help="api server: bind address (default 0.0.0.0)")
    p.add_argument("--max-pending", type=int, default=8,
                   help="api server: max requests in flight or queued; "
                        "excess get 429 + Retry-After (bounded admission)")
    p.add_argument("--request-timeout", type=float, default=0.0,
                   help="api server: default per-request deadline in seconds "
                        "(0 = none); requests may lower it with a 'timeout' "
                        "body field.  Expired requests return a truncated "
                        "completion with finish_reason=\"timeout\"")
    p.add_argument("--io-timeout", type=float, default=15.0,
                   help="api server: socket read/write timeout; a client "
                        "stalled sending its body gets 408, one stalled "
                        "reading a stream is treated as disconnected")
    p.add_argument("--drain-grace", type=float, default=30.0,
                   help="api server: on SIGTERM/SIGINT, seconds granted to "
                        "in-flight requests before their deadlines clamp")
    p.add_argument("--step-timeout", type=float, default=None,
                   help="watchdog: seconds a device step may block before "
                        "StepTimeout (default DLLAMA_STEP_TIMEOUT or none); "
                        "turns a silently hung device into a diagnosable "
                        "error naming the step, position and mesh")
    # ---- artifact integrity / state recovery (docs/ROBUSTNESS.md) ----
    p.add_argument("--verify-weights", action="store_true",
                   help="verify each tensor's crc32 against the model's "
                        "sidecar checksum manifest (<model>.m.sum, written "
                        "by tools/checksum_model.py) on first read; the "
                        "header digest is always verified when the manifest "
                        "exists.  Fails fast with ArtifactError on any "
                        "corruption instead of decoding garbage")
    p.add_argument("--numeric-checks", action="store_true",
                   help="check host-fetched logits for NaN/Inf every step "
                        "and raise NumericFault (step, pos) instead of "
                        "emitting garbage tokens (default "
                        "DLLAMA_NUMERIC_CHECKS)")
    p.add_argument("--snapshot-dir", default=None,
                   help="api server: directory for engine-state snapshots; "
                        "on SIGTERM drain the KV cache/position/RNG persist "
                        "here and the next boot warm-starts from it "
                        "(validated: a corrupt or mismatched snapshot "
                        "cold-starts with a logged reason)")
    p.add_argument("--handoff", action="store_true",
                   help="api server: on SIGTERM drain, export each in-flight "
                        "scheduler request as a per-request DLREQ01 hand-off "
                        "record (KV pages + decode state) fetchable via "
                        "/admin/export/<rid>, and accept records from peers "
                        "at /admin/import — the fleet router migrates "
                        "requests between replicas with these during a "
                        "rolling restart (docs/SERVING.md).  Requires the "
                        "paged scheduler (--batch-slots + --kv-pages)")
    # ---- fleet router (router/ package; docs/SERVING.md) ----
    p.add_argument("--backends", default=None,
                   help="router mode: comma-separated replica addresses "
                        "(host:port,...) fronted by this router; each must "
                        "be a dllama-api server")
    p.add_argument("--probe-interval", type=float, default=2.0,
                   help="router mode: seconds between /health probes of "
                        "each backend")
    p.add_argument("--eject-after", type=int, default=3,
                   help="router mode: consecutive probe/dispatch failures "
                        "before a backend is ejected from dispatch")
    p.add_argument("--readmit-after", type=int, default=2,
                   help="router mode: consecutive successful probes before "
                        "an ejected backend is re-admitted (hysteresis: "
                        "one lucky probe does not un-eject)")
    p.add_argument("--router-retries", type=int, default=2,
                   help="router mode: max re-dispatches of a request to "
                        "another backend when one fails before any "
                        "response bytes were forwarded")
    p.add_argument("--upstream-timeout", type=float, default=120.0,
                   help="router mode: socket timeout per upstream request "
                        "(connect + per-read); a backend silent past this "
                        "is treated as failed")
    # ---- crash tolerance (router resume + pod supervisor;
    #      docs/ROBUSTNESS.md) ----
    p.add_argument("--handoff-ttl", type=float, default=0.0,
                   help="api server: seconds an exported DLREQ01 hand-off "
                        "record waits unclaimed before it is garbage-"
                        "collected (dllama_handoff_expired_total counts "
                        "them); 0 = keep until claimed.  Bounds drain "
                        "time when the router never comes to collect")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   help="router mode: seconds an open upstream stream may "
                        "go silent before the replica is treated as dead "
                        "(force-ejected) and the stream resumed elsewhere; "
                        "catches wedged-but-connected replicas (SIGSTOP, "
                        "device hang) that a connect timeout never sees.  "
                        "Also bounds time-to-first-token, so set it above "
                        "worst-case queue + prefill + compile.  0 = off")
    p.add_argument("--checkpoint-interval", type=float, default=0.0,
                   help="router mode: seconds between proactive DLREQ01 "
                        "checkpoints of each in-flight greedy stream "
                        "(GET /admin/checkpoint/<rid>); a crashed "
                        "replica's streams then resume from the latest "
                        "checkpoint instead of re-prefilling the whole "
                        "prompt.  Requires replicas running --handoff. "
                        "0 = off (resume falls back to deterministic "
                        "re-run)")
    p.add_argument("--resume-policy", choices=["auto", "never"],
                   default="auto",
                   help="router mode: default mid-stream crash behavior — "
                        "auto resumes greedy streams on a peer (byte-"
                        "identical; sampled streams always get the honest "
                        "replica_lost), never disables resume fleet-wide. "
                        "Per-request override: \"resume_policy\" body "
                        "field")
    p.add_argument("--supervise", action="store_true",
                   help="serve-pod: run each replica as a child PROCESS "
                        "under a supervisor that respawns it on crash "
                        "(same port + device set, warm --snapshot-dir "
                        "restore) and SIGKILLs+respawns it when /health "
                        "hangs; crash-looping replicas are quarantined "
                        "(--respawn-max/--respawn-window)")
    p.add_argument("--respawn-max", type=int, default=5,
                   help="serve-pod --supervise: deaths tolerated inside "
                        "--respawn-window before a replica is quarantined "
                        "instead of respawned")
    p.add_argument("--respawn-window", type=float, default=30.0,
                   help="serve-pod --supervise: sliding window (seconds) "
                        "for the crash-loop counter")
    # ---- elastic pod (router/elastic.py; docs/SERVING.md) ----
    p.add_argument("--elastic", action="store_true",
                   help="serve-pod --supervise: load-driven autoscaling "
                        "and live tp reshape — a control loop samples "
                        "fleet /health signals and spawns, drains, or "
                        "reshapes replicas within the --pod-devices "
                        "budget.  Needs --handoff + --batch-slots/"
                        "--kv-pages (in-flight requests migrate over "
                        "the hand-off wire)")
    p.add_argument("--pod-devices", type=int, default=0,
                   help="serve-pod --elastic: total device budget the "
                        "pod may partition into replicas (default "
                        "dp × tp — no headroom to grow)")
    p.add_argument("--min-replicas", type=int, default=1,
                   help="serve-pod --elastic: scale-down floor")
    p.add_argument("--max-replicas", type=int, default=0,
                   help="serve-pod --elastic: scale-up ceiling "
                        "(default: the boot dp)")
    p.add_argument("--elastic-interval", type=float, default=2.0,
                   help="serve-pod --elastic: seconds between control-"
                        "loop ticks (one fleet sample per tick)")
    p.add_argument("--elastic-window", type=int, default=5,
                   help="serve-pod --elastic: samples in the sliding "
                        "window; EVERY sample must agree before a "
                        "policy action fires (sustained signal, not a "
                        "spike)")
    p.add_argument("--elastic-cooldown", type=float, default=30.0,
                   help="serve-pod --elastic: seconds after any "
                        "topology action before the policy may act "
                        "again (the window also refills from empty)")
    p.add_argument("--scale-up-util", type=float, default=0.85,
                   help="serve-pod --elastic: sustained fleet slot "
                        "utilization at or above this adds a replica")
    p.add_argument("--scale-down-util", type=float, default=0.15,
                   help="serve-pod --elastic: sustained utilization at "
                        "or below this (with an empty queue) retires "
                        "the most-idle replica")
    p.add_argument("--scale-up-queue", type=float, default=2.0,
                   help="serve-pod --elastic: sustained queued requests "
                        "per replica at or above this also triggers "
                        "scale-up")
    p.add_argument("--reshape-kv-low", type=float, default=0.08,
                   help="serve-pod --elastic: sustained effective-free "
                        "KV fraction at or below this reshapes to "
                        "fewer, wider replicas (tp×2) — the long-"
                        "context answer")
    # ---- observability (docs/OBSERVABILITY.md) ----
    p.add_argument("--log-format", choices=["human", "json"], default=None,
                   help="log output format: human-readable lines or JSON "
                        "lines (one object per record, grep-able by "
                        "request_id).  Default: DLLAMA_LOG env, else human")
    p.add_argument("--log-level", default=None,
                   choices=["debug", "info", "warning", "error"],
                   help="log verbosity for the dllama logger tree "
                        "(default: DLLAMA_LOG env, else info)")
    p.add_argument("--trace-buffer", type=int, default=None,
                   help="span ring capacity for /debug/trace (default "
                        "DLLAMA_TRACE_BUFFER, else 8192)")
    p.add_argument("--flight-buffer", type=int, default=None,
                   help="flight-recorder ring capacity for /debug/requests "
                        "(default DLLAMA_FLIGHT_BUFFER, else 512)")
    p.add_argument("--event-buffer", type=int, default=None,
                   help="event-journal ring capacity for /debug/events "
                        "(default DLLAMA_EVENT_BUFFER, else 2048)")
    p.add_argument("--event-log", default=None, metavar="PATH",
                   help="also append every event-journal record as a JSONL "
                        "line to PATH (append mode — restarts extend), so "
                        "spawn/quarantine/scale/reshape incidents survive "
                        "the process that emitted them")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="declarative latency/error objectives, e.g. "
                        "'ttft_p95=1500ms,itl_p99=120ms,error_rate=0.5%%'. "
                        "Burn rates over rolling windows (DLLAMA_SLO_WINDOWS, "
                        "default 5m,1h) feed slo_burn_rate gauges and the "
                        "/health verdict.  Default: DLLAMA_SLO env")
    return p


def load_stack(args, batch: int | None = None) -> tuple[Engine, Tokenizer]:
    import jax.numpy as jnp
    if not args.model or not args.tokenizer:
        raise SystemExit("--model and --tokenizer are required for this mode")
    wft = quants.FLOAT_TYPE_BY_NAME[args.weights_float_type] if args.weights_float_type else None
    mf = mfile.MFile(args.model, weights_ftype=wft,
                     verify=getattr(args, "verify_weights", False))
    bft = args.buffer_float_type
    if bft == "q80":
        print("💡 bufferFloatType q80 → bf16 (activations stay on-chip; Q80's "
              "wire compression has no wire to compress here)")
        bft = "bf16"
    dtype = jnp.dtype(DTYPES[bft])
    cfg = ModelConfig.from_spec(mf.spec, dtype=dtype)
    print(f"💡 arch: {mf.spec.arch_name}")
    print(f"💡 dim: {cfg.dim}\n💡 nLayers: {cfg.n_layers}\n💡 nHeads: {cfg.n_heads}")
    print(f"💡 nKvHeads: {cfg.n_kv_heads}\n💡 vocabSize: {cfg.vocab_size}\n💡 seqLen: {cfg.seq_len}")
    mesh = parse_workers(args.workers, sp=args.sp, dp=args.dp, ep=args.ep)
    axes = {k: v for k, v in mesh.shape.items() if v > 1} or {"tp": 1}
    print("💡 mesh: " + " ".join(f"{k}={v}" for k, v in axes.items()))
    # fused qkv/w13 is the single-chip fast layout; under tp>1 the unfused
    # per-tensor layout shards cleanly (see load_params)
    cfg, params = load_params(mf, cfg, dtype=dtype,
                              keep_quantized=not args.dequantize,
                              fuse=mesh.shape.get("tp", 1) == 1)
    kv_dtype = ("q8" if args.kv_cache_dtype == "q8"
                else jnp.dtype(DTYPES[args.kv_cache_dtype])
                if args.kv_cache_dtype else None)
    engine = Engine(cfg, params, mesh=mesh, seq_len=args.max_seq_len,
                    kv_dtype=kv_dtype, batch=batch or max(args.dp, 1),
                    step_timeout=getattr(args, "step_timeout", None),
                    # flag turns checks ON; absent → None keeps the
                    # DLLAMA_NUMERIC_CHECKS env default
                    numeric_checks=(True if getattr(args, "numeric_checks",
                                                    False) else None))
    tok = Tokenizer(tfile.read_tfile(args.tokenizer))
    if tok.vocab_size != cfg.vocab_size:
        raise SystemExit("tokenizer is incompatible with model (vocab size mismatch)")
    return engine, tok


def load_draft_engine(args, target: Engine) -> Engine:
    """Load ``--draft-model`` as a second, smaller Engine on the target's
    mesh for ``--spec draft`` (runtime/spec.py DraftModelProposer): same
    slot count and context as the target, contiguous slot-aligned KV (the
    draft pool is tiny, paging would only add indirection).  Weights are
    a second full load; the KV cache is the only per-slot state."""
    import jax.numpy as jnp
    if not args.draft_model:
        raise SystemExit("--spec draft needs --draft-model")
    wft = (quants.FLOAT_TYPE_BY_NAME[args.weights_float_type]
           if args.weights_float_type else None)
    mf = mfile.MFile(args.draft_model, weights_ftype=wft,
                     verify=getattr(args, "verify_weights", False))
    bft = args.buffer_float_type
    dtype = jnp.dtype(DTYPES["bf16" if bft == "q80" else bft])
    cfg = ModelConfig.from_spec(mf.spec, dtype=dtype)
    if cfg.vocab_size != target.cfg.vocab_size:
        raise SystemExit("--draft-model vocab size differs from the "
                         "target's (drafted ids must be target token ids)")
    print(f"💡 draft arch: {mf.spec.arch_name} "
          f"({cfg.n_layers} layers, dim {cfg.dim})")
    cfg, params = load_params(mf, cfg, dtype=dtype,
                              keep_quantized=not args.dequantize,
                              fuse=target.mesh.shape.get("tp", 1) == 1)
    return Engine(cfg, params, mesh=target.mesh, seq_len=target.seq_len,
                  batch=target.batch,
                  step_timeout=getattr(args, "step_timeout", None))


def _seed(args) -> int:
    return args.seed if args.seed is not None else int(time.time())


def _print_slo_summary(args) -> None:
    """End-of-run SLO verdict beside the dispatch summary (obs/slo.py);
    silent unless the operator declared objectives (main() validates the
    spec up front and stashes the engine)."""
    slo = getattr(args, "_slo_engine", None)
    if slo is not None:
        print(slo.summary_line())


def _encode_prompt(engine, tok, prompt: str) -> list[int]:
    """Prompt encoding with the reference's BOS rule (ModelConfig.add_bos:
    Grok-1 prompts get no BOS, dllama.cpp:27)."""
    return tok.encode(prompt, add_bos=engine.cfg.add_bos)


def cmd_inference(args) -> None:
    """Benchmark mode (dllama.cpp:45-93): prints per-token G/I/T."""
    engine, tok = load_stack(args)
    prompt = args.prompt or "Hello world"
    ids = _encode_prompt(engine, tok, prompt)
    steps = args.steps or 64
    if args.chunk > 1:
        print(f"💡 decode runs on-device in chunks of {args.chunk}; G/I/T "
              "lines within a chunk are that chunk's per-token averages")
    if args.warmup > 0:
        t0 = time.perf_counter()
        for _ in engine.generate_stream(
                ids, len(ids) + args.warmup, temperature=args.temperature,
                topp=args.topp, seed=_seed(args), chunk=args.chunk):
            pass
        engine.reset()
        print(f"💡 warmup: {args.warmup} tokens in "
              f"{time.perf_counter() - t0:.1f}s (compile excluded from stats)")
    stats = RunStats()
    pieces = []
    prev = tok.bos_id
    for token, st in engine.generate_stream(
            ids, steps + len(ids), temperature=args.temperature, topp=args.topp,
            seed=_seed(args), chunk=args.chunk):
        piece = tok.decode_piece(prev, token).decode("utf-8", errors="replace")
        prev = token
        if st.generation_ms > 0:
            stats.add(st)
        print(f"🔶 G {st.generation_ms:7.2f} ms I {st.inference_ms:7.2f} ms "
              f"T {st.transfer_ms:6.2f} ms S {st.sent_bytes / 1024:6.1f} kB "
              f"R {st.recv_bytes / 1024:6.1f} kB | {piece!r}")
        pieces.append(piece)
    print(f"Generated tokens:    {len(stats.tokens)}")
    print(f"Avg tokens / second: {stats.tokens_per_second:.2f}")
    print(f"Avg generation time: {stats.avg_generation_ms:.2f} ms")
    print(f"Avg inference time:  {stats.avg_inference_ms:.2f} ms")
    print(f"Avg transfer time:   {stats.avg_transfer_ms:.2f} ms")
    print(f"Avg sent / recv:     {stats.avg_sent_bytes / 1024:.1f} kB / "
          f"{stats.avg_recv_bytes / 1024:.1f} kB")
    # kernel-dispatch ledger (obs/dispatch.py): which matmul paths this run
    # actually took, and loudly whether anything degraded — a benchmark
    # number from an XLA-dequant fallback must not read as a clean result
    from .obs import dispatch as obs_dispatch
    print(obs_dispatch.summary_line())
    coll = obs_dispatch.collective_line()
    if coll:
        print(coll)
    _print_slo_summary(args)
    if engine.timing_mode == "host-fetch":
        # remote tunnel: the ready marker fires at dispatch, so I above is
        # the whole host-fetch wall (T≈0 by construction) — the xplane
        # profiler below supplies the genuine on-device split
        # (VERDICT r04 Weak #1; runtime/engine.py timing_mode)
        print("💡 remote backend: I is host-fetch wall time (device ready "
              "marker unreliable over the tunnel); profiled on-device split "
              "follows")

    # the remote auto-profile can be suppressed (DLLAMA_AUTO_PROFILE=0) by
    # harnesses that already do their own xplane pass on a deadline — the
    # bench's CLI stage must not risk its kill window on a second profile
    import os as _os
    auto_prof = (engine.timing_mode == "host-fetch"
                 and _os.environ.get("DLLAMA_AUTO_PROFILE", "1") != "0")
    if args.profile_split or args.profile_ops or auto_prof:
        from .runtime.profiling import summarize_split, top_ops, \
            traced_op_times
        if engine.pos + 4 > engine.seq_len:
            engine.reset()
            engine.prefill(ids)
        last = ids[-1]
        n_steps = 3
        times = traced_op_times(lambda: engine.decode_one(last), steps=n_steps)
        if times is None:
            print("Profiled split:      unavailable (xplane tooling missing)")
        else:
            sp = summarize_split(times, n_steps)
            n_dev = engine.mesh.size
            print(f"Profiled decode step (mesh sum / {n_dev} devices): "
                  f"compute {sp['compute_ms']:.2f} ms, "
                  f"collectives {sp['collective_ms']:.2f} ms "
                  f"({sp['collective_pct']:.1f}%)")
            n_top = 10 if args.profile_ops else 5
            for op, ms in top_ops(times, n_top, n_steps):
                print(f"  top op {ms:8.2f} ms  {op}")


def cmd_generate(args) -> None:
    engine, tok = load_stack(args)
    if args.prompt is None:
        raise SystemExit("generate mode requires --prompt")
    ids = _encode_prompt(engine, tok, args.prompt)
    steps = args.steps or engine.seq_len
    prev = tok.bos_id
    eos = (tok.eos_id,) if tok.eos_id >= 0 else ()
    if args.pld > 0:
        if args.temperature != 0:
            raise SystemExit("--pld is greedy-only; set --temperature 0")
        if args.dp > 1 or args.sp > 1:
            raise SystemExit("--pld is single-stream; drop --dp/--sp "
                             "(tp/ep meshes are fine)")
        for token in engine.generate_pld_stream(ids, steps, k=args.pld,
                                                eos_ids=eos):
            sys.stdout.write(tok.decode_piece(prev, token)
                             .decode("utf-8", errors="replace"))
            sys.stdout.flush()  # text appears per verify window, not at end
            prev = token
        print()
        return
    for token, _ in engine.generate_stream(
            ids, steps, temperature=args.temperature, topp=args.topp,
            seed=_seed(args), eos_ids=eos, chunk=args.chunk):
        sys.stdout.write(tok.decode_piece(prev, token).decode("utf-8", errors="replace"))
        sys.stdout.flush()
        prev = token
    print()


def cmd_batch(args) -> None:
    """Batched generation of DISTINCT prompts in one lockstep decode
    (beyond reference — the reference fixes batch=1, tasks.cpp:199-210).

    Prompts come from ``--prompts-file`` (one per line) or a single
    ``--prompt``.  Each stream's output is printed under its own header
    after the batch finishes; the summary line reports aggregate batched
    throughput — the point of batching: the decode matmuls amortize one
    weight read over all rows, so tokens/second scales with batch while
    ms/token stays near the single-stream cost.
    """
    if args.prompts_file:
        with open(args.prompts_file, "r", encoding="utf-8") as f:
            prompts = [ln.rstrip("\r\n") for ln in f if ln.strip()]
    elif args.prompt is not None:
        prompts = [args.prompt]
    else:
        raise SystemExit("batch mode requires --prompts-file or --prompt")
    if args.dp > 1 and len(prompts) % args.dp:
        raise SystemExit(f"{len(prompts)} prompts do not shard over dp={args.dp}")
    engine, tok = load_stack(args, batch=len(prompts))
    id_lists = [_encode_prompt(engine, tok, p) for p in prompts]
    steps = args.steps or engine.seq_len
    eos = (tok.eos_id,) if tok.eos_id >= 0 else ()
    t0 = time.perf_counter()
    outs = engine.generate_batch(id_lists, steps,
                                 temperature=args.temperature, topp=args.topp,
                                 seed=_seed(args), eos_ids=eos, chunk=args.chunk)
    dt = time.perf_counter() - t0
    generated = sum(len(o) - len(p) for o, p in zip(outs, id_lists))
    for r, o in enumerate(outs):
        print(f"▶ stream {r}")
        print(tok.decode(o))
    print(f"Generated tokens:    {generated} over {len(prompts)} streams")
    if dt > 0:
        print(f"Batched throughput:  {generated / dt:.2f} tok/s")
    from .obs import dispatch as obs_dispatch
    print(obs_dispatch.summary_line())
    coll = obs_dispatch.collective_line()
    if coll:
        print(coll)
    _print_slo_summary(args)


def cmd_chat(args) -> None:
    """Multi-turn REPL (dllama.cpp:111-203): one KV cache per conversation."""
    engine, tok = load_stack(args)
    stops = TokenizerChatStops(tok)
    template = ChatTemplate(tok.chat_template, tok.vocab[tok.chat_eos_id].decode("utf-8", "replace"))
    eos_detector = EosDetector(tok.chat_eos_id, stops.stops,
                               padding_left=2, padding_right=2)  # dllama.cpp:198-199

    print("💻 System prompt (optional): ", end="", flush=True)
    system = sys.stdin.readline().strip()
    first = True
    # one sampler stream per REPL session (app.cpp:33 seeds one Sampler per
    # process): the seed is resolved ONCE here — even unset --seed — and
    # later turns continue the stream rather than re-seeding from the wall
    # clock every turn (VERDICT r04 Weak #6)
    session_seed: int | None = _seed(args)
    while True:
        print("\n👱 User\n> ", end="", flush=True)
        user = sys.stdin.readline()
        if not user:
            break
        user = user.strip()
        if not user:
            continue
        items = []
        if first and system:
            items.append(ChatItem("system", system))
        items.append(ChatItem("user", user))
        first = False
        text = template.generate(items, True)
        ids = tok.encode(text, add_bos=engine.pos == 0)
        if engine.pos + len(ids) + 2 >= engine.seq_len:
            print("🚫 context window is full")
            break
        print("\n🤖 Assistant")
        eos_detector.clear()
        prompt_end = engine.pos + len(ids)
        stream = engine.generate_stream(
            ids, engine.seq_len - engine.pos, temperature=args.temperature,
            topp=args.topp, seed=session_seed, chunk=args.chunk,
            eos_ids=(tok.chat_eos_id,))
        session_seed = None  # continue the session stream on later turns

        def emit(delta):
            sys.stdout.write(delta)
            sys.stdout.flush()

        drain_generation(engine, tok, eos_detector, stream, len(ids),
                         prompt_end, emit)
        print()


def cmd_worker(args) -> None:
    """Join a multi-host run as one SPMD process (reference: the TCP worker
    that executes the same task list as root, dllama.cpp:205-219 +
    Worker::work tasks.cpp:230-256).

    Requires process coordinates (--coordinator/--nproc/--proc-id or the
    DLLAMA_* env vars) and the same model flags as the root: every process
    executes the same XLA programs; only process 0 owns stdout.  Within a
    single host no worker processes exist at all — the mesh devices are the
    workers — so without coordinates this mode just explains the mapping.
    """
    from .parallel.distributed import distributed_env

    if not args.coordinator and distributed_env() is None:
        print("On this framework the reference's worker processes are TPU mesh devices\n"
              "inside one program: run the root command with --workers tpu:N instead.\n"
              "For MULTI-HOST runs (e.g. a v5e-16/32 pod slice), start this mode on\n"
              "every host with --coordinator host:port --nproc N --proc-id K and the\n"
              "same --model/--tokenizer/--prompt flags; process 0 prints, the rest\n"
              "compute. (reference: dllama.cpp:205-219 TCP worker; transport here is\n"
              "XLA collectives over ICI/DCN — see dllama_tpu/parallel/distributed.py)")
        return
    # init happened in main(); suppress stdout on non-root processes and run
    # the mirrored program
    from .parallel.distributed import is_output_process

    if not is_output_process():
        import os
        sys.stdout = open(os.devnull, "w")
    WORKER_PROGRAMS[args.program](args)


def cmd_router(args) -> None:
    """Fleet router: front N dllama-api replicas (router/ package; no
    model or jax in this process — it only proxies HTTP)."""
    from .router.service import main as router_main
    router_main(args)


def cmd_serve_pod(args) -> None:
    """Pod-slice serving: partition the local devices into ``--dp``
    tensor-parallel replicas of ``--workers tpu:N`` chips each, serve
    the OpenAI surface per replica, and front them with the fleet
    router on ``--port`` (router/pod.py)."""
    from .router.pod import main as pod_main
    pod_main(args)


# One table drives the --program choices AND the worker dispatch, so a
# new mirrored program cannot be added to one and missed in the other
# (chat stays out: interactive, single-host only).
WORKER_PROGRAMS = {"generate": cmd_generate, "inference": cmd_inference,
                   "batch": cmd_batch}


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    from .obs.log import configure as configure_logging
    configure_logging(args.log_format, args.log_level)
    from .obs import events as obs_events, flight as obs_flight, \
        trace as obs_trace
    obs_trace.configure(args.trace_buffer)
    obs_flight.configure(args.flight_buffer)
    obs_events.configure(args.event_buffer, args.event_log)
    # validate --slo up front (a bad spec must not surface only after a
    # long run); the engine is consulted again by _print_slo_summary
    spec = args.slo or os.environ.get("DLLAMA_SLO", "")
    if spec:
        from .obs.slo import SloEngine
        try:
            args._slo_engine = SloEngine.from_spec(spec)
        except ValueError as e:
            raise SystemExit(f"--slo: {e}")
    from .parallel.distributed import distributed_env, init_distributed
    if args.coordinator or distributed_env() is not None:
        init_distributed(args.coordinator, args.nproc, args.proc_id)
    {"inference": cmd_inference, "generate": cmd_generate,
     "chat": cmd_chat, "worker": cmd_worker, "batch": cmd_batch,
     "router": cmd_router, "serve-pod": cmd_serve_pod}[args.mode](args)


if __name__ == "__main__":
    main()
