"""Sharding specs: the TP slice layout as `NamedSharding` PartitionSpecs.

This module is the direct TPU-native port of the reference's slicing math
(`/root/reference/src/commands.cpp:8-105`):

* ``RowMatmulSlice`` (split the *output* dim: wq/wk/wv, w1/w3, MoE up/gate/
  down, transformer.cpp:287-289,300-301,319-321) → shard the weight's
  output axis on ``tp``; activations come out head/hidden-sharded with NO
  communication (the reference's broadcast of the replicated input,
  syncUnitBuffer tasks.cpp:44-65, is free here because the input is already
  replicated on every chip).
* ``ColMatmulSlice`` (split the *input* dim: wo, w2,
  transformer.cpp:290,320) → shard the weight's input axis on ``tp``; XLA
  inserts one all-reduce for the partial sums, replacing the reference's
  gather-to-root + merge + re-broadcast round trip
  (llama2-tasks.cpp:115-131,153-156).
* ``KvCacheSlice`` (commands.cpp:94-99) → shard the cache's kv-head axis.
* ``MultiHeadAttSlice``/``RopeSlice`` (commands.cpp:72-92,101-105) → free:
  head-sharded q/k/v make per-head attention and RoPE local by
  construction.

The reference's constraints carry over: ``nSlices ≤ nKvHeads``
(transformer.cpp:88-91) is checked in :func:`check_tp_constraint`; the 2^n
node-count restriction disappears (any divisor of the head counts works).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig

REPL = P()


def valid_tp_degrees(cfg: ModelConfig) -> list[int]:
    """Every tensor-parallel degree this model accepts: divisors of both
    head counts and of hidden_dim, capped at nKvHeads (a shard owns whole
    KV heads, so no degree past that can be legal)."""
    return [d for d in range(1, cfg.n_kv_heads + 1)
            if cfg.n_heads % d == 0 and cfg.n_kv_heads % d == 0
            and cfg.hidden_dim % d == 0]


def check_tp_constraint(cfg: ModelConfig, tp: int) -> None:
    """Reference parity: cannot split across more nodes than KV heads
    (transformer.cpp:88-91).  Head counts must divide evenly because a
    shard owns whole heads (MultiHeadAttSlice asserts nHeads % nSlices == 0,
    commands.cpp:101-105).  Every rejection names the degrees that WOULD
    work, so the operator's next command can be right, not just different."""
    valid = valid_tp_degrees(cfg)
    hint = f"valid tp degrees for this model: {valid}"
    if tp > cfg.n_kv_heads:
        raise ValueError(
            f"tensor-parallel degree {tp} exceeds nKvHeads={cfg.n_kv_heads} "
            "(reference: 'This version does not support more nodes than the "
            f"number of KV heads', transformer.cpp:88-91); {hint}")
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError(f"head counts ({cfg.n_heads}/{cfg.n_kv_heads}) not "
                         f"divisible by tp={tp}; {hint}")
    if cfg.hidden_dim % tp:
        raise ValueError(f"hidden_dim {cfg.hidden_dim} not divisible by "
                         f"tp={tp}; {hint}")


def param_specs(cfg: ModelConfig) -> dict[str, P]:
    """PartitionSpec per parameter (layer-stacked layouts from params.py)."""
    specs = {
        "embedding": REPL,                   # root-owned in the reference; replicated here
        "wq": P(None, None, "tp"),           # RowMatmulSlice: out dim = heads
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wqkv": P(None, None, "tp"),         # fused q|k|v (quantized load): the concat
                                             # axis is shard-mixed, so GSPMD reshards at
                                             # the split — correct, but unfused layouts
                                             # are preferred for tp>1
        "wo": P(None, "tp", None),           # ColMatmulSlice: in dim = heads
        "w13": P(None, None, "tp"),
        "rms_att": REPL,
        "rms_ffn": REPL,
        "rms_final": REPL,
        "wcls": P(None, "tp"),               # vocab-sharded logits; gathered on host fetch
    }
    if cfg.is_moe:
        specs.update({
            "router": REPL,                  # root-computed in the reference (grok1-tasks.cpp:59)
            # dense-TP MoE: hidden dim sliced on tp (transformer.cpp:
            # 299-317); the expert axis additionally shards over ep — a
            # no-op on the default ep=1 mesh, the beyond-reference
            # expert-parallel layout when ep>1
            "up": P(None, "ep", None, "tp"),
            "gate": P(None, "ep", None, "tp"),
            "down": P(None, "ep", "tp", None),
        })
        if cfg.post_block_norms:
            specs.update({"rms_moe": REPL, "rms_ffn2": REPL})
    else:
        specs.update({
            "w1": P(None, None, "tp"),
            "w2": P(None, "tp", None),
            "w3": P(None, None, "tp"),
        })
    return specs


def param_shardings(cfg: ModelConfig, mesh: Mesh) -> dict[str, NamedSharding]:
    return {k: NamedSharding(mesh, spec) for k, spec in param_specs(cfg).items()}


def kv_cache_spec(seq_axis: str | None = None) -> P:
    """Cache (L, B, Hkv, S, Dh): kv-head axis on tp (KvCacheSlice,
    commands.cpp:94-99); optionally the seq axis on ``sp`` for
    sequence-parallel long context."""
    return P(None, "dp", "tp", seq_axis, None)


def kv_cache_sharding(mesh: Mesh, seq_axis: str | None = None) -> NamedSharding:
    return NamedSharding(mesh, kv_cache_spec(seq_axis))


def place_params(params: dict, cfg: ModelConfig, mesh: Mesh) -> dict:
    """Upload host params onto the mesh with their TP shardings.

    This replaces the reference's weight-distribution phase
    (``loadRoot`` streaming slices over sockets, transformer.cpp:389-404):
    `jax.device_put` slices each array and uploads only each chip's shard.

    Packed Q40 weights (ops/q40.py QTensor) shard with the *same* spec as
    their dense counterpart: the block-local nibble layout keeps every
    32-row quantization block on one shard, so slicing the packed array's
    row axis at 1/tp is exactly the reference's ``splitWeights`` on the
    quantized bytes (commands.cpp:19-36).  ``jax.device_put`` applies the
    sharding to both pytree leaves (qpacked + scales, whose row counts are
    N/2 and N/32 — both divisible at block granularity).
    """
    specs = param_specs(cfg)
    out = {}
    for k, v in params.items():
        spec = specs[k]
        # packed-Q40 expert stacks shard the expert axis over ep like their
        # dense counterparts: the fused kernel's expert select decodes the
        # flat index per shard and psums the owner's product
        # (ops/q40.py _sharded_matmul_ep), so quantized MoE weight
        # residency scales 1/ep — what lets packed Grok-1-314B fit its
        # 16-chip plan (tools/memory_plan.py, docs/MEMORY.md)
        if not _spec_divides(v, spec, mesh):
            # e.g. a Q40 scales plane (n/32 rows) that doesn't divide the
            # mesh axis: keep the tensor replicated — q40.matmul makes the
            # matching per-tensor fallback (_tp_shardable) at trace time
            print(f"⚠️  sharding: {k} {jax.tree.leaves(v)[0].shape} does not "
                  f"divide mesh {dict(mesh.shape)} evenly; replicating")
            spec = REPL
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out


def _spec_divides(v, spec: P, mesh: Mesh) -> bool:
    """True if every leaf of ``v`` shards evenly under ``spec`` on ``mesh``."""
    for leaf in jax.tree.leaves(v):
        for dim, axes in zip(leaf.shape, spec):
            if axes is None:
                continue
            for ax in (axes if isinstance(axes, tuple) else (axes,)):
                n = mesh.shape[ax]
                if dim % n:
                    return False
    return True
