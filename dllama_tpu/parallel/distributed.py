"""Multi-host (multi-process) mesh membership.

The reference scales beyond one box by adding TCP worker processes
(`dllama worker --port …`, dllama.cpp:205-219, served by SocketServer,
socket.cpp:355-397).  The TPU-native equivalent is JAX process groups:
every host runs the *same* SPMD program, `jax.distributed.initialize`
wires the processes into one runtime, and `jax.devices()` then spans all
hosts — a v5e-16/32 pod slice shows up as one mesh, and the existing
`--workers tpu:N` sharding covers it with XLA collectives riding
ICI/DCN instead of the reference's TCP star.

Operational contract (mirrors the reference's "start workers first, then
root", socket.cpp:174-178): every process — the root is simply process 0 —
runs the same CLI command with the same model/tokenizer/prompt flags plus
its process coordinates (``--coordinator host:port --nproc N --proc-id K``
or the DLLAMA_COORDINATOR / DLLAMA_NPROC / DLLAMA_PROC_ID environment
variables).  Process 0's host:port is the coordination service; non-zero
processes print nothing (the reference's workers likewise own no stdout
contract — only root prints, transformer.cpp:213-224).
"""

from __future__ import annotations

import os


def distributed_env() -> tuple[str | None, int | None, int | None] | None:
    """Read process coordinates from the environment, or ``None`` when no
    DLLAMA_* coordinate is set.  Unset fields stay ``None`` so the
    nproc>1-requires-proc-id validation applies to the env path too."""
    coord = os.environ.get("DLLAMA_COORDINATOR")
    nproc = os.environ.get("DLLAMA_NPROC")
    pid = os.environ.get("DLLAMA_PROC_ID")
    if not coord and nproc is None and pid is None:
        return None
    return (coord or None,
            int(nproc) if nproc is not None else None,
            int(pid) if pid is not None else None)


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None, *,
                     max_retries: int | None = None,
                     backoff: float | None = None,
                     max_backoff: float = 30.0) -> int:
    """Join (or create, as process 0) the multi-host process group.

    Arguments fall back to the DLLAMA_* environment variables.  Returns the
    process id.  Must run before the first device query in the process —
    the same constraint the backend pinning imposes everywhere else
    (hostenv.py).

    Connection failures retry with exponential backoff (``backoff``, then
    ×2 per attempt, capped at ``max_backoff``; ``max_retries`` extra
    attempts, env defaults ``DLLAMA_INIT_RETRIES``/``DLLAMA_INIT_BACKOFF``,
    5 and 0.5 s).  The coordinator not being up yet is the NORMAL case
    under the reference's start-order contract ("start workers first,
    then root", socket.cpp:174-178): non-zero processes routinely launch
    before process 0's coordination service is listening, and a
    fail-fast here — the pre-retry behavior — forces operators to
    hand-sequence the fleet.  Argument/spec errors (ValueError) never
    retry.  docs/ROBUSTNESS.md covers the contract.
    """
    env = distributed_env()
    if env is not None:
        # flags win per field; env fills the gaps (a scheduler may export
        # per-host DLLAMA_PROC_ID while the flags are identical everywhere)
        ec, en, ep = env
        coordinator = coordinator if coordinator is not None else ec
        num_processes = num_processes if num_processes is not None else en
        process_id = process_id if process_id is not None else ep
    if coordinator is None:
        raise ValueError(
            "multi-host init needs --coordinator host:port (+ --nproc/--proc-id) "
            "or DLLAMA_COORDINATOR/DLLAMA_NPROC/DLLAMA_PROC_ID")
    if (num_processes or 1) > 1 and process_id is None:
        # defaulting to 0 would register every such host as the root and
        # deadlock the coordinator waiting for the missing ids
        raise ValueError("--proc-id is required when --nproc > 1")
    if max_retries is None:
        max_retries = int(os.environ.get("DLLAMA_INIT_RETRIES", "5"))
    if backoff is None:
        backoff = float(os.environ.get("DLLAMA_INIT_BACKOFF", "0.5"))
    import time

    import jax

    from ..runtime.faults import FAULTS

    for attempt in range(max_retries + 1):
        try:
            FAULTS.fire("distributed.initialize")
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes if num_processes is not None else 1,
                process_id=process_id if process_id is not None else 0)
            return jax.process_index()
        except ValueError:
            raise  # bad coordinates, not a transient connection failure
        except (ConnectionError, OSError, RuntimeError) as e:
            # jax surfaces grpc connect/deadline failures as RuntimeError;
            # ConnectionError/OSError cover the socket layer underneath
            if attempt >= max_retries:
                raise
            delay = min(backoff * (2 ** attempt), max_backoff)
            import sys
            print(f"⚠️  coordinator {coordinator} not reachable "
                  f"(attempt {attempt + 1}/{max_retries + 1}: {e}); "
                  f"retrying in {delay:.2f}s", file=sys.stderr)
            time.sleep(delay)
    raise AssertionError("unreachable")  # the loop returns or raises


def is_output_process() -> bool:
    """True when this process owns stdout (process 0, or single-process)."""
    import jax

    try:
        return jax.process_index() == 0
    except Exception:
        return True
