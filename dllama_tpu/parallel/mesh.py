"""Device-mesh construction.

The reference's cluster topology is ``--workers host:port …`` — a TCP star
of 2^n CPU nodes (socket.cpp:160-185).  Here the topology is a
``jax.sharding.Mesh`` over TPU chips on ICI; the CLI keeps the contract as
``--workers tpu:N``.

Axes:
* ``tp`` — tensor parallel: the reference's slice index
  (RowMatmulSlice/ColMatmulSlice, commands.cpp:8-70).
* ``sp`` — sequence parallel (ring attention) for long context; the
  reference has no equivalent (SURVEY §5: its only long-context lever is
  TP's 1/n KV shrink).
* ``dp`` — data parallel over batch; the reference is fixed batch-1.
* ``ep`` — expert parallel: MoE expert stacks sharded over experts (the
  reference replicates all experts on every node and TP-slices them,
  transformer.cpp:299-317 — that layout remains the default here; ep is
  the beyond-reference alternative for models whose expert set outgrows
  one chip).
"""

from __future__ import annotations

import contextlib

import jax
import numpy as np
from jax.sharding import Mesh

# --- active mesh -----------------------------------------------------------
# The transformer's attention dispatch reads this at *trace* time to decide
# whether to run the sequence-parallel shard_map path (ops/sp_attention.py).
# The Engine enters the context around its jitted calls; tracing happens on
# the first call, so the mesh is visible exactly when the decision is made.
_ACTIVE: list[Mesh] = []


@contextlib.contextmanager
def active_mesh(mesh: Mesh):
    _ACTIVE.append(mesh)
    try:
        yield mesh
    finally:
        _ACTIVE.pop()


def get_active_mesh() -> Mesh | None:
    return _ACTIVE[-1] if _ACTIVE else None


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    New jax exports shard_map at top level with a ``check_vma`` kwarg;
    older jax only has ``jax.experimental.shard_map`` where the same
    knob is spelled ``check_rep``.  Every shard_map in this package goes
    through here so kernels don't carry per-call-site version checks.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    # check_rep (the old spelling) has no replication rule for while/cond
    # bodies our attention kernels use, so the old branch always runs
    # unchecked — the new-jax path keeps the check where it works.
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def make_mesh(tp: int | None = None, sp: int = 1, dp: int = 1, ep: int = 1,
              devices=None) -> Mesh:
    """Build a (dp, sp, ep, tp) mesh; tp defaults to all remaining devices.

    tp is the innermost axis so tensor-parallel collectives ride the
    fastest ICI links (the scaling-book recipe: put the most
    bandwidth-hungry axis innermost).  The ``ep`` axis always exists
    (size 1 unless requested) so expert PartitionSpecs can mention it
    unconditionally.
    """
    devices = list(devices if devices is not None else jax.devices())
    if tp is None:
        tp = len(devices) // (sp * dp * ep)
        if tp == 0:
            raise ValueError(
                f"mesh sp={sp}×dp={dp}×ep={ep} already exceeds "
                f"{len(devices)} devices")
    n = dp * sp * ep * tp
    if n > len(devices):
        raise ValueError(
            f"mesh {dp}x{sp}x{ep}x{tp} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, sp, ep, tp)
    return Mesh(arr, axis_names=("dp", "sp", "ep", "tp"))


def parse_workers(workers: str | None, sp: int = 1, dp: int = 1, ep: int = 1,
                  devices=None) -> Mesh:
    """Parse the CLI ``--workers`` value (+ ``--sp``/``--dp``/``--ep``
    degrees) into a mesh.

    ``tpu:N`` → N-way tensor parallel (the BASELINE.json north-star form);
    ``None``/"" → all remaining devices go to tp.  ``sp``/``dp``/``ep`` add
    sequence-parallel (long context), data-parallel (batch), and
    expert-parallel axes — capability beyond the reference, whose only
    option is TP (README.md:7); the total dp·sp·ep·tp must fit the device
    count.  Host:port worker lists are the reference's CPU-cluster
    transport and are intentionally not supported — the transport here is
    XLA collectives.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not workers:
        return make_mesh(sp=sp, dp=dp, ep=ep, devices=devices)
    if workers.startswith("tpu:"):
        n = int(workers.split(":", 1)[1])
        return make_mesh(tp=n, sp=sp, dp=dp, ep=ep, devices=devices)
    raise ValueError(
        f"unsupported --workers value {workers!r}: this framework replaces the "
        "TCP star with a TPU mesh; use 'tpu:N'")
