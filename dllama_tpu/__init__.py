"""dllama_tpu — a TPU-native distributed LLM inference framework.

A from-scratch re-design of the capability surface of
``zhengpeirong/distributed-llama`` (a C++ tensor-parallel CPU-cluster
inference engine) for TPUs: JAX/XLA/Pallas for the compute path, a 1-D ICI
device mesh + ``NamedSharding`` in place of the reference's TCP star
topology, and XLA collectives in place of its hand-rolled socket
broadcast/gather.

Subpackages
-----------
- ``quants``     — Q40/Q80 block quantization (`.m`-file compatible)
- ``io``         — `.m` model / `.t` tokenizer file formats
- ``tokenizer``  — BPE encode/decode, chat templates, EOS detection
- ``sampling``   — greedy / temperature / top-p sampler
- ``ops``        — core kernels: rmsnorm, RoPE, attention, Pallas matmuls
- ``models``     — Llama / Mixtral / Grok-1 forward passes
- ``parallel``   — mesh construction + sharding specs (tensor/sequence par.)
- ``runtime``    — engine: compiled prefill/decode, KV cache, generation
- ``server``     — OpenAI-compatible HTTP API
- ``train``      — optional training step (beyond-reference capability)
"""

__version__ = "0.1.0"
