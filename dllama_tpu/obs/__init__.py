"""Unified observability for the serving stack (docs/OBSERVABILITY.md).

Three stdlib-only building blocks, threaded through every layer:

* :mod:`.metrics` — THE process-global registry of counters, gauges and
  fixed-bucket histograms, with two exposition paths from the one
  registry: the backward-compatible ``/metrics`` JSON dict and
  Prometheus text format 0.0.4.
* :mod:`.log` — structured logging (JSON lines or human format) with a
  contextvar-carried request ID stamped on every record, so one grep of
  the server log reconstructs a request's full lifecycle across server,
  engine, fault and snapshot code.
* :mod:`.trace` — lightweight always-on in-process spans in a bounded
  ring buffer, dumpable as Chrome ``trace_event`` JSON (``/debug/trace``
  + ``tools/trace_dump.py``); the cheap first-line latency attribution
  next to the heavyweight XLA tracer (``runtime/profiling.py``).
* :mod:`.dispatch` — the kernel-dispatch ledger: which matmul path every
  weight actually took (pallas-fused / pallas-blocked / xla-dequant /
  dense), labeled degrade counters replacing the old warn-once prints,
  and the process-wide ``degraded`` flag that ``/health`` and the
  end-of-run CLI summary surface.
* :mod:`.cost` — the analytic roofline cost model: FLOPs/bytes-moved
  per dispatch family computed from the model config and dispatch shape
  (no device counters), the per-backend peak table behind the
  ``dllama_mfu`` / ``dllama_mbu`` gauges, and per-request chip-time
  attribution feeding the flight recorder's cost block.
* :mod:`.flight` — the request flight recorder (per-request lifecycle
  records keyed by ``X-Request-Id``, served at ``/debug/requests``) and
  the per-dispatch slot timeline behind ``/debug/timeline`` and the
  scheduler goodput decomposition.
* :mod:`.slo` — declarative latency/error objectives with rolling
  multi-window burn rates (``--slo`` / ``DLLAMA_SLO``), feeding
  ``slo_burn_rate`` gauges and the ``/health`` verdict.
* :mod:`.events` — the pod event journal: bounded, monotonically-
  sequenced structured lifecycle events (spawn/respawn/quarantine/
  scale/reshape/hand-off/preempt…), served at ``/debug/events`` with a
  ``?since=<seq>`` cursor and optionally persisted as JSONL
  (``--event-log``).

Nothing here imports jax (or anything beyond the stdlib): the engine,
loaders, and server all import ``obs`` freely with no cycle risk, and a
metric bump on the decode hot path costs one small lock.
"""

from __future__ import annotations

from . import cost, dispatch, events, flight, log, metrics, slo, \
    trace  # noqa: F401
