"""Declarative latency/error SLOs with rolling multi-window burn rates.

An operator states targets once (``--slo
"ttft_p95=1500ms,itl_p99=120ms,error_rate=0.5%"`` or ``DLLAMA_SLO``) and
the engine turns the registry's already-recorded histograms and counters
into the standard SRE question: *how fast is the error budget burning?*

Grammar::

    spec      := objective ("," objective)*
    objective := METRIC "_p" QUANTILE "=" DURATION      # latency
               | "error_rate" "=" PERCENT               # availability
    METRIC    := "ttft" | "itl" | "queue_wait" | "duration" | "step"
    DURATION  := number ["ms" | "s"]                    # bare => ms
    PERCENT   := number ["%"]                           # bare => fraction

Burn-rate math (Google SRE workbook, multiwindow): a latency objective
``ttft_p95=1500ms`` allows 5% of requests to exceed 1.5 s.  Over each
rolling window the engine computes ``bad/total`` from deltas of the
histogram's cumulative counts and divides by the allowed fraction::

    burn(window) = (bad_in_window / total_in_window) / (1 - quantile)

``burn == 1.0`` spends the budget exactly as fast as the objective
permits; ``burn >= 1.0`` on *all* windows is **violating** (the long
window proves sustained damage, the short window clears quickly after
recovery — the same fast-recall/fast-reset pairing production alerting
uses); ``>= 1.0`` on only some windows is **at-risk**; otherwise **ok**.
Thresholds resolve to the nearest histogram bucket boundary at or above
the target (fixed buckets make the window deltas O(1)); the resolved
boundary is reported so the approximation is visible.  Windows default
to 5m/1h and come from ``DLLAMA_SLO_WINDOWS`` (e.g. ``"3s,12s"`` in the
fault drills).

Exposition: ``slo_burn_rate{objective,window}`` gauges,
``slo_violations_total{objective}`` counters (bumped on the transition
into violating, so the count is scrape-rate independent), a verdict
block in ``GET /health``, and :meth:`SloEngine.summary_line` printed at
end of run next to the kernel-dispatch summary.
"""

from __future__ import annotations

import bisect
import os
import re
import threading
import time
from collections import deque

from . import metrics as obs_metrics
from .log import get_logger

_log = get_logger("obs.slo")

DEFAULT_WINDOWS = "5m,1h"

#: latency metric name -> (histogram handle, seconds per histogram unit)
_LATENCY_METRICS = {
    "ttft": (lambda: obs_metrics.TTFT, 1.0),
    "itl": (lambda: obs_metrics.INTER_TOKEN, 1.0),
    "queue_wait": (lambda: obs_metrics.QUEUE_WAIT, 1.0),
    "duration": (lambda: obs_metrics.REQUEST_DURATION, 1.0),
    "step": (lambda: obs_metrics.ENGINE_GENERATION_MS, 1e-3),
}

_OBJ_RE = re.compile(r"^([a-z_]+)_p(\d{1,2}(?:\.\d+)?)$")


def _parse_duration_s(text: str, *, where: str) -> float:
    m = re.fullmatch(r"\s*([0-9]*\.?[0-9]+)\s*(ms|s)?\s*", text)
    if not m or float(m.group(1)) <= 0:
        raise ValueError(f"bad duration {text!r} in {where!r} "
                         f"(want e.g. 1500ms or 1.5s)")
    v = float(m.group(1))
    return v if m.group(2) == "s" else v / 1e3


def parse_windows(spec: str) -> list[tuple[str, float]]:
    """``"5m,1h"`` -> ``[("5m", 300.0), ("1h", 3600.0)]`` (ascending)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        m = re.fullmatch(r"([0-9]*\.?[0-9]+)(s|m|h)", part)
        if not m:
            raise ValueError(f"bad SLO window {part!r} (want e.g. 5m, 1h, 30s)")
        scale = {"s": 1.0, "m": 60.0, "h": 3600.0}[m.group(2)]
        secs = float(m.group(1)) * scale
        if secs <= 0:
            raise ValueError(f"bad SLO window {part!r}: must be positive")
        out.append((part, secs))
    if not out:
        raise ValueError("empty SLO window spec")
    out.sort(key=lambda w: w[1])
    return out


class Objective:
    """One parsed objective bound to its registry metric."""

    def __init__(self, key: str, *, kind: str, allowed: float,
                 target_display: str, hist=None, threshold=None):
        self.key = key
        self.kind = kind                      # "latency" | "error_rate"
        self.allowed = allowed                # allowed bad fraction
        self.target_display = target_display
        self.hist = hist
        self.threshold = threshold            # in histogram units
        self.boundary = None                  # resolved bucket upper
        self._boundary_idx = None
        if hist is not None:
            i = bisect.bisect_left(hist.uppers, threshold)
            self._boundary_idx = i
            self.boundary = (hist.uppers[i] if i < len(hist.uppers)
                             else float("inf"))
            if self.boundary == float("inf"):
                _log.warning(
                    "slo objective %s: target %s is beyond the largest "
                    "%s bucket — only +Inf observations count as bad",
                    key, target_display, hist.name)

    def counts(self) -> tuple[float, float]:
        """Current cumulative ``(bad, total)`` for this objective."""
        if self.kind == "error_rate":
            bad = obs_metrics.SERVER_ERRORS.value
            total = bad + obs_metrics.REQUESTS_SERVED.value
            return float(bad), float(total)
        cum, _, count = self.hist.snapshot()
        i = self._boundary_idx
        good = cum[i] if i < len(self.hist.uppers) else count
        return float(count - good), float(count)


def parse_slo(spec: str) -> list[Objective]:
    """Parse the ``--slo`` grammar; raises ``ValueError`` with a message
    naming the offending objective (the CLI surfaces it verbatim)."""
    objectives = []
    seen = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad SLO objective {part!r}: want name=target")
        name, _, target = part.partition("=")
        name, target = name.strip(), target.strip()
        if name in seen:
            raise ValueError(f"duplicate SLO objective {name!r}")
        seen.add(name)
        if name == "error_rate":
            m = re.fullmatch(r"([0-9]*\.?[0-9]+)\s*(%)?", target)
            if not m:
                raise ValueError(f"bad error_rate target {target!r} "
                                 f"(want e.g. 0.5% or 0.005)")
            frac = float(m.group(1)) / (100.0 if m.group(2) else 1.0)
            if not 0 < frac < 1:
                raise ValueError(f"error_rate target {target!r} must be in "
                                 f"(0, 100%)")
            objectives.append(Objective(
                name, kind="error_rate", allowed=frac,
                target_display=f"{frac * 100:g}%"))
            continue
        m = _OBJ_RE.match(name)
        if not m or m.group(1) not in _LATENCY_METRICS:
            known = ", ".join(sorted(_LATENCY_METRICS))
            raise ValueError(
                f"unknown SLO objective {name!r} (want <metric>_p<q> with "
                f"metric in {{{known}}}, or error_rate)")
        metric, q = m.group(1), float(m.group(2))
        if not 0 < q < 100:
            raise ValueError(f"bad quantile in {name!r}: must be in (0, 100)")
        hist_fn, unit_s = _LATENCY_METRICS[metric]
        hist = hist_fn()
        threshold = _parse_duration_s(target, where=part) / unit_s
        objectives.append(Objective(
            name, kind="latency", allowed=1.0 - q / 100.0,
            target_display=target, hist=hist, threshold=threshold))
    if not objectives:
        raise ValueError("empty SLO spec")
    return objectives


class SloEngine:
    """Rolling multi-window burn-rate evaluation over registry metrics.

    Snapshots of each objective's cumulative ``(bad, total)`` are kept in
    a time-stamped deque; a window's burn is computed from the delta
    between now and the newest snapshot at least that old (a partially
    filled window uses the oldest snapshot — early traffic is judged
    against the traffic actually seen, not diluted by imagined history).
    """

    def __init__(self, objectives: list[Objective],
                 windows: list[tuple[str, float]] | None = None):
        if not objectives:
            raise ValueError("SloEngine needs at least one objective")
        self.objectives = objectives
        self.windows = windows or parse_windows(DEFAULT_WINDOWS)
        self._lock = threading.Lock()
        self._samples: deque = deque()
        self._min_spacing = max(0.2, self.windows[0][1] / 50.0)
        self._verdicts = {o.key: "ok" for o in objectives}
        self._max_age = self.windows[-1][1] * 1.2 + 60.0

    @classmethod
    def from_spec(cls, spec: str, windows_spec: str | None = None
                  ) -> "SloEngine":
        ws = windows_spec or os.environ.get("DLLAMA_SLO_WINDOWS",
                                            DEFAULT_WINDOWS)
        return cls(parse_slo(spec), parse_windows(ws))

    @property
    def spec_display(self) -> str:
        return ",".join(f"{o.key}={o.target_display}"
                        for o in self.objectives)

    def evaluate(self, now: float | None = None) -> dict:
        """Compute burns, update gauges/counters, return the verdict
        block served in ``/health``.  ``now`` is ``time.monotonic()``
        unless a test injects simulated time."""
        if now is None:
            now = time.monotonic()
        current = {o.key: o.counts() for o in self.objectives}
        with self._lock:
            if not self._samples or \
                    now - self._samples[-1][0] >= self._min_spacing:
                self._samples.append((now, current))
            while self._samples and now - self._samples[0][0] > self._max_age:
                self._samples.popleft()
            samples = list(self._samples)

        out_objs = {}
        worst = "ok"
        for o in self.objectives:
            bad_now, total_now = current[o.key]
            burns = {}
            for label, secs in self.windows:
                base = None
                for t, snap in reversed(samples):
                    if t <= now - secs:
                        base = snap.get(o.key)
                        break
                if base is None and samples:
                    t0, snap0 = samples[0]
                    # the oldest sample IS "now" on the very first call:
                    # no history yet, judge the cumulative totals directly
                    base = (0.0, 0.0) if t0 >= now else snap0.get(o.key)
                if base is None:
                    base = (0.0, 0.0)
                d_bad = max(bad_now - base[0], 0.0)
                d_total = max(total_now - base[1], 0.0)
                burn = (d_bad / d_total) / o.allowed if d_total > 0 else 0.0
                burn = round(burn, 4)
                burns[label] = burn
                obs_metrics.SLO_BURN_RATE.set(o.key, label, burn)
            if all(b >= 1.0 for b in burns.values()):
                verdict = "violating"
            elif any(b >= 1.0 for b in burns.values()):
                verdict = "at_risk"
            else:
                verdict = "ok"
            with self._lock:
                if verdict == "violating" and \
                        self._verdicts[o.key] != "violating":
                    obs_metrics.SLO_VIOLATIONS.inc(o.key)
                    _log.warning("slo objective %s VIOLATING: burn %s "
                                 "(target %s)", o.key, burns,
                                 o.target_display)
                self._verdicts[o.key] = verdict
            entry = {"target": o.target_display, "verdict": verdict,
                     "burn": burns}
            if o.boundary is not None:
                entry["resolved_boundary"] = o.boundary
            out_objs[o.key] = entry
            rank = {"ok": 0, "at_risk": 1, "violating": 2}
            if rank[verdict] > rank[worst]:
                worst = verdict
        return {"status": worst,
                "windows": [label for label, _ in self.windows],
                "objectives": out_objs}

    def summary_line(self) -> str:
        """End-of-run one-liner, printed beside the dispatch summary."""
        res = self.evaluate()
        viol = obs_metrics.SLO_VIOLATIONS.json_value()
        parts = []
        for key, entry in res["objectives"].items():
            burns = "/".join(f"{entry['burn'][w]:g}" for w in res["windows"])
            parts.append(f"{key}<={entry['target']} burn {burns} "
                         f"[{entry['verdict']}]")
        wins = "/".join(res["windows"])
        tail = f"; violations {viol}" if viol else ""
        return (f"slo: {res['status'].upper()} over {wins} — "
                + "; ".join(parts) + tail)
