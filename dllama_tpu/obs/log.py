"""Structured logging with a contextvar-carried request ID.

The serving stack logs through one root logger (``dllama``) configured
here: either human-readable lines or JSON lines (one object per record),
selected by ``--log-format``/``--log-level`` or the ``DLLAMA_LOG`` env
var (``json``, ``debug``, or combined ``json:debug``).

The request ID set at accept time (server/api.py) rides a
:data:`contextvars.ContextVar`, so every record logged on the request's
thread — server handler, engine step, fault firing, snapshot save —
carries the same ID with zero plumbing through call signatures.  It is
stamped via :func:`logging.setLogRecordFactory` (not a handler filter:
filters on an ancestor logger do not apply to propagated records), which
means call sites must never pass ``request_id`` through ``extra=``.

Grep contract (docs/OBSERVABILITY.md): with ``--log-format json``,
``grep <request_id> server.log`` reconstructs the request's lifecycle
(accept → queue → prefill → decode → finish/error).
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import sys
import time
import uuid

#: the per-request correlation ID; ``None`` outside a request context.
request_id_var: contextvars.ContextVar = contextvars.ContextVar(
    "dllama_request_id", default=None)

ROOT = "dllama"


def get_logger(name: str) -> logging.Logger:
    """Child of the ``dllama`` root (``get_logger("server.api")`` →
    ``dllama.server.api``)."""
    return logging.getLogger(f"{ROOT}.{name}")


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def set_request_id(rid) -> None:
    request_id_var.set(rid)


def current_request_id():
    return request_id_var.get()


# -- record factory: stamp the contextvar on EVERY record ------------------

_base_factory = None


def _factory(*args, **kwargs):
    record = _base_factory(*args, **kwargs)
    record.request_id = request_id_var.get()
    return record


def _install_factory() -> None:
    global _base_factory
    if _base_factory is None:
        _base_factory = logging.getLogRecordFactory()
        logging.setLogRecordFactory(_factory)


_install_factory()


# -- formatters ------------------------------------------------------------

#: LogRecord attributes that are plumbing, not user-supplied ``extra=``.
_RESERVED = set(vars(logging.LogRecord("", 0, "", 0, "", (), None))) | {
    "request_id", "message", "asctime", "taskName"}


def _extras(record: logging.LogRecord) -> dict:
    return {k: v for k, v in vars(record).items() if k not in _RESERVED}


class JsonFormatter(logging.Formatter):
    """One JSON object per line; ``event`` is the log message, extra
    keyword fields ride alongside it at the top level."""

    def format(self, record: logging.LogRecord) -> str:
        out = {"ts": round(record.created, 6),
               "level": record.levelname,
               "logger": record.name,
               "event": record.getMessage()}
        rid = getattr(record, "request_id", None)
        if rid:
            out["request_id"] = rid
        out.update(_extras(record))
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class HumanFormatter(logging.Formatter):
    """``HH:MM:SS LEVEL logger [rid] event k=v ...`` — the terminal view."""

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        rid = getattr(record, "request_id", None)
        rid_part = f" [{rid}]" if rid else ""
        parts = [f"{ts} {record.levelname:<7} {record.name}{rid_part} "
                 f"{record.getMessage()}"]
        parts += [f"{k}={v}" for k, v in _extras(record).items()]
        line = " ".join(parts)
        if record.exc_info:
            line += "\n" + self.formatException(record.exc_info)
        return line


# -- configuration ---------------------------------------------------------

_TAG = "_dllama_obs_handler"

_FORMATS = {"json", "human"}
_LEVELS = {"debug", "info", "warning", "error", "critical"}


def _parse_env(spec: str):
    """``DLLAMA_LOG="json:debug"`` (either part optional, any order)."""
    fmt = level = None
    for part in spec.replace(",", ":").split(":"):
        part = part.strip().lower()
        if not part:
            continue
        if part in _FORMATS:
            fmt = part
        elif part in _LEVELS:
            level = part
    return fmt, level


def configure(log_format=None, log_level=None, *, stream=None,
              force: bool = False) -> logging.Logger:
    """Configure the ``dllama`` root logger (idempotent unless ``force``).

    Precedence: explicit args (CLI flags) > ``DLLAMA_LOG`` env > defaults
    (``human`` / ``info``)."""
    env_fmt, env_level = _parse_env(os.environ.get("DLLAMA_LOG", ""))
    fmt = (log_format or env_fmt or "human").lower()
    level = (log_level or env_level or "info").upper()

    root = logging.getLogger(ROOT)
    ours = [h for h in root.handlers if getattr(h, _TAG, False)]
    if ours and not force:
        root.setLevel(level)
        return root
    for h in ours:
        root.removeHandler(h)

    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonFormatter() if fmt == "json" else HumanFormatter())
    setattr(handler, _TAG, True)
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    return root
