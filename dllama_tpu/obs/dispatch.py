"""Kernel-dispatch ledger: which matmul path actually ran, and why not.

The perf contract of this codebase is the fused Pallas dequant-matmul
(ops/q40.py, ops/q8.py); every dispatch that silently falls off it —
probe failure, hardware-illegal blocked tiles, a weight that doesn't
shard over the mesh — used to announce itself as one scrollback
``print`` and then vanish.  A production run could misreport a
several-×-slower XLA-dequant decode as a clean number (VERDICT r05).

This module is the single funnel those decisions flow through:

* :func:`record_dispatch` — every resolved matmul dispatch bumps the
  ``matmul_dispatch`` family (labels ``codec``/``path``).  Dispatches
  are recorded at *trace time* (q40.matmul runs inside ``jax.jit``
  tracing), so counts are per compiled call site, not per decode step —
  exactly the granularity at which a path decision exists.
* :func:`record_degrade` — every fallback off the requested/fast path
  bumps ``q40_degrade_total{reason=...}`` (or the q8 twin), emits ONE
  structured log record per distinct site (warn-once keyed by
  ``warn_key``, replacing the old ``_FALLBACK_WARNED`` prints), and
  flips the process-wide :func:`degraded` flag that ``/health``,
  ``/metrics`` and the end-of-run CLI summary surface.

Stdlib-only (obs package contract: importable without jax).
"""

from __future__ import annotations

import threading

from . import metrics as obs_metrics
from .log import get_logger

_log = get_logger("obs.dispatch")

_lock = threading.Lock()
_degraded = False
_reasons: dict[str, int] = {}        # "codec:reason" -> occurrences
_dispatches: dict[str, int] = {}     # "codec/path"   -> occurrences
_warned: set = set()                 # (codec, reason, warn_key) logged once


def record_dispatch(codec: str, path: str, **ctx) -> None:
    """Record one resolved matmul dispatch.

    ``codec`` is the weight storage ("q40", "q8", "dense"); ``path`` the
    executed implementation ("pallas-fused", "pallas-blocked",
    "xla-dequant", "dense").  Extra keyword context (rows, tiles, kind,
    layout) rides on the debug log record only.
    """
    obs_metrics.MATMUL_DISPATCH.inc(codec, path)
    with _lock:
        key = f"{codec}/{path}"
        _dispatches[key] = _dispatches.get(key, 0) + 1
    _log.debug("dispatch", extra={"codec": codec, "path": path, **ctx})


def record_degrade(codec: str, reason: str, *, warn_key=None, **ctx) -> None:
    """Record one degrade off the fast path: labeled counter + degraded
    flag always; a WARNING log record once per (codec, reason, warn_key)
    so a degrade firing on every layer of every forward logs once, while
    the counter keeps the true occurrence count."""
    global _degraded
    if codec == "q8":
        counter = obs_metrics.Q8_DEGRADE
    elif codec == "attn":
        counter = obs_metrics.ATTN_DEGRADE
    else:
        counter = obs_metrics.Q40_DEGRADE
    counter.inc(reason)
    with _lock:
        _degraded = True
        rk = f"{codec}:{reason}"
        _reasons[rk] = _reasons.get(rk, 0) + 1
        wk = (codec, reason, warn_key)
        first = wk not in _warned
        _warned.add(wk)
    if first:
        _log.warning("kernel_degrade",
                     extra={"codec": codec, "reason": reason, **ctx})


def record_cost(entries: dict) -> None:
    """Bump the analytic roofline counters for one landed dispatch.

    ``entries`` is :meth:`obs.cost.CostModel.dispatch_cost`'s
    ``{(codec, path, phase): {"flops": n, "bytes": n}}`` map — the
    runtime side of the ledger: :func:`record_dispatch` says which path
    a call site *compiled*, this says what the landed dispatches *cost*.
    """
    for (codec, path, phase), e in entries.items():
        if e.get("flops"):
            obs_metrics.DISPATCH_FLOPS.inc(codec, path, phase,
                                           n=e["flops"])
        if e.get("bytes"):
            obs_metrics.DISPATCH_BYTES.inc(codec, path, phase,
                                           n=e["bytes"])


def degraded() -> bool:
    """True once any dispatch degraded off its fast path this process."""
    with _lock:
        return _degraded


def reasons() -> dict[str, int]:
    """``{"codec:reason": occurrences}`` for every degrade recorded."""
    with _lock:
        return dict(_reasons)


def dispatches() -> dict[str, int]:
    """``{"codec/path": occurrences}`` for every dispatch recorded."""
    with _lock:
        return dict(_dispatches)


def summary() -> dict:
    """One JSON-able view of the ledger (health endpoint, tools)."""
    with _lock:
        return {"degraded": _degraded,
                "degrades": dict(_reasons),
                "dispatches": dict(_dispatches)}


def summary_line() -> str:
    """The end-of-run CLI summary: one line that makes a degraded run
    impossible to read as a clean number."""
    with _lock:
        deg = dict(_reasons)
        paths = dict(_dispatches)
    path_part = " ".join(f"{k}×{v}" for k, v in sorted(paths.items())) \
        or "none recorded"
    if deg:
        deg_part = " ".join(f"{k}×{v}" for k, v in sorted(deg.items()))
        return (f"⚠️  kernel dispatch: DEGRADED ({deg_part}); "
                f"paths: {path_part}")
    return f"💡 kernel dispatch: clean; paths: {path_part}"


def collective_line() -> str | None:
    """End-of-run collective-overlap share: of the tp-sharded col-matmul
    call sites this process compiled, how many took the fused RDMA ring
    (transfer overlapped with accumulate) vs the plain-psum fallback.
    None when no tp collective was dispatched at all (tp=1 runs stay
    silent)."""
    with _lock:
        fused = _dispatches.get("q40/tp_fused_reduce", 0)
        psum = _dispatches.get("q40/tp_psum", 0)
    total = fused + psum
    if not total:
        return None
    return (f"🔗 tp collectives: {fused}/{total} sharded matmul sites "
            f"fused (overlap share {fused / total:.2f})")


def reset() -> None:
    """Clear the ledger AND its registry counters (test isolation)."""
    global _degraded
    with _lock:
        _degraded = False
        _reasons.clear()
        _dispatches.clear()
        _warned.clear()
    obs_metrics.MATMUL_DISPATCH.reset()
    obs_metrics.Q40_DEGRADE.reset()
    obs_metrics.Q8_DEGRADE.reset()
    obs_metrics.ATTN_DEGRADE.reset()
    obs_metrics.DISPATCH_FLOPS.reset()
    obs_metrics.DISPATCH_BYTES.reset()
    obs_metrics.CLASS_CHIP_MS.reset()
    obs_metrics.MFU.reset()
    obs_metrics.MBU.reset()
    from . import cost as obs_cost
    obs_cost.TRACKER.reset()
