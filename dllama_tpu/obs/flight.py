"""Per-request flight recorder and per-step slot timeline.

Two bounded rings answer the two questions the coarse scheduler gauges
cannot: *"why was this request slow?"* and *"where did the wall-clock of
step N go?"*.

* :class:`FlightRecorder` keeps one lifecycle record per request, keyed
  by ``X-Request-Id``: submit time, queue wait, admit slot, every prefill
  chunk (tokens, dispatch wall), every decode burst (steps, tokens,
  wall/step time), the retire reason, kernel-degrade events that fired
  during the request, and final TTFT / inter-token stats.  Both serving
  paths populate it — the ``SlotScheduler`` with per-dispatch detail, the
  lockstep mutex path with coarse phases — so ``GET /debug/requests``
  (recent summaries) and ``GET /debug/requests/<id>`` (full record) work
  regardless of how a request was served.
* :class:`SlotTimeline` keeps one entry per scheduler dispatch: each
  slot's phase (``prefill``/``decode``/``pad``), tokens produced, device
  time, and the host gap / idle sleep since the previous dispatch.
  ``GET /debug/timeline`` serves it and ``tools/trace_dump.py --slots``
  renders it as one Perfetto track per slot.

Ring capacities come from ``--flight-buffer`` / ``DLLAMA_FLIGHT_BUFFER``
(records) with the same warn-once malformed-value fallback as the trace
ring.  All record timestamps are ``time.time()`` for display plus
``perf_counter`` fields where durations are derived; phase ``ms`` values
are dispatch wall times (a mixed dispatch charges its full wall to every
row that rode it — rows are lockstepped, that IS their latency).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from . import dispatch as _dispatch
from .log import current_request_id
from .trace import parse_buffer_env

DEFAULT_FLIGHT_CAPACITY = 512
DEFAULT_TIMELINE_CAPACITY = 4096


def _flight_capacity() -> int:
    return parse_buffer_env("DLLAMA_FLIGHT_BUFFER", DEFAULT_FLIGHT_CAPACITY)


class FlightRecorder:
    """Bounded insertion-ordered map of per-request lifecycle records.

    ``submit`` is get-or-create-or-merge: the server handler and the
    scheduler both call it for the same request ID (the ticket carries
    the handler's contextvar ID into the scheduler thread) and the two
    field sets union instead of clobbering.  A *retired* record under a
    reused ID is replaced — a client recycling ``X-Request-Id`` starts a
    fresh flight, it does not append to last week's."""

    def __init__(self, capacity: int | None = None):
        self._lock = threading.Lock()
        self._capacity = max(1, capacity if capacity is not None
                             else _flight_capacity())
        self._records: OrderedDict[str, dict] = OrderedDict()

    # -- capacity ----------------------------------------------------------
    def resize(self, capacity: int) -> None:
        with self._lock:
            self._capacity = max(1, int(capacity))
            while len(self._records) > self._capacity:
                self._records.popitem(last=False)

    @property
    def capacity(self) -> int:
        return self._capacity

    # -- lifecycle hooks ---------------------------------------------------
    def _rid(self, rid):
        return rid if rid is not None else current_request_id()

    def _get_locked(self, rid: str) -> dict | None:
        return self._records.get(rid)

    def submit(self, rid=None, **fields) -> None:
        """Open (or merge into) the record for ``rid``.  Fields already
        present win — first writer (usually the server handler) sets the
        authoritative submit picture, later writers only fill gaps."""
        rid = self._rid(rid)
        if rid is None:
            return
        with self._lock:
            rec = self._records.get(rid)
            if rec is not None and "finish" in rec:
                del self._records[rid]     # reused ID: start a fresh flight
                rec = None
            if rec is None:
                rec = {"request_id": rid,
                       "submitted_at": round(time.time(), 6),
                       "phases": [],
                       "degrade_base": dict(_dispatch.reasons()),
                       "itl": {"count": 0, "sum_s": 0.0, "max_s": 0.0}}
                self._records[rid] = rec
                while len(self._records) > self._capacity:
                    self._records.popitem(last=False)
            for k, v in fields.items():
                rec.setdefault(k, v)

    def admit(self, rid=None, *, slot=None, queued_ms=None, **fields) -> None:
        rid = self._rid(rid)
        if rid is None:
            return
        with self._lock:
            rec = self._get_locked(rid)
            if rec is None:
                return
            if slot is not None:
                rec["slot"] = slot
            if queued_ms is not None and "queued_ms" not in rec:
                rec["queued_ms"] = round(float(queued_ms), 3)
            rec["admitted_at"] = round(time.time(), 6)
            for k, v in fields.items():
                rec.setdefault(k, v)

    def phase(self, rid=None, kind: str = "", **fields) -> None:
        """Append one phase entry (``prefill_chunk`` / ``decode_burst`` /
        ``verify_burst``)."""
        rid = self._rid(rid)
        if rid is None:
            return
        with self._lock:
            rec = self._get_locked(rid)
            if rec is None:
                return
            entry = {"kind": kind}
            for k, v in fields.items():
                entry[k] = round(v, 3) if isinstance(v, float) else v
            rec["phases"].append(entry)

    def cost(self, rid=None, **inc) -> None:
        """Accumulate roofline cost attribution (``chip_ms``, ``flops``,
        ``hbm_bytes``, ``kv_page_ms``) into the record's cost block —
        one call per dispatch the request rode, raw floats summed here
        and rounded only at exposition (get/recent)."""
        rid = self._rid(rid)
        if rid is None:
            return
        with self._lock:
            rec = self._get_locked(rid)
            if rec is None:
                return
            cost = rec.setdefault(
                "cost", {"chip_ms": 0.0, "flops": 0.0,
                         "hbm_bytes": 0.0, "kv_page_ms": 0.0})
            for k, v in inc.items():
                cost[k] = cost.get(k, 0.0) + float(v)

    @staticmethod
    def _cost_view(rec: dict) -> dict | None:
        cost = rec.get("cost")
        if cost is None:
            return None
        return {k: round(v, 3) for k, v in cost.items()}

    def first_token(self, rid=None, ttft_s: float = 0.0) -> None:
        """The exact value the serving layer observed into the TTFT
        histogram — stored verbatim so record and histogram agree."""
        rid = self._rid(rid)
        if rid is None:
            return
        with self._lock:
            rec = self._get_locked(rid)
            if rec is not None and "ttft_s" not in rec:
                rec["ttft_s"] = float(ttft_s)

    def inter_token(self, rid=None, gap_s: float = 0.0) -> None:
        rid = self._rid(rid)
        if rid is None:
            return
        with self._lock:
            rec = self._get_locked(rid)
            if rec is None:
                return
            itl = rec["itl"]
            itl["count"] += 1
            itl["sum_s"] += float(gap_s)
            itl["max_s"] = max(itl["max_s"], float(gap_s))

    def retire(self, rid=None, reason: str = "done", **fields) -> None:
        """Close the record.  The first specific reason wins: the
        scheduler retires with stop/length/timeout/... before the server
        handler's generic fallback fires in its ``finally``."""
        rid = self._rid(rid)
        if rid is None:
            return
        with self._lock:
            rec = self._get_locked(rid)
            if rec is None or "finish" in rec:
                return
            rec["finish"] = reason
            rec["ended_at"] = round(time.time(), 6)
            rec["duration_ms"] = round(
                (rec["ended_at"] - rec["submitted_at"]) * 1e3, 3)
            base = rec.pop("degrade_base", {})
            now = _dispatch.reasons()
            during = {k: int(v - base.get(k, 0)) for k, v in now.items()
                      if v > base.get(k, 0)}
            rec["degraded"] = _dispatch.degraded()
            rec["degrade_events"] = during
            itl = rec["itl"]
            if itl["count"]:
                itl["avg_s"] = round(itl["sum_s"] / itl["count"], 6)
            for k, v in fields.items():
                if v is not None:
                    rec.setdefault(k, v)

    # -- exposition --------------------------------------------------------
    def get(self, rid: str) -> dict | None:
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                return None
            out = dict(rec)
            out["phases"] = [dict(p) for p in rec["phases"]]
            out["itl"] = dict(rec["itl"])
            if "cost" in rec:
                out["cost"] = self._cost_view(rec)
            out.pop("degrade_base", None)
            return out

    def recent(self, n: int = 50) -> list[dict]:
        """Newest-first summaries for ``GET /debug/requests``."""
        with self._lock:
            recs = [dict(rec)
                    for rec in list(self._records.values())[-max(0, n):]]
        out = []
        for rec in reversed(recs):
            row = {k: rec.get(k) for k in
                   ("request_id", "submitted_at", "slot", "n_prompt",
                    "produced", "queued_ms", "ttft_s", "duration_ms",
                    "finish", "path", "priority", "preempt_count")}
            if "cost" in rec:
                row["cost"] = self._cost_view(rec)
            out.append(row)
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()


class SlotTimeline:
    """Ring of per-dispatch entries, one slot map per scheduler step."""

    def __init__(self, capacity: int = DEFAULT_TIMELINE_CAPACITY):
        self._lock = threading.Lock()
        self._steps = deque(maxlen=max(1, capacity))
        self._seq = 0

    def record_step(self, *, ts: float, wall_ms: float,
                    device_ms: float | None = None,
                    host_gap_ms: float = 0.0, idle_ms: float = 0.0,
                    steps: int = 1, t_width: int = 1,
                    slots: list[dict] | None = None,
                    error: bool = False, overlapped: bool = False,
                    hidden_host_ms: float = 0.0,
                    discarded: bool = False) -> None:
        """``ts`` is the dispatch-start ``perf_counter`` (the span clock,
        so ``--slots`` tracks align with the request spans in Perfetto).

        ``overlapped`` marks a dispatch that was already enqueued on
        device while its predecessor landed; its ``hidden_host_ms`` is
        the host-side gap the device outlived (reported here and in the
        hidden-gap counter, NOT silently dropped — and not double-counted
        into ``host_gap_ms``, which stays the *exposed* gap).
        ``discarded`` marks a pipelined dispatch thrown away at a
        pipeline flush point: its tokens were never fanned out."""
        with self._lock:
            self._seq += 1
            entry = {"seq": self._seq, "t": round(time.time(), 6),
                     "ts": ts, "wall_ms": round(wall_ms, 3),
                     "host_gap_ms": round(host_gap_ms, 3),
                     "idle_ms": round(idle_ms, 3),
                     "steps": steps, "t_width": t_width,
                     "overlapped": bool(overlapped),
                     "hidden_host_ms": round(hidden_host_ms, 3),
                     "slots": slots or []}
            if device_ms is not None:
                entry["device_ms"] = round(device_ms, 3)
            if error:
                entry["error"] = True
            if discarded:
                entry["discarded"] = True
            self._steps.append(entry)

    def snapshot(self, n: int | None = None) -> list[dict]:
        with self._lock:
            steps = list(self._steps)
        if n is not None:
            steps = steps[-max(0, n):]
        return [dict(e) for e in steps]

    def clear(self) -> None:
        with self._lock:
            self._steps.clear()
            self._seq = 0


#: THE process-global rings both serving paths and /debug read.
RECORDER = FlightRecorder()
TIMELINE = SlotTimeline()


def submit(rid=None, **fields) -> None:
    RECORDER.submit(rid, **fields)


def admit(rid=None, **kw) -> None:
    RECORDER.admit(rid, **kw)


def phase(rid=None, kind: str = "", **fields) -> None:
    RECORDER.phase(rid, kind, **fields)


def cost(rid=None, **inc) -> None:
    RECORDER.cost(rid, **inc)


def first_token(rid=None, ttft_s: float = 0.0) -> None:
    RECORDER.first_token(rid, ttft_s)


def inter_token(rid=None, gap_s: float = 0.0) -> None:
    RECORDER.inter_token(rid, gap_s)


def retire(rid=None, reason: str = "done", **fields) -> None:
    RECORDER.retire(rid, reason, **fields)


def get(rid: str) -> dict | None:
    return RECORDER.get(rid)


def recent(n: int = 50) -> list[dict]:
    return RECORDER.recent(n)


def configure(capacity: int | None = None) -> None:
    """Apply a CLI-chosen capacity (``--flight-buffer``) after import."""
    if capacity is not None:
        RECORDER.resize(capacity)


def clear() -> None:
    RECORDER.clear()
    TIMELINE.clear()
