"""The pod event journal: bounded, monotonically-sequenced fleet events.

Respawns, quarantines, ejections, scale/reshape actions, hand-offs and
preemptions used to exist only as log lines — greppable after the fact,
invisible to a dashboard, impossible to lay against a latency regression
without timestamp archaeology.  This module gives every process one
structured ring of lifecycle events:

* ``emit(kind, **fields)`` appends ``{"seq", "ts", "kind", ...fields}``
  — ``seq`` is a process-monotonic cursor, ``ts`` is wall-clock seconds.
* ``snapshot(since=N)`` returns only events after cursor ``N``, so
  pollers (``fleet_top``, ``trace_replay``) tail the journal without
  re-downloading the ring every tick.  Served at ``/debug/events`` by
  both the router/pod process and every replica.
* ``configure(capacity=..., log_path=...)`` applies ``--event-buffer``-
  style sizing (``DLLAMA_EVENT_BUFFER``, default 2048) and optional
  JSONL persistence (``--event-log``): every event is also appended to
  a file, one object per line, surviving the process that emitted it.

Event kinds (docs/OBSERVABILITY.md "Fleet observability"): ``spawn``,
``death``, ``respawn``, ``quarantine``, ``eject``, ``readmit``,
``retire``, ``scale``, ``reshape``, ``handoff``, ``resume``,
``preempt``.  The set is advisory, not enforced — a new subsystem can
emit a new kind without touching this module — but ``KINDS`` is what
the docs table and ``fleet_top`` legend are generated from.

Like every ``obs`` module: stdlib only, one small lock per append,
process-global singleton (``JOURNAL``).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from . import metrics
from .log import get_logger
from .trace import parse_buffer_env

_log = get_logger("obs.events")

DEFAULT_CAPACITY = 2048

#: the canonical kinds — docs/OBSERVABILITY.md keeps a row per kind.
KINDS = ("spawn", "death", "respawn", "quarantine", "eject", "readmit",
         "retire", "scale", "reshape", "handoff", "resume", "preempt")


def _capacity() -> int:
    return parse_buffer_env("DLLAMA_EVENT_BUFFER", DEFAULT_CAPACITY)


class EventJournal:
    """Lock + ring of structured events with a monotonic sequence."""

    def __init__(self, capacity: int | None = None):
        self._lock = threading.Lock()
        self._events = deque(maxlen=capacity or _capacity())
        self._seq = 0
        self._log_file = None
        self._log_path = None
        self._log_failed = False

    # -- configuration ---------------------------------------------------

    def resize(self, capacity: int) -> None:
        with self._lock:
            self._events = deque(self._events, maxlen=max(1, int(capacity)))

    @property
    def capacity(self) -> int:
        return self._events.maxlen or 0

    def set_log_path(self, path: str | None) -> None:
        """Persist every future event as a JSONL line to ``path`` (append
        mode — restarts extend, never truncate).  ``None`` turns it off."""
        with self._lock:
            if self._log_file is not None:
                try:
                    self._log_file.close()
                except OSError:
                    pass
                self._log_file = None
            self._log_path = path
            self._log_failed = False
            if path:
                try:
                    self._log_file = open(path, "a", encoding="utf-8")
                except OSError as e:
                    self._log_failed = True
                    _log.warning("--event-log %s unwritable: %s (journal "
                                 "stays in-memory only)", path, e)

    # -- the hot path ----------------------------------------------------

    def emit(self, kind: str, **fields) -> dict:
        """Append one event; returns the stored record (with seq/ts)."""
        ev = {"kind": kind, "ts": round(time.time(), 6)}
        ev.update({k: v for k, v in fields.items() if v is not None})
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)
            f = self._log_file
            if f is not None:
                try:
                    f.write(json.dumps(ev, sort_keys=True) + "\n")
                    f.flush()
                except (OSError, ValueError):
                    # one warning, then stop trying: a full disk must not
                    # turn every supervisor action into a log storm
                    if not self._log_failed:
                        self._log_failed = True
                        _log.warning("--event-log %s write failed; further "
                                     "events stay in-memory only",
                                     self._log_path)
                    self._log_file = None
        metrics.POD_EVENTS.inc(kind)
        return ev

    # -- readers ---------------------------------------------------------

    def snapshot(self, since: int | None = None) -> dict:
        """Events after cursor ``since`` (all retained ones when None),
        plus the cursor to pass on the next poll and how much of the
        ring's history has already scrolled off."""
        with self._lock:
            events = [dict(e) for e in self._events
                      if since is None or e["seq"] > since]
            next_seq = self._seq
            oldest = self._events[0]["seq"] if self._events else next_seq + 1
        return {"events": events, "next_seq": next_seq,
                "oldest_seq": oldest, "capacity": self.capacity}

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


#: THE process-global journal.
JOURNAL = EventJournal()


def emit(kind: str, **fields) -> dict:
    return JOURNAL.emit(kind, **fields)


def snapshot(since: int | None = None) -> dict:
    return JOURNAL.snapshot(since)


def configure(capacity: int | None = None, log_path: str | None = None) -> None:
    """Apply CLI choices (``--event-buffer`` sizing via env is already
    read at import; ``--event-log`` persistence) after import."""
    if capacity is not None:
        JOURNAL.resize(capacity)
    if log_path is not None:
        JOURNAL.set_log_path(log_path)


def clear() -> None:
    JOURNAL.clear()
