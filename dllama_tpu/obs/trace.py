"""Always-on in-process spans in a bounded ring buffer.

The cheap first line of latency attribution: every request records a
handful of spans (request → queue_wait → prefill → decode_chunk → emit →
snapshot) into a fixed-capacity deque — no flags, no files, roughly one
``perf_counter`` pair and a dict per span — and ``GET /debug/trace`` (or
``tools/trace_dump.py``) dumps the recent ones as Chrome ``trace_event``
JSON for ``chrome://tracing`` / Perfetto.  When a span points at a phase
worth dissecting, ``--profile-split`` (runtime/profiling.py) remains the
heavyweight XLA-level tool.

Timestamps are ``time.perf_counter()`` seconds (converted to µs in the
export); they order and measure correctly within one process but are not
wall-clock.  Capacity comes from ``--trace-buffer`` /
``DLLAMA_TRACE_BUFFER`` (legacy alias ``DLLAMA_TRACE_CAPACITY``;
default 8192 spans ≈ a few hundred requests); a malformed value warns
once and falls back, mirroring the ``DLLAMA_Q40_BLOCK_TILES`` contract.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager

from .log import get_logger, request_id_var

_log = get_logger("obs.trace")

DEFAULT_CAPACITY = 8192

_warned_specs: set = set()


def parse_buffer_env(var: str, default: int, legacy: str | None = None) -> int:
    """Ring capacity from ``var`` (falling back to ``legacy``); a value
    that is not a positive integer logs one warning per distinct spec and
    falls back to ``default`` — never raises (the buffer size must not be
    able to take the server down)."""
    spec = os.environ.get(var)
    if spec is None and legacy is not None:
        spec = os.environ.get(legacy)
    if spec is None or spec == "":
        return default
    try:
        cap = int(spec)
        if cap < 1:
            raise ValueError(spec)
        return cap
    except ValueError:
        key = (var, spec)
        if key not in _warned_specs:
            _warned_specs.add(key)
            _log.warning("%s=%r is not a positive integer; using default %d",
                         var, spec, default)
        return default


def _capacity() -> int:
    return parse_buffer_env("DLLAMA_TRACE_BUFFER", DEFAULT_CAPACITY,
                            legacy="DLLAMA_TRACE_CAPACITY")


class Tracer:
    """Lock + ring buffer of completed spans (dicts)."""

    def __init__(self, capacity: int | None = None):
        self._lock = threading.Lock()
        self._spans = deque(maxlen=capacity or _capacity())

    def record(self, name: str, t0: float, t1: float, rid=None,
               **args) -> None:
        """Record a completed span; ``t0``/``t1`` are perf_counter secs.
        ``rid`` overrides the ambient contextvar request ID — threads that
        work on behalf of another request (the scheduler loop) stamp the
        ticket's ID explicitly."""
        th = threading.current_thread()
        span = {"name": name, "ts": t0, "dur": max(t1 - t0, 0.0),
                "tid": th.ident or 0, "thread": th.name,
                "rid": rid if rid is not None else request_id_var.get(),
                "args": args}
        with self._lock:
            self._spans.append(span)

    def resize(self, capacity: int) -> None:
        """Re-bound the ring, keeping the most recent spans that fit."""
        with self._lock:
            self._spans = deque(self._spans, maxlen=max(1, int(capacity)))

    @property
    def capacity(self) -> int:
        return self._spans.maxlen or 0

    @contextmanager
    def span(self, name: str, **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, t0, time.perf_counter(), **args)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def trace_events(self, last_requests: int | None = None) -> list[dict]:
        """Chrome ``trace_event`` array; optionally only the spans of the
        last N distinct request IDs (id-less spans always kept)."""
        spans = self.snapshot()
        if last_requests is not None:
            keep, order = set(), 0
            for s in reversed(spans):
                rid = s["rid"]
                if rid is not None and rid not in keep:
                    if order >= last_requests:
                        continue
                    keep.add(rid)
                    order += 1
            spans = [s for s in spans if s["rid"] is None or s["rid"] in keep]

        tids, names = {}, {}
        for s in spans:
            if s["tid"] not in tids:
                tids[s["tid"]] = len(tids) + 1
                names[s["tid"]] = s["thread"]

        events = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": t,
                   "args": {"name": f"{names[raw]} ({raw})"}}
                  for raw, t in tids.items()]
        for s in spans:
            args = dict(s["args"])
            if s["rid"]:
                args["request_id"] = s["rid"]
            events.append({"name": s["name"], "cat": "dllama", "ph": "X",
                           "ts": round(s["ts"] * 1e6, 3),
                           "dur": round(s["dur"] * 1e6, 3),
                           "pid": 1, "tid": tids[s["tid"]], "args": args})
        return events

    def trace_json(self, last_requests: int | None = None) -> dict:
        return {"traceEvents": self.trace_events(last_requests),
                "displayTimeUnit": "ms"}


#: THE process-global tracer.
TRACER = Tracer()


def record(name: str, t0: float, t1: float, rid=None, **args) -> None:
    TRACER.record(name, t0, t1, rid=rid, **args)


def configure(capacity: int | None = None) -> None:
    """Apply a CLI-chosen capacity (``--trace-buffer``) after import."""
    if capacity is not None:
        TRACER.resize(capacity)


def span(name: str, **args):
    return TRACER.span(name, **args)


def trace_json(last_requests: int | None = None) -> dict:
    return TRACER.trace_json(last_requests)


def clear() -> None:
    TRACER.clear()
