"""Always-on in-process spans in a bounded ring buffer.

The cheap first line of latency attribution: every request records a
handful of spans (request → queue_wait → prefill → decode_chunk → emit →
snapshot) into a fixed-capacity deque — no flags, no files, roughly one
``perf_counter`` pair and a dict per span — and ``GET /debug/trace`` (or
``tools/trace_dump.py``) dumps the recent ones as Chrome ``trace_event``
JSON for ``chrome://tracing`` / Perfetto.  When a span points at a phase
worth dissecting, ``--profile-split`` (runtime/profiling.py) remains the
heavyweight XLA-level tool.

Timestamps are ``time.perf_counter()`` seconds (converted to µs in the
export); they order and measure correctly within one process but are not
wall-clock.  The ``raw()`` export therefore samples ``(perf_now,
wall_now)`` at serve time so a cross-process stitcher (the router's
``/debug/trace?scope=fleet``) can compute a per-replica offset and shift
every ring onto one wall-clock axis.  Capacity comes from
``--trace-buffer`` / ``DLLAMA_TRACE_BUFFER`` (legacy alias
``DLLAMA_TRACE_CAPACITY``; default 8192 spans ≈ a few hundred requests);
a malformed value warns once and falls back, mirroring the
``DLLAMA_Q40_BLOCK_TILES`` contract.

Fleet trace context: ``X-Dllama-Trace`` carries one id for a request's
whole life across router hops and DLREQ01 migrations.  The id rides a
contextvar for the accepting thread plus a bounded rid→trace map
(``set_trace``/``trace_of``) for threads that work on behalf of another
request (the scheduler loop stamps spans with an explicit ``rid``, and
the map resolves those to the trace id without touching call sites).
"""

from __future__ import annotations

import contextvars
import os
import re
import threading
import time
import uuid
from collections import OrderedDict, deque
from contextlib import contextmanager

from .log import get_logger, request_id_var

_log = get_logger("obs.trace")

DEFAULT_CAPACITY = 8192

_warned_specs: set = set()

# ---------------------------------------------------------------------------
# Fleet trace context (X-Dllama-Trace)
# ---------------------------------------------------------------------------

#: header value charset — same shape as request ids so proxies/log greps
#: treat them alike; anything else is stripped at the trust boundary.
_TRACE_RE = re.compile(r"[^A-Za-z0-9._-]")
_TRACE_MAX = 64

#: ambient trace id for the thread/task that accepted the request.
trace_id_var: contextvars.ContextVar = contextvars.ContextVar(
    "dllama_trace_id", default=None)

#: rid → trace id, bounded LRU so abandoned requests can't grow it.
_RID_TRACE_CAP = 4096
_rid_trace: OrderedDict = OrderedDict()
_rid_trace_lock = threading.Lock()


def new_trace_id() -> str:
    """A fresh 32-hex trace id (uuid4, no dashes) — traceparent-sized."""
    return uuid.uuid4().hex


def sanitize_trace_id(raw: str | None) -> str | None:
    """Clamp an untrusted header value to the id charset; None if empty."""
    if not raw:
        return None
    return _TRACE_RE.sub("", raw)[:_TRACE_MAX] or None


def set_trace(rid: str | None, trace_id: str | None) -> None:
    """Associate a request id with a trace id (LRU-bounded)."""
    if not rid or not trace_id:
        return
    with _rid_trace_lock:
        _rid_trace[rid] = trace_id
        _rid_trace.move_to_end(rid)
        while len(_rid_trace) > _RID_TRACE_CAP:
            _rid_trace.popitem(last=False)


def trace_of(rid: str | None) -> str | None:
    """The trace id associated with ``rid`` (or None)."""
    if not rid:
        return None
    with _rid_trace_lock:
        return _rid_trace.get(rid)


def parse_buffer_env(var: str, default: int, legacy: str | None = None) -> int:
    """Ring capacity from ``var`` (falling back to ``legacy``); a value
    that is not a positive integer logs one warning per distinct spec and
    falls back to ``default`` — never raises (the buffer size must not be
    able to take the server down)."""
    spec = os.environ.get(var)
    if spec is None and legacy is not None:
        spec = os.environ.get(legacy)
    if spec is None or spec == "":
        return default
    try:
        cap = int(spec)
        if cap < 1:
            raise ValueError(spec)
        return cap
    except ValueError:
        key = (var, spec)
        if key not in _warned_specs:
            _warned_specs.add(key)
            _log.warning("%s=%r is not a positive integer; using default %d",
                         var, spec, default)
        return default


def _capacity() -> int:
    return parse_buffer_env("DLLAMA_TRACE_BUFFER", DEFAULT_CAPACITY,
                            legacy="DLLAMA_TRACE_CAPACITY")


class Tracer:
    """Lock + ring buffer of completed spans (dicts)."""

    def __init__(self, capacity: int | None = None):
        self._lock = threading.Lock()
        self._spans = deque(maxlen=capacity or _capacity())
        self._seq = 0

    def record(self, name: str, t0: float, t1: float, rid=None,
               **args) -> None:
        """Record a completed span; ``t0``/``t1`` are perf_counter secs.
        ``rid`` overrides the ambient contextvar request ID — threads that
        work on behalf of another request (the scheduler loop) stamp the
        ticket's ID explicitly.  The span's fleet trace id resolves from
        the rid→trace map first, then the ambient contextvar."""
        th = threading.current_thread()
        rid = rid if rid is not None else request_id_var.get()
        trace = trace_of(rid) or trace_id_var.get()
        span = {"name": name, "ts": t0, "dur": max(t1 - t0, 0.0),
                "tid": th.ident or 0, "thread": th.name,
                "rid": rid, "trace": trace, "args": args}
        with self._lock:
            self._seq += 1
            span["seq"] = self._seq
            self._spans.append(span)

    def resize(self, capacity: int) -> None:
        """Re-bound the ring, keeping the most recent spans that fit."""
        with self._lock:
            self._spans = deque(self._spans, maxlen=max(1, int(capacity)))

    @property
    def capacity(self) -> int:
        return self._spans.maxlen or 0

    @contextmanager
    def span(self, name: str, **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, t0, time.perf_counter(), **args)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def raw(self, since: int | None = None) -> dict:
        """Machine-oriented export for incremental polling and fleet
        stitching: spans with their ring sequence numbers (only those
        after ``since`` when given), the cursor to pass next time, and a
        paired ``(perf_now, wall_now)`` clock sample so a cross-process
        consumer can map perf_counter timestamps to wall-clock."""
        with self._lock:
            spans = [dict(s) for s in self._spans
                     if since is None or s.get("seq", 0) > since]
            next_seq = self._seq
        return {"spans": spans, "next_seq": next_seq,
                "capacity": self.capacity,
                "perf_now": time.perf_counter(), "wall_now": time.time()}

    def trace_events(self, last_requests: int | None = None) -> list[dict]:
        """Chrome ``trace_event`` array; optionally only the spans of the
        last N distinct request IDs (id-less spans always kept)."""
        spans = self.snapshot()
        if last_requests is not None:
            keep, order = set(), 0
            for s in reversed(spans):
                rid = s["rid"]
                if rid is not None and rid not in keep:
                    if order >= last_requests:
                        continue
                    keep.add(rid)
                    order += 1
            spans = [s for s in spans if s["rid"] is None or s["rid"] in keep]

        tids, names = {}, {}
        for s in spans:
            if s["tid"] not in tids:
                tids[s["tid"]] = len(tids) + 1
                names[s["tid"]] = s["thread"]

        events = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": t,
                   "args": {"name": f"{names[raw]} ({raw})"}}
                  for raw, t in tids.items()]
        for s in spans:
            args = dict(s["args"])
            if s["rid"]:
                args["request_id"] = s["rid"]
            if s.get("trace"):
                args["trace_id"] = s["trace"]
            events.append({"name": s["name"], "cat": "dllama", "ph": "X",
                           "ts": round(s["ts"] * 1e6, 3),
                           "dur": round(s["dur"] * 1e6, 3),
                           "pid": 1, "tid": tids[s["tid"]], "args": args})
        return events

    def trace_json(self, last_requests: int | None = None) -> dict:
        return {"traceEvents": self.trace_events(last_requests),
                "displayTimeUnit": "ms"}


#: THE process-global tracer.
TRACER = Tracer()


def record(name: str, t0: float, t1: float, rid=None, **args) -> None:
    TRACER.record(name, t0, t1, rid=rid, **args)


def configure(capacity: int | None = None) -> None:
    """Apply a CLI-chosen capacity (``--trace-buffer``) after import."""
    if capacity is not None:
        TRACER.resize(capacity)


def span(name: str, **args):
    return TRACER.span(name, **args)


def trace_json(last_requests: int | None = None) -> dict:
    return TRACER.trace_json(last_requests)


def raw(since: int | None = None) -> dict:
    return TRACER.raw(since)


def clear() -> None:
    TRACER.clear()
