"""Process-global metric registry with dual exposition (JSON + Prometheus).

One :class:`Registry` instance (:data:`REGISTRY`) is the single source of
truth for every counter, gauge, and histogram in the process — the
serving layer's ``ServerMetrics``, ``io/integrity.py``'s verification
counters, and the engine's step-latency histograms all register here.
Two exposition paths read the same registry:

* :func:`snapshot_json` — the ``/metrics`` JSON dict.  Backward
  compatible: every pre-registry key (``requests_served``, ``uptime_s``,
  ``checksum_failures``, ...) keeps its name and flat-int shape, and the
  counters are *seeded at import* so a dashboard never confuses "metric
  missing" with "zero".  Histograms appear as ``{"count", "sum", "avg",
  "buckets": {le: cumulative_count}}`` objects under new keys, plus a
  ``schema_version`` field.  Merging serving and integrity counters
  through one registry also fixes the old ``{**a, **b}`` exposure, where
  a key collision silently dropped a counter — here a name collision is
  a registration-time :class:`ValueError`.
* :func:`render_prometheus` — text exposition format 0.0.4 (``# HELP`` /
  ``# TYPE`` lines; histogram ``_bucket{le=...}`` / ``_sum`` /
  ``_count`` series with cumulative buckets), scrapeable by an
  off-the-shelf Prometheus at ``GET /metrics`` with ``Accept:
  text/plain`` (server/api.py negotiates).

Everything is thread-safe (one small lock per metric; the threaded API
server bumps from request threads while scrapes snapshot concurrently)
and stdlib-only.  See docs/OBSERVABILITY.md for the metric catalog.
"""

from __future__ import annotations

import bisect
import threading
import time

#: bumped when a key changes meaning or shape in the JSON exposition
SCHEMA_VERSION = 2


def _fmt(v: float) -> str:
    """Prometheus-style number rendering: integral values print without a
    trailing ``.0`` (``le="2.5"`` but ``le="1"``), everything else as the
    shortest round-tripping float."""
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class Counter:
    """Monotonically increasing count."""

    kind = "counter"

    def __init__(self, name: str, json_key: str, help: str = ""):
        self.name = name
        self.json_key = json_key
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def json_value(self):
        return self.value

    def render(self, lines: list[str]) -> None:
        if self.help:
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} counter")
        lines.append(f"{self.name} {_fmt(self.value)}")


class Gauge:
    """A value that goes up and down (or is computed at read time via
    ``fn`` — e.g. uptime)."""

    kind = "gauge"

    def __init__(self, name: str, json_key: str, help: str = "", fn=None):
        self.name = name
        self.json_key = json_key
        self.help = help
        self.fn = fn
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        if self.fn is not None:
            return float(self.fn())
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0

    def json_value(self):
        return round(self.value, 6)

    def render(self, lines: list[str]) -> None:
        if self.help:
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} gauge")
        lines.append(f"{self.name} {_fmt(self.value)}")


class LabeledCounter:
    """A counter family: one Prometheus metric name, one sample per label
    value (``dllama_q40_degrade_total{reason="probe_failed"} 2``).  The
    JSON exposition is a dict keyed by the label value (multi-label
    children join their values with ``/``).  Children are created on
    first increment — a scrape between registration and the first event
    sees an empty family, which Prometheus accepts."""

    kind = "counter"

    def __init__(self, name: str, json_key: str, labels, help: str = ""):
        self.name = name
        self.json_key = json_key
        self.help = help
        self.labels = (labels,) if isinstance(labels, str) else tuple(labels)
        self._lock = threading.Lock()
        self._children: dict[tuple, float] = {}

    def inc(self, *values, n: float = 1) -> None:
        if len(values) != len(self.labels):
            raise ValueError(f"{self.name} takes {len(self.labels)} label "
                             f"value(s) {self.labels}, got {values!r}")
        key = tuple(str(v) for v in values)
        with self._lock:
            self._children[key] = self._children.get(key, 0) + n

    def get(self, *values):
        key = tuple(str(v) for v in values)
        with self._lock:
            return self._children.get(key, 0)

    @property
    def total(self):
        with self._lock:
            return sum(self._children.values())

    def reset(self) -> None:
        # test isolation parity with Counter.reset: drop the samples (a
        # zeroed-but-present label would survive into unrelated tests)
        with self._lock:
            self._children.clear()

    def json_value(self):
        with self._lock:
            return {"/".join(k): (v if isinstance(v, int) else round(v, 6))
                    for k, v in sorted(self._children.items())}

    def render(self, lines: list[str]) -> None:
        if self.help:
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} counter")
        with self._lock:
            items = sorted(self._children.items())
        for values, count in items:
            lbl = ",".join(f'{l}="{v}"' for l, v in zip(self.labels, values))
            lines.append(f"{self.name}{{{lbl}}} {_fmt(count)}")


class LabeledGauge:
    """A gauge family (one sample per label-value combination; ``label``
    may be a single label name or a tuple of names).  ``fn`` — when set —
    computes the whole family at read time as a ``{label_value: number}``
    dict (e.g. per-device HBM stats queried at scrape); an empty dict
    means the backend has no data and the family renders no samples
    (graceful absence, never a fake zero)."""

    kind = "gauge"

    def __init__(self, name: str, json_key: str, label, help: str = "",
                 fn=None):
        self.name = name
        self.json_key = json_key
        self.labels = (label,) if isinstance(label, str) else tuple(label)
        self.help = help
        self.fn = fn
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    @property
    def label(self) -> str:  # back-compat for single-label callers
        return self.labels[0]

    def set(self, *args) -> None:
        """``set(label_value, ..., v)`` — the last positional is the value,
        everything before it is one value per label."""
        *values, v = args
        if len(values) != len(self.labels):
            raise ValueError(f"{self.name} takes {len(self.labels)} label "
                             f"value(s) {self.labels}, got {values!r}")
        key = tuple(str(x) for x in values)
        with self._lock:
            self._values[key] = float(v)

    def get(self, *values) -> float:
        key = tuple(str(x) for x in values)
        with self._lock:
            return self._values.get(key, 0.0)

    def _items(self) -> dict[tuple, float]:
        if self.fn is not None:
            try:
                return {(str(k),) if not isinstance(k, tuple)
                        else tuple(str(x) for x in k): float(v)
                        for k, v in (self.fn() or {}).items()}
            except Exception:
                return {}
        with self._lock:
            return dict(self._values)

    def values(self) -> dict:
        """Single-label families keep their historical flat-string keys;
        multi-label families join label values with ``/``."""
        if len(self.labels) == 1:
            return {k[0]: v for k, v in self._items().items()}
        return {"/".join(k): v for k, v in self._items().items()}

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def json_value(self):
        return {k: round(v, 6) for k, v in sorted(self.values().items())}

    def render(self, lines: list[str]) -> None:
        if self.help:
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} gauge")
        for k, v in sorted(self._items().items()):
            lbl = ",".join(f'{l}="{x}"' for l, x in zip(self.labels, k))
            lines.append(f"{self.name}{{{lbl}}} {_fmt(v)}")


class Histogram:
    """Fixed-bucket histogram (Prometheus semantics: cumulative buckets,
    an implicit ``+Inf`` bucket, ``sum`` and ``count`` series).

    Buckets are chosen at registration and never change — fixed buckets
    make ``observe`` an O(log n_buckets) bisect plus two adds under one
    lock, cheap enough for the per-token emit path."""

    kind = "histogram"

    def __init__(self, name: str, json_key: str, buckets, help: str = ""):
        ups = sorted(float(b) for b in buckets)
        if not ups:
            raise ValueError(f"histogram {name!r} needs at least one bucket")
        self.name = name
        self.json_key = json_key
        self.help = help
        self.uppers = tuple(ups)
        self._lock = threading.Lock()
        self._counts = [0] * (len(ups) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.uppers, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        """(cumulative_counts incl. +Inf, sum, count) — one consistent
        view (a concurrent ``observe`` lands wholly before or after)."""
        with self._lock:
            raw = list(self._counts)
            total, count = self._sum, self._count
        cum, acc = [], 0
        for c in raw:
            acc += c
            cum.append(acc)
        return cum, total, count

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self._counts)
            self._sum = 0.0
            self._count = 0

    def json_value(self):
        cum, total, count = self.snapshot()
        labels = [_fmt(u) for u in self.uppers] + ["+Inf"]
        return {"count": count, "sum": round(total, 6),
                "avg": round(total / count, 6) if count else 0.0,
                "buckets": dict(zip(labels, cum))}

    def render(self, lines: list[str]) -> None:
        if self.help:
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} histogram")
        cum, total, count = self.snapshot()
        for upper, c in zip(list(self.uppers) + [float("inf")], cum):
            lines.append(f'{self.name}_bucket{{le="{_fmt(upper)}"}} {c}')
        lines.append(f"{self.name}_sum {_fmt(round(total, 9))}")
        lines.append(f"{self.name}_count {count}")


class Registry:
    """Named metric collection with get-or-create registration.

    ``json_key`` is the flat key in the JSON exposition (the pre-registry
    ``/metrics`` names); the Prometheus ``name`` derives from it
    (``dllama_<key>`` + ``_total`` for counters) unless given explicitly
    — e.g. when the JSON key predates unit-suffix conventions."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_name: dict[str, object] = {}
        self._by_json: dict[str, object] = {}
        self.started_at = time.time()

    def _register(self, cls, json_key: str, name: str | None, args, kwargs):
        name = name or ("dllama_" + json_key
                        + ("_total" if cls.kind == "counter" else ""))
        with self._lock:
            existing = self._by_json.get(json_key) or self._by_name.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {json_key!r} already registered as "
                        f"{existing.kind}, not {cls.kind}")
                return existing
            m = cls(name, json_key, *args, **kwargs)
            self._by_name[name] = m
            self._by_json[json_key] = m
            return m

    def counter(self, json_key: str, help: str = "",
                name: str | None = None) -> Counter:
        return self._register(Counter, json_key, name, (help,), {})

    def gauge(self, json_key: str, help: str = "", name: str | None = None,
              fn=None) -> Gauge:
        return self._register(Gauge, json_key, name, (help,), {"fn": fn})

    def histogram(self, json_key: str, buckets, help: str = "",
                  name: str | None = None) -> Histogram:
        return self._register(Histogram, json_key, name, (buckets, help), {})

    def labeled_counter(self, json_key: str, labels, help: str = "",
                        name: str | None = None) -> LabeledCounter:
        return self._register(LabeledCounter, json_key, name, (labels, help),
                              {})

    def labeled_gauge(self, json_key: str, label, help: str = "",
                      name: str | None = None, fn=None) -> LabeledGauge:
        g = self._register(LabeledGauge, json_key, name, (label, help), {})
        if fn is not None:
            # get-or-create may return an earlier registration; the newest
            # reader wins (an Engine re-init re-binds the device query)
            g.fn = fn
        return g

    def metrics(self) -> list:
        with self._lock:
            return list(self._by_name.values())

    def snapshot_json(self) -> dict:
        out = {"schema_version": SCHEMA_VERSION,
               "uptime_s": round(time.time() - self.started_at, 3)}
        for m in self.metrics():
            out[m.json_key] = m.json_value()
        return out

    def render_prometheus(self) -> str:
        lines = [
            "# HELP dllama_uptime_seconds Seconds since process metrics init.",
            "# TYPE dllama_uptime_seconds gauge",
            f"dllama_uptime_seconds {_fmt(round(time.time() - self.started_at, 3))}",
        ]
        for m in self.metrics():
            m.render(lines)
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every metric (test isolation; registration survives)."""
        for m in self.metrics():
            m.reset()


#: THE process-global registry both exposition paths read.
REGISTRY = Registry()


def snapshot_json() -> dict:
    return REGISTRY.snapshot_json()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


# -- standard buckets ------------------------------------------------------
# Latency buckets span cold-compile tails (a first request on CPU can take
# tens of seconds) down to sub-ms steady-state inter-token gaps.
TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
INTER_TOKEN_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                       0.1, 0.25, 0.5, 1.0, 2.5)
DURATION_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                    10.0, 30.0, 60.0, 120.0, 300.0)
STEP_MS_BUCKETS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                   1000, 2500, 5000)
BYTES_BUCKETS = (256, 1024, 4096, 16384, 65536, 262144,
                 1048576, 4194304, 16777216)


# -- standard metrics, seeded at import ------------------------------------
# Seeding keeps every exported key present from boot (a counter that
# appears only after its first event reads as "metric missing" to a
# dashboard, not "zero") and gives call sites module-level handles with
# no per-call registry lookup.

# serving counters (server/api.py ServerMetrics is a view over these)
REQUESTS_SERVED = REGISTRY.counter(
    "requests_served", "Requests completed successfully.")
REQUESTS_REJECTED_429 = REGISTRY.counter(
    "requests_rejected_429", "Requests rejected by bounded admission.")
REQUESTS_REJECTED_503 = REGISTRY.counter(
    "requests_rejected_503", "Requests rejected while draining.")
READ_TIMEOUTS_408 = REGISTRY.counter(
    "read_timeouts_408", "Request bodies that stalled past --io-timeout.")
DEADLINE_TIMEOUTS = REGISTRY.counter(
    "deadline_timeouts", "Requests truncated by their deadline.")
CLIENT_DISCONNECTS = REGISTRY.counter(
    "client_disconnects", "Clients that vanished mid-request.")
SERVER_ERRORS = REGISTRY.counter(
    "server_errors", "Requests that failed with a 500.")

# artifact-integrity counters (io/integrity.py delegates here)
CHECKSUM_VERIFIED = REGISTRY.counter(
    "checksum_verified", "Artifact regions whose crc32 verified clean.")
CHECKSUM_FAILURES = REGISTRY.counter(
    "checksum_failures", "Artifact regions whose crc32 mismatched.")
NUMERIC_FAULTS = REGISTRY.counter(
    "numeric_faults", "NaN/Inf logits caught by --numeric-checks.")
SNAPSHOT_RESTORES = REGISTRY.counter(
    "snapshot_restores", "Engine warm starts restored from a snapshot.")

# gauges
AVG_REQUEST_S = REGISTRY.gauge(
    "avg_request_s", "EMA request duration (feeds Retry-After).",
    name="dllama_request_duration_ema_seconds")

# request-path histograms (server/api.py)
TTFT = REGISTRY.histogram(
    "ttft_seconds", TTFT_BUCKETS,
    "Time from request admission to the first emitted delta.")
INTER_TOKEN = REGISTRY.histogram(
    "inter_token_seconds", INTER_TOKEN_BUCKETS,
    "Gap between consecutive emitted deltas of one request.")
QUEUE_WAIT = REGISTRY.histogram(
    "queue_wait_seconds", TTFT_BUCKETS,
    "Time an admitted request waited for the engine mutex.")
REQUEST_DURATION = REGISTRY.histogram(
    "request_duration_seconds", DURATION_BUCKETS,
    "Whole-request wall time, admission to completion.")

# engine-step histograms (runtime/engine.py; reference G/I/T contract —
# per-token values, chunk averages for the on-device chunked decode)
ENGINE_GENERATION_MS = REGISTRY.histogram(
    "engine_generation_ms", STEP_MS_BUCKETS,
    "Per-token whole-step wall time (G), milliseconds.")
ENGINE_INFERENCE_MS = REGISTRY.histogram(
    "engine_inference_ms", STEP_MS_BUCKETS,
    "Per-token device execution time (I), milliseconds.")
ENGINE_TRANSFER_MS = REGISTRY.histogram(
    "engine_transfer_ms", STEP_MS_BUCKETS,
    "Per-token host<->device boundary time (T), milliseconds.")
ENGINE_COLLECTIVE_MS = REGISTRY.histogram(
    "engine_collective_ms", STEP_MS_BUCKETS,
    "Measured tp all-reduce latency of a decode-width partial sum "
    "across the engine's mesh (Engine.probe_collective), milliseconds.")
HOST_DEVICE_SENT_BYTES = REGISTRY.histogram(
    "host_device_sent_bytes", BYTES_BUCKETS,
    "Host->device bytes per engine dispatch (tokens + scalars).")
HOST_DEVICE_RECV_BYTES = REGISTRY.histogram(
    "host_device_recv_bytes", BYTES_BUCKETS,
    "Device->host bytes per engine fetch (logits or token ids).")

# kernel-dispatch ledger (obs/dispatch.py; fed from ops/q40.py + ops/q8.py)
MATMUL_DISPATCH = REGISTRY.labeled_counter(
    "matmul_dispatch", ("codec", "path"),
    "Matmul dispatch decisions by codec (q40/q8/dense) and executed path "
    "(pallas-fused, pallas-blocked, xla-dequant, dense).  Counted at "
    "trace time: one bump per compiled call site, not per decode step.")
Q40_DEGRADE = REGISTRY.labeled_counter(
    "q40_degrade", "reason",
    "Q40 dispatches degraded off the fused Pallas path, by reason.")
Q8_DEGRADE = REGISTRY.labeled_counter(
    "q8_degrade", "reason",
    "Q80 dispatches degraded off the fused Pallas path, by reason.")
ATTN_DEGRADE = REGISTRY.labeled_counter(
    "attn_degrade", "reason",
    "Paged-attention dispatches degraded off the fused page-walk Pallas "
    "kernel (ops/attention.py paged-fused), by reason.")

# performance economics (obs/cost.py): the analytic roofline model's
# FLOPs / bytes-moved per dispatch family, per-class chip-time
# attribution, and the MFU/MBU utilization gauges (achieved rate over
# the per-backend peak table).  Bumped by the scheduler at dispatch-land
# time through the ledger seam (dispatch.record_cost).
DISPATCH_FLOPS = REGISTRY.labeled_counter(
    "dispatch_flops", ("codec", "path", "phase"),
    "Model FLOPs per analytic dispatch family: weight codec or KV codec, "
    "cost path (matmul / attention / paged-gather / paged-decode / "
    "paged-fused / tp-ring), and request phase (prefill / decode / "
    "verify).")
DISPATCH_BYTES = REGISTRY.labeled_counter(
    "dispatch_bytes", ("codec", "path", "phase"),
    "Bytes moved per analytic dispatch family (same labels as "
    "dispatch_flops): packed weight reads, KV reads+writes (page-"
    "granular when paged), and TP ring all-reduce hop bytes.")
CLASS_CHIP_MS = REGISTRY.labeled_counter(
    "class_chip_ms", "class",
    "Chip-time attributed to retired+live requests by QoS class "
    "(interactive / standard / batch): each dispatch's wall pro-rated "
    "across its occupied rows — cost-per-tenant as a scrape.")
MFU = REGISTRY.gauge(
    "mfu",
    "Model FLOPs utilization: achieved FLOP/s over dispatch wall divided "
    "by the backend peak (obs/cost.py peak table; CPU measures once).")
MBU = REGISTRY.gauge(
    "mbu",
    "Memory-bandwidth utilization: achieved HBM bytes/s over dispatch "
    "wall divided by the backend peak (TP ring bytes excluded).")

# compile telemetry (runtime/engine.py): bucketed-prefill recompiles vs
# executable-cache hits, and how long each fresh compile stalled the host
COMPILE_S_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                     30.0, 60.0, 120.0)
ENGINE_RECOMPILES = REGISTRY.counter(
    "engine_recompiles",
    "XLA executables built by the engine (new step shape or chunk spec).")
ENGINE_CACHE_HITS = REGISTRY.counter(
    "engine_executable_cache_hits",
    "Engine steps served by an already-compiled executable.")
ENGINE_COMPILE_S = REGISTRY.histogram(
    "engine_compile_seconds", COMPILE_S_BUCKETS,
    "First-call wall time of each fresh engine executable (trace + XLA "
    "compile dominate; includes the first execution's dispatch).")
ENGINE_LIVE_EXECUTABLES = REGISTRY.gauge(
    "engine_live_executables",
    "Compiled executables the live engines currently hold.")

# continuous-batching scheduler (runtime/scheduler.py).  Efficiency is
# set per dispatch: live rows / slots — pad/free rows ride the lockstep
# step for free but represent unsold capacity, which is exactly what this
# gauge makes visible.  The one-shot list-prompt path sets it too (its
# pad rows are the same unsold capacity).
SCHED_SLOTS_OCCUPIED = REGISTRY.gauge(
    "sched_slots_occupied", "Batch slots holding a live request.")
SCHED_QUEUE_DEPTH = REGISTRY.gauge(
    "sched_queue_depth", "Requests admitted but waiting for a free slot.")
SCHED_BATCH_EFFICIENCY = REGISTRY.gauge(
    "sched_batch_efficiency",
    "Live rows per lockstep step / batch slots (last dispatch).")
SCHED_SLOT_JOINS = REGISTRY.labeled_counter(
    "sched_slot_joins", ("slot",),
    "Requests admitted into a batch slot, by slot index.")
SCHED_SLOT_RETIRES = REGISTRY.labeled_counter(
    "sched_slot_retires", ("slot", "reason"),
    "Requests retired from a batch slot, by slot index and reason "
    "(stop/length/timeout/aborted/error/drain).")

# paged KV pool + radix prefix cache (runtime/pagepool.py, driven by the
# scheduler).  Pages bound KV memory by live tokens instead of
# slots × max-seq; prefix hits replace re-prefill with shared pages.
KV_PAGES_TOTAL = REGISTRY.gauge(
    "kv_pages_total",
    "Usable pages in the paged KV pool (page 0, the reserved scratch "
    "page, excluded).")
KV_PAGES_IN_USE = REGISTRY.gauge(
    "kv_pages_in_use",
    "KV pages currently referenced by live slots or the prefix cache.")
PREFIX_HITS = REGISTRY.counter(
    "prefix_hits",
    "Admissions whose prompt matched a cached prefix in the radix tree.")
PREFIX_TOKENS_REUSED = REGISTRY.counter(
    "prefix_tokens_reused",
    "Prompt tokens bound to shared KV pages instead of being "
    "re-prefilled.")
KV_POOL_EXHAUSTED = REGISTRY.counter(
    "kv_pool_exhausted",
    "Admissions deferred because the page pool had no free pages (the "
    "request waits queued until retirements free pages).")

# KV memory tiering (runtime/kvtier.py, --kv-reserve optimistic): under
# pressure a mid-decode grow evicts cold radix entries and spills the
# idle-longest slot's pages to the pinned host-RAM pool; spilled slots
# page back in on demand.  Spill/page-in counters are page-granular; the
# host-pool gauge is the live byte footprint of spilled KV; the codec
# gauge names the active page format (bf16/f32/int8) exactly once.
KV_PAGES_SPILLED = REGISTRY.counter(
    "kv_pages_spilled",
    "KV pages copied device-to-host and freed by the tiering policy "
    "(--kv-reserve optimistic under pool pressure).")
KV_PAGES_PAGED_IN = REGISTRY.counter(
    "kv_pages_paged_in",
    "Spilled KV pages copied back host-to-device when their slot "
    "rejoined the dispatch.")
KV_SPILL_BYTES = REGISTRY.counter(
    "kv_spill_bytes",
    "Bytes of KV page data moved device-to-host by spills (values plus "
    "per-position scale planes for int8 pages).")
KV_HOST_POOL_BYTES = REGISTRY.gauge(
    "kv_host_pool_bytes",
    "Bytes of spilled KV currently resident in the host-RAM pool "
    "(bounded by --kv-host-pool-mb).")
KV_PAGE_CODEC = REGISTRY.labeled_gauge(
    "kv_page_codec", "codec",
    "Active paged-KV page format (1 for the engine's codec: the pool "
    "dtype, e.g. bfloat16, or int8 under --kv-quant int8).")

# device-memory telemetry: per-device HBM gauges.  The reader fn is bound
# by runtime/engine.py at import (jax stays out of the obs package);
# backends without memory_stats (CPU) expose an empty family, not zeros.
HBM_BYTES_IN_USE = REGISTRY.labeled_gauge(
    "hbm_bytes_in_use", "device",
    "Per-device HBM bytes currently allocated (jax memory_stats).")
HBM_BYTES_PEAK = REGISTRY.labeled_gauge(
    "hbm_bytes_peak", "device",
    "Per-device peak HBM bytes allocated since process start.")

# scheduler goodput accounting (runtime/scheduler.py + obs/flight.py):
# every millisecond between the scheduler's first and last dispatch lands
# in exactly one component, so the family sums to the measured wall time.
# prefill/decode/pad split each dispatch by row occupancy; host_gap is
# un-slept time between dispatches (token fanout, admission, array prep);
# idle is time slept waiting for work.
HOST_GAP_MS_BUCKETS = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                       250, 1000)
SCHED_STEP_TIME_MS = REGISTRY.labeled_counter(
    "sched_step_time_ms", ("component",),
    "Scheduler wall-time decomposition in milliseconds, by component "
    "(prefill|decode|pad|host_gap|idle).")
SCHED_GOODPUT_RATIO = REGISTRY.gauge(
    "sched_goodput_ratio",
    "Fraction of scheduler wall time spent on live rows "
    "((prefill+decode) / all components), cumulative since start.")
SCHED_HOST_GAP_MS = REGISTRY.histogram(
    "sched_host_gap_ms", HOST_GAP_MS_BUCKETS,
    "Host-side gap between consecutive scheduler dispatches (ms), "
    "excluding idle sleep — the dispatch overhead ROADMAP item 3 "
    "(on-device multi-step decode) would amortize.")

# overlapped dispatch pipeline (runtime/scheduler.py --sched-overlap):
# dispatch N+1 is enqueued on device while dispatch N's tokens transfer
# and fan out host-side.  Host work the device outlived is HIDDEN (not in
# the goodput components — the device never waited); host work that
# outlived the device stays exposed host_gap.  The ratio/depth gauges
# make the pipeline state observable; a forced flush drives depth to 0.
SCHED_OVERLAP_RATIO = REGISTRY.gauge(
    "sched_overlap_ratio",
    "Fraction of scheduler dispatches enqueued while their predecessor "
    "was still in flight (cumulative since start).")
SCHED_INFLIGHT_DEPTH = REGISTRY.gauge(
    "sched_inflight_depth",
    "Scheduler dispatches enqueued on device but not yet landed "
    "(2 while the pipeline is full, 0 after a flush).")
SCHED_HOST_GAP_HIDDEN_MS = REGISTRY.counter(
    "sched_host_gap_hidden_ms",
    "Host-side dispatch-gap milliseconds hidden behind device execution "
    "by the overlapped pipeline (reported separately, never double-"
    "counted into sched_step_time_ms components).")
SCHED_OVERLAP_DISCARDS = REGISTRY.counter(
    "sched_overlap_discards",
    "Pipelined dispatches landed and thrown away at a pipeline flush "
    "point (admission, retire, cancel/deadline, drain, hand-off export).")

# speculative decoding (runtime/spec.py proposers + the scheduler's
# ragged verify bursts, --spec).  Proposed counts drafts fed into verify
# dispatches; accepted counts the leading drafts the target model's own
# argmax confirmed.  accepted/proposed is the acceptance rate that sets
# the speedup (each accepted draft is one extra token per weight read).
SCHED_SPEC_PROPOSED = REGISTRY.counter(
    "sched_spec_proposed",
    "Draft tokens proposed into slot-verify dispatches (--spec).")
SCHED_SPEC_ACCEPTED = REGISTRY.labeled_counter(
    "sched_spec_accepted", ("proposer",),
    "Proposed draft tokens the verify step accepted, by proposer "
    "(pld / draft).")
SCHED_SPEC_ACCEPT_RATIO = REGISTRY.gauge(
    "sched_spec_accept_ratio",
    "Cumulative accepted/proposed draft-token ratio since start "
    "(0 until the first proposal; collapses toward 0 under a reject "
    "storm while served bytes stay exact).")

# multi-tenant QoS (runtime/scheduler.py preemption + server shedding).
# A higher-priority request that cannot admit evicts the lowest-priority
# longest-remaining slot through the DLREQ01 export path and parks the
# record; the server sheds low-priority admissions while the SLO error
# budget burns.
SCHED_PREEMPTIONS = REGISTRY.labeled_counter(
    "sched_preemptions", ("reason",),
    "Slot preemptions triggered by a higher-priority request, by trigger "
    "(no_free_slot / pool_exhausted).")
SCHED_PREEMPT_PARKED = REGISTRY.gauge(
    "sched_preempt_parked",
    "Preempted requests currently parked as DLREQ01 records awaiting "
    "re-admission (RAM or --preempt-spill-dir).")
ADMISSIONS_SHED = REGISTRY.labeled_counter(
    "admissions_shed", ("class",),
    "Admissions refused (429) by SLO-driven shedding, per priority class "
    "(batch sheds on a fast-window burn, standard only while violating; "
    "interactive is never shed).")

# SLO burn-rate engine (obs/slo.py): burn = observed bad fraction over a
# rolling window / allowed bad fraction; >= 1.0 means the error budget is
# burning faster than the objective permits.
SLO_BURN_RATE = REGISTRY.labeled_gauge(
    "slo_burn_rate", ("objective", "window"),
    "Error-budget burn rate per objective and rolling window "
    "(>= 1.0 means the budget is being spent faster than allowed).")
SLO_VIOLATIONS = REGISTRY.labeled_counter(
    "slo_violations", ("objective",),
    "Transitions of an objective into the violating state (all windows "
    "burning >= 1.0) since process start.")

# per-request KV hand-off (runtime/scheduler.py export/import seam +
# server /admin/export/<rid> and /admin/import).  A draining replica
# exports each active slot as a DLREQ01 record; the router re-binds it
# on a geometry-compatible peer so decode resumes without re-prefill.
HANDOFF_EXPORTS = REGISTRY.counter(
    "handoff_exports",
    "Hand-off records fetched from this replica via /admin/export "
    "(one per drained in-flight request picked up by the router).")
HANDOFF_IMPORTS = REGISTRY.counter(
    "handoff_imports",
    "Hand-off records accepted via /admin/import and resumed in a "
    "local batch slot.")
HANDOFF_IMPORT_REJECTS = REGISTRY.counter(
    "handoff_import_rejects",
    "Hand-off records refused at /admin/import (geometry fingerprint "
    "mismatch or corrupt/invalid record).")

# fleet router (router/ package — a separate process; these families
# are exported by the *router's* /metrics, not a replica's).  Dispatch,
# retry, ejection, and hand-off counters quantify the rolling-restart
# story: a healthy fleet drains with handoffs>0 and replica_lost==0.
ROUTER_DISPATCH = REGISTRY.labeled_counter(
    "router_dispatch", ("backend",),
    "Requests dispatched to each backend replica.")
ROUTER_RETRIES = REGISTRY.counter(
    "router_retries",
    "Requests re-dispatched to another replica after a backend failed "
    "before any response bytes reached the client.")
ROUTER_EJECTIONS = REGISTRY.labeled_counter(
    "router_ejections", ("backend",),
    "Backend transitions into the ejected state (probe/dispatch "
    "failure streak reached the ejection threshold).")
ROUTER_READMITS = REGISTRY.labeled_counter(
    "router_readmits", ("backend",),
    "Ejected backends re-admitted after consecutive successful probes.")
ROUTER_HANDOFFS = REGISTRY.counter(
    "router_handoffs",
    "In-flight requests migrated between replicas via KV hand-off "
    "(export from a draining backend, import on a peer).")
ROUTER_REPLICA_LOST = REGISTRY.counter(
    "router_replica_lost",
    "Streaming requests finished with finish_reason=replica_lost "
    "because their backend died after response bytes were sent.")
ROUTER_BACKEND_LATENCY_S = REGISTRY.labeled_gauge(
    "router_backend_latency_s", ("backend",),
    "EWMA of health-probe round-trip latency per backend, seconds.")
ROUTER_RESUMES = REGISTRY.labeled_counter(
    "router_resumes", ("outcome",),
    "Mid-stream resume attempts after a backend died with bytes "
    "already forwarded, by outcome: checkpoint (resumed from a cached "
    "DLREQ01 checkpoint), rerun (re-dispatched and prefix-verified on "
    "a peer), mismatch (regenerated prefix diverged — honest "
    "replica_lost), no_peer (no healthy peer could take it), failed "
    "(the resume dispatch itself died).")
ROUTER_STALLS = REGISTRY.counter(
    "router_stalls",
    "Streams cut by the router's stall watchdog (--stall-timeout): the "
    "backend was connected but produced no bytes for the window — a "
    "wedged replica treated as dead.")
HANDOFF_EXPIRED = REGISTRY.counter(
    "handoff_expired",
    "Parked DLREQ01 export records dropped unclaimed after "
    "--handoff-ttl (the router that triggered the drain never fetched "
    "them).")
POD_RESPAWNS = REGISTRY.labeled_counter(
    "pod_respawns", ("replica", "reason"),
    "serve-pod supervisor respawns of a replica process, by replica "
    "index and reason (exit = process died, hung = health probes "
    "stalled while the process lived).")
POD_REPLICAS_UP = REGISTRY.gauge(
    "pod_replicas_up",
    "serve-pod supervised replica processes currently alive (a "
    "quarantined crash-looper stays down and is not counted).")
POD_REPLICAS_DESIRED = REGISTRY.gauge(
    "pod_replicas_desired",
    "Elastic pod replica target: what the control loop is converging "
    "toward (desired > up means a scale-up or reshape is in flight).")
POD_SCALE_EVENTS = REGISTRY.labeled_counter(
    "pod_scale_events", ("direction", "reason"),
    "Elastic pod topology actions by direction (up / down / reshape) "
    "and reason (load, idle, kv_pressure, manual, quarantined).")
POD_RESHAPE_SECONDS = REGISTRY.histogram(
    "pod_reshape_seconds", (1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0),
    "Wall time of one live tp reshape, first spawn/retire to "
    "convergence — every in-flight request migrated, all replicas on "
    "the new shape.")

# fleet observability plane (obs/events.py + router federation).  The
# event counter lives in whichever process emitted the event (router
# for spawn/eject/scale, replica for preempt/resume/handoff); the
# fleet_* families live only in the router/pod process, bumped by the
# federating scraper itself.
POD_EVENTS = REGISTRY.labeled_counter(
    "pod_events", ("kind",),
    "Structured events appended to this process's event journal "
    "(/debug/events), by kind: spawn, death, respawn, quarantine, "
    "eject, readmit, retire, scale, reshape, handoff, resume, "
    "preempt.")
FLEET_REPLICA_UP = REGISTRY.labeled_gauge(
    "fleet_replica_up", ("replica",),
    "Federated-scrape reachability per registered replica: 1 = the "
    "last fleet /metrics scrape of this replica succeeded, 0 = it "
    "failed or timed out (the replica is still listed, marked stale, "
    "never silently dropped).")
FLEET_SCRAPE_ERRORS = REGISTRY.labeled_counter(
    "fleet_scrape_errors", ("replica",),
    "Failed or timed-out per-replica scrapes during fleet /metrics "
    "federation, by replica address.")
FLEET_SCRAPE_SECONDS = REGISTRY.histogram(
    "fleet_scrape_seconds", (0.005, 0.02, 0.05, 0.1, 0.25, 1.0, 5.0),
    "Wall time of one whole federated /metrics fan-out (all replicas "
    "scraped concurrently, slowest replica dominates).")
