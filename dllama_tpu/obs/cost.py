"""Analytic roofline cost model for the dispatch ledger.

The ledger (:mod:`.dispatch`) records *which* kernel path every dispatch
took; this module says what each dispatch *cost* — FLOPs and bytes moved
— from nothing but the model config and the dispatch shape, so the
accounting adds zero work to the hot path (no device counters, no
profiler).  The scheduler calls :meth:`CostModel.dispatch_cost` once per
landed dispatch and:

* bumps ``dllama_dispatch_flops_total`` / ``dllama_dispatch_bytes_total``
  ``{codec, path, phase}`` through the ledger seam
  (:func:`.dispatch.record_cost`),
* pro-rates chip-time and FLOPs across the occupied rows into each
  request's flight-record cost block and
  ``dllama_class_chip_ms_total{class}``,
* feeds :data:`TRACKER`, whose achieved FLOP/s / bytes-per-s divided by
  the per-backend peak table give the ``dllama_mfu`` / ``dllama_mbu``
  gauges.

The model is deliberately *simple enough to hand-check* (tests pin it
token by token for the tiny config) and is documented in docs/PERF.md:

* matmul FLOPs: ``2 * tokens * params_touched`` over the seven per-layer
  projections (wq/wk/wv/wo, w1/w2/w3) plus the logits head for every
  sampled/verified position.  Norms, rotary and elementwise work are
  excluded (<<1%).
* attention FLOPs: ``4 * dim * ctx`` per query token per layer (QK^T
  plus the weighted value sum).
* weight bytes: the packed size of every matmul weight — Q40 18 B /
  Q80 34 B per 32-weight block, dense ``itemsize`` per weight — read
  ONCE per forward pass (a decode burst of ``steps`` sequential
  single-token passes reads them ``steps`` times; that is exactly the
  batching-amortization story the roofline exists to show).
* KV bytes: per-position write + context read per layer; the int8 codec
  counts 1 B values plus the per-(head, position) f32 scale planes;
  paged reads round context up to page granularity (pages move whole).
* TP ring bytes: ``2 * (tp-1) * elems * 4`` aggregate hop bytes per
  all-reduce, two all-reduces (o-proj, w2) per layer per token.  Ring
  bytes ride their own ``tp-ring`` ledger path and are *excluded* from
  MBU (interconnect, not HBM).

Import contract: stdlib-only at module import, like every ``obs``
module.  numpy is imported lazily inside the CPU microbenchmark and the
engine adapter, which only run where the runtime already did.
"""

from __future__ import annotations

import os
import threading
import time

# Q40/Q80 packed-block geometry (dllama_tpu.quants; duplicated here as
# plain ints so importing obs never pulls numpy).
_BLOCK = 32
_CODEC_BLOCK_BYTES = {"q40": 18, "q8": 34}

#: per-device peaks, matched by substring of the lowercased jax
#: ``device_kind`` — (dense bf16 FLOP/s, HBM bytes/s).  v2/v3 entries are
#: per *core* (one jax device); v4+ are per chip (megacore).
TPU_PEAKS = (
    ("v6e", 918e12, 1640e9),
    ("trillium", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),
    ("v5 lite", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 61.25e12, 450e9),
    ("v2", 22.5e12, 300e9),
)

_lock = threading.Lock()
_device_kind: str | None = None
_platform: str | None = None
_peaks_cache: dict | None = None
_cpu_measured: tuple[float, float] | None = None


def set_backend(device_kind: str | None, platform: str | None) -> None:
    """Bind the accelerator identity the peak lookup keys on (called by
    the runtime once it knows its devices; obs itself never imports jax).
    """
    global _device_kind, _platform, _peaks_cache
    with _lock:
        _device_kind = device_kind
        _platform = platform
        _peaks_cache = None


def _measure_cpu_peaks() -> tuple[float, float]:
    """Measured-once CPU fallback: a small f32 GEMM for FLOP/s and a big
    array copy for memory bytes/s.  Crude (one shape, one trial kept),
    but it anchors MFU/MBU to *this* host instead of pretending a CPU
    has TPU peaks.  Override with DLLAMA_PEAK_FLOPS / DLLAMA_PEAK_BYTES_S
    when determinism matters (tests do)."""
    global _cpu_measured
    if _cpu_measured is not None:
        return _cpu_measured
    import numpy as np
    n = 384
    a = np.random.default_rng(0).standard_normal((n, n), np.float32)
    b = a.T.copy()
    a @ b  # warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        a @ b
        best = min(best, time.perf_counter() - t0)
    flops = 2 * n ** 3 / max(best, 1e-9)
    buf = np.zeros(32 << 20, np.uint8)
    t0 = time.perf_counter()
    buf.copy()
    dt = max(time.perf_counter() - t0, 1e-9)
    bps = 2.0 * buf.nbytes / dt  # one read + one write stream
    _cpu_measured = (flops, bps)
    return _cpu_measured


def peaks() -> dict:
    """``{"flops", "bytes_per_s", "source", "device"}`` for the bound
    backend — env override first, then the TPU table, then the CPU
    microbenchmark; all-``None`` peaks when nothing matched (gauges stay
    0 rather than lying)."""
    global _peaks_cache
    with _lock:
        if _peaks_cache is not None:
            return _peaks_cache
        kind, platform = _device_kind, _platform
    env_f = os.environ.get("DLLAMA_PEAK_FLOPS")
    env_b = os.environ.get("DLLAMA_PEAK_BYTES_S")
    out = None
    if env_f or env_b:
        out = {"flops": float(env_f) if env_f else None,
               "bytes_per_s": float(env_b) if env_b else None,
               "source": "env", "device": kind or platform}
    elif kind:
        lk = kind.lower()
        for sub, fl, bp in TPU_PEAKS:
            if sub in lk:
                out = {"flops": fl, "bytes_per_s": bp,
                       "source": "table", "device": kind}
                break
    if out is None and platform == "cpu":
        try:
            fl, bp = _measure_cpu_peaks()
            out = {"flops": fl, "bytes_per_s": bp,
                   "source": "measured", "device": kind or "cpu"}
        except Exception:  # numpy missing / sandboxed — stay peakless
            out = None
    if out is None:
        out = {"flops": None, "bytes_per_s": None,
               "source": "none", "device": kind or platform}
    with _lock:
        _peaks_cache = out
    return out


class PerfTracker:
    """Cumulative achieved work over cumulative dispatch wall, the
    denominators MFU/MBU need.  ``wall_ms`` is the full dispatch wall
    (the chip is busy for the whole lockstep step, padding included), so
    padding and short batches show up as lower utilization — which is
    the point."""

    def __init__(self):
        self._lock = threading.Lock()
        self.flops = 0.0
        self.hbm_bytes = 0.0
        self.wall_ms = 0.0

    def note(self, flops: float, hbm_bytes: float, wall_ms: float) -> None:
        with self._lock:
            self.flops += flops
            self.hbm_bytes += hbm_bytes
            self.wall_ms += wall_ms

    def _util(self, achieved: float, peak: float | None) -> float | None:
        with self._lock:
            wall_s = self.wall_ms / 1e3
        if not peak or wall_s <= 0:
            return None
        return achieved / wall_s / peak

    def mfu(self) -> float | None:
        with self._lock:
            f = self.flops
        return self._util(f, peaks()["flops"])

    def mbu(self) -> float | None:
        with self._lock:
            b = self.hbm_bytes
        return self._util(b, peaks()["bytes_per_s"])

    def snapshot(self) -> dict:
        with self._lock:
            out = {"flops_total": self.flops,
                   "hbm_bytes_total": self.hbm_bytes,
                   "chip_wall_ms": round(self.wall_ms, 3)}
        out["mfu"] = self.mfu()
        out["mbu"] = self.mbu()
        return out

    def reset(self) -> None:
        with self._lock:
            self.flops = self.hbm_bytes = 0.0
            self.wall_ms = 0.0


#: process-global tracker behind the dllama_mfu / dllama_mbu gauges
TRACKER = PerfTracker()


def summary() -> dict:
    """The ``/health`` perf block: utilization, cumulative work, and the
    peak table entry it was divided by."""
    out = TRACKER.snapshot()
    out["peaks"] = peaks()
    try:
        from . import metrics as obs_metrics
        out["chip_ms_by_class"] = obs_metrics.CLASS_CHIP_MS.json_value()
    except Exception:
        out["chip_ms_by_class"] = {}
    return out


class CostModel:
    """FLOPs/bytes for one llama-family model at one serving config.

    Pure integer arithmetic per row (the tests hand-count it); only the
    dispatch-level weight-read split across phases divides.  ``rows``
    passed to :meth:`dispatch_cost` are ``(phase, pos, n_new)`` tuples —
    ``phase`` in {"prefill", "decode", "verify"}, ``pos`` the row's cache
    clock at enqueue, ``n_new`` the *useful* tokens it advanced (chunk
    width, burst steps, or 1 + drafts)."""

    def __init__(self, *, dim: int, hidden_dim: int, n_layers: int,
                 n_heads: int, n_kv_heads: int, vocab_size: int,
                 weight_codec: str = "dense", weight_el_bytes: int = 2,
                 kv_codec: str = "kv_f32", kv_el_bytes: int = 4,
                 tp: int = 1, paged: bool = False, page_size: int = 0,
                 n_experts: int = 0, n_active_experts: int = 0,
                 fused: bool = False):
        self.dim = dim
        self.hidden_dim = hidden_dim
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.n_kv_heads = n_kv_heads
        self.vocab_size = vocab_size
        self.head_size = dim // n_heads
        self.kv_dim = self.head_size * n_kv_heads
        self.weight_codec = weight_codec
        self.weight_el_bytes = weight_el_bytes
        self.kv_codec = kv_codec
        self.kv_el_bytes = kv_el_bytes
        self.tp = max(1, int(tp))
        self.paged = paged
        self.page_size = int(page_size or 0)
        #: decode attention runs the fused page-walk Pallas kernel (one
        #: attention-family dispatch; same FLOPs/bytes, different family
        #: so MFU/MBU attribution matches the ledger path)
        self.fused = bool(fused)
        self.moe = n_experts > 0
        self.n_active_experts = n_active_experts

        ffn = 3 * dim * hidden_dim  # w1 + w2 + w3
        if self.moe:
            ffn *= n_active_experts
        attn = 2 * dim * dim + 2 * dim * self.kv_dim  # wq+wo, wk+wv
        #: matmul weights touched per token (logits head separate)
        self.params_per_token = n_layers * (attn + ffn)

    # --- building blocks (all return ints) -------------------------------

    def codec_bytes(self, n_params: int) -> int:
        """Stored bytes of ``n_params`` matmul weights under the weight
        codec (Q40 18 B per 32, Q80 34 B per 32, dense itemsize each)."""
        bb = _CODEC_BLOCK_BYTES.get(self.weight_codec)
        if bb is not None:
            return n_params // _BLOCK * bb
        return n_params * self.weight_el_bytes

    def weight_read_bytes(self) -> int:
        """Bytes to stream the full matmul weight set (incl. the logits
        head) through the chip once — one forward pass."""
        return (self.codec_bytes(self.params_per_token)
                + self.codec_bytes(self.dim * self.vocab_size))

    def matmul_flops(self, tokens: int) -> int:
        return 2 * tokens * self.params_per_token

    def logit_flops(self, n_positions: int) -> int:
        return 2 * n_positions * self.dim * self.vocab_size

    @staticmethod
    def _ctx_sum(pos: int, n_new: int) -> int:
        # sum of context lengths seen by the n_new query tokens:
        # (pos+1) + (pos+2) + ... + (pos+n_new)
        return n_new * pos + n_new * (n_new + 1) // 2

    def attn_flops(self, pos: int, n_new: int) -> int:
        """QK^T + weighted V sum: 4 * dim MACs -> FLOPs per (query,
        context) pair, per layer."""
        return 4 * self.dim * self.n_layers * self._ctx_sum(pos, n_new)

    def kv_pos_bytes(self) -> int:
        """Bytes one (k, v) position occupies in one layer."""
        if self.kv_codec == "kv_int8":
            # 1 B values + per-(head, position) f32 scale planes
            return 2 * (self.kv_dim + 4 * self.n_kv_heads)
        return 2 * self.kv_dim * self.kv_el_bytes

    def kv_write_bytes(self, n_new: int) -> int:
        return n_new * self.n_layers * self.kv_pos_bytes()

    def _read_positions(self, pos: int, n_new: int, burst: bool) -> int:
        def paged_up(c: int) -> int:
            if self.paged and self.page_size:
                return -(-c // self.page_size) * self.page_size
            return c
        if burst:
            # steps sequential single-token passes, each re-reading its
            # full context
            return sum(paged_up(pos + j + 1) for j in range(n_new))
        # one block forward over n_new tokens streams the final context
        return paged_up(pos + n_new)

    def kv_read_bytes(self, pos: int, n_new: int, burst: bool) -> int:
        return (self._read_positions(pos, n_new, burst)
                * self.n_layers * self.kv_pos_bytes())

    def ring_bytes(self, tokens: int) -> int:
        """Aggregate TP ring all-reduce hop bytes: two f32 reduces of
        ``dim`` per layer per token, ``2*(tp-1)`` hop copies per
        element across the ring."""
        if self.tp <= 1:
            return 0
        return tokens * self.n_layers * 2 * (2 * (self.tp - 1)) * self.dim * 4

    # --- per-dispatch assembly -------------------------------------------

    def row_cost(self, phase: str, pos: int, n_new: int) -> dict:
        """One row's own work (weight reads EXCLUDED — they are shared
        per pass and split at dispatch level)."""
        burst = phase == "decode"
        n_logits = 1 if phase == "prefill" else n_new
        flops = (self.matmul_flops(n_new) + self.logit_flops(n_logits)
                 + self.attn_flops(pos, n_new))
        kv = (self.kv_write_bytes(n_new)
              + self.kv_read_bytes(pos, n_new, burst))
        return {"phase": phase, "flops": flops, "kv_bytes": kv,
                "attn_flops": self.attn_flops(pos, n_new),
                "ring_bytes": self.ring_bytes(n_new)}

    def attn_path(self, phase: str) -> str:
        if not self.paged:
            return "attention"
        if phase == "decode":
            return "paged-fused" if self.fused else "paged-decode"
        return "paged-gather"

    def dispatch_cost(self, rows, steps: int = 1) -> dict:
        """Cost of one landed dispatch.

        ``rows``: ``(phase, pos, n_new)`` per occupied row; ``steps``:
        forward passes the dispatch ran (a decode burst re-reads weights
        every pass — callers pass the burst length, 1 otherwise).

        Returns ``{"entries": {(codec, path, phase): {"flops", "bytes"}},
        "per_row": [...], "flops": total, "hbm_bytes": total-minus-ring}``.
        """
        rows = [(p, int(pos), int(n)) for p, pos, n in rows]
        n_rows = max(1, len(rows))
        passes = max(1, int(steps))
        w_read = self.weight_read_bytes() * passes
        entries: dict[tuple, dict] = {}

        def bump(codec, path, phase, flops=0, nbytes=0):
            e = entries.setdefault((codec, path, phase),
                                   {"flops": 0, "bytes": 0})
            e["flops"] += flops
            e["bytes"] += nbytes

        per_row = []
        for phase, pos, n_new in rows:
            rc = self.row_cost(phase, pos, n_new)
            w_share = w_read / n_rows
            bump(self.weight_codec, "matmul", phase,
                 flops=rc["flops"] - rc["attn_flops"], nbytes=w_share)
            bump(self.kv_codec, self.attn_path(phase), phase,
                 flops=rc["attn_flops"], nbytes=rc["kv_bytes"])
            if rc["ring_bytes"]:
                bump(self.weight_codec, "tp-ring", phase,
                     nbytes=rc["ring_bytes"])
            per_row.append({"phase": phase, "flops": rc["flops"],
                            "hbm_bytes": w_share + rc["kv_bytes"]})
        flops = sum(e["flops"] for e in entries.values())
        hbm = sum(e["bytes"] for (c, path, p), e in entries.items()
                  if path != "tp-ring")
        return {"entries": entries, "per_row": per_row,
                "flops": flops, "hbm_bytes": hbm}


def model_from_engine(engine) -> CostModel | None:
    """Build a CostModel from a live engine (weight codec sniffed from
    the placed params, KV codec from the cache planes) and bind the peak
    lookup to its devices.  Returns None rather than raise: cost
    accounting must never take serving down."""
    try:
        cfg = engine.cfg
        codec, el = "dense", 2
        vals = []
        for v in (engine.params or {}).values():
            vals.extend(v if isinstance(v, (list, tuple)) else [v])
        for v in vals:
            m = type(v).__module__ or ""
            if m.endswith(".q40"):
                codec = "q40"
                break
            if m.endswith(".q8"):
                codec = "q8"
                break
        else:
            import numpy as np
            for v in vals:
                if hasattr(v, "dtype") and hasattr(v, "ndim") \
                        and getattr(v, "ndim", 0) >= 2:
                    el = np.dtype(v.dtype).itemsize
                    break
        cache = engine.cache
        if getattr(cache, "quantized", False):
            kv_codec, kv_el = "kv_int8", 1
        else:
            import numpy as np
            kv_el = np.dtype(cache.k.dtype).itemsize
            kv_codec = f"kv_{np.dtype(cache.k.dtype).name}"
        try:
            dev = next(iter(engine.mesh.devices.flat))
            set_backend(getattr(dev, "device_kind", None),
                        getattr(dev, "platform", None))
        except Exception:
            pass
        fused = False
        if engine.paged:
            try:
                # ask the attention ladder what the decode trace will
                # actually pick for this geometry (probe is cached), so
                # cost families track the ledger path
                from ..ops import attention as _attn
                fused, _ = _attn._fused_choice(
                    1, cfg.n_heads, cfg.n_kv_heads,
                    int(getattr(engine, "kv_page_size", 0) or 0),
                    cfg.dim // cfg.n_heads, kv_codec == "kv_int8")
            except Exception:
                fused = False
        return CostModel(
            dim=cfg.dim, hidden_dim=cfg.hidden_dim, n_layers=cfg.n_layers,
            n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads,
            vocab_size=cfg.vocab_size, weight_codec=codec,
            weight_el_bytes=el, kv_codec=kv_codec, kv_el_bytes=kv_el,
            tp=engine.mesh.shape.get("tp", 1), paged=bool(engine.paged),
            page_size=getattr(engine, "kv_page_size", 0) or 0,
            n_experts=getattr(cfg, "n_experts", 0) or 0,
            n_active_experts=getattr(cfg, "n_active_experts", 0) or 0,
            fused=fused)
    except Exception:
        return None


def reset() -> None:
    """Test isolation: clear the tracker and cached backend peaks."""
    global _peaks_cache
    TRACKER.reset()
    with _lock:
        _peaks_cache = None
