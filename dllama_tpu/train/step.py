"""Training step (beyond-reference capability).

The reference is inference-only (SURVEY §0), but the functional forward
pass makes a training step nearly free in JAX: cross-entropy loss +
``jax.grad`` + an optax optimizer, jitted over the same mesh/shardings as
inference.  This is what ``__graft_entry__.dryrun_multichip`` exercises to
prove the multi-chip shardings compile end-to-end (forward *and* backward
collectives).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import optax

from ..models.config import ModelConfig
from ..models.transformer import forward, init_kv_cache


def cross_entropy_loss(params, cfg: ModelConfig, tokens: jax.Array) -> jax.Array:
    """Next-token cross entropy over ``tokens`` (B, T+1)."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    cache = init_kv_cache(cfg, inputs.shape[0], inputs.shape[1])
    logits, _ = forward(params, cfg, inputs, cache, jnp.int32(0))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def make_train_step(cfg: ModelConfig, optimizer: optax.GradientTransformation):
    """Returns ``train_step(params, opt_state, tokens) -> (params, opt_state,
    loss)`` — jit it with the caller's shardings/donations."""

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(cross_entropy_loss)(params, cfg, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step
