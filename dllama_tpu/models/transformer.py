"""Unified transformer forward pass: Llama / Mixtral / Grok-1.

One function serves prefill (T > 1) and decode (T == 1): tokens enter as
``(B, T)``, the KV cache as ``(L, B, Hkv, S, Dh)`` pairs, and ``pos`` is a
traced scalar, so a single compiled program handles every step of
autoregression — the TPU answer to the reference's per-token task-list
execution (`Inference::infer`, tasks.cpp:199-210).

The layer loop is a ``lax.scan`` over layer-stacked weights. Structural
differences between the three reference task graphs
(llama2-tasks.cpp:241-298, grok1-tasks.cpp:275-354, mixtral-tasks.cpp:5-78)
are *static* config properties, so each arch compiles to its own fused
program:

* Llama   — pre-norm residual attention + SwiGLU FFN
* Mixtral — same attention, MoE FFN, rotate-half RoPE
* Grok-1  — embedding ×78.38…, post-sub-block rmsnorms before each residual
            add, MoE with GELU, logits ×0.577…

Tensor-parallel execution needs no code here: weights arrive sharded
(parallel/sharding.py) and XLA inserts the all-reduces the reference
hand-rolls as gather+merge (llama2-tasks.cpp:115-131).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops import q40, q8
from ..ops.attention import (gqa_attention_at, paged_gqa_attention_at,
                             paged_update_kv_rows, paged_write_indices,
                             quantize_kv, slot_gqa_attention_at,
                             update_kv_cache_at, update_kv_cache_rows)
from ..ops.kernels import ACTIVATIONS, apply_rope, rmsnorm, rope_angles, softmax_f32
from ..ops.sp_attention import ring_attention, sp_gqa_attention, sp_update_kv_cache_at
from ..parallel.mesh import get_active_mesh
from .config import ModelConfig
from .params import Params


# Quantized-MoE prefill unrolls the per-expert loop statically up to this
# many experts (schedulable by XLA); larger counts switch to a lax.scan so
# compile time / program size stay O(1) in the expert count (see moe_ffn).
MOE_PREFILL_UNROLL_MAX = 8


class KVCache(NamedTuple):
    k: jax.Array  # (L, B, Hkv, S, Dh) — cfg dtype, or int8 when quantized
    v: jax.Array
    # per-(layer, row, head, position) dequant scales, (L, B, Hkv, S, 1)
    # f32 — present only for the quantized cache.  Kept 5-D (trailing 1)
    # so one NamedSharding broadcast over the cache pytree shards values
    # and scales identically.
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_kv_cache(cfg: ModelConfig, batch: int, seq_len: int | None = None,
                  dtype=None, quant: bool = False) -> KVCache:
    """Preallocated full-length cache (reference: transformer.cpp:280-282).

    The reference holds F32 caches; dtype is configurable here because a
    bf16 cache halves HBM traffic in the decode attention — the main
    bandwidth consumer at long context.  ``quant=True`` goes further
    (beyond reference): int8 values + per-(head, position) f32 scales —
    ~1.97× less cache HBM traffic and residency than bf16 (the ~3%
    overhead is the scales), so max context per chip nearly doubles.
    Quantization happens at cache-write time (update_cache_at); attention
    dequantizes on read (block-wise on the long-context decode path, so
    the HBM read stays int8-sized).
    """
    s = seq_len or cfg.seq_len
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, s, cfg.head_size)
    if quant:
        sshape = shape[:-1] + (1,)
        return KVCache(jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                       jnp.zeros(sshape, jnp.float32),
                       jnp.zeros(sshape, jnp.float32))
    dt = dtype or cfg.dtype
    return KVCache(jnp.zeros(shape, dt), jnp.zeros(shape, dt))


def init_kv_pool(cfg: ModelConfig, n_pages: int, page_size: int,
                 dtype=None, quant: bool = False) -> KVCache:
    """Paged KV pool: the stacked layout with the batch axis generalized
    to physical pages and the sequence axis shrunk to one page —
    ``(L, n_pages, Hkv, page_size, Dh)``.  Axis-for-axis compatible with
    the contiguous cache's sharding spec (pages ride the batch axis, the
    page interior rides the sequence axis).  Page 0 is the reserved
    scratch page (see ops.attention paged section); slots address the
    pool through per-slot page tables, so pool memory is bounded by live
    *tokens*, not slots × max-seq.

    ``quant=True`` (``--kv-quant int8``) stores int8 values plus a
    per-(page, head, position) f32 scale plane ``(L, P, Hkv, ps, 1)`` —
    the page-granular mirror of the contiguous quantized cache's codec
    (same quantize_kv absmax math, same ~2× HBM saving), so a pool page
    is self-describing: values and scales always travel together through
    spills, snapshots and DLREQ01 hand-offs."""
    shape = (cfg.n_layers, n_pages, cfg.n_kv_heads, page_size, cfg.head_size)
    if quant:
        sshape = shape[:-1] + (1,)
        return KVCache(jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                       jnp.zeros(sshape, jnp.float32),
                       jnp.zeros(sshape, jnp.float32))
    dt = dtype or cfg.dtype
    return KVCache(jnp.zeros(shape, dt), jnp.zeros(shape, dt))


def _mm(x, w, cfg: ModelConfig, kind: str | None = None):
    """Matmul that accepts dense arrays or packed Q40 weights.  Weight
    dtype/format is a per-tensor property (the reference likewise
    dispatches per weight dtype, funcs.cpp:414-455).  ``kind`` declares the
    weight's TP slicing ("row"/"col", commands.cpp:8-70) so the fused
    kernel can run per shard on a multi-device mesh (ops/q40.py)."""
    return q40.mm(x, w, impl=cfg.quant_impl, kind=kind).astype(cfg.dtype)


def update_cache_at(cache: KVCache, k_new, v_new, layer, pos) -> KVCache:
    """Write one layer's step KV window into the stacked cache at
    ``(layer, pos)`` — quantizing to int8 + per-position scales first when
    the cache is quantized (see init_kv_cache)."""
    if not cache.quantized:
        ck, cv = update_kv_cache_at(cache.k, cache.v, k_new, v_new, layer, pos)
        return KVCache(ck, cv)
    qk, sk = quantize_kv(k_new)
    qv, sv = quantize_kv(v_new)
    zero = jnp.zeros((), layer.dtype)
    idx = (layer, zero, zero, pos.astype(layer.dtype), zero)
    return KVCache(
        jax.lax.dynamic_update_slice(cache.k, qk[None], idx),
        jax.lax.dynamic_update_slice(cache.v, qv[None], idx),
        jax.lax.dynamic_update_slice(cache.k_scale, sk[None], idx),
        jax.lax.dynamic_update_slice(cache.v_scale, sv[None], idx))


def _attention_block(x, lp, cfg: ModelConfig, cache: KVCache, cos, sin, pos,
                     layer, offsets=None, pos_rows=None, paged=None):
    """One attention sub-block.  ``cache`` holds the *stacked*
    (L, B, Hkv, S, Dh) buffers carried through the layer scan; this layer
    writes its (B, Hkv, T, Dh) step window in place at ``(layer, pos)`` and
    reads back only its own layer slice for attention (see
    ops.attention.update_kv_cache_at for the cost model)."""
    b, t, d = x.shape
    hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_size

    xb = rmsnorm(x, lp["rms_att"])
    if "wqkv" in lp:  # fused projection (quantized load): one kernel launch
        qkv = _mm(xb, lp["wqkv"], cfg)
        q, k, v = jnp.split(qkv, [hq * dh, (hq + hkv) * dh], axis=-1)
    else:
        q = _mm(xb, lp["wq"], cfg, kind="row")
        k = _mm(xb, lp["wk"], cfg, kind="row")
        v = _mm(xb, lp["wv"], cfg, kind="row")
    q = q.reshape(b, t, hq, dh)
    k = k.reshape(b, t, hkv, dh)
    v = v.reshape(b, t, hkv, dh)

    q = apply_rope(q, cos, sin, interleaved=cfg.rope_interleaved)
    k = apply_rope(k, cos, sin, interleaved=cfg.rope_interleaved)

    q = q.transpose(0, 2, 1, 3)  # (B, Hq, T, Dh)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    mesh = get_active_mesh()
    sp_on = mesh is not None and mesh.shape.get("sp", 1) > 1
    ring = sp_on and cfg.ring_prefill and t > 1
    if pos_rows is not None:
        # continuous-batching slots: per-row write positions and per-row
        # causal ceilings (sp meshes and quantized caches are gated off
        # the slot path at the engine boundary)
        if paged is not None:
            # paged pool: same slot semantics, reads/writes indirected
            # through the page table (write indices precomputed once in
            # forward_slots — identical for every layer)
            page_table, pidx, oidx = paged
            if cache.quantized:
                # int8 pages: quantize the step window once, scatter
                # values and per-position scales through the same write
                # indices, and let attention dequantize on read
                qk, sk = quantize_kv(k)
                qv, sv = quantize_kv(v)
                ck, cv = paged_update_kv_rows(cache.k, cache.v, qk, qv,
                                              layer, pidx, oidx)
                csk, csv = paged_update_kv_rows(cache.k_scale, cache.v_scale,
                                                sk, sv, layer, pidx, oidx)
                cache = KVCache(ck, cv, csk, csv)
                att = paged_gqa_attention_at(
                    q, cache.k, cache.v, layer, page_table, pos_rows,
                    scales=(cache.k_scale, cache.v_scale))
            else:
                ck, cv = paged_update_kv_rows(cache.k, cache.v, k, v, layer,
                                              pidx, oidx)
                cache = KVCache(ck, cv)
                att = paged_gqa_attention_at(q, cache.k, cache.v, layer,
                                             page_table, pos_rows)
        else:
            ck, cv = update_kv_cache_rows(cache.k, cache.v, k, v, layer,
                                          pos_rows)
            cache = KVCache(ck, cv)
            att = slot_gqa_attention_at(q, cache.k, cache.v, layer, pos_rows)
        att = att.transpose(0, 2, 1, 3).reshape(b, t, hq * dh)
        return _mm(att, lp["wo"], cfg, kind="col"), cache
    if t == 1 and sp_on:
        # seq-sharded cache: explicit shard-local write (no GSPMD-chosen
        # gather/scatter per decode step); quantized caches are gated off
        # sp meshes at the engine boundary
        ck, cv = sp_update_kv_cache_at(cache.k, cache.v, k, v, layer, pos, mesh)
        cache = KVCache(ck, cv)
    else:
        cache = update_cache_at(cache, k, v, layer, pos)
    if sp_on:
        # ragged batches are gated off sp meshes at the engine boundary
        # (Engine.generate_batch raises), so offsets is always None here
        if ring:
            # from-scratch prefill: the fresh block IS the whole history
            # (engine gates this on pos==0), so attend blockwise over the
            # sequence-sharded q/k/v ring — no cache read, O(T/sp) memory
            att = ring_attention(q, k, v, mesh, pos0=pos)
        else:
            # sequence-parallel decode / continuation: seq-sharded cache,
            # one-round distributed softmax combine; the layer is sliced
            # inside the shard body (see sp_gqa_attention)
            att = sp_gqa_attention(q, cache.k, cache.v, pos, t, mesh, layer=layer)
    else:
        att = gqa_attention_at(
            q, cache.k, cache.v, layer, pos, t, start=offsets,
            scales=((cache.k_scale, cache.v_scale) if cache.quantized else None))
    att = att.transpose(0, 2, 1, 3).reshape(b, t, hq * dh)
    out = _mm(att, lp["wo"], cfg, kind="col")  # col-sharded: partial sums all-reduced here
    return out, cache


def _dense_ffn(xb, lp, cfg: ModelConfig):
    act = ACTIVATIONS[cfg.hidden_act]
    if "w13" in lp:  # fused gate+up (quantized load)
        h13 = _mm(xb, lp["w13"], cfg)
        h1, h3 = jnp.split(h13, 2, axis=-1)
        h = act(h1) * h3
    else:
        h = act(_mm(xb, lp["w1"], cfg, kind="row")) * _mm(xb, lp["w3"], cfg, kind="row")
    return _mm(h, lp["w2"], cfg, kind="col")


def moe_ffn(xb2d: jax.Array, lp, cfg: ModelConfig) -> jax.Array:
    """Mixture-of-experts FFN (grok1-tasks.cpp:56-228 semantics).

    Routing: softmax over *all* expert logits, top-k, renormalize the
    selected probabilities (grokMoeRouterSoftmax/Topk/NormWeights,
    grok1-tasks.cpp:60-114).

    Two execution strategies, chosen statically by token count:
    * decode (few tokens): compute only the k selected experts — with
      packed-Q40 experts each (token, k) pair runs the fused dequant-
      matmul on a ``QLayerView`` whose flat index selects the expert, so
      HBM reads are bounded by the k active experts' *packed* bytes
      (the reference likewise keeps MoE Q40 end-to-end,
      transformer.cpp:299-317); dense experts use a gather + einsum.
    * prefill (many tokens): run every expert and mask — regular shapes
      on the MXU; quantized experts unroll a static expert loop so only
      one expert's weights are dequantized at a time.

    Experts are TP-sliced like the reference (all experts on all shards,
    hidden dim sharded — transformer.cpp:299-317).  Under an ``ep`` mesh
    axis the expert stacks additionally shard over experts — dense via the
    PartitionSpecs (GSPMD inserts the gather), packed Q40 via the fused
    kernel's per-shard flat-index decode + psum (q40._sharded_matmul_ep) —
    so MoE weight residency scales 1/ep in both layouts.
    """
    n, d = xb2d.shape
    e, k = cfg.n_experts, cfg.n_active_experts
    act = ACTIVATIONS[cfg.hidden_act]

    router = lp["router"]
    router_logits = xb2d.astype(jnp.float32) @ router.astype(jnp.float32)  # (N, E)
    probs = softmax_f32(router_logits)
    top_vals, top_idx = jax.lax.top_k(probs, k)  # (N, k)
    weights = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)

    quant = isinstance(lp["up"], (q40.QTensor, q40.QLayerView))

    if n <= 4 and quant:
        # decode, packed experts: per-(token, slot) fused matmuls on the
        # selected expert's packed planes
        outs = []
        for i in range(n):
            xi = xb2d[i:i + 1]
            acc = jnp.zeros((1, d), jnp.float32)
            for j in range(k):
                sel = top_idx[i, j]
                up = lp["up"].select(sel, e)
                gate = lp["gate"].select(sel, e)
                down = lp["down"].select(sel, e)
                h = act(_mm(xi, gate, cfg, kind="row")) * _mm(xi, up, cfg, kind="row")
                o = q40.mm(h, down, impl=cfg.quant_impl, kind="col",
                           out_dtype=jnp.float32)
                acc = acc + weights[i, j] * o
            outs.append(acc)
        return jnp.concatenate(outs, 0).astype(cfg.dtype)

    if n <= 4 and not quant:  # decode path: gather selected experts' weights
        up_w = jnp.take(lp["up"], top_idx, axis=0)      # (N, k, D, F)
        gate_w = jnp.take(lp["gate"], top_idx, axis=0)  # (N, k, D, F)
        down_w = jnp.take(lp["down"], top_idx, axis=0)  # (N, k, F, D)
        h = act(jnp.einsum("nd,nkdf->nkf", xb2d, gate_w)) * jnp.einsum("nd,nkdf->nkf", xb2d, up_w)
        out = jnp.einsum("nkf,nkfd->nkd", h, down_w)
        return jnp.einsum("nk,nkd->nd", weights.astype(out.dtype), out)

    dense_w = jnp.zeros((n, e), weights.dtype)
    dense_w = jnp.put_along_axis(dense_w, top_idx, weights, axis=-1, inplace=False)

    if quant:
        # prefill, packed experts: one expert dequantized at a time with a
        # masked accumulate.  Up to MOE_PREFILL_UNROLL_MAX experts the loop
        # is a static unroll (XLA can interleave/schedule the per-expert
        # kernels freely — the right trade for 8-expert Mixtral/Grok-1);
        # past it, a lax.scan with a *traced* expert index bounds compile
        # time and program size at O(1) in E (VERDICT r04 Weak #3: the
        # unconditional unroll scaled both linearly, which would not
        # survive a 64-expert model).  Both paths run the same per-expert
        # math; the scan's QLayerView.select simply gets a traced index —
        # exactly how the decode path already selects experts.
        def one_expert(ei):
            up = lp["up"].select(ei, e)
            gate = lp["gate"].select(ei, e)
            down = lp["down"].select(ei, e)
            h = act(_mm(xb2d, gate, cfg, kind="row")) * _mm(xb2d, up, cfg, kind="row")
            return q40.mm(h, down, impl=cfg.quant_impl, kind="col",
                          out_dtype=jnp.float32)

        if e <= MOE_PREFILL_UNROLL_MAX:
            out = jnp.zeros((n, d), jnp.float32)
            for ei in range(e):
                oe = one_expert(jnp.int32(ei))
                out = out + dense_w[:, ei:ei + 1].astype(jnp.float32) * oe
        else:
            def body(acc, ei):
                w_e = jax.lax.dynamic_slice_in_dim(dense_w, ei, 1, axis=1)
                return acc + w_e.astype(jnp.float32) * one_expert(ei), None

            out, _ = jax.lax.scan(body, jnp.zeros((n, d), jnp.float32),
                                  jnp.arange(e, dtype=jnp.int32))
        return out.astype(cfg.dtype)

    # prefill path: dense dispatch over all experts
    h = act(jnp.einsum("nd,edf->nef", xb2d, lp["gate"])) * jnp.einsum("nd,edf->nef", xb2d, lp["up"])
    outs = jnp.einsum("nef,efd->ned", h, lp["down"])
    return jnp.einsum("ne,ned->nd", dense_w.astype(outs.dtype), outs)


def run_blocks(params: Params, cfg: ModelConfig, tokens: jax.Array,
               cache: KVCache, pos: jax.Array,
               offsets: jax.Array | None = None,
               pos_rows: jax.Array | None = None,
               paged=None) -> tuple[jax.Array, KVCache]:
    """Embed + all transformer blocks; returns the residual stream (B, T, D)
    and the updated cache.

    ``offsets`` (B,) enables ragged batches of *distinct* streams via left
    padding (beyond reference — the reference fixes batch=1,
    tasks.cpp:199-210): row ``r``'s prompt is right-aligned so every row
    ends at the same cache slot, its real tokens live at cache positions
    ``offsets[r]..``, and its RoPE positions are the cache position minus
    the offset — each stream sees exactly the angles and keys it would see
    decoding alone, so batched greedy output matches the single-stream
    run token for token."""
    b, t = tokens.shape
    x = jnp.take(params["embedding"], tokens, axis=0).astype(cfg.dtype)
    if cfg.embedding_scale != 1.0:
        x = x * jnp.asarray(cfg.embedding_scale, cfg.dtype)

    positions = pos + jnp.arange(t)
    if pos_rows is not None:
        # continuous-batching slots: every row has its own clock, and slot
        # requests always start at cache position 0, so cache position ==
        # logical RoPE position (no offset subtraction)
        positions = pos_rows[:, None] + jnp.arange(t)[None, :]
    elif offsets is not None:
        # per-row logical positions; pad slots clamp to 0 (their k/q values
        # are garbage either way and masked out of every live row's view)
        positions = jnp.maximum(positions[None, :] - offsets[:, None], 0)
    cos, sin = rope_angles(positions, cfg.head_size, cfg.rope_theta)  # (T, Dh/2)

    layer_keys = [k for k in params if k not in ("embedding", "rms_final", "wcls")]
    # Packed-Q40 weights stay out of the scan's xs: the scan would slice a
    # per-layer copy of the stacked HBM buffer every step; instead the body
    # gets a QLayerView and the fused kernel indexes the stacked buffer
    # directly (scalar-prefetch index_map, ops/q40.py).
    qt_keys = [k for k in layer_keys
               if isinstance(params[k], (q40.QTensor, q40.BlockedQTensor,
                                         q8.Q8Tensor))]
    stacked = {k: params[k] for k in layer_keys if k not in qt_keys}

    def block(carry, layer):
        x, kvc = carry
        idx, lp = layer
        lp = dict(lp)
        for k in qt_keys:
            lp[k] = q40.QLayerView(params[k], idx)
        att_out, kvc = _attention_block(x, lp, cfg, kvc, cos, sin, pos,
                                        idx, offsets=offsets,
                                        pos_rows=pos_rows, paged=paged)
        if cfg.post_block_norms:
            att_out = rmsnorm(att_out, lp["rms_ffn"])  # grokRmfFfnNorm
        x = x + att_out

        if cfg.is_moe:
            pre = lp["rms_moe"] if cfg.post_block_norms else lp["rms_ffn"]
            xb = rmsnorm(x, pre)
            ff = moe_ffn(xb.reshape(b * t, cfg.dim), lp, cfg).reshape(b, t, cfg.dim)
            if cfg.post_block_norms:
                ff = rmsnorm(ff, lp["rms_ffn2"])  # grokMoeRmsNormFinal
        else:
            xb = rmsnorm(x, lp["rms_ffn"])
            ff = _dense_ffn(xb, lp, cfg)
        x = x + ff
        return (x, kvc), None

    # The stacked caches are scan *carries*, not xs/ys: each layer touches
    # only its own (layer, pos) window in place.  Routing them through
    # xs/ys makes XLA slice out and restack a full layer slab per step and
    # defensively copy the whole cache in the enclosing decode loop —
    # measured ~8 ms/token at 7B/1k, comparable to all the matmuls.
    (x, cache), _ = jax.lax.scan(
        block, (x, cache), (jnp.arange(cfg.n_layers), stacked))
    return x, cache


def _head(params: Params, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    x = rmsnorm(x, params["rms_final"])
    # out_dtype=f32 keeps the matmul's f32 accumulation for the sampler
    # instead of a round trip through the bf16 activation dtype
    logits = q40.mm(x, params["wcls"], impl=cfg.quant_impl, out_dtype=jnp.float32,
                    kind="row")
    if cfg.logit_scale != 1.0:
        logits = logits * cfg.logit_scale
    return logits


def forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
            cache: KVCache, pos: jax.Array,
            offsets: jax.Array | None = None) -> tuple[jax.Array, KVCache]:
    """Run the model over ``tokens`` (B, T) starting at position ``pos``.

    Returns logits (B, T, V) in f32 and the updated cache.
    """
    x, cache = run_blocks(params, cfg, tokens, cache, pos, offsets=offsets)
    return _head(params, cfg, x), cache


def forward_last(params: Params, cfg: ModelConfig, tokens: jax.Array,
                 cache: KVCache, pos: jax.Array, last_index: jax.Array,
                 offsets: jax.Array | None = None
                 ) -> tuple[jax.Array, KVCache]:
    """Like :func:`forward` but applies the LM head only at ``last_index``,
    returning (B, V) — avoids materializing (T, V) logits during prefill
    when only the next-token distribution is needed.  With left-padded
    ragged batches (``offsets``) every row's genuine last token sits at
    the same final index, so the shared ``last_index`` needs no per-row
    variant."""
    x, cache = run_blocks(params, cfg, tokens, cache, pos, offsets=offsets)
    x_last = jax.lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)[:, 0]  # (B, D)
    return _head(params, cfg, x_last), cache


def forward_slots(params: Params, cfg: ModelConfig, tokens: jax.Array,
                  cache: KVCache, pos_rows: jax.Array, n_valid: jax.Array,
                  page_table: jax.Array | None = None
                  ) -> tuple[jax.Array, KVCache]:
    """Continuous-batching slot step: run ``tokens`` (B, T) where row ``r``
    occupies cache positions ``pos_rows[r]..pos_rows[r]+T-1`` and only its
    first ``n_valid[r]`` tokens are real.  Returns the logits at each
    row's last *valid* token (B, V) and the updated cache.

    This is what lets a joining request prefill while its neighbors keep
    decoding: a prefilling slot feeds a prompt chunk (``n_valid`` = chunk
    length), a decoding slot feeds its previous sample plus padding
    (``n_valid`` = 1), and a free slot rides along at position 0.  Rows
    never see each other (attention masks per row, everything else is
    row-local), so each slot's stream is bit-identical to decoding alone.
    Garbage written above a row's ``n_valid`` window lands at positions
    the row has not reached yet — masked by its causal ceiling until the
    real tokens overwrite them (see ops.attention.slot_gqa_attention_at).

    With ``page_table`` (B, max_pages) the cache is a paged pool
    (:func:`init_kv_pool`) and every read/write is indirected through the
    table; logical semantics — positions, ceilings, RoPE clocks — are
    unchanged, which is what makes paged greedy output byte-identical to
    the contiguous layout.  Invalid-token writes are redirected to the
    scratch page instead of landing above the ceiling.
    """
    t = tokens.shape[1]
    paged = None
    if page_table is not None:
        ps = cache.k.shape[3]
        pidx, oidx = paged_write_indices(page_table, pos_rows, n_valid, t, ps)
        paged = (page_table, pidx, oidx)
    x, cache = run_blocks(params, cfg, tokens, cache, jnp.int32(0),
                          pos_rows=pos_rows, paged=paged)
    idx = jnp.clip(n_valid - 1, 0, t - 1)
    x_last = jax.vmap(
        lambda row, i: jax.lax.dynamic_index_in_dim(row, i, 0, keepdims=False)
    )(x, idx)  # (B, D): per-row last-valid gather
    return _head(params, cfg, x_last), cache


def forward_slots_all(params: Params, cfg: ModelConfig, tokens: jax.Array,
                      cache: KVCache, pos_rows: jax.Array, n_valid: jax.Array,
                      page_table: jax.Array | None = None
                      ) -> tuple[jax.Array, KVCache]:
    """:func:`forward_slots` keeping EVERY position's logits (B, T, V)
    instead of the per-row last-valid gather — the slot-verify forward.
    Position ``j`` of row ``r`` is the model's next-token distribution
    after consuming ``tokens[r, :j+1]``, which is exactly what acceptance
    of a K-token proposal window needs (decode_loop.slot_verify_chunk).
    T is small (spec_k + 1), so the (B, T, V) buffer stays modest; the
    KV write/mask semantics — including stale writes above a row's
    ``n_valid`` landing beyond its causal ceiling (or in the scratch
    page when paged) — are identical to :func:`forward_slots`."""
    t = tokens.shape[1]
    paged = None
    if page_table is not None:
        ps = cache.k.shape[3]
        pidx, oidx = paged_write_indices(page_table, pos_rows, n_valid, t, ps)
        paged = (page_table, pidx, oidx)
    x, cache = run_blocks(params, cfg, tokens, cache, jnp.int32(0),
                          pos_rows=pos_rows, paged=paged)
    return _head(params, cfg, x), cache
