"""Model configuration.

Bridges the on-disk ``ModelSpec`` (`.m` header, transformer.cpp:12-125) to
the runtime: adds compute dtype and derives the per-arch structural flags
that the reference encodes as three separate hand-built task lists
(`buildLlamaArch` llama2-tasks.cpp:241-298, `buildGrok1Arch`
grok1-tasks.cpp:275-354, `buildMixtralArch` mixtral-tasks.cpp:5-78).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax.numpy as jnp

from ..io import mfile

# Grok-1 scaling constants (grok1-tasks.cpp:13, :272)
GROK_EMBEDDING_SCALE = 78.38367176906169
GROK_LOGIT_SCALE = 0.5773502691896257


@dataclass(frozen=True)
class ModelConfig:
    arch: int
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    n_experts: int
    n_active_experts: int
    vocab_size: int
    seq_len: int
    hidden_act: int
    rope_theta: float
    dtype: jnp.dtype = jnp.float32
    # matmul implementation for Q40-quantized weights: "pallas" (fused
    # kernel, single-chip), "xla" (partitionable emulation, used under TP
    # sharding and on CPU), or "auto" (pallas on TPU for decode-sized
    # inputs, xla otherwise).  Static so each choice compiles its own
    # program.
    quant_impl: str = "auto"
    # static flag set by the engine for a from-scratch prefill on an sp>1
    # mesh: attention runs blockwise ring attention over the fresh
    # sequence-sharded q/k/v (ops/sp_attention.py) instead of the
    # cache-reading one-round combine — O(T/sp) activation memory
    ring_prefill: bool = False

    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    @property
    def kv_dim(self) -> int:
        return self.head_size * self.n_kv_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def rope_interleaved(self) -> bool:
        """Llama uses adjacent-pair RoPE; Grok-1/Mixtral use the rotate-half
        ("Falcon") convention (transformer.cpp:227-231)."""
        return self.arch == mfile.ARCH_LLAMA

    @property
    def add_bos(self) -> bool:
        """Whether prompts get a BOS token (reference: dllama.cpp:27 —
        Grok-1 prompts are encoded without BOS; chat mode always adds it)."""
        return self.arch != mfile.ARCH_GROK1

    @property
    def embedding_scale(self) -> float:
        return GROK_EMBEDDING_SCALE if self.arch == mfile.ARCH_GROK1 else 1.0

    @property
    def logit_scale(self) -> float:
        return GROK_LOGIT_SCALE if self.arch == mfile.ARCH_GROK1 else 1.0

    @property
    def post_block_norms(self) -> bool:
        """Grok-1 normalizes each sub-block's *output* before the residual
        add (grokRmfFfnNorm / grokMoeRmsNormFinal, grok1-tasks.cpp:16-41,
        :245-263); Llama/Mixtral add raw outputs to the residual."""
        return self.arch == mfile.ARCH_GROK1

    @classmethod
    def from_spec(cls, spec: mfile.ModelSpec, dtype=jnp.float32) -> "ModelConfig":
        return cls(
            arch=spec.arch, dim=spec.dim, hidden_dim=spec.hidden_dim,
            n_layers=spec.n_layers, n_heads=spec.n_heads,
            n_kv_heads=spec.n_kv_heads, n_experts=spec.n_experts,
            n_active_experts=spec.n_active_experts, vocab_size=spec.vocab_size,
            seq_len=spec.seq_len, hidden_act=spec.hidden_act,
            rope_theta=spec.rope_theta, dtype=dtype)

    def with_(self, **kw) -> "ModelConfig":
        return replace(self, **kw)


def tiny_config(arch=mfile.ARCH_LLAMA, *, dim=64, hidden_dim=96, n_layers=2,
                n_heads=4, n_kv_heads=2, n_experts=0, n_active_experts=0,
                vocab_size=128, seq_len=64, hidden_act=mfile.ACT_SILU,
                rope_theta=10000.0, dtype=jnp.float32) -> ModelConfig:
    """Small config for tests — the analogue of the reference's hand-sized
    test fixtures (llama2-tasks-test.cpp:528-554)."""
    return ModelConfig(arch=arch, dim=dim, hidden_dim=hidden_dim,
                       n_layers=n_layers, n_heads=n_heads, n_kv_heads=n_kv_heads,
                       n_experts=n_experts, n_active_experts=n_active_experts,
                       vocab_size=vocab_size, seq_len=seq_len,
                       hidden_act=hidden_act, rope_theta=rope_theta, dtype=dtype)
