"""Parameter pytrees: random init (tests) and `.m`-file loading.

Weights are stored **input-dim-first** (``x @ w``) and **layer-stacked**
(leading ``n_layers`` axis) so the whole transformer body runs as one
``lax.scan`` — one compiled block program regardless of depth, instead of
the reference's 25·nLayers-entry static task list (tasks.cpp:36-42).

The `.m` file stores each matmul row-major ``(d_out, n_in)``
(transformer.cpp:428-487 walk order); the loader dequantizes and transposes
once on host.  Sharding happens at device placement (parallel/sharding.py),
which replaces the reference's ``splitWeights`` + socket streaming
(transformer.cpp:389-404).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..io import mfile
from ..ops import q40, q8
from .config import ModelConfig

Params = dict  # pytree: str -> jnp.ndarray | q40.QTensor


def param_shapes(cfg: ModelConfig) -> dict[str, tuple[int, ...]]:
    L, D, F, V = cfg.n_layers, cfg.dim, cfg.hidden_dim, cfg.vocab_size
    Hq = cfg.n_heads * cfg.head_size       # == D
    Hkv = cfg.n_kv_heads * cfg.head_size   # == kv_dim
    E = cfg.n_experts
    shapes = {
        "embedding": (V, D),
        "wq": (L, D, Hq),
        "wk": (L, D, Hkv),
        "wv": (L, D, Hkv),
        "wo": (L, Hq, D),
        "rms_att": (L, D),
        "rms_ffn": (L, D),
        "rms_final": (D,),
        "wcls": (D, V),
    }
    if cfg.is_moe:
        shapes.update({
            "router": (L, D, E),
            "up": (L, E, D, F),
            "gate": (L, E, D, F),
            "down": (L, E, F, D),
        })
        if cfg.post_block_norms:  # Grok-1 extra norms
            shapes.update({"rms_moe": (L, D), "rms_ffn2": (L, D)})
    else:
        shapes.update({"w1": (L, D, F), "w2": (L, F, D), "w3": (L, D, F)})
    return shapes


def init_params(cfg: ModelConfig, seed: int = 0, scale: float = 0.02) -> Params:
    """Deterministic random params — the analogue of the reference's xorshift
    weight fixtures (llama2-tasks-test.cpp:556-562)."""
    rng = np.random.RandomState(seed)
    params: Params = {}
    for name, shape in param_shapes(cfg).items():
        if name.startswith("rms"):
            x = np.ones(shape, dtype=np.float32)
        else:
            x = (rng.standard_normal(shape) * scale).astype(np.float32)
        params[name] = jnp.asarray(x, dtype=jnp.float32 if name.startswith("rms") else cfg.dtype)
    return params


def _stack(mf: mfile.MFile, names: list[str], transpose: bool, dtype) -> np.ndarray:
    mats = []
    for name in names:
        t = mf.tensor(name)
        if transpose:
            t = np.ascontiguousarray(t.T)
        mats.append(t)
    return np.stack(mats).astype(dtype)


def _stack_q(mf: mfile.MFile, names: list[str | list[str]], codec=q40):
    """Layer-stack quantized tensors straight from their packed file bytes —
    the weights never touch f32 on host (the reference likewise keeps Q40
    end-to-end on its production path, funcs.cpp:287-386); the repack is a
    byte transpose per tensor (native csrc/q40pack.cpp when built).

    ``codec`` is ``ops.q40`` or ``ops.q8`` — the reference dispatches its
    matmul on the weight file type (funcs.cpp:414-455) and so does the
    loader here.

    An inner list of names concatenates those tensors' output dims into one
    fused weight (e.g. q+k+v), which halves-again the fused kernel's launch
    count per layer."""
    def entry(name):
        t = mf.info(name)
        d = int(np.prod(t.shape[:-1]))
        return (mf.raw(name), d, t.shape[-1])

    groups = [[entry(g) for g in ([name] if isinstance(name, str) else name)]
              for name in names]
    return codec.pack_file_groups(groups)


def quantize_matmuls(params: Params, cfg: ModelConfig,
                     fuse: bool = True) -> Params:
    """Convert the dense matmul weights of a params pytree to packed Q40
    (host-side).  Used by benchmarks/tests to exercise the quantized path
    from randomly-initialized params.  MoE expert stacks quantize too
    (``(L, E, n, d)`` → blocks along the input axis, the reference keeps
    experts Q40 end-to-end, transformer.cpp:299-317); the router and the
    embedding stay dense.

    ``fuse=True`` additionally concatenates q/k/v (and w1/w3) output dims
    into single ``wqkv``/``w13`` tensors — see load_params."""
    out = dict(params)
    if fuse:
        out["wqkv"] = q40.quantize(np.concatenate(
            [np.asarray(params[k], np.float32) for k in ("wq", "wk", "wv")], axis=-1))
        del out["wq"], out["wk"], out["wv"]
        keys = ["wo", "wcls"]
        if not cfg.is_moe:
            out["w13"] = q40.quantize(np.concatenate(
                [np.asarray(params[k], np.float32) for k in ("w1", "w3")], axis=-1))
            del out["w1"], out["w3"]
            keys.append("w2")
    else:
        keys = ["wq", "wk", "wv", "wo", "wcls"]
        if not cfg.is_moe:
            keys += ["w1", "w2", "w3"]
    if cfg.is_moe:
        keys += ["up", "gate", "down"]
    for k in keys:
        out[k] = q40.quantize(np.asarray(params[k], np.float32))
    return out


def _stack_q_experts(mf: mfile.MFile, cfg: ModelConfig, fname: str, codec=q40):
    """Layer×expert-stacked packed expert weights (Q40 or Q80 ``codec``),
    filled tensor by tensor into preallocated host arrays — no f32
    materialization and no transient double-buffering, so host RAM transit
    is bounded by the packed size (~0.69 B/weight for Q40).  Replaces the
    dense f32 expert loading that made Mixtral-8x7B (~90 GB f32 transit)
    unloadable (VERDICT r01)."""
    L, E = cfg.n_layers, cfg.n_experts
    t0 = mf.info(f"layers.0.experts.0.{fname}")
    d = int(np.prod(t0.shape[:-1]))
    n = t0.shape[-1]
    np_ = codec.padded_n(n)
    qp = codec.alloc_value_plane((L, E), np_, d)
    cls = codec.Tensor
    sc = np.zeros((L, E, np_ // 32, d), np.float16)
    for l in range(L):
        for e in range(E):
            codec.repack_file_bytes_into(
                mf.raw(f"layers.{l}.experts.{e}.{fname}"), d, n, qp[l, e], sc[l, e])
    if not np.isfinite(sc).all():  # same loud-failure rule as pack_file_groups
        raise ValueError(f"{fname}: expert scale plane contains inf/NaN f16 "
                         "scales — corrupt or overflowed .m tensor")
    return cls(jnp.asarray(qp), jnp.asarray(sc.view(np.uint16)), (n, d))


def load_params(mf: mfile.MFile, cfg: ModelConfig | None = None,
                dtype=None, keep_quantized: bool = False,
                fuse: bool = True) -> tuple[ModelConfig, Params]:
    """Load a `.m` file into the runtime layout.

    Mirrors ``Transformer::loadRoot`` (transformer.cpp:428-487) but instead
    of streaming slices to workers, produces host arrays that the engine
    places onto the mesh with shardings (upload happens once, sliced by
    XLA, riding PCIe/ICI instead of the reference's TCP star).

    ``keep_quantized=True`` keeps Q40/Q80 matmul weights packed for their
    fused dequant-matmuls (ops/q40.py, ops/q8.py — the reference likewise
    dispatches its matmul on the weight ftype, funcs.cpp:414-455).  Q40 is
    the production path (3.5× the decode bandwidth of dense bf16; Q80 is
    ~1.9×).  Norms, the embedding, and the router are dequantized either
    way; F16/F32 files always load dense.

    ``fuse=True`` concatenates q/k/v (and w1/w3) into single ``wqkv``/
    ``w13`` tensors on the quantized path — right for single-chip decode
    (fewer kernel launches); pass ``fuse=False`` under tp>1, where the
    concat axis would be shard-mixed and GSPMD would reshard every step.
    """
    if cfg is None:
        cfg = ModelConfig.from_spec(mf.spec)
    if dtype is None:
        dtype = cfg.dtype
    np_dtype = np.dtype(jnp.dtype(dtype).name) if dtype != jnp.bfloat16 else jnp.bfloat16
    ftype = mf.spec.weights_ftype
    quant = keep_quantized and ftype in (mfile.quants.Q40, mfile.quants.Q80)
    codec = q40 if ftype == mfile.quants.Q40 else q8
    L = cfg.n_layers
    p: Params = {}
    p["embedding"] = mf.tensor("token_embedding").astype(np_dtype)
    if quant and fuse:
        p["wqkv"] = _stack_q(
            mf, [[f"layers.{i}.wq", f"layers.{i}.wk", f"layers.{i}.wv"]
                 for i in range(L)], codec)
        p["wo"] = _stack_q(mf, [f"layers.{i}.wo" for i in range(L)], codec)
    elif quant:
        for key in ("wq", "wk", "wv", "wo"):
            p[key] = _stack_q(mf, [f"layers.{i}.{key}" for i in range(L)], codec)
    else:
        for key in ("wq", "wk", "wv", "wo"):
            p[key] = _stack(mf, [f"layers.{i}.{key}" for i in range(L)], True, np_dtype)
    p["rms_att"] = _stack(mf, [f"layers.{i}.rms_att" for i in range(L)], False, np.float32)
    p["rms_ffn"] = _stack(mf, [f"layers.{i}.rms_ffn" for i in range(L)], False, np.float32)
    if cfg.is_moe:
        p["router"] = _stack(mf, [f"layers.{i}.moe_router" for i in range(L)], True, np_dtype)
        if quant:
            for key in ("up", "gate", "down"):
                p[key] = _stack_q_experts(mf, cfg, key, codec)
        else:
            for key, fname in [("up", "up"), ("gate", "gate"), ("down", "down")]:
                per_layer = []
                for i in range(L):
                    mats = [np.ascontiguousarray(mf.tensor(f"layers.{i}.experts.{e}.{fname}").T)
                            for e in range(cfg.n_experts)]
                    per_layer.append(np.stack(mats))
                p[key] = np.stack(per_layer).astype(np_dtype)
        if cfg.post_block_norms:
            p["rms_moe"] = _stack(mf, [f"layers.{i}.rms_moe" for i in range(L)], False, np.float32)
            p["rms_ffn2"] = _stack(mf, [f"layers.{i}.rms_ffn2" for i in range(L)], False, np.float32)
    elif quant and fuse:
        p["w13"] = _stack_q(
            mf, [[f"layers.{i}.w1", f"layers.{i}.w3"] for i in range(L)], codec)
        p["w2"] = _stack_q(mf, [f"layers.{i}.w2" for i in range(L)], codec)
    elif quant:
        for key in ("w1", "w2", "w3"):
            p[key] = _stack_q(mf, [f"layers.{i}.{key}" for i in range(L)], codec)
    else:
        for key in ("w1", "w2", "w3"):
            p[key] = _stack(mf, [f"layers.{i}.{key}" for i in range(L)], True, np_dtype)
    p["rms_final"] = mf.tensor("rms_final").astype(np.float32)
    if quant:
        tw = mf.info("wcls")
        p["wcls"] = codec.pack_file_groups(
            [[(mf.raw("wcls"), int(np.prod(tw.shape[:-1])), tw.shape[-1])]],
            stacked=False)
    else:
        p["wcls"] = np.ascontiguousarray(mf.tensor("wcls").T).astype(np_dtype)
    return cfg, {k: v if isinstance(v, (q40.QTensor, q8.Q8Tensor)) else jnp.asarray(v)
                 for k, v in p.items()}
