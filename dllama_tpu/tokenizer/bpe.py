"""Sentencepiece-style greedy BPE tokenizer.

Behavior-compatible with the reference ``Tokenizer``
(/root/reference/src/tokenizer.cpp:170-292 encode, :150-161 decode):

* encode: optional BOS, a dummy-prefix space token (when the vocab has one),
  UTF-8 codepoint chunking with byte fallback (``byte + 3``), then repeated
  highest-score pair merges.
* decode: piece lookup, with ``<0xNN>`` raw-byte pieces mapped back to single
  bytes, and the leading space stripped from the piece that follows BOS.

The merge loop produces identical token ids to the reference's
rescan-per-merge (best score wins, earliest position on ties) but runs in
O(n log n) via a heap over candidate pairs on a linked list — the
reference's O(n²) rescan (tokenizer.cpp:258-287) is quadratic in prompt
length, which matters once ring-prefill makes 100k-token prompts real.
A native C++ implementation of the same algorithm (csrc/bpe.cpp) is used
when built; this module's pure-Python version is the fallback and the
behavioral spec.
"""

from __future__ import annotations

import heapq
import re

from ..io.tfile import TokenizerData

_BYTE_PIECE_RE = re.compile(rb"^<0x([0-9A-Fa-f]{2})>$")


class Tokenizer:
    def __init__(self, data: TokenizerData):
        self.data = data
        self.vocab: list[bytes] = data.vocab
        self.scores: list[float] = data.scores
        self.bos_id = data.bos_id
        self.eos_id = data.eos_id
        self.chat_eos_id = data.chat_eos_id
        self.chat_template = data.chat_template
        self.chat_stop = data.chat_stop
        self.vocab_size = data.vocab_size
        self._index: dict[bytes, int] = {}
        # first occurrence wins, matching bsearch over a vocab sorted with
        # duplicate strings (reference str_lookup, tokenizer.cpp:163-168)
        for i, piece in enumerate(self.vocab):
            self._index.setdefault(piece, i)
    def lookup(self, piece: bytes) -> int:
        return self._index.get(piece, -1)

    def encode(self, text: str | bytes, add_bos: bool = True, add_eos: bool = False) -> list[int]:
        raw = text.encode("utf-8") if isinstance(text, str) else text
        tokens: list[int] = []
        if add_bos and self.bos_id >= 0:
            tokens.append(self.bos_id)

        # dummy prefix (sentencepiece add_dummy_prefix; tokenizer.cpp:197-207)
        if raw:
            dummy = self.lookup(b" ")
            if dummy != -1:
                tokens.append(dummy)

        # UTF-8 codepoint chunking with byte fallback (tokenizer.cpp:218-256)
        i = 0
        n = len(raw)
        while i < n:
            j = i + 1
            # absorb continuation bytes (10xxxxxx), at most 3 (cp length ≤ 4)
            while j < n and (raw[j] & 0xC0) == 0x80 and (j - i) < 4:
                j += 1
            chunk = raw[i:j]
            tid = self.lookup(chunk)
            if tid != -1:
                tokens.append(tid)
            else:
                # byte fallback: vocab ids 3.. are the raw bytes (tokenizer.cpp:
                # 250-253).  The reference indexes b+3 unconditionally — UB when
                # the vocab has no byte pieces; emit <unk> (id 0) instead.
                tokens.extend(b + 3 if b + 3 < len(self.vocab) else 0
                              for b in chunk)
            i = j

        # greedy merge of the best-scoring adjacent pair (tokenizer.cpp:
        # 258-287 semantics: global best score per round, earliest position
        # on ties — realized with a lazy heap over a doubly-linked list
        # instead of the reference's whole-list rescan per merge)
        tokens = self._merge(tokens)

        if add_eos and self.eos_id >= 0:
            tokens.append(self.eos_id)
        return tokens

    def _merge(self, tokens: list[int]) -> list[int]:
        """Greedy best-pair merges, reference-identical order."""
        n = len(tokens)
        if n < 2:
            return tokens
        from ..native import bpe_merge

        merged = bpe_merge(self, tokens)
        if merged is not None:
            return merged
        ids = list(tokens)
        nxt = list(range(1, n)) + [-1]
        prv = [-1] + list(range(n - 1))
        alive = [True] * n
        index = self._index
        vocab = self.vocab
        scores = self.scores

        heap: list[tuple[float, int, int, int, int, int]] = []

        def push(a: int, b: int):
            if a < 0 or b < 0:
                return
            mid = index.get(vocab[ids[a]] + vocab[ids[b]], -1)
            # the strict > -1e10 keeps reference parity for sentinel/-inf
            # scores (its best_score starts at -1e10, tokenizer.cpp:262)
            if mid != -1 and scores[mid] > -1e10:
                # (-score, left position, expected ids, merged id): position
                # order along the list never changes, so the original index
                # reproduces the reference's earliest-index tie-break
                heapq.heappush(heap, (-scores[mid], a, ids[a], ids[b], b, mid))

        for k in range(n - 1):
            push(k, k + 1)
        while heap:
            _, a, ia, ib, b, mid = heapq.heappop(heap)
            if not (alive[a] and alive[b] and nxt[a] == b
                    and ids[a] == ia and ids[b] == ib):
                continue  # stale candidate
            ids[a] = mid
            alive[b] = False
            nxt[a] = nxt[b]
            if nxt[b] != -1:
                prv[nxt[b]] = a
            push(prv[a], a)
            push(a, nxt[a])
        out = []
        k = 0
        while k != -1:
            out.append(ids[k])
            k = nxt[k]
        return out

    def decode_piece(self, prev_token: int, token: int) -> bytes:
        """One token → bytes (tokenizer.cpp:150-161)."""
        piece = self.vocab[token]
        if prev_token == self.bos_id and piece.startswith(b" "):
            piece = piece[1:]
        m = _BYTE_PIECE_RE.match(piece)
        if m:
            return bytes([int(m.group(1), 16)])
        return piece

    def decode(self, tokens: list[int]) -> str:
        out = bytearray()
        prev = self.bos_id
        for t in tokens:
            if t == self.bos_id:
                prev = t
                continue
            out += self.decode_piece(prev, t)
            prev = t
        return out.decode("utf-8", errors="replace")
