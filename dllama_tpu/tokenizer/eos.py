"""Streaming stop-sequence detector.

Behavior-compatible with the reference ``EosDetector``
(/root/reference/src/tokenizer.cpp:475-547): pieces are appended to a buffer;
the detector reports ``EOS`` (hard stop: EOS token id or a full stop-string
match), ``MAYBE_EOS`` (the buffer is a prefix of a stop string — hold the
text back), or ``NOT_EOS``.  ``padding_left``/``padding_right`` tolerate up
to that many junk characters before/after the stop string.  ``get_delta()``
returns the text that is safe to emit (``None`` if nothing).
"""

from __future__ import annotations

MAYBE_EOS = 0
EOS = 1
NOT_EOS = 2


class EosDetector:
    def __init__(self, eos_id: int, stops: list[str], padding_left: int = 0, padding_right: int = 0):
        self.eos_id = eos_id
        self.stops = stops
        self.padding_left = padding_left
        self.padding_right = padding_right
        self.buffer = ""
        self.eos_pos = -1

    def append(self, token_id: int, piece: str) -> int:
        piece_len = len(piece)
        self.buffer += piece
        pos = len(self.buffer)

        if token_id == self.eos_id:
            self.eos_pos = pos - piece_len
            return EOS
        self.eos_pos = -1

        for stop in self.stops:
            stop_size = len(stop)
            # too much accumulated text to still be (padded) stop string
            if pos > stop_size + self.padding_left + self.padding_right:
                continue
            for lo in range(self.padding_left + 1):
                n = pos - lo
                if n == 0 or n > stop_size + self.padding_right:
                    continue
                n = min(n, stop_size)
                if self.buffer[lo: lo + n] == stop[:n]:
                    if n == stop_size:
                        self.eos_pos = lo
                        return EOS
                    return MAYBE_EOS
        return NOT_EOS

    def get_delta(self) -> str | None:
        if self.eos_pos == -1:
            return self.buffer if self.buffer else None
        if self.eos_pos == 0:
            return None
        return self.buffer[: self.eos_pos]

    def clear(self):
        self.buffer = ""
