"""Chat templates + chat stop strings.

Behavior-compatible with the reference ``ChatTemplate`` /
``TokenizerChatStops`` (/root/reference/src/tokenizer.cpp:417-473): the
template *type* is detected by substring match on the Jinja template string
embedded in the `.t` file, and each known type is re-implemented natively.
"""

from __future__ import annotations

from dataclasses import dataclass

from .bpe import Tokenizer

TEMPLATE_LLAMA3 = "llama3"
TEMPLATE_ZEPHYR = "zephyr"
TEMPLATE_CHATML = "chatml"


@dataclass
class ChatItem:
    role: str
    message: str


def detect_template_type(chat_template: str) -> str:
    """Substring-based detection (tokenizer.cpp:440-452)."""
    if "<|start_header_id|>" in chat_template:
        return TEMPLATE_LLAMA3
    if "<|user|>" in chat_template:
        return TEMPLATE_ZEPHYR
    if "<|im_start|>" in chat_template:
        return TEMPLATE_CHATML
    raise ValueError("Not supported chat template")


class ChatTemplate:
    def __init__(self, chat_template: str | None, eos: str):
        if chat_template is None:
            raise ValueError("The tokenizer does not include chat template")
        self.type = detect_template_type(chat_template)
        self.eos = eos

    def generate(self, items: list[ChatItem], append_generation_prompt: bool) -> str:
        """Render messages (tokenizer.cpp:454-473)."""
        out: list[str] = []
        if self.type == TEMPLATE_LLAMA3:
            for it in items:
                out.append(f"<|start_header_id|>{it.role}<|end_header_id|>\n\n{it.message}{self.eos}")
            if append_generation_prompt:
                out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        elif self.type == TEMPLATE_CHATML:
            for it in items:
                out.append(f"<|im_start|>{it.role}\n{it.message}<|im_end|>\n")
            if append_generation_prompt:
                out.append("<|im_start|>assistant\n")
        elif self.type == TEMPLATE_ZEPHYR:
            for it in items:
                out.append(f"<|{it.role}|>\n{it.message}{self.eos}\n")
            if append_generation_prompt:
                out.append("<|assistant|>\n")
        return "".join(out)


class TokenizerChatStops:
    """Stop strings for chat mode (tokenizer.cpp:417-434): the chat-EOS
    token's piece, plus the tokenizer's optional extra stop string."""

    def __init__(self, tokenizer: Tokenizer):
        if tokenizer.chat_eos_id < 0:
            raise ValueError("tokenizer has no chat EOS id; regenerate the .t file")
        stops = [tokenizer.vocab[tokenizer.chat_eos_id].decode("utf-8", errors="replace")]
        if tokenizer.chat_stop:
            stops.append(tokenizer.chat_stop)
        self.stops = stops
        self.max_stop_length = max(len(s) for s in stops)
