"""Elastic pod control loop: load-driven autoscaling and live reshape.

``serve-pod --supervise --elastic`` closes the loop that PR 14's
supervisor left open.  The supervisor answers "a replica died"; this
module answers "the traffic changed shape".  Three moves, all built
from primitives that already exist:

* **scale-up** — allocate ``tp`` devices from the :class:`DevicePool`,
  spawn a fresh replica child (warm ``--snapshot-dir`` boot), register
  it with the router's :class:`~.registry.Registry` at runtime, and let
  the registry's hysteretic admission gate traffic: the newcomer takes
  no requests until its first healthy probe.
* **scale-down** — pick the most-idle replica (highest registry score),
  fence admissions (``Registry.retire``), then SIGTERM it so the
  existing drain path runs: the replica exports every live slot as a
  DLREQ01 record, its streams finish ``handoff``, and the router
  re-binds each one onto a surviving peer.  Devices return to the pool.
* **reshape** — change the per-replica tp degree live (4×tp=1 ⇄ 2×tp=2)
  by interleaving the two moves above: spawn new-shape replicas while
  devices are free, retire old-shape ones to free more, and let the
  hand-off wire migrate every in-flight request.  PR 12 made DLREQ01
  fingerprints mesh-layout-agnostic, so a record exported from a tp=1
  replica imports cleanly on a tp=2 one; layout is placement, not
  identity.

The policy (:class:`ElasticPolicy`) is a pure function of a sliding
window of fleet samples — no threads, no sockets — so the hysteresis
and cooldown behavior is unit-testable without booting a pod.  The
:class:`ElasticController` owns the one policy thread and executes at
most one topology action at a time; manual ``/admin/scale`` and
``/admin/reshape`` commands preempt the policy but run through the
exact same serialized executor, so chaos during a reshape contends
with nothing but the reshape itself.
"""

from __future__ import annotations

import collections
import threading
import time

from ..obs import events as obs_events, metrics as obs_metrics
from ..obs.log import get_logger

_log = get_logger("router.elastic")


class DevicePool:
    """Ordinal accounting for the pod's device budget.

    Replicas borrow contiguous ordinal runs when one exists (contiguous
    chips share the fastest ICI links, matching ``partition_devices``'s
    boot-time layout) and fall back to the lowest free ordinals when
    fragmentation from prior scale events leaves no run.  On CPU hosts
    the ordinals are bookkeeping only (each child fabricates its own
    virtual devices); on TPU hosts they become
    ``TPU_VISIBLE_DEVICES``."""

    def __init__(self, total: int):
        if total < 1:
            raise ValueError(f"device pool needs >= 1 device, got {total}")
        self.total = int(total)
        self._free = set(range(self.total))
        self._lock = threading.Lock()

    @property
    def free(self) -> int:
        with self._lock:
            return len(self._free)

    def allocate(self, n: int) -> list[int]:
        """``n`` ordinals, contiguous-preferred.  Raises ``ValueError``
        when the pool cannot satisfy the request — the caller treats
        that as "no capacity", never as a crash."""
        if n < 1:
            raise ValueError(f"device pool: allocation size must be >= 1, "
                             f"got {n}")
        with self._lock:
            if n > len(self._free):
                raise ValueError(
                    f"device pool: want {n} devices, "
                    f"{len(self._free)}/{self.total} free")
            free = sorted(self._free)
            got = free[:n]
            for i in range(len(free) - n + 1):
                run = free[i:i + n]
                if run[-1] - run[0] == n - 1:
                    got = run
                    break
            self._free.difference_update(got)
            return list(got)

    def release(self, ordinals) -> None:
        """Return ordinals to the pool.  Double-release and out-of-range
        ordinals raise — both are accounting bugs worth failing loudly
        on (a silently double-freed device would be handed to two
        replicas)."""
        with self._lock:
            for o in ordinals:
                if not 0 <= o < self.total:
                    raise ValueError(f"device pool: ordinal {o} outside "
                                     f"0..{self.total - 1}")
                if o in self._free:
                    raise ValueError(f"device pool: double release of "
                                     f"ordinal {o}")
            self._free.update(ordinals)


class Decision:
    """One policy verdict: scale ``up``/``down`` or ``reshape`` to a
    new tp degree, with the reason that becomes the metric label."""

    __slots__ = ("direction", "reason", "tp")

    def __init__(self, direction: str, reason: str, tp: int | None = None):
        self.direction = direction
        self.reason = reason
        self.tp = tp

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Decision({self.direction!r}, {self.reason!r}, tp={self.tp})"


class ElasticPolicy:
    """Sliding-window threshold policy with hysteresis and cooldown.

    A decision needs EVERY sample in the window to agree (sustained
    signal, not a spike), plus ``cooldown`` seconds since the last
    topology action; ``note_action`` also clears the window because
    samples taken under the old topology say nothing about the new one.
    The thresholds are deliberately asymmetric (``up_util`` well above
    ``down_util``) so the fleet never oscillates on a load level that
    sits between them.

    Signals per sample (dicts built by the controller from registry
    health blocks):

    * ``util`` — busy slots / total slots across eligible replicas
    * ``queue_per_replica`` — fleet queue depth / replica count
    * ``kv_free_frac`` — effective free KV pages / total pages
    """

    def __init__(self, *, window: int = 5, cooldown: float = 30.0,
                 up_util: float = 0.85, down_util: float = 0.15,
                 up_queue: float = 2.0, kv_low: float = 0.08,
                 min_replicas: int = 1, max_replicas: int = 8):
        self.window = max(2, int(window))
        self.cooldown = max(0.0, float(cooldown))
        self.up_util = float(up_util)
        self.down_util = float(down_util)
        self.up_queue = float(up_queue)
        self.kv_low = float(kv_low)
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self._samples: collections.deque = collections.deque(
            maxlen=self.window)
        self._last_action: float | None = None

    def observe(self, sample: dict) -> None:
        self._samples.append(sample)

    def note_action(self, now: float) -> None:
        """A topology action happened (policy-driven or manual): start
        the cooldown and drop samples measured under the old shape."""
        self._last_action = now
        self._samples.clear()

    def decide(self, now: float, *, n_replicas: int, tp: int,
               free_devices: int) -> Decision | None:
        if len(self._samples) < self.window:
            return None
        if self._last_action is not None \
                and now - self._last_action < self.cooldown:
            return None
        samples = list(self._samples)
        hot = all(s["util"] >= self.up_util
                  or s["queue_per_replica"] >= self.up_queue
                  for s in samples)
        kv_starved = all(s["kv_free_frac"] <= self.kv_low for s in samples)
        idle = all(s["util"] <= self.down_util
                   and s["queue_per_replica"] <= 0 for s in samples)
        total_devices = n_replicas * tp + free_devices
        if kv_starved and tp * 2 <= total_devices \
                and total_devices // (tp * 2) >= self.min_replicas:
            # long-context pressure: fewer, fatter replicas double the
            # per-replica KV pool (throughput-heavy mix → widen tp)
            return Decision("reshape", "kv_pressure", tp=tp * 2)
        if hot and n_replicas < self.max_replicas:
            if free_devices >= tp:
                return Decision("up", "load")
            if tp > 1:
                # no spare devices: trade tp for dp — more, thinner
                # replicas serve a latency-bound interactive surge
                return Decision("reshape", "load", tp=max(1, tp // 2))
            return None
        if idle and n_replicas > self.min_replicas:
            return Decision("down", "idle")
        return None


class ElasticController:
    """One thread that samples, decides, and reshapes the pod.

    The pod's process mechanics stay in ``router/pod.py`` behind the
    ``ops`` object (spawn / retire / live replica listing / quarantine
    reaping) so this module never touches ``subprocess`` and the policy
    plumbing is testable with fakes.  All topology actions — policy
    decisions AND manual ``/admin`` commands — run serialized on the
    controller thread; ``request_scale``/``request_reshape`` only
    enqueue (latest command wins) and return, so the admin surface
    never blocks on a drain.
    """

    def __init__(self, ops, registry, pool: DevicePool,
                 policy: ElasticPolicy, *, tp: int,
                 interval: float = 2.0, drain_grace: float = 30.0,
                 boot_timeout: float = 120.0):
        self.ops = ops
        self.registry = registry
        self.pool = pool
        self.policy = policy
        self.tp = max(1, int(tp))
        self.interval = max(0.05, float(interval))
        self.drain_grace = max(0.0, float(drain_grace))
        self.boot_timeout = max(1.0, float(boot_timeout))
        self._lock = threading.Lock()
        self._pending: tuple[str, int] | None = None
        self._busy: str | None = None      # current action, for /health
        self._last_decision: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        obs_metrics.POD_REPLICAS_DESIRED.set(len(self.ops.live_replicas()))

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="pod-elastic", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 2.0)

    # -- admin surface (router /admin/scale, /admin/reshape) ------------
    def request_scale(self, n: int) -> dict:
        n = max(self.policy.min_replicas,
                min(self.policy.max_replicas, int(n)))
        with self._lock:
            self._pending = ("scale", n)
        return {"accepted": True, "target_replicas": n}

    def request_reshape(self, tp: int) -> dict:
        tp = int(tp)
        if tp < 1:
            raise ValueError(f"reshape tp must be >= 1, got {tp}")
        total = self._total_devices()
        if tp > total:
            raise ValueError(f"reshape tp={tp} exceeds the pod's "
                             f"{total}-device budget")
        with self._lock:
            self._pending = ("reshape", tp)
        return {"accepted": True, "target_tp": tp}

    def fleet_status(self) -> dict:
        reps = self.ops.live_replicas()
        with self._lock:
            busy, last = self._busy, self._last_decision
        return {
            "elastic": True,
            "tp": self.tp,
            "n_replicas": len(reps),
            "min_replicas": self.policy.min_replicas,
            "max_replicas": self.policy.max_replicas,
            "device_pool": {"total": self.pool.total,
                            "free": self.pool.free},
            "busy": busy,
            "last_decision": last,
            "replicas": [{"idx": r.idx, "port": r.port, "tp": r.tp,
                          "retiring": r.retiring} for r in reps],
        }

    # -- control loop ---------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — loop must survive
                _log.error("elastic_tick_failed", extra={"error": repr(e)})

    def _tick(self) -> None:
        self._reap_quarantined()
        with self._lock:
            cmd, self._pending = self._pending, None
        if cmd is not None:
            kind, arg = cmd
            if kind == "scale":
                self._run(f"scale:{arg}", self._scale_to, arg, "manual")
            else:
                self._run(f"reshape:{arg}", self._reshape, arg, "manual")
            return
        sample = self._sample()
        if sample is None:
            return
        self.policy.observe(sample)
        now = time.monotonic()
        d = self.policy.decide(
            now, n_replicas=len(self.ops.live_replicas()),
            tp=self.tp, free_devices=self.pool.free)
        if d is None:
            return
        with self._lock:
            self._last_decision = {"direction": d.direction,
                                   "reason": d.reason, "tp": d.tp}
        _log.info("elastic_decision", extra={
            "direction": d.direction, "reason": d.reason, "tp": d.tp})
        n = len(self.ops.live_replicas())
        if d.direction == "up":
            self._run("scale_up", self._scale_to, n + 1, d.reason)
        elif d.direction == "down":
            self._run("scale_down", self._scale_to, n - 1, d.reason)
        else:
            self._run(f"reshape:{d.tp}", self._reshape, d.tp, d.reason)

    def _run(self, label: str, fn, *args) -> None:
        with self._lock:
            self._busy = label
        try:
            fn(*args)
        finally:
            with self._lock:
                self._busy = None
            self.policy.note_action(time.monotonic())

    # -- signal sampling ------------------------------------------------
    def _sample(self) -> dict | None:
        """One fleet-wide sample from the registry's cached health
        blocks (no extra probes — the registry already polls)."""
        slots = busy = queue = 0
        kv_total = kv_free = 0
        n = 0
        for b in self.registry.eligible_backends():
            h = b.last_health or {}
            occ = h.get("scheduler") or {}
            cap = h.get("capacity") or {}
            if occ.get("slots"):
                slots += occ["slots"]
                busy += occ.get("active", 0)
            else:
                # slot-less replica: approximate with admission depth
                slots += max(h.get("max_pending", 1), 1)
                busy += h.get("in_flight", 0)
            queue += cap.get("queue_depth") or 0
            tot = occ.get("kv_pages_total")
            if tot:
                kv_total += tot
                kvp = cap.get("kv_pressure") or {}
                free = kvp.get("effective_free")
                if free is None:
                    free = occ.get("kv_pages_free") or 0
                kv_free += free
            n += 1
        if n == 0:
            return None
        return {
            "util": busy / slots if slots else 0.0,
            "queue_per_replica": queue / n,
            "kv_free_frac": kv_free / kv_total if kv_total else 1.0,
        }

    # -- topology actions (controller thread only) ----------------------
    def _total_devices(self) -> int:
        return self.pool.total

    def _reap_quarantined(self) -> None:
        """A crash-looper the supervisor quarantined still holds devices
        and a registry row; reclaim both so the pool can respawn
        capacity elsewhere."""
        for rep in self.ops.reap_quarantined():
            self.registry.remove(f"127.0.0.1:{rep.port}")
            self.pool.release(rep.ordinals)
            obs_metrics.POD_SCALE_EVENTS.inc("down", "quarantined")
            obs_events.emit("scale", direction="down",
                            reason="quarantined",
                            replica=f"127.0.0.1:{rep.port}", idx=rep.idx)
            obs_metrics.POD_REPLICAS_DESIRED.set(
                len(self.ops.live_replicas()))
            _log.warning("elastic_reaped_quarantined", extra={
                "replica": rep.idx, "port": rep.port,
                "devices_released": rep.ordinals})

    def _scale_to(self, n: int, reason: str) -> None:
        n = max(self.policy.min_replicas,
                min(self.policy.max_replicas, int(n)))
        obs_metrics.POD_REPLICAS_DESIRED.set(n)
        while len(self.ops.live_replicas()) < n and not self._stop.is_set():
            if not self._spawn_one(self.tp, reason):
                break
        while len(self.ops.live_replicas()) > n and not self._stop.is_set():
            if not self._retire_one(reason):
                break

    def _spawn_one(self, tp: int, reason: str) -> bool:
        try:
            ordinals = self.pool.allocate(tp)
        except ValueError as e:
            _log.warning("elastic_scale_up_blocked",
                         extra={"error": str(e)})
            return False
        try:
            rep = self.ops.spawn(tp, ordinals)
        except Exception as e:  # noqa: BLE001 — spawn must not kill loop
            self.pool.release(ordinals)
            _log.error("elastic_spawn_failed", extra={"error": repr(e)})
            return False
        addr = f"127.0.0.1:{rep.port}"
        self.registry.add(addr)
        obs_metrics.POD_SCALE_EVENTS.inc("up", reason)
        obs_events.emit("scale", direction="up", reason=reason,
                        replica=addr, idx=rep.idx, tp=tp)
        _log.info("elastic_scale_up", extra={
            "replica": rep.idx, "port": rep.port, "tp": tp,
            "devices": ordinals, "reason": reason})
        self._wait_admitted(addr)
        return True

    def _wait_admitted(self, addr: str) -> bool:
        """Block (controller thread only) until the registry's hysteretic
        admission lets the newcomer take traffic, or the boot budget
        runs out — on timeout the supervisor's quarantine ladder owns
        recovery, the controller just stops waiting."""
        deadline = time.monotonic() + self.boot_timeout
        while time.monotonic() < deadline and not self._stop.is_set():
            b = self.registry.get(addr)
            if b is None:
                return False            # reaped while booting
            if b.last_health is not None and not b.ejected:
                return True
            time.sleep(min(0.1, self.interval))
        _log.warning("elastic_admission_timeout", extra={"addr": addr})
        return False

    def _retire_one(self, reason: str, *, shape_tp: int | None = None
                    ) -> bool:
        """Fence, drain, and remove one replica; returns False when no
        replica can be retired safely (nobody left to migrate onto)."""
        reps = [r for r in self.ops.live_replicas() if not r.retiring]
        if shape_tp is not None:
            reps = [r for r in reps if r.tp == shape_tp]
        if not reps:
            return False
        survivors = [r for r in self.ops.live_replicas()
                     if not r.retiring]
        if len(survivors) <= 1:
            _log.warning("elastic_scale_down_blocked", extra={
                "reason": "last replica cannot retire"})
            return False
        victim = self._pick_victim(reps)
        addr = f"127.0.0.1:{victim.port}"
        self.registry.retire(addr)      # admission fence, pre-SIGTERM
        _log.info("elastic_retiring", extra={
            "replica": victim.idx, "port": victim.port,
            "tp": victim.tp, "reason": reason})
        # SIGTERM runs the replica's drain: live slots export DLREQ01
        # records, streams finish "handoff", the router re-binds each
        # on a surviving peer.  The wait is bounded; a replica that
        # ignores its grace is killed (its streams take the resume
        # ladder instead — still zero client-visible drops for greedy).
        self.ops.retire(victim, grace=self.drain_grace)
        self.registry.remove(addr)
        self.pool.release(victim.ordinals)
        obs_metrics.POD_SCALE_EVENTS.inc("down", reason)
        obs_events.emit("scale", direction="down", reason=reason,
                        replica=addr, idx=victim.idx, tp=victim.tp)
        _log.info("elastic_scale_down", extra={
            "replica": victim.idx, "port": victim.port, "reason": reason})
        return True

    def _pick_victim(self, reps):
        """Most-idle replica by the registry's own score so retirement
        migrates the fewest in-flight requests."""
        best, best_score = reps[0], float("-inf")
        for r in reps:
            b = self.registry.get(f"127.0.0.1:{r.port}")
            score = self.registry.score(b) if b is not None \
                else float("-inf")
            if score > best_score:
                best, best_score = r, score
        return best

    def _reshape(self, tp_new: int, reason: str) -> None:
        """Live tp change: interleave spawn-new-shape / retire-old-shape
        until every live replica runs ``tp_new``.  Converges under
        chaos — a SIGKILLed retiring replica just finishes retiring
        faster (the bounded wait sees the exit), a SIGKILLed new-shape
        replica is the supervisor's respawn problem, and each loop pass
        re-reads live state rather than trusting a plan."""
        tp_new = int(tp_new)
        if tp_new < 1 or tp_new == self.tp:
            return
        t0 = time.monotonic()
        live = self.ops.live_replicas()
        budget = sum(r.tp for r in live) + self.pool.free
        target = max(self.policy.min_replicas,
                     min(self.policy.max_replicas, budget // tp_new))
        if target < 1:
            _log.warning("elastic_reshape_blocked", extra={
                "tp": tp_new, "budget": budget})
            return
        _log.info("elastic_reshape_start", extra={
            "tp_from": self.tp, "tp_to": tp_new, "target": target,
            "reason": reason})
        obs_events.emit("reshape", phase="start", tp_from=self.tp,
                        tp_to=tp_new, target=target, reason=reason)
        self.tp = tp_new
        obs_metrics.POD_REPLICAS_DESIRED.set(target)
        # generous overall bound: a wedged drain cannot wedge the
        # controller forever, and partial progress is still progress
        deadline = time.monotonic() + self.boot_timeout \
            + (target + len(live)) * (self.drain_grace + 10.0)
        while not self._stop.is_set() and time.monotonic() < deadline:
            reps = self.ops.live_replicas()
            new = [r for r in reps if r.tp == tp_new and not r.retiring]
            old = [r for r in reps if r.tp != tp_new and not r.retiring]
            if not old and len(new) >= target:
                break
            if len(new) < target and self.pool.free >= tp_new:
                self._spawn_one(tp_new, reason)
            elif old:
                if not self._retire_one(reason, shape_tp=old[0].tp):
                    # nothing retirable yet (last eligible survivor);
                    # give boots in flight a beat to admit
                    time.sleep(min(0.2, self.interval))
            elif len(new) < target:
                # devices still tied up in a retiring replica's drain
                time.sleep(min(0.2, self.interval))
            else:
                break
        obs_metrics.POD_RESHAPE_SECONDS.observe(time.monotonic() - t0)
        obs_metrics.POD_SCALE_EVENTS.inc("reshape", reason)
        obs_events.emit("reshape", phase="done", tp_to=tp_new,
                        reason=reason,
                        seconds=round(time.monotonic() - t0, 3),
                        replicas=len(self.ops.live_replicas()))
        _log.info("elastic_reshape_done", extra={
            "tp": tp_new, "seconds": round(time.monotonic() - t0, 3),
            "replicas": len(self.ops.live_replicas())})
