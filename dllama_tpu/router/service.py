"""Router HTTP service: OpenAI-surface proxy over the replica registry.

One ``ThreadingHTTPServer`` (stdlib only — the router carries no model,
no tokenizer, no jax) that fronts N ``dllama-api`` replicas:

* ``POST /v1/completions`` and ``/v1/chat/completions`` dispatch to the
  least-loaded healthy replica (:mod:`.registry`), stamping
  ``X-Request-Id`` (the fleet-wide correlation id — the replica's
  flight record and hand-off record both key on it) and
  ``X-Dllama-Hop`` (this router's instance id) on the upstream hop.
* A backend that fails **before any response bytes were forwarded** is
  retried on another replica — the request was idempotent up to that
  point.  A backend that dies **mid-stream** is transparently resumed
  on a peer when the request is greedy (``temperature: 0``) and
  streaming: tier 1 imports the latest proactive DLREQ01 checkpoint
  (``--checkpoint-interval``), tier 2 replays the request and swallows
  the regenerated prefix, verified char-by-char — the client's bytes
  are identical to an uninterrupted run.  Sampled requests (no
  determinism to lean on) and ``resume_policy: "never"`` keep the
  honest ``finish_reason="replica_lost"`` chunk: the truncation is
  flagged, never silent.  A stream that goes *silent* without the
  socket dying (``--stall-timeout``) is treated the same way, and the
  wedged replica is force-ejected.
* A replica that begins draining finishes each in-flight scheduler
  request with the internal ``finish_reason="handoff"``.  The router
  intercepts it (never forwarded), fetches the request's DLREQ01 record
  from ``/admin/export/<rid>``, offers it to geometry-compatible peers
  via ``/admin/import?emitted_chars=N``, and splices the peer's
  continuation into the client's still-open stream — the client sees
  one seamless completion across the replica move.  A request that had
  produced no client-visible bytes yet (e.g. it was still queued) falls
  back to a plain full retry.
* ``GET /health`` is the fleet aggregate, ``/metrics`` the router's own
  registry (router_* families), ``/debug/requests`` the router-side
  flight ring — same observability surface as a replica, one process up.

See docs/SERVING.md for the topology and the rolling-restart runbook.
"""

from __future__ import annotations

import http.client
import json
import re
import signal
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from ..obs import events as obs_events, flight as obs_flight, \
    metrics as obs_metrics, trace as obs_trace
from ..obs.log import get_logger, set_request_id
from ..runtime.snapshot import RecordStore
from ..server.backoff import jittered_retry_after
from .fleet import FleetScraper
from .registry import Backend, Registry

_log = get_logger("router.service")

_RID_RE = re.compile(r"[^A-Za-z0-9._-]")
_RID_MAX = 64
MAX_BODY_BYTES = 1 << 20
_PRIORITIES = ("interactive", "standard", "batch")


def _iter_sse(resp):
    """Yield the payload of each ``data:`` line from an SSE response.

    Our servers emit exactly one ``data: ...`` line per event, so
    per-line is per-event; blank separator lines are skipped."""
    while True:
        line = resp.readline()
        if not line:
            return
        line = line.rstrip(b"\r\n")
        if line.startswith(b"data: "):
            yield line[len(b"data: "):]


def _evt_fields(evt: dict, chat: bool) -> tuple[str, str | None]:
    """(delta_text, finish_reason) of one upstream SSE event."""
    choice = (evt.get("choices") or [{}])[0]
    if chat:
        text = (choice.get("delta") or {}).get("content") or ""
    else:
        text = choice.get("text") or ""
    return text, choice.get("finish_reason")


class RouterState:
    def __init__(self, registry: Registry, *, retries: int = 2,
                 upstream_timeout: float = 120.0,
                 model_name: str = "fleet",
                 stall_timeout: float = 0.0,
                 checkpoint_interval: float = 0.0,
                 resume_policy: str = "auto",
                 resume_window: float = 10.0,
                 fleet_scope_default: bool = False):
        self.registry = registry
        self.retries = max(0, int(retries))
        self.upstream_timeout = float(upstream_timeout)
        self.model_name = model_name
        # ---- crash tolerance (mid-stream resume; docs/ROBUSTNESS.md) --
        # stall_timeout: per-read socket timeout on an open upstream
        # stream — a connected-but-silent replica (SIGSTOP, device hang)
        # is treated as dead after this window.  checkpoint_interval:
        # how often the background poller snapshots each greedy stream's
        # slot via GET /admin/checkpoint/<rid>; 0 disables.  Cached
        # checkpoints expire after 4 intervals (min 30 s) — a crashed
        # request's record must not outlive its usefulness.
        self.stall_timeout = max(0.0, float(stall_timeout))
        self.checkpoint_interval = max(0.0, float(checkpoint_interval))
        self.resume_policy = resume_policy \
            if resume_policy in ("auto", "never") else "auto"
        # resume_window: how long a resume keeps trying before the
        # honest replica_lost — the natural peer is often seconds away
        # (mid-readmission after a respawn, or momentarily saturated),
        # and a resume that gives up in milliseconds wastes the ladder
        self.resume_window = max(0.0, float(resume_window))
        self.checkpoints = RecordStore(
            ttl=max(4.0 * self.checkpoint_interval, 30.0)
            if self.checkpoint_interval > 0 else 0.0)
        self._streams_lock = threading.Lock()
        self._streams: dict[str, Backend] = {}
        # hop id: correlates every replica-side flight record this
        # router created (X-Dllama-Hop) with this process
        self.hop = f"router-{uuid.uuid4().hex[:8]}"
        self.started_at = time.time()
        # elastic pod controller (router/elastic.py), set by serve-pod
        # --elastic: surfaces the fleet block in /health and accepts
        # /admin/scale + /admin/reshape commands
        self.elastic = None
        # fleet federation (router/fleet.py): /metrics?scope=fleet
        # scrapes every registered replica and re-exposes everything
        # with a replica label; serve-pod makes fleet the default scope
        # (its replicas sit on loopback ephemeral ports — the pod's
        # public port is the only scrapeable surface)
        self.fleet = FleetScraper(registry)
        self.fleet_scope_default = bool(fleet_scope_default)

    def connect(self, b: Backend) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(b.host, b.port,
                                          timeout=self.upstream_timeout)

    # -- checkpoint targets (greedy in-flight streams) ------------------
    def track_stream(self, rid: str, b: Backend) -> None:
        with self._streams_lock:
            self._streams[rid] = b

    def untrack_stream(self, rid: str) -> None:
        with self._streams_lock:
            self._streams.pop(rid, None)
        self.checkpoints.discard(rid)

    def checkpoint_targets(self) -> list[tuple[str, Backend]]:
        with self._streams_lock:
            return list(self._streams.items())

    def health(self) -> dict:
        snap = self.registry.snapshot()
        out = {
            "status": "ok" if snap["available"] else "unavailable",
            "ready": snap["available"] > 0,
            "role": "router",
            "hop": self.hop,
            "model": self.model_name,
            "uptime_s": round(time.time() - self.started_at, 3),
            **snap,
        }
        if self.elastic is not None:
            out["fleet"] = self.elastic.fleet_status()
        return out


class _Ctx:
    """Per-request forwarding state shared across dispatch attempts."""

    def __init__(self):
        self.chars = 0            # completion-text chars forwarded
        self.text = ""            # the forwarded completion text itself
        #                           (the byte-parity oracle for resume)
        self.headers_sent = False  # client SSE headers committed
        self.client_gone = False
        self.finished = False      # a finish_reason reached the client
        self.busy = None           # last (status, body, retry_after)
        self.cid = None            # id/model/created of the first
        self.model = None          # upstream chunk — reused when the
        self.created = None        # router must fabricate chunks


def make_handler(state: RouterState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "dllama-router"

        def log_message(self, fmt, *args):  # route through our logger
            _log.debug("%s " + fmt, self.client_address[0], *args)

        # -- plumbing --------------------------------------------------
        def _json(self, code: int, obj: dict, headers=()) -> None:
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.send_header("X-Request-Id", getattr(self, "_rid", "") or "")
            if getattr(self, "_trace", None):
                self.send_header("X-Dllama-Trace", self._trace)
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            try:
                self.wfile.write(data)
            except OSError:
                pass

        def _relay(self, code: int, data: bytes, ctype: str | None,
                   headers=()) -> None:
            self.send_response(code)
            self.send_header("Content-Type",
                             ctype or "application/octet-stream")
            self.send_header("Content-Length", str(len(data)))
            self.send_header("X-Request-Id", getattr(self, "_rid", "") or "")
            if getattr(self, "_trace", None):
                self.send_header("X-Dllama-Trace", self._trace)
            for k, v in headers:
                if v:
                    self.send_header(k, v)
            self.end_headers()
            try:
                self.wfile.write(data)
            except OSError:
                pass

        def _sse_headers(self, ctx: _Ctx) -> None:
            if ctx.headers_sent:
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.send_header("X-Request-Id", self._rid)
            if getattr(self, "_trace", None):
                self.send_header("X-Dllama-Trace", self._trace)
            self.end_headers()
            ctx.headers_sent = True

        def _client_event(self, ctx: _Ctx, payload: bytes) -> bool:
            if ctx.client_gone:
                return False
            try:
                self.wfile.write(b"data: " + payload + b"\n\n")
                self.wfile.flush()
                return True
            except OSError:
                ctx.client_gone = True
                return False

        def _client_chunk(self, ctx: _Ctx, chat: bool, text: str,
                          finish: str | None) -> None:
            """Fabricate a chunk in the client's endpoint shape (used
            for hand-off continuations and replica_lost finishes)."""
            if chat:
                if text:
                    self._client_event(ctx, json.dumps({
                        "id": ctx.cid, "object": "chat.completion.chunk",
                        "created": ctx.created, "model": ctx.model,
                        "choices": [{"index": 0,
                                     "delta": {"content": text},
                                     "finish_reason": None}]}).encode())
                if finish is not None:
                    self._client_event(ctx, json.dumps({
                        "id": ctx.cid, "object": "chat.completion.chunk",
                        "created": ctx.created, "model": ctx.model,
                        "choices": [{"index": 0, "delta": {},
                                     "finish_reason": finish}]}).encode())
            else:
                self._client_event(ctx, json.dumps({
                    "id": ctx.cid, "object": "text_completion",
                    "created": ctx.created, "model": ctx.model,
                    "choices": [{"text": text, "index": 0,
                                 "finish_reason": finish,
                                 "logprobs": None}]}).encode())
            if text:
                ctx.chars += len(text)
                ctx.text += text
            if finish is not None:
                ctx.finished = True

        # -- GET surface -----------------------------------------------
        def do_GET(self):
            self._rid = _RID_RE.sub(
                "", self.headers.get("X-Request-Id") or "")[:_RID_MAX] \
                or uuid.uuid4().hex[:16]
            path, _, query = self.path.partition("?")
            if path in ("/health", "/healthz"):
                self._json(200, state.health())
            elif path == "/metrics":
                q = parse_qs(query)
                accept = self.headers.get("Accept") or ""
                prom = (q.get("format", [""])[0] == "prometheus"
                        or "text/plain" in accept or "openmetrics" in accept)
                scope = q.get("scope", [""])[0] or (
                    "fleet" if state.fleet_scope_default else "self")
                if scope not in ("fleet", "self"):
                    self._json(400, {"error": f"unknown scope {scope!r}; "
                                              "expected fleet|self"})
                    return
                if prom:
                    text = state.fleet.federated_prometheus() \
                        if scope == "fleet" \
                        else obs_metrics.render_prometheus()
                    data = text.encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                elif scope == "fleet":
                    self._json(200, state.fleet.federated_json())
                else:
                    self._json(200, obs_metrics.snapshot_json())
            elif path == "/debug/trace":
                # scope=fleet stitches every replica's span ring (plus
                # the router's own) into one wall-clock-aligned Perfetto
                # timeline with journal markers; ?trace=<id> narrows to
                # one request's fleet-wide story.  Default scope is the
                # router's own ring, same contract as a replica's.
                qs = parse_qs(query)
                scope = qs.get("scope", [""])[0]
                if scope == "fleet":
                    self._json(200, state.fleet.fleet_trace(
                        trace=qs.get("trace", [None])[0]))
                    return
                if "since" in qs:
                    try:
                        since = int(qs["since"][0])
                    except ValueError:
                        since = 0
                    self._json(200, obs_trace.raw(since))
                    return
                try:
                    last = int(qs["last"][0]) if "last" in qs else 20
                except ValueError:
                    last = 20
                self._json(200, obs_trace.trace_json(last))
            elif path == "/debug/events":
                # the pod event journal (spawn/death/respawn/quarantine/
                # eject/readmit/scale/reshape live here in the router
                # process); ?since=<seq> tails incrementally,
                # ?scope=fleet folds in every replica's journal too
                qs = parse_qs(query)
                since = None
                if "since" in qs:
                    try:
                        since = int(qs["since"][0])
                    except ValueError:
                        since = 0
                if qs.get("scope", [""])[0] == "fleet":
                    self._json(200, state.fleet.fleet_events(since))
                else:
                    self._json(200, obs_events.snapshot(since))
            elif path == "/debug/requests":
                try:
                    n = int(q[0]) if (q := parse_qs(query).get("n")) else 50
                except ValueError:
                    n = 50
                self._json(200, {"requests": obs_flight.recent(n)})
            elif path.startswith("/debug/requests/"):
                rid = path[len("/debug/requests/"):]
                rec = obs_flight.get(rid)
                if rec is None:
                    self._json(404, {"error": f"no flight record for "
                                              f"request id {rid!r}"})
                else:
                    self._json(200, rec)
            elif path == "/v1/models":
                self._proxy_models()
            else:
                self._json(404, {"error": f"unknown path {path}"})

        def _admin_elastic(self, path, query):
            """Elastic pod control surface: ``POST /admin/scale?n=N``
            and ``POST /admin/reshape?tp=N``.  Commands are accepted
            (202) and executed asynchronously on the controller
            thread; convergence is observable through the ``fleet``
            block in ``/health``."""
            ctl = state.elastic
            if ctl is None:
                self._json(404, {"error": "this router has no elastic "
                                          "controller (run serve-pod "
                                          "--supervise --elastic)"})
                return
            q = parse_qs(query)
            try:
                if path == "/admin/scale":
                    if "n" not in q:
                        raise ValueError("scale needs ?n=<replicas>")
                    out = ctl.request_scale(int(q["n"][0]))
                else:
                    if "tp" not in q:
                        raise ValueError("reshape needs ?tp=<degree>")
                    out = ctl.request_reshape(int(q["tp"][0]))
            except ValueError as e:
                self._json(400, {"error": f"bad elastic command: {e}"})
                return
            self._json(202, out)

        def _proxy_models(self):
            b = state.registry.pick()
            if b is None:
                self._json(503, {"error": "no backend available"},
                           headers=[("Retry-After",
                                     jittered_retry_after(5))])
                return
            try:
                conn = state.connect(b)
                try:
                    conn.request("GET", "/v1/models")
                    resp = conn.getresponse()
                    data = resp.read()
                    self._relay(resp.status, data,
                                resp.getheader("Content-Type"))
                finally:
                    conn.close()
            except OSError:
                state.registry.record_failure(b)
                self._json(502, {"error": f"backend {b.addr} unreachable"})

        # -- POST surface ----------------------------------------------
        def do_POST(self):
            path, _, query = self.path.partition("?")
            if path in ("/admin/scale", "/admin/reshape"):
                self._admin_elastic(path, query)
                return
            if path not in ("/v1/completions", "/v1/chat/completions"):
                self._json(404, {"error": f"unknown path {path}"})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                self._json(400, {"error": "bad Content-Length"})
                return
            if length <= 0:
                self._json(400, {"error": "request body required"})
                return
            if length > MAX_BODY_BYTES:
                self._json(413, {"error": "request body too large"})
                return
            try:
                raw = self.rfile.read(length)
            except OSError:
                return
            try:
                body = json.loads(raw)
            except ValueError as e:
                self._json(400, {"error": f"bad JSON: {e}"})
                return
            self._rid = _RID_RE.sub(
                "", self.headers.get("X-Request-Id") or "")[:_RID_MAX] \
                or uuid.uuid4().hex[:16]
            set_request_id(self._rid)
            # fleet trace context: adopt the client's X-Dllama-Trace or
            # mint one here at the fleet edge.  Propagated on every
            # upstream hop and carried inside DLREQ01 records, so a
            # request that is handed off / resumed between replicas is
            # ONE trace id across every process's span ring.
            self._trace = obs_trace.sanitize_trace_id(
                self.headers.get("X-Dllama-Trace")) \
                or obs_trace.new_trace_id()
            obs_trace.set_trace(self._rid, self._trace)
            # QoS class rides alongside X-Request-Id: body field wins
            # over the header; unknown values degrade to None (the
            # replica applies its own default/validation)
            prio = body.get("priority") \
                or self.headers.get("X-Dllama-Priority")
            prio = str(prio).strip().lower() if prio is not None else None
            self._prio = prio if prio in _PRIORITIES else None
            # resume_policy is a ROUTER contract, never forwarded: with
            # "auto" (the default) a greedy stream whose replica dies
            # mid-decode is transparently resumed on a peer; "never"
            # keeps today's honest finish_reason="replica_lost"
            resume = body.pop("resume_policy", None)
            if resume is not None:
                resume = str(resume).strip().lower()
                if resume not in ("auto", "never"):
                    self._json(400, {
                        "error": f"unknown resume_policy {resume!r}; "
                                 "expected auto|never"})
                    return
                raw = json.dumps(body).encode()
            self._resume_policy = resume
            # the byte-parity resume guarantee only exists for greedy
            # decode (the house invariant); sampled requests are never
            # silently regenerated
            self._greedy = body.get("temperature") == 0
            self._proxy_completion(path, raw, body)

        def _proxy_completion(self, path: str, raw: bytes,
                              body: dict) -> None:
            chat = path == "/v1/chat/completions"
            stream = bool(body.get("stream"))
            rid = self._rid
            obs_flight.submit(rid, path=path, stream=stream, hop=state.hop,
                              priority=self._prio)
            ctx = _Ctx()
            tried: list[Backend] = []
            retries_left = state.retries
            # the checkpoint poller only follows greedy streams: those
            # are the only ones the resume ladder may replay, so
            # checkpointing anything else would be wasted /admin work
            track = (stream and getattr(self, "_greedy", False)
                     and state.checkpoint_interval > 0)
            try:
                while True:
                    b = state.registry.pick(exclude=tried,
                                            priority=self._prio)
                    if b is None:
                        self._out_of_backends(ctx, chat, rid)
                        return
                    tried.append(b)
                    obs_flight.phase(rid, "dispatch", backend=b.addr)
                    obs_metrics.ROUTER_DISPATCH.inc(b.addr)
                    if track:
                        state.track_stream(rid, b)
                    state.registry.acquire(b)
                    try:
                        verdict = self._attempt(b, path, raw, chat,
                                                stream, rid, ctx)
                    finally:
                        state.registry.release(b)
                    if verdict == "done":
                        obs_flight.retire(rid, reason="done",
                                          backend=b.addr)
                        return
                    if verdict == "busy":
                        continue  # not a failure; try the next replica
                    if verdict == "lost":
                        self._handle_lost(b, path, raw, chat, stream,
                                          rid, ctx, tried)
                        return
                    # verdict == "retry": nothing client-visible
                    # happened — the request is still idempotent
                    if retries_left <= 0:
                        self._out_of_backends(ctx, chat, rid)
                        return
                    retries_left -= 1
                    obs_metrics.ROUTER_RETRIES.inc()
                    obs_flight.phase(rid, "retry", backend=b.addr)
            finally:
                if track:
                    state.untrack_stream(rid)

        def _out_of_backends(self, ctx: _Ctx, chat: bool,
                             rid: str) -> None:
            """No replica can take (or finish) this request."""
            if ctx.headers_sent:
                self._finish_replica_lost(ctx, chat, rid)
                return
            if ctx.busy is not None:
                status, data, retry_after = ctx.busy
                self._relay(status, data, "application/json",
                            headers=[("Retry-After", retry_after)])
                obs_flight.retire(rid, reason=f"busy_{status}")
                return
            self._json(503, {"error": "no backend available"},
                       headers=[("Retry-After", jittered_retry_after(5))])
            obs_flight.retire(rid, reason="no_backend")

        def _finish_replica_lost(self, ctx: _Ctx, chat: bool,
                                 rid: str) -> None:
            """End a stream that already carried content: flag the
            truncation instead of silently closing the socket."""
            obs_metrics.ROUTER_REPLICA_LOST.inc()
            if ctx.headers_sent and not ctx.client_gone:
                self._client_chunk(ctx, chat, "", "replica_lost")
                self._client_event(ctx, b"[DONE]")
            elif not ctx.headers_sent:
                # non-stream request whose backend vanished after the
                # retry budget: a 502 is the honest answer
                self._json(502, {"error": "backend lost mid-request",
                                 "finish_reason": "replica_lost"})
            obs_flight.retire(rid, reason="replica_lost")

        # -- mid-stream resume (crash tolerance) -----------------------
        def _handle_lost(self, dead: Backend, path: str, raw: bytes,
                         chat: bool, stream: bool, rid: str, ctx: _Ctx,
                         tried: list[Backend]) -> None:
            """A backend died after forwarding content.  For a greedy
            stream under ``resume_policy=auto`` the router resumes on a
            peer instead of truncating: tier 1 imports the most recent
            DLREQ01 checkpoint (KV intact — no re-prefill), tier 2
            replays the original request and swallows the regenerated
            prefix (greedy decode is deterministic, so the peer
            re-produces byte-identical text — verified char by char).
            Anything non-greedy, non-stream, or opted out keeps the
            honest ``finish_reason="replica_lost"``.
            """
            policy = getattr(self, "_resume_policy", None) \
                or state.resume_policy
            resumable = (stream and ctx.headers_sent
                         and not ctx.client_gone and not ctx.finished
                         and policy == "auto"
                         and getattr(self, "_greedy", False))
            if not resumable:
                self._finish_replica_lost(ctx, chat, rid)
                return
            obs_flight.phase(rid, "resume", backend=dead.addr,
                             chars=ctx.chars)
            record = state.checkpoints.pop(rid)
            if record is not None:
                got = self._offer_record(record, ctx.chars,
                                         exclude=set(tried))
                if got is not None:
                    peer, resp, conn = got
                    obs_flight.phase(rid, "resume_checkpoint",
                                     backend=peer.addr)
                    try:
                        verdict = self._relay_continuation(
                            peer, resp, chat, rid, ctx)
                    finally:
                        conn.close()
                    if verdict == "done":
                        obs_metrics.ROUTER_RESUMES.inc("checkpoint")
                        obs_flight.retire(rid, reason="resumed",
                                          backend=peer.addr)
                        obs_events.emit(
                            "resume", rid=rid, tier="checkpoint",
                            src=dead.addr, dst=peer.addr,
                            trace=getattr(self, "_trace", None))
                        return
                    # the continuation died too — fall through to the
                    # re-run tier; ctx.text still covers every char the
                    # client has seen, so the prefix oracle holds
            verdict = self._resume_rerun(path, raw, chat, rid, ctx,
                                         tried)
            if verdict == "done":
                obs_metrics.ROUTER_RESUMES.inc("rerun")
                obs_flight.retire(rid, reason="resumed")
                obs_events.emit("resume", rid=rid, tier="rerun",
                                src=dead.addr,
                                trace=getattr(self, "_trace", None))
                return
            obs_metrics.ROUTER_RESUMES.inc(verdict)
            self._finish_replica_lost(ctx, chat, rid)

        def _resume_rerun(self, path: str, raw: bytes, chat: bool,
                          rid: str, ctx: _Ctx,
                          tried: list[Backend]) -> str:
            """Tier-2 resume: replay the ORIGINAL request on up to
            ``retries+1`` fresh peers per round, for up to
            ``resume_window`` seconds.  Returns ``done`` on a spliced
            finish, ``mismatch`` on prefix divergence, ``failed`` on a
            replica-side error event, ``no_peer`` when the window
            closes with the fleet still exhausted.

            The window (not a single pass) is the point: right after a
            crash the best peer is often seconds away — the victim's
            replacement mid-readmission, or the survivor riding out a
            saturation burst (429 → ``retry``) — and truncating the
            client over a transient costs the whole resume.  Round one
            excludes the backends the request already died on; later
            rounds trust the registry's live ejection state instead, so
            a respawned victim becomes eligible the moment it is
            re-admitted."""
            deadline = time.monotonic() + state.resume_window
            first_round = True
            while True:
                round_tried = list(tried) if first_round else []
                for _ in range(state.retries + 1):
                    b = state.registry.pick(
                        exclude=round_tried,
                        priority=getattr(self, "_prio", None))
                    if b is None:
                        break
                    round_tried.append(b)
                    obs_flight.phase(rid, "resume_rerun",
                                     backend=b.addr)
                    state.registry.acquire(b)
                    try:
                        verdict = self._rerun_attempt(b, path, raw,
                                                      chat, rid, ctx)
                    finally:
                        state.registry.release(b)
                    if verdict != "retry":
                        return verdict
                first_round = False
                if time.monotonic() >= deadline:
                    return "no_peer"
                time.sleep(0.5)

        def _rerun_attempt(self, b: Backend, path: str, raw: bytes,
                           chat: bool, rid: str, ctx: _Ctx) -> str:
            """One re-run on one peer: swallow the regenerated prefix
            (comparing against ``ctx.text`` — any divergence aborts the
            splice), then forward the remainder into the client's open
            stream as if it never broke."""
            try:
                conn = state.connect(b)
            except OSError:
                state.registry.record_failure(b)
                return "retry"
            try:
                try:
                    headers = {"Content-Type": "application/json",
                               "X-Request-Id": rid,
                               "X-Dllama-Hop": state.hop}
                    if getattr(self, "_trace", None):
                        headers["X-Dllama-Trace"] = self._trace
                    if getattr(self, "_prio", None):
                        headers["X-Dllama-Priority"] = self._prio
                    conn.request("POST", path, raw, headers=headers)
                    if state.stall_timeout > 0 and conn.sock is not None:
                        # armed before getresponse: a close-delimited
                        # response nulls conn.sock (see _attempt)
                        conn.sock.settimeout(state.stall_timeout)
                    resp = conn.getresponse()
                except OSError:
                    state.registry.record_failure(b)
                    return "retry"
                if resp.status != 200 or "text/event-stream" not in (
                        resp.getheader("Content-Type") or ""):
                    resp.read()
                    return "retry"
                prefix = ctx.text
                pos = 0  # chars of the prefix re-verified so far
                try:
                    for payload in _iter_sse(resp):
                        if payload == b"[DONE]":
                            state.registry.record_success(b)
                            if ctx.finished:
                                self._client_event(ctx, b"[DONE]")
                                return "done"
                            return "retry"
                        try:
                            evt = json.loads(payload)
                        except ValueError:
                            continue
                        if "error" in evt:
                            # deterministic server-side error: a third
                            # peer would hit it too — stop here
                            return "failed"
                        text, finish = _evt_fields(evt, chat)
                        if finish == "handoff":
                            # the peer began draining mid-re-run: chase
                            # its record; emitted_chars=ctx.chars makes
                            # the importer absorb whatever prefix was
                            # still unregenerated
                            got = self._handoff(b, rid, chat, ctx,
                                                stream=True)
                            return "done" if got == "done" else "failed"
                        if pos < len(prefix):
                            k = min(len(text), len(prefix) - pos)
                            if text[:k] != prefix[pos:pos + k]:
                                _log.warning(
                                    "resume prefix mismatch at char %d "
                                    "on %s (request %s): re-run is not "
                                    "byte-identical; aborting splice",
                                    pos, b.addr, rid)
                                return "mismatch"
                            pos += k
                            text = text[k:]
                        if finish is not None and pos < len(prefix):
                            # finished before regenerating everything
                            # the client already saw — divergence
                            return "mismatch"
                        if text or finish is not None:
                            self._client_chunk(ctx, chat, text, finish)
                            if ctx.client_gone:
                                return "done"
                except TimeoutError:
                    obs_metrics.ROUTER_STALLS.inc()
                    state.registry.force_eject(
                        b, "stream stall (--stall-timeout)")
                except (OSError, http.client.HTTPException):
                    state.registry.record_failure(b)
                if ctx.finished:
                    self._client_event(ctx, b"[DONE]")
                    return "done"
                # the re-run died mid-way; ctx.text grew to cover all
                # forwarded chars, so another peer can pick up the
                # (longer) prefix — still a clean retry
                return "retry"
            finally:
                conn.close()

        def _attempt(self, b: Backend, path: str, raw: bytes, chat: bool,
                     stream: bool, rid: str, ctx: _Ctx) -> str:
            """One dispatch to one backend.  Returns a verdict:
            ``done`` (response fully relayed), ``busy`` (replica said
            429/503 — try a sibling), ``retry`` (backend failed with
            nothing forwarded), ``lost`` (failed after content)."""
            try:
                conn = state.connect(b)
            except OSError:
                state.registry.record_failure(b)
                return "retry"
            try:
                try:
                    headers = {"Content-Type": "application/json",
                               "X-Request-Id": rid,
                               "X-Dllama-Hop": state.hop}
                    if getattr(self, "_trace", None):
                        headers["X-Dllama-Trace"] = self._trace
                    if getattr(self, "_prio", None):
                        headers["X-Dllama-Priority"] = self._prio
                    conn.request("POST", path, raw, headers=headers)
                    if stream and state.stall_timeout > 0 \
                            and conn.sock is not None:
                        # per-read deadline on the stream: a replica that
                        # is connected but silent (SIGSTOP, device hang)
                        # trips TimeoutError in _relay_stream and is
                        # treated as dead.  Armed BEFORE getresponse —
                        # a close-delimited response nulls conn.sock
                        # when the headers land — so it also bounds
                        # time-to-first-token (queue + prefill + compile
                        # all count) and the flag must exceed worst-case
                        # cold-start; see docs/ROBUSTNESS.md.
                        conn.sock.settimeout(state.stall_timeout)
                    resp = conn.getresponse()
                except OSError:
                    state.registry.record_failure(b)
                    return "retry"
                if resp.status in (429, 503):
                    ctx.busy = (resp.status, resp.read(),
                                resp.getheader("Retry-After") or "5")
                    return "busy"
                if resp.status != 200:
                    # a client error is between the client and the model
                    # server — relay it verbatim, no retry
                    self._relay(resp.status, resp.read(),
                                resp.getheader("Content-Type"))
                    state.registry.record_success(b)
                    obs_flight.phase(rid, "relay_error",
                                     status=resp.status)
                    return "done"
                if "text/event-stream" in (resp.getheader("Content-Type")
                                           or ""):
                    return self._relay_stream(b, resp, chat, rid, ctx)
                try:
                    data = resp.read()
                except OSError:
                    state.registry.record_failure(b)
                    return "retry"
                state.registry.record_success(b)
                return self._relay_json(b, data, chat, rid, ctx)
            finally:
                conn.close()

        def _relay_stream(self, b: Backend, resp, chat: bool, rid: str,
                          ctx: _Ctx) -> str:
            self._sse_headers(ctx)
            try:
                for payload in _iter_sse(resp):
                    if payload == b"[DONE]":
                        state.registry.record_success(b)
                        self._client_event(ctx, b"[DONE]")
                        return "done"
                    try:
                        evt = json.loads(payload)
                    except ValueError:
                        continue
                    if "error" in evt:
                        # replica-side server error mid-stream: relay it
                        # and the DONE that follows; no retry (the
                        # replica is alive and already answered)
                        self._client_event(ctx, payload)
                        ctx.finished = True
                        continue
                    if ctx.cid is None:
                        ctx.cid = evt.get("id")
                        ctx.model = evt.get("model")
                        ctx.created = evt.get("created")
                    text, finish = _evt_fields(evt, chat)
                    if finish == "handoff":
                        # internal signal — never forwarded.  The held-
                        # back text riding this chunk is NOT forwarded
                        # either: the importer re-emits everything past
                        # ctx.chars, so dropping it here keeps the
                        # client's stream gapless and duplicate-free.
                        return self._handoff(b, rid, chat, ctx,
                                             stream=True)
                    if not self._client_event(ctx, payload):
                        return "done"  # client gone; nothing to salvage
                    ctx.chars += len(text)
                    ctx.text += text
                    if finish is not None:
                        ctx.finished = True
            except TimeoutError:
                # TimeoutError precedes the OSError catch (it IS an
                # OSError since 3.10): a stalled read is a wedged-but-
                # connected replica, which a failure streak would never
                # eject (its /health may still answer) — force it out.
                obs_metrics.ROUTER_STALLS.inc()
                state.registry.force_eject(
                    b, "stream stall (--stall-timeout)")
                obs_flight.phase(rid, "stream_stall", backend=b.addr)
                if ctx.finished:
                    self._client_event(ctx, b"[DONE]")
                    return "done"
                return "retry" if ctx.chars == 0 else "lost"
            except (OSError, http.client.HTTPException):
                pass
            # upstream socket died (or closed without [DONE])
            state.registry.record_failure(b)
            if ctx.finished:
                # the finish chunk made it out; only [DONE] was lost
                self._client_event(ctx, b"[DONE]")
                return "done"
            return "retry" if ctx.chars == 0 else "lost"

        def _relay_json(self, b: Backend, data: bytes, chat: bool,
                        rid: str, ctx: _Ctx) -> str:
            try:
                obj = json.loads(data)
                choice = (obj.get("choices") or [{}])[0]
                finish = choice.get("finish_reason")
            except (ValueError, AttributeError):
                self._relay(200, data, "application/json")
                return "done"
            if finish != "handoff":
                self._relay(200, data, "application/json")
                return "done"
            # the replica drained mid-request: the buffered JSON holds a
            # partial completion.  Resume on a peer and splice.
            partial = (choice.get("message") or {}).get("content", "") \
                if chat else choice.get("text", "")
            cont = self._handoff_collect(b, rid, len(partial))
            if cont is None:
                if not partial:
                    return "retry"  # nothing to lose: full re-run
                obs_metrics.ROUTER_REPLICA_LOST.inc()
                self._patch_json(obj, chat, partial, "replica_lost", None)
                self._relay(200, json.dumps(obj).encode(),
                            "application/json")
                obs_flight.retire(rid, reason="replica_lost")
                return "done"
            tail, cont_finish, completion_tokens = cont
            if chat and cont_finish == "length":
                cont_finish = "stop"  # the chat budget contract
            self._patch_json(obj, chat, partial + tail, cont_finish,
                             completion_tokens)
            self._relay(200, json.dumps(obj).encode(), "application/json")
            return "done"

        @staticmethod
        def _patch_json(obj: dict, chat: bool, text: str,
                        finish: str, completion_tokens: int | None) -> None:
            choice = obj["choices"][0]
            choice["finish_reason"] = finish
            if chat:
                choice.setdefault("message", {})["content"] = text
            else:
                choice["text"] = text
            usage = obj.get("usage")
            if usage and completion_tokens is not None:
                usage["completion_tokens"] = completion_tokens
                usage["total_tokens"] = \
                    usage.get("prompt_tokens", 0) + completion_tokens

        # -- KV hand-off -----------------------------------------------
        def _fetch_record(self, b: Backend, rid: str) -> bytes | None:
            try:
                conn = state.connect(b)
                try:
                    conn.request("GET", f"/admin/export/{rid}")
                    resp = conn.getresponse()
                    data = resp.read()
                    return data if resp.status == 200 else None
                finally:
                    conn.close()
            except OSError:
                return None

        def _offer_record(self, record: bytes, emitted_chars: int,
                          exclude) -> tuple[Backend, object, object] | None:
            """POST the record to peers best-first; returns the open
            ``(peer, response, connection)`` of the accepting one."""
            for peer in state.registry.handoff_peers(exclude=exclude):
                try:
                    conn = state.connect(peer)
                    conn.request(
                        "POST",
                        f"/admin/import?emitted_chars={emitted_chars}",
                        record,
                        headers={"Content-Type":
                                 "application/octet-stream"})
                    resp = conn.getresponse()
                except OSError:
                    state.registry.record_failure(peer)
                    continue
                if resp.status == 200:
                    return peer, resp, conn
                body = resp.read()
                conn.close()
                if resp.status == 409:
                    _log.info("peer %s refused hand-off (geometry): %s",
                              peer.addr, body[:200])
                    continue  # incompatible shape — a sibling may fit
                if resp.status in (429, 503):
                    continue  # saturated/draining — try the next peer
                # 400 = the record itself is bad; no peer will differ
                _log.warning("hand-off import rejected (%d): %s",
                             resp.status, body[:200])
                return None
            return None

        def _handoff(self, b: Backend, rid: str, chat: bool, ctx: _Ctx,
                     *, stream: bool) -> str:
            """Migrate an exported request to a peer and splice its
            continuation into the client's open stream."""
            obs_flight.phase(rid, "handoff", backend=b.addr,
                             emitted_chars=ctx.chars)
            record = self._fetch_record(b, rid)
            got = self._offer_record(record, ctx.chars, exclude={b}) \
                if record else None
            if got is None:
                # no record (request was still queued — nothing decoded)
                # or no peer could take it: retry from scratch if the
                # client saw nothing, else flag the truncation
                return "retry" if ctx.chars == 0 else "lost"
            peer, resp, conn = got
            obs_metrics.ROUTER_HANDOFFS.inc()
            obs_flight.phase(rid, "handoff_resume", backend=peer.addr)
            obs_events.emit("handoff", rid=rid, src=b.addr, dst=peer.addr,
                            chars=ctx.chars,
                            trace=getattr(self, "_trace", None))
            try:
                return self._relay_continuation(peer, resp, chat, rid,
                                                ctx)
            finally:
                conn.close()

        def _relay_continuation(self, peer: Backend, resp, chat: bool,
                                rid: str, ctx: _Ctx) -> str:
            """Forward a ``/admin/import`` continuation (always
            text_completion-shaped) re-wrapped in the client's endpoint
            shape, with the original stream's id/model/created."""
            try:
                for payload in _iter_sse(resp):
                    if payload == b"[DONE]":
                        state.registry.record_success(peer)
                        if not ctx.finished:
                            # error event upstream ended without finish
                            return "lost"
                        self._client_event(ctx, b"[DONE]")
                        return "done"
                    try:
                        evt = json.loads(payload)
                    except ValueError:
                        continue
                    if evt.get("object") == "handoff.usage" \
                            or "error" in evt:
                        continue
                    choice = (evt.get("choices") or [{}])[0]
                    text = choice.get("text") or ""
                    finish = choice.get("finish_reason")
                    if finish == "handoff":
                        # the peer started draining too — chase the
                        # record to the next replica (chained hand-off)
                        if text:
                            self._client_chunk(ctx, chat, text, None)
                        return self._handoff(peer, rid, chat, ctx,
                                             stream=True)
                    if chat and finish == "length":
                        finish = "stop"
                    self._client_chunk(ctx, chat, text, finish)
                    if ctx.client_gone:
                        return "done"
            except (OSError, http.client.HTTPException):
                pass
            state.registry.record_failure(peer)
            if ctx.finished:
                self._client_event(ctx, b"[DONE]")
                return "done"
            return "lost"  # the record was consumed; no second chance

        def _handoff_collect(self, b: Backend, rid: str,
                             emitted_chars: int
                             ) -> tuple[str, str, int | None] | None:
            """Non-streaming twin of :meth:`_handoff`: fetch + offer,
            then drain the continuation into ``(tail_text, finish,
            completion_tokens)``.  Follows chained hand-offs."""
            obs_flight.phase(rid, "handoff", backend=b.addr,
                             emitted_chars=emitted_chars)
            record = self._fetch_record(b, rid)
            got = self._offer_record(record, emitted_chars,
                                     exclude={b}) if record else None
            if got is None:
                return None
            peer, resp, conn = got
            obs_metrics.ROUTER_HANDOFFS.inc()
            obs_flight.phase(rid, "handoff_resume", backend=peer.addr)
            obs_events.emit("handoff", rid=rid, src=b.addr, dst=peer.addr,
                            chars=emitted_chars,
                            trace=getattr(self, "_trace", None))
            parts: list[str] = []
            finish = None
            completion_tokens = None
            try:
                for payload in _iter_sse(resp):
                    if payload == b"[DONE]":
                        break
                    try:
                        evt = json.loads(payload)
                    except ValueError:
                        continue
                    if evt.get("object") == "handoff.usage":
                        completion_tokens = (evt.get("usage") or {}) \
                            .get("completion_tokens")
                        continue
                    if "error" in evt:
                        return None
                    choice = (evt.get("choices") or [{}])[0]
                    parts.append(choice.get("text") or "")
                    finish = choice.get("finish_reason") or finish
            except (OSError, http.client.HTTPException):
                state.registry.record_failure(peer)
                return None
            finally:
                conn.close()
            if finish == "handoff":
                nxt = self._handoff_collect(
                    peer, rid, emitted_chars + sum(map(len, parts)))
                if nxt is None:
                    return None
                tail2, finish2, ct2 = nxt
                return "".join(parts) + tail2, finish2, ct2
            if finish is None:
                return None
            state.registry.record_success(peer)
            return "".join(parts), finish, completion_tokens

    return Handler


def _checkpoint_loop(state: RouterState, stop: threading.Event) -> None:
    """Proactive DLREQ01 checkpointing of in-flight greedy streams.

    Every ``checkpoint_interval`` seconds, snapshot each tracked
    stream's slot via ``GET /admin/checkpoint/<rid>`` on its current
    backend and cache the record.  When that backend later dies
    mid-stream, tier-1 resume imports the cached record on a peer —
    the request restarts from the checkpoint's KV state instead of
    re-prefilling the whole prompt (the win grows with context
    length).  A failed poll is skipped, never fatal: the stream it
    covers is still live and tier-2 re-run remains available."""
    while not stop.wait(state.checkpoint_interval):
        for rid, b in state.checkpoint_targets():
            try:
                # short deadline: one hung replica must not stall the
                # whole poll round for upstream_timeout
                conn = http.client.HTTPConnection(
                    b.host, b.port,
                    timeout=max(2.0, state.checkpoint_interval))
                try:
                    conn.request("GET", f"/admin/checkpoint/{rid}")
                    resp = conn.getresponse()
                    data = resp.read()
                finally:
                    conn.close()
            except OSError:
                continue
            if resp.status == 200 and data:
                state.checkpoints.put(rid, data)
        state.checkpoints.sweep()


def serve(state: RouterState, *, host: str = "0.0.0.0",
          port: int = 9990) -> None:
    httpd = ThreadingHTTPServer((host, port), make_handler(state))
    httpd.daemon_threads = True
    state.registry.start()
    ckpt_stop = threading.Event()
    ckpt_thread = None
    if state.checkpoint_interval > 0:
        ckpt_thread = threading.Thread(
            target=_checkpoint_loop, args=(state, ckpt_stop),
            name="router-checkpoint", daemon=True)
        ckpt_thread.start()

    def _shutdown(signum, frame):
        _log.info("router signal %d: shutting down", signum)
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    _log.info("router listening on %s:%d fronting %s", host, port,
              ",".join(b.addr for b in state.registry.backends))
    print(f"💡 router on {host}:{port} → "
          f"{len(state.registry.backends)} backends", flush=True)
    try:
        httpd.serve_forever()
    finally:
        ckpt_stop.set()
        if ckpt_thread is not None:
            ckpt_thread.join(timeout=state.checkpoint_interval + 3.0)
        state.registry.stop()
        httpd.server_close()


def main(args) -> None:
    addrs = [a.strip() for a in (getattr(args, "backends", None) or "")
             .split(",") if a.strip()]
    if not addrs:
        raise SystemExit("router mode requires --backends host:port,...")
    registry = Registry(
        addrs,
        probe_interval=getattr(args, "probe_interval", 2.0),
        eject_after=getattr(args, "eject_after", 3),
        readmit_after=getattr(args, "readmit_after", 2),
        probe_timeout=min(float(getattr(args, "upstream_timeout", 120.0)),
                          5.0))
    state = RouterState(
        registry,
        retries=getattr(args, "router_retries", 2),
        upstream_timeout=getattr(args, "upstream_timeout", 120.0),
        stall_timeout=getattr(args, "stall_timeout", 0.0),
        checkpoint_interval=getattr(args, "checkpoint_interval", 0.0),
        resume_policy=getattr(args, "resume_policy", "auto"))
    serve(state, host=getattr(args, "host", "0.0.0.0"),
          port=getattr(args, "port", 9990))
