"""Fleet router: front N ``dllama-api`` replicas with one OpenAI surface.

The reference's topology is a single root process that owns admission,
sampling, and the residual stream — one process is the whole service.
This package is the step past that: a standalone router process
(``python -m dllama_tpu.router --backends host:port,...``) that

* probes each replica's ``/health`` and scores it on the machine-
  readable ``capacity`` block (free slots, free KV pages, queue depth,
  degraded flag, SLO verdict) — :mod:`.registry`;
* dispatches each request to the least-loaded healthy replica, with
  hysteretic ejection after consecutive failures and re-admission after
  consecutive healthy probes;
* retries a request on another replica when a backend dies before any
  response bytes were forwarded, and finishes the stream with
  ``finish_reason="replica_lost"`` when it dies after;
* migrates in-flight requests off a draining replica via per-request
  DLREQ01 KV hand-off records (``/admin/export`` → ``/admin/import``),
  so ``SIGTERM``-one-replica is a zero-error rolling restart —
  :mod:`.service`.

The router is pure stdlib HTTP plumbing: no jax, no model, no
tokenizer.  It reuses the obs stack (flight recorder, metric registry)
in its own process, so ``/debug/requests`` and ``/metrics`` work the
same way here as on a replica.  See docs/SERVING.md.
"""
