"""``python -m dllama_tpu.router`` — start the fleet router without
importing the model/jax stack (the full CLI's ``dllama router`` mode
works too; this entry point is what deploy scripts and the fault drills
use because it starts in milliseconds)."""

import argparse

from ..obs import flight as obs_flight
from ..obs.log import configure as configure_logging
from .service import main as service_main


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dllama_tpu.router",
        description="Fleet router fronting N dllama-api replicas "
                    "(docs/SERVING.md)")
    p.add_argument("--backends", required=True,
                   help="comma-separated replica addresses (host:port,...)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9990)
    p.add_argument("--probe-interval", type=float, default=2.0,
                   help="seconds between /health probes per backend")
    p.add_argument("--eject-after", type=int, default=3,
                   help="consecutive failures before ejection")
    p.add_argument("--readmit-after", type=int, default=2,
                   help="consecutive healthy probes before re-admission")
    p.add_argument("--router-retries", type=int, default=2,
                   help="max re-dispatches before giving up on a request")
    p.add_argument("--upstream-timeout", type=float, default=120.0,
                   help="socket timeout per upstream request (seconds)")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   help="seconds an open upstream stream may go silent "
                        "before the watchdog treats the replica as dead "
                        "(0 disables)")
    p.add_argument("--checkpoint-interval", type=float, default=0.0,
                   help="seconds between proactive DLREQ01 checkpoints "
                        "of in-flight greedy streams (0 disables)")
    p.add_argument("--resume-policy", choices=["auto", "never"],
                   default="auto",
                   help="default mid-stream crash behavior: auto resumes "
                        "greedy streams on a peer, never keeps the "
                        "honest replica_lost")
    p.add_argument("--log-format", choices=["human", "json"], default=None)
    p.add_argument("--log-level", default=None,
                   choices=["debug", "info", "warning", "error"])
    p.add_argument("--flight-buffer", type=int, default=None,
                   help="router-side flight ring capacity")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    configure_logging(args.log_format, args.log_level)
    obs_flight.configure(args.flight_buffer)
    service_main(args)


if __name__ == "__main__":
    main()
