"""``dllama serve-pod``: dp × tp engine replicas in one process, fronted
by the fleet router on one public port.

The single-replica serving story shards one engine over every local
device.  On a pod slice that wastes the topology: decode is
latency-bound per request, so past the tp degree that saturates ICI
bandwidth, extra chips buy more *replicas*, not faster tokens.  This
mode partitions the local devices into ``--dp`` independent replicas of
``--workers tpu:N`` chips each:

* the model is read from disk ONCE (host-side), then placed per replica
  mesh — no N× disk traffic for N replicas;
* every replica runs the full serving stack — OpenAI surface, slot
  scheduler, paged KV, hand-off — on its own loopback port (ephemeral,
  never a collision), exactly the process a standalone ``dllama-api``
  would be;
* the fleet router (:mod:`.registry` + :mod:`.service`) starts in the
  same process with the replicas auto-registered as backends, so the
  operator sees ONE address and the usual probe/eject/score dispatch.

``SIGTERM`` drains through the router path like any fleet: the router
stops, then each replica's server shuts down.  Cross-replica request
migration (DLREQ01) keeps working because the replicas expose the same
``/admin/export``/``/admin/import`` surface as external backends.
"""

from __future__ import annotations

from ..obs.log import get_logger

_log = get_logger("router.pod")


def parse_pod_tp(workers: str | None, n_devices: int, dp: int) -> int:
    """Per-replica tp degree: ``--workers tpu:N`` names it explicitly;
    default splits every local device evenly over the dp replicas."""
    if workers is None:
        tp, rem = divmod(n_devices, dp)
        if tp < 1:
            raise SystemExit(
                f"serve-pod: {dp} replicas need at least {dp} of the "
                f"{n_devices} local devices")
        return tp
    w = workers.strip().lower()
    if not w.startswith("tpu:"):
        raise SystemExit(f"serve-pod: --workers takes tpu:N, got {workers!r}")
    try:
        tp = int(w.split(":", 1)[1])
    except ValueError:
        raise SystemExit(f"serve-pod: --workers takes tpu:N, got {workers!r}")
    if tp < 1:
        raise SystemExit(f"serve-pod: tp degree must be >= 1, got {tp}")
    return tp


def partition_devices(devices, dp: int, tp: int) -> list[list]:
    """dp disjoint tp-sized device groups, contiguous in enumeration
    order (tp innermost keeps each replica's collectives on the
    fastest links, matching make_mesh's axis order)."""
    need = dp * tp
    if need > len(devices):
        raise SystemExit(
            f"serve-pod: dp={dp} × tp={tp} needs {need} devices, "
            f"only {len(devices)} present")
    if need < len(devices):
        _log.warning("pod_devices_idle", extra={
            "used": need, "present": len(devices)})
    return [list(devices[r * tp:(r + 1) * tp]) for r in range(dp)]


def main(args) -> None:
    import jax
    import jax.numpy as jnp

    from .. import quants
    from ..cli import DTYPES
    from ..io import mfile, tfile
    from ..models.config import ModelConfig
    from ..models.params import load_params
    from ..obs import dispatch as obs_dispatch
    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import check_tp_constraint
    from ..runtime.engine import Engine
    from ..runtime.scheduler import SlotScheduler
    from ..server import api
    from ..tokenizer.bpe import Tokenizer
    from .registry import Registry
    from .service import RouterState
    from .service import serve as router_serve

    if not args.model or not args.tokenizer:
        raise SystemExit("--model and --tokenizer are required for serve-pod")
    if args.sp > 1 or args.ep > 1:
        raise SystemExit("serve-pod partitions devices into dp × tp "
                         "replicas; --sp/--ep are not supported here "
                         "(run a single replica with dllama-api instead)")
    devices = jax.devices()
    dp = max(args.dp, 1)
    tp = parse_pod_tp(args.workers, len(devices), dp)
    groups = partition_devices(devices, dp, tp)

    wft = quants.FLOAT_TYPE_BY_NAME[args.weights_float_type] \
        if args.weights_float_type else None
    mf = mfile.MFile(args.model, weights_ftype=wft,
                     verify=getattr(args, "verify_weights", False))
    bft = "bf16" if args.buffer_float_type == "q80" else args.buffer_float_type
    dtype = jnp.dtype(DTYPES[bft])
    cfg = ModelConfig.from_spec(mf.spec, dtype=dtype)
    # fail before the (minutes-long) weight load, with the valid-degrees
    # hint naming the tp that WOULD work
    check_tp_constraint(cfg, tp)
    cfg, params = load_params(mf, cfg, dtype=dtype,
                              keep_quantized=not args.dequantize,
                              fuse=tp == 1)
    tok = Tokenizer(tfile.read_tfile(args.tokenizer))
    if tok.vocab_size != cfg.vocab_size:
        raise SystemExit("tokenizer is incompatible with model "
                         "(vocab size mismatch)")
    kv_dtype = ("q8" if args.kv_cache_dtype == "q8"
                else jnp.dtype(DTYPES[args.kv_cache_dtype])
                if args.kv_cache_dtype else None)

    replicas: list[tuple[str, object, SlotScheduler | None]] = []
    try:
        for r, devs in enumerate(groups):
            mesh = make_mesh(tp=tp, devices=devs)
            engine = Engine(cfg, params, mesh=mesh, seq_len=args.max_seq_len,
                            kv_dtype=kv_dtype, batch=1,
                            step_timeout=getattr(args, "step_timeout", None),
                            numeric_checks=(True if getattr(
                                args, "numeric_checks", False) else None))
            batch_engine = None
            scheduler = None
            if args.batch_slots > 0:
                if args.kv_pages > 0 and engine.cache.quantized:
                    raise SystemExit("--kv-pages needs a dense KV cache; "
                                     "drop --kv-cache-dtype q8")
                batch_engine = Engine(
                    engine.cfg, engine.params, mesh=mesh,
                    batch=args.batch_slots, seq_len=args.max_seq_len,
                    kv_dtype=engine.cache.k.dtype,
                    step_timeout=getattr(args, "step_timeout", None),
                    kv_pages=args.kv_pages, kv_page_size=args.kv_page_size)
                try:
                    scheduler = SlotScheduler(
                        batch_engine,
                        prefill_chunk=args.sched_prefill_chunk,
                        max_wait_ms=args.sched_max_wait_ms,
                        max_queue=args.sched_max_queue,
                        prefix_reuse=not args.no_prefix_reuse,
                        overlap=not args.no_sched_overlap,
                        preempt=not args.no_preempt,
                        preempt_age_ms=args.preempt_age_ms,
                        preempt_cap=args.preempt_cap,
                        spill_dir=args.preempt_spill_dir)
                except ValueError as e:
                    _log.warning("slot_scheduler_disabled",
                                 extra={"replica": r, "reason": str(e)})
            state = api.ApiState(
                engine, tok, default_temperature=args.temperature,
                default_topp=args.topp, chunk=args.chunk,
                batch_engine=batch_engine, max_pending=args.max_pending,
                request_timeout=args.request_timeout,
                io_timeout=args.io_timeout, drain_grace=args.drain_grace,
                scheduler=scheduler,
                handoff=getattr(args, "handoff", False))
            # loopback + ephemeral port: the OS picks, so dp replicas can
            # never collide with each other or the public port
            server = api.serve(state, host="127.0.0.1", port=0,
                               block=False, install_signals=False)
            addr = "127.0.0.1:%d" % server.server_address[1]
            replicas.append((addr, server, scheduler))
            _log.info("pod_replica_up", extra={
                "replica": r, "tp": tp, "addr": addr,
                "devices": [str(d) for d in devs]})

        registry = Registry(
            [a for a, _, _ in replicas],
            probe_interval=args.probe_interval,
            eject_after=args.eject_after,
            readmit_after=args.readmit_after,
            probe_timeout=min(float(args.upstream_timeout), 5.0))
        rstate = RouterState(registry, retries=args.router_retries,
                             upstream_timeout=args.upstream_timeout)
        print(f"💡 serve-pod: {dp} replica(s) × tp={tp} over "
              f"{dp * tp}/{len(devices)} devices; router on :{args.port}")
        router_serve(rstate, host=args.host, port=args.port)
    finally:
        for _, server, scheduler in replicas:
            try:
                server.shutdown()
                server.server_close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            if scheduler is not None:
                scheduler.close()
        print(obs_dispatch.summary_line())
        coll = obs_dispatch.collective_line()
        if coll:
            print(coll)
