"""``dllama serve-pod``: dp × tp engine replicas in one process, fronted
by the fleet router on one public port.

The single-replica serving story shards one engine over every local
device.  On a pod slice that wastes the topology: decode is
latency-bound per request, so past the tp degree that saturates ICI
bandwidth, extra chips buy more *replicas*, not faster tokens.  This
mode partitions the local devices into ``--dp`` independent replicas of
``--workers tpu:N`` chips each:

* the model is read from disk ONCE (host-side), then placed per replica
  mesh — no N× disk traffic for N replicas;
* every replica runs the full serving stack — OpenAI surface, slot
  scheduler, paged KV, hand-off — on its own loopback port (ephemeral,
  never a collision), exactly the process a standalone ``dllama-api``
  would be;
* the fleet router (:mod:`.registry` + :mod:`.service`) starts in the
  same process with the replicas auto-registered as backends, so the
  operator sees ONE address and the usual probe/eject/score dispatch.

``SIGTERM`` drains through the router path like any fleet: the router
stops, then each replica's server shuts down.  Cross-replica request
migration (DLREQ01) keeps working because the replicas expose the same
``/admin/export``/``/admin/import`` surface as external backends.

``--supervise`` trades the shared weight load for crash isolation:
each replica becomes a child **process** (``python -m
dllama_tpu.server.api`` on a fixed loopback port) under a
:class:`Supervisor` that respawns it on death — same port, same
device set, warm ``--snapshot-dir`` restore — so the registry's
hysteretic re-admission folds the replacement back into rotation with
no operator action.  A replica that keeps dying (``--respawn-max``
deaths inside ``--respawn-window`` seconds) is quarantined instead of
respawned forever; a replica whose process is alive but whose
``/health`` stops answering (device hang, wedged runtime) is killed
and respawned as ``reason="hung"``.  See docs/ROBUSTNESS.md for the
full crash matrix.
"""

from __future__ import annotations

import collections
import http.client
import os
import re
import socket
import subprocess
import sys
import threading
import time

from ..obs import events as obs_events, metrics as obs_metrics
from ..obs.log import get_logger
from ..runtime.faults import FAULTS

_log = get_logger("router.pod")


def parse_pod_tp(workers: str | None, n_devices: int, dp: int) -> int:
    """Per-replica tp degree: ``--workers tpu:N`` names it explicitly;
    default splits every local device evenly over the dp replicas."""
    if workers is None:
        tp, rem = divmod(n_devices, dp)
        if tp < 1:
            raise SystemExit(
                f"serve-pod: {dp} replicas need at least {dp} of the "
                f"{n_devices} local devices")
        return tp
    w = workers.strip().lower()
    if not w.startswith("tpu:"):
        raise SystemExit(f"serve-pod: --workers takes tpu:N, got {workers!r}")
    try:
        tp = int(w.split(":", 1)[1])
    except ValueError:
        raise SystemExit(f"serve-pod: --workers takes tpu:N, got {workers!r}")
    if tp < 1:
        raise SystemExit(f"serve-pod: tp degree must be >= 1, got {tp}")
    return tp


def partition_devices(devices, dp: int, tp: int) -> list[list]:
    """dp disjoint tp-sized device groups, contiguous in enumeration
    order (tp innermost keeps each replica's collectives on the
    fastest links, matching make_mesh's axis order)."""
    need = dp * tp
    if need > len(devices):
        raise SystemExit(
            f"serve-pod: dp={dp} × tp={tp} needs {need} devices, "
            f"only {len(devices)} present")
    if need < len(devices):
        _log.warning("pod_devices_idle", extra={
            "used": need, "present": len(devices)})
    return [list(devices[r * tp:(r + 1) * tp]) for r in range(dp)]


# -- supervised (crash-isolated) pod ------------------------------------

def _hold_port() -> tuple[int, socket.socket]:
    """A fixed port the OS just proved free — with the bound socket
    STILL HELD, closing the pick-then-bind race: nothing else on the
    host can claim the port between allocation and the child's bind.
    :meth:`Supervisor.spawn` closes the held socket immediately before
    ``Popen`` (SO_REUSEADDR on both sides, so the child rebinds the
    address with no TIME_WAIT stall).  The residual window while the
    child loads its model is covered by the quarantine ladder: a stolen
    port makes the child's bind fail, which is a death, which feeds
    ``--respawn-max``.  Respawns rebind the SAME address, so the
    registry's hysteretic re-admission recovers the replacement with no
    reconfiguration."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    return s.getsockname()[1], s


def _free_port() -> int:
    """Back-compat shim over :func:`_hold_port` for callers that only
    want the number (tests); the race-free path is holding the
    socket."""
    port, s = _hold_port()
    s.close()
    return port


def _child_env(base: dict, tp: int, ordinals: list[int]) -> dict:
    """Device partition for one replica child, by environment:

    * CPU hosts (``JAX_PLATFORMS=cpu`` — the test path) get
      ``--xla_force_host_platform_device_count=<tp>`` so each child sees
      exactly its tp virtual devices.
    * TPU hosts get ``TPU_VISIBLE_DEVICES=<ordinals>`` (the libtpu
      convention for multiple processes sharing one host's chips); each
      child then runs single-process jax over its own chip subset.
    """
    env = dict(base)
    if env.get("JAX_PLATFORMS", "").startswith("cpu"):
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_"
                            f"count={tp}").strip()
    else:
        env["TPU_VISIBLE_DEVICES"] = ",".join(str(o) for o in ordinals)
    return env


def _replica_argv(args, port: int, snapdir: str | None) -> list[str]:
    """Child command line: a standalone ``dllama-api`` replica on a fixed
    loopback port, carrying the pod's serving flags.  The child uses
    every device its environment makes visible (tp), so no partitioning
    flags are forwarded."""
    argv = [sys.executable, "-m", "dllama_tpu.server.api",
            "--model", args.model, "--tokenizer", args.tokenizer,
            "--host", "127.0.0.1", "--port", str(port),
            "--temperature", str(args.temperature),
            "--topp", str(args.topp),
            "--chunk", str(args.chunk),
            "--max-pending", str(args.max_pending),
            "--request-timeout", str(args.request_timeout),
            "--io-timeout", str(args.io_timeout),
            "--drain-grace", str(args.drain_grace),
            "--buffer-float-type", args.buffer_float_type]
    if getattr(args, "max_seq_len", None) is not None:
        argv += ["--max-seq-len", str(args.max_seq_len)]
    if args.batch_slots > 0:
        argv += ["--batch-slots", str(args.batch_slots),
                 "--kv-pages", str(args.kv_pages),
                 "--kv-page-size", str(args.kv_page_size)]
        if getattr(args, "no_prefix_reuse", False):
            argv.append("--no-prefix-reuse")
    if getattr(args, "handoff", False):
        argv.append("--handoff")
    if getattr(args, "handoff_ttl", 0.0):
        argv += ["--handoff-ttl", str(args.handoff_ttl)]
    if snapdir:
        argv += ["--snapshot-dir", snapdir]
    if getattr(args, "weights_float_type", None):
        argv += ["--weights-float-type", args.weights_float_type]
    if getattr(args, "kv_cache_dtype", None):
        argv += ["--kv-cache-dtype", args.kv_cache_dtype]
    if getattr(args, "log_format", None):
        argv += ["--log-format", args.log_format]
    return argv


class _Replica:
    """One supervised child: its spawn recipe plus crash-loop history."""

    def __init__(self, idx: int, port: int, argv: list[str], env: dict,
                 *, tp: int = 1, ordinals: list[int] | None = None,
                 sock: socket.socket | None = None):
        self.idx = idx
        self.port = port
        self.argv = argv
        self.env = env
        self.tp = tp                      # mesh shape (elastic reshape)
        self.ordinals = ordinals if ordinals is not None else []
        self.sock = sock                  # held bound port (race fence)
        self.proc: subprocess.Popen | None = None
        self.deaths: collections.deque = collections.deque()
        self.quarantined = False
        self.retiring = False    # elastic drain in progress: no respawn
        self.ready = False       # answered /health since last spawn
        self.hang_streak = 0


class Supervisor:
    """Keeps the pod's replica children alive.

    Three failure shapes, three answers (docs/ROBUSTNESS.md):

    * **death** (any exit, SIGKILL included) — respawn on the same port
      and device set; ``--snapshot-dir`` makes it a warm start and the
      registry re-admits it after ``readmit_after`` healthy probes.
    * **crash loop** — more than ``respawn_max`` deaths inside
      ``respawn_window`` seconds quarantines the replica (structured
      ``pod_replica_quarantined`` log, no further respawns): a
      deterministic crasher respawned forever would grind the fleet
      with prefill churn.
    * **hang** — process alive, ``/health`` silent for ``hang_probes``
      consecutive probes: SIGKILL then respawn (``reason="hung"``).
      Hang detection only arms after the child's FIRST healthy answer
      since spawn, so a model still loading or compiling is never shot.

    The ``pod.respawn`` fault point fires before each respawn; a raising
    fault counts as another death in the crash-loop window.
    """

    def __init__(self, replicas: list[_Replica], *, respawn_max: int = 5,
                 respawn_window: float = 30.0, hang_probes: int = 3,
                 poll_interval: float = 1.0, probe_timeout: float = 2.0):
        self.replicas = replicas
        self.respawn_max = max(1, int(respawn_max))
        self.respawn_window = float(respawn_window)
        self.hang_probes = max(1, int(hang_probes))
        self.poll_interval = float(poll_interval)
        self.probe_timeout = float(probe_timeout)
        self._lock = threading.Lock()     # replicas-list mutation
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def spawn(self, rep: _Replica) -> None:
        if rep.sock is not None:
            # held-port fence ends here: release the bound socket in the
            # instant before the child binds the same address
            try:
                rep.sock.close()
            except OSError:
                pass
            rep.sock = None
        rep.proc = subprocess.Popen(rep.argv, env=rep.env)
        rep.ready = False
        rep.hang_streak = 0
        _log.info("pod_replica_spawned", extra={
            "replica": rep.idx, "port": rep.port, "pid": rep.proc.pid})
        obs_events.emit("spawn", replica=f"127.0.0.1:{rep.port}",
                        idx=rep.idx, pid=rep.proc.pid, tp=rep.tp)

    # -- runtime membership (elastic pod) -------------------------------
    def add(self, rep: _Replica) -> None:
        """Spawn and adopt a replica mid-flight (elastic scale-up)."""
        self.spawn(rep)
        with self._lock:
            self.replicas.append(rep)
        obs_metrics.POD_REPLICAS_UP.set(self.replicas_up())

    def remove(self, rep: _Replica) -> None:
        """Forget a replica (elastic scale-down; process already
        reaped by the caller)."""
        with self._lock:
            try:
                self.replicas.remove(rep)
            except ValueError:
                return
        obs_metrics.POD_REPLICAS_UP.set(self.replicas_up())

    def snapshot(self) -> list[_Replica]:
        with self._lock:
            return list(self.replicas)

    def start(self) -> None:
        for rep in self.snapshot():
            self.spawn(rep)
        obs_metrics.POD_REPLICAS_UP.set(len(self.replicas))
        self._thread = threading.Thread(target=self._watch,
                                        name="pod-supervisor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(
                timeout=self.poll_interval + self.probe_timeout + 2.0)
        reps = self.snapshot()
        for rep in reps:
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.terminate()
        deadline = time.monotonic() + 10.0
        for rep in reps:
            if rep.proc is None:
                continue
            try:
                rep.proc.wait(timeout=max(0.1,
                                          deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait(timeout=5.0)

    # -- watch loop -----------------------------------------------------
    def _probe(self, rep: _Replica) -> bool:
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", rep.port, timeout=self.probe_timeout)
            try:
                conn.request("GET", "/health")
                return conn.getresponse().status == 200
            finally:
                conn.close()
        except OSError:
            return False

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval):
            for rep in self.snapshot():
                if rep.quarantined or rep.retiring:
                    # retiring: the elastic controller owns the drain —
                    # its exit is completion, not a death to respawn
                    continue
                if rep.proc is None:
                    # a previous respawn attempt itself failed: treat
                    # every poll without a process as another death so
                    # the crash-loop window still converges
                    self._died(rep, "respawn")
                elif rep.proc.poll() is not None:
                    self._died(rep, "exit")
                elif self._probe(rep):
                    rep.ready = True
                    rep.hang_streak = 0
                elif rep.ready:
                    rep.hang_streak += 1
                    if rep.hang_streak >= self.hang_probes:
                        _log.warning("pod_replica_hung", extra={
                            "replica": rep.idx, "pid": rep.proc.pid,
                            "failed_probes": rep.hang_streak})
                        rep.proc.kill()  # wedged, not draining: no grace
                        try:
                            rep.proc.wait(timeout=10.0)
                        except subprocess.TimeoutExpired:
                            pass
                        self._died(rep, "hung")
            obs_metrics.POD_REPLICAS_UP.set(self.replicas_up())

    def replicas_up(self) -> int:
        return sum(1 for rep in self.snapshot()
                   if not rep.quarantined and rep.proc is not None
                   and rep.proc.poll() is None)

    def _died(self, rep: _Replica, reason: str) -> None:
        now = time.monotonic()
        rep.deaths.append(now)
        while rep.deaths and now - rep.deaths[0] > self.respawn_window:
            rep.deaths.popleft()
        _log.warning("pod_replica_died", extra={
            "replica": rep.idx, "reason": reason,
            "returncode": rep.proc.returncode if rep.proc else None,
            "deaths_in_window": len(rep.deaths)})
        obs_events.emit("death", replica=f"127.0.0.1:{rep.port}",
                        idx=rep.idx, reason=reason,
                        returncode=rep.proc.returncode if rep.proc
                        else None,
                        deaths_in_window=len(rep.deaths))
        if len(rep.deaths) > self.respawn_max:
            rep.quarantined = True
            rep.proc = None
            _log.error("pod_replica_quarantined", extra={
                "replica": rep.idx, "reason": reason,
                "deaths": len(rep.deaths),
                "window_s": self.respawn_window})
            obs_events.emit("quarantine", replica=f"127.0.0.1:{rep.port}",
                            idx=rep.idx, reason=reason,
                            deaths=len(rep.deaths),
                            window_s=self.respawn_window)
            return
        try:
            FAULTS.fire("pod.respawn")
            self.spawn(rep)
        except Exception as e:  # noqa: BLE001 — injected or exec failure
            _log.error("pod_respawn_failed", extra={
                "replica": rep.idx, "error": str(e)})
            rep.proc = None
            return
        obs_metrics.POD_RESPAWNS.inc(str(rep.idx), reason)
        obs_events.emit("respawn", replica=f"127.0.0.1:{rep.port}",
                        idx=rep.idx, reason=reason,
                        pid=rep.proc.pid if rep.proc else None)


class _PodOps:
    """Process mechanics the elastic controller drives.  Lives here so
    :mod:`.elastic` never touches subprocess/sockets and stays
    unit-testable with fakes."""

    def __init__(self, sup: Supervisor, args, snapshot_root: str | None):
        self.sup = sup
        self.args = args
        self.snapshot_root = snapshot_root
        self._next_idx = 1 + max(
            (r.idx for r in sup.snapshot()), default=-1)

    def spawn(self, tp: int, ordinals: list[int]) -> _Replica:
        idx, self._next_idx = self._next_idx, self._next_idx + 1
        port, sock = _hold_port()
        snapdir = None
        if self.snapshot_root:
            snapdir = os.path.join(self.snapshot_root, f"replica{idx}")
            os.makedirs(snapdir, exist_ok=True)
        rep = _Replica(
            idx, port, _replica_argv(self.args, port, snapdir),
            _child_env(os.environ, tp, ordinals),
            tp=tp, ordinals=list(ordinals), sock=sock)
        self.sup.add(rep)
        return rep

    def retire(self, rep: _Replica, *, grace: float) -> None:
        """SIGTERM → drain (live slots export DLREQ01, streams finish
        ``handoff``) → bounded wait → SIGKILL if the grace blows.  The
        ``retiring`` flag stops the supervisor treating the exit as a
        death to respawn."""
        rep.retiring = True
        proc = rep.proc
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=grace + 10.0)
            except subprocess.TimeoutExpired:
                _log.warning("pod_retire_kill", extra={
                    "replica": rep.idx, "grace_s": grace})
                proc.kill()
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass
        self.sup.remove(rep)

    def live_replicas(self) -> list[_Replica]:
        return [r for r in self.sup.snapshot() if not r.quarantined]

    def reap_quarantined(self) -> list[_Replica]:
        out = [r for r in self.sup.snapshot() if r.quarantined]
        for r in out:
            self.sup.remove(r)
        return out


def supervise_main(args) -> None:
    """``serve-pod --supervise``: subprocess replicas under a
    :class:`Supervisor`, fleet router in this (jax-free) parent.

    The parent deliberately never imports jax: initializing a backend
    here would hold the very devices the children need.  The cost of
    isolation is dp separate weight loads (children cannot share a
    host-side read); the payoff is that a replica crash takes down ONE
    process and the supervisor puts it back.  ``--elastic`` adds the
    control loop from :mod:`.elastic`: the pod grows, shrinks, and
    reshapes its replica set under load, within the ``--pod-devices``
    budget."""
    if not args.model or not args.tokenizer:
        raise SystemExit("--model and --tokenizer are required for "
                         "serve-pod")
    from .elastic import DevicePool, ElasticController, ElasticPolicy
    from .registry import Registry
    from .service import RouterState
    from .service import serve as router_serve

    dp = max(args.dp, 1)
    # device count is unknowable without initializing jax; an explicit
    # --workers tpu:N names the per-replica degree, default is 1
    tp = parse_pod_tp(args.workers, 0, dp) if args.workers else 1
    elastic_on = getattr(args, "elastic", False)
    if elastic_on:
        if not getattr(args, "handoff", False):
            raise SystemExit("serve-pod: --elastic needs --handoff "
                             "(scale-down migrates in-flight requests "
                             "over the hand-off wire)")
        if args.batch_slots <= 0 or args.kv_pages <= 0:
            raise SystemExit("serve-pod: --elastic needs --batch-slots "
                             "and --kv-pages (fleet signals come from "
                             "slot-scheduler occupancy)")
    pool_size = getattr(args, "pod_devices", 0) or dp * tp
    if pool_size < dp * tp:
        raise SystemExit(f"serve-pod: --pod-devices {pool_size} cannot "
                         f"seat the boot shape dp={dp} × tp={tp}")
    pool = DevicePool(pool_size)
    snapshot_root = getattr(args, "snapshot_dir", None)
    replicas = []
    for r in range(dp):
        port, sock = _hold_port()
        snapdir = None
        if snapshot_root:
            snapdir = os.path.join(snapshot_root, f"replica{r}")
            os.makedirs(snapdir, exist_ok=True)
        ordinals = pool.allocate(tp)
        replicas.append(_Replica(
            r, port, _replica_argv(args, port, snapdir),
            _child_env(os.environ, tp, ordinals),
            tp=tp, ordinals=ordinals, sock=sock))

    sup = Supervisor(
        replicas,
        respawn_max=getattr(args, "respawn_max", 5),
        respawn_window=getattr(args, "respawn_window", 30.0),
        poll_interval=min(1.0, float(args.probe_interval)),
        probe_timeout=min(float(args.upstream_timeout), 2.0))
    sup.start()
    controller = None
    try:
        registry = Registry(
            [f"127.0.0.1:{rep.port}" for rep in replicas],
            probe_interval=args.probe_interval,
            eject_after=args.eject_after,
            readmit_after=args.readmit_after,
            probe_timeout=min(float(args.upstream_timeout), 5.0))
        rstate = RouterState(
            registry, retries=args.router_retries,
            upstream_timeout=args.upstream_timeout,
            stall_timeout=getattr(args, "stall_timeout", 0.0),
            checkpoint_interval=getattr(args, "checkpoint_interval", 0.0),
            resume_policy=getattr(args, "resume_policy", "auto"),
            # the replicas sit on loopback ephemeral ports: the pod's
            # public /metrics defaults to the federated fleet scope so
            # one external scrape sees every replica's families
            fleet_scope_default=True)
        if elastic_on:
            policy = ElasticPolicy(
                window=getattr(args, "elastic_window", 5),
                cooldown=getattr(args, "elastic_cooldown", 30.0),
                up_util=getattr(args, "scale_up_util", 0.85),
                down_util=getattr(args, "scale_down_util", 0.15),
                up_queue=getattr(args, "scale_up_queue", 2.0),
                kv_low=getattr(args, "reshape_kv_low", 0.08),
                min_replicas=getattr(args, "min_replicas", 1),
                max_replicas=getattr(args, "max_replicas", dp))
            controller = ElasticController(
                _PodOps(sup, args, snapshot_root), registry, pool, policy,
                tp=tp,
                interval=getattr(args, "elastic_interval", 2.0),
                drain_grace=float(args.drain_grace))
            rstate.elastic = controller
            controller.start()
        print(f"💡 serve-pod: supervising {dp} replica process(es) × "
              f"tp={tp}"
              + (f" [elastic {policy.min_replicas}"
                 f"–{policy.max_replicas} over {pool_size} devices]"
                 if elastic_on else "")
              + f"; router on :{args.port}")
        router_serve(rstate, host=args.host, port=args.port)
    finally:
        if controller is not None:
            controller.stop()
        sup.stop()


def main(args) -> None:
    if getattr(args, "supervise", False):
        supervise_main(args)
        return
    if getattr(args, "elastic", False):
        raise SystemExit("serve-pod: --elastic requires --supervise "
                         "(only process replicas can be spawned, "
                         "drained, and reshaped at runtime)")

    import jax
    import jax.numpy as jnp

    from .. import quants
    from ..cli import DTYPES
    from ..io import mfile, tfile
    from ..models.config import ModelConfig
    from ..models.params import load_params
    from ..obs import dispatch as obs_dispatch
    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import check_tp_constraint
    from ..runtime.engine import Engine
    from ..runtime.scheduler import SlotScheduler
    from ..server import api
    from ..tokenizer.bpe import Tokenizer
    from .registry import Registry
    from .service import RouterState
    from .service import serve as router_serve

    if not args.model or not args.tokenizer:
        raise SystemExit("--model and --tokenizer are required for serve-pod")
    if args.sp > 1 or args.ep > 1:
        raise SystemExit("serve-pod partitions devices into dp × tp "
                         "replicas; --sp/--ep are not supported here "
                         "(run a single replica with dllama-api instead)")
    devices = jax.devices()
    dp = max(args.dp, 1)
    tp = parse_pod_tp(args.workers, len(devices), dp)
    groups = partition_devices(devices, dp, tp)

    wft = quants.FLOAT_TYPE_BY_NAME[args.weights_float_type] \
        if args.weights_float_type else None
    mf = mfile.MFile(args.model, weights_ftype=wft,
                     verify=getattr(args, "verify_weights", False))
    bft = "bf16" if args.buffer_float_type == "q80" else args.buffer_float_type
    dtype = jnp.dtype(DTYPES[bft])
    cfg = ModelConfig.from_spec(mf.spec, dtype=dtype)
    # fail before the (minutes-long) weight load, with the valid-degrees
    # hint naming the tp that WOULD work
    check_tp_constraint(cfg, tp)
    cfg, params = load_params(mf, cfg, dtype=dtype,
                              keep_quantized=not args.dequantize,
                              fuse=tp == 1)
    tok = Tokenizer(tfile.read_tfile(args.tokenizer))
    if tok.vocab_size != cfg.vocab_size:
        raise SystemExit("tokenizer is incompatible with model "
                         "(vocab size mismatch)")
    kv_dtype = ("q8" if args.kv_cache_dtype == "q8"
                else jnp.dtype(DTYPES[args.kv_cache_dtype])
                if args.kv_cache_dtype else None)

    replicas: list[tuple[str, object, SlotScheduler | None]] = []
    try:
        for r, devs in enumerate(groups):
            mesh = make_mesh(tp=tp, devices=devs)
            engine = Engine(cfg, params, mesh=mesh, seq_len=args.max_seq_len,
                            kv_dtype=kv_dtype, batch=1,
                            step_timeout=getattr(args, "step_timeout", None),
                            numeric_checks=(True if getattr(
                                args, "numeric_checks", False) else None))
            batch_engine = None
            scheduler = None
            if args.batch_slots > 0:
                if args.kv_pages > 0 and engine.cache.quantized:
                    raise SystemExit("--kv-pages needs a dense KV cache; "
                                     "drop --kv-cache-dtype q8")
                batch_engine = Engine(
                    engine.cfg, engine.params, mesh=mesh,
                    batch=args.batch_slots, seq_len=args.max_seq_len,
                    kv_dtype=engine.cache.k.dtype,
                    step_timeout=getattr(args, "step_timeout", None),
                    kv_pages=args.kv_pages, kv_page_size=args.kv_page_size)
                try:
                    scheduler = SlotScheduler(
                        batch_engine,
                        prefill_chunk=args.sched_prefill_chunk,
                        max_wait_ms=args.sched_max_wait_ms,
                        max_queue=args.sched_max_queue,
                        prefix_reuse=not args.no_prefix_reuse,
                        overlap=not args.no_sched_overlap,
                        preempt=not args.no_preempt,
                        preempt_age_ms=args.preempt_age_ms,
                        preempt_cap=args.preempt_cap,
                        spill_dir=args.preempt_spill_dir)
                except ValueError as e:
                    _log.warning("slot_scheduler_disabled",
                                 extra={"replica": r, "reason": str(e)})
            state = api.ApiState(
                engine, tok, default_temperature=args.temperature,
                default_topp=args.topp, chunk=args.chunk,
                batch_engine=batch_engine, max_pending=args.max_pending,
                request_timeout=args.request_timeout,
                io_timeout=args.io_timeout, drain_grace=args.drain_grace,
                scheduler=scheduler,
                handoff=getattr(args, "handoff", False),
                handoff_ttl=getattr(args, "handoff_ttl", 0.0))
            # loopback + ephemeral port: the OS picks, so dp replicas can
            # never collide with each other or the public port
            server = api.serve(state, host="127.0.0.1", port=0,
                               block=False, install_signals=False)
            addr = "127.0.0.1:%d" % server.server_address[1]
            replicas.append((addr, server, scheduler))
            _log.info("pod_replica_up", extra={
                "replica": r, "tp": tp, "addr": addr,
                "devices": [str(d) for d in devs]})

        registry = Registry(
            [a for a, _, _ in replicas],
            probe_interval=args.probe_interval,
            eject_after=args.eject_after,
            readmit_after=args.readmit_after,
            probe_timeout=min(float(args.upstream_timeout), 5.0))
        rstate = RouterState(
            registry, retries=args.router_retries,
            upstream_timeout=args.upstream_timeout,
            stall_timeout=getattr(args, "stall_timeout", 0.0),
            checkpoint_interval=getattr(args, "checkpoint_interval", 0.0),
            resume_policy=getattr(args, "resume_policy", "auto"),
            fleet_scope_default=True)
        print(f"💡 serve-pod: {dp} replica(s) × tp={tp} over "
              f"{dp * tp}/{len(devices)} devices; router on :{args.port}")
        router_serve(rstate, host=args.host, port=args.port)
    finally:
        for _, server, scheduler in replicas:
            try:
                server.shutdown()
                server.server_close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            if scheduler is not None:
                scheduler.close()
        print(obs_dispatch.summary_line())
        coll = obs_dispatch.collective_line()
        if coll:
            print(coll)
