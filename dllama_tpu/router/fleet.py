"""Fleet federation: one public scrape covering every loopback replica.

The pod's replicas serve rich ``/metrics`` / ``/debug/trace`` /
``/debug/events`` surfaces — on loopback ephemeral ports an external
scraper can never reach.  This module runs inside the router/pod
process (stdlib only, like the rest of ``router/``) and federates them:

* :meth:`FleetScraper.federated_json` / ``federated_prometheus`` —
  concurrently scrape every *registered* replica (ejected and retiring
  ones included: their process may still be alive and their last state
  is exactly what an incident review needs) and re-expose every family
  with a ``replica`` label.  The router's own families ride along under
  ``replica="router"`` so one scrape is the whole fleet.  A replica
  that fails its scrape is **marked, never dropped**:
  ``dllama_fleet_replica_up{replica=...} 0`` in the Prometheus text,
  ``"up": false`` (plus the last good snapshot flagged ``"stale":
  true``) in the JSON.
* :meth:`FleetScraper.fleet_trace` — stitch the per-replica span rings
  into ONE Perfetto timeline.  Each process exports its ring with a
  paired ``(perf_now, wall_now)`` clock sample (``obs/trace.py
  raw()``); the stitcher computes ``offset = wall_now − perf_now`` per
  process and shifts every span onto the shared wall-clock axis — a
  track (pid) per replica, the router's own spans on pid 1, and
  instant-event markers from each process's event journal (hand-offs,
  respawns, preemptions) laid over the spans.  ``?trace=<id>`` narrows
  to one request's fleet-wide story.
* :meth:`FleetScraper.fleet_events` — the per-process event journals,
  keyed by replica, for ``fleet_top``'s scrolling tail.

Scrapes fan out on a small thread pool with a short per-replica
timeout: the slowest replica bounds the scrape, a dead one costs one
timeout, and the public ``/metrics`` stays serveable throughout.
"""

from __future__ import annotations

import http.client
import json
import re
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..obs import events as obs_events, metrics as obs_metrics, \
    trace as obs_trace
from ..obs.log import get_logger

_log = get_logger("router.fleet")

#: per-replica scrape deadline, seconds — a hung replica must not stall
#: the public scrape for upstream_timeout.
SCRAPE_TIMEOUT = 2.0

#: ``name{labels} value [timestamp]`` — one Prometheus 0.0.4 sample.
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)(\s+\S+)?$")

#: a pre-existing ``replica`` label inside a scraped sample (the
#: router's own fleet_* families carry one) — renamed to
#: ``exported_replica`` on federation, the Prometheus convention, so
#: the injected label is never duplicated.
_INNER_REPLICA_RE = re.compile(r'(?<![a-zA-Z0-9_])replica=')


def _label_value(raw: str) -> str:
    """Escape a replica address for use inside a label value."""
    return raw.replace("\\", r"\\").replace('"', r'\"')


def parse_prometheus(text: str) -> dict[str, dict]:
    """Parse exposition text into ``{family: {"help", "type",
    "samples": [(sample_name, labels_or_None, value_text)]}}``.

    Sample names may extend the family name (``_bucket``/``_sum``/
    ``_count``); a sample line with no preceding header becomes its own
    family (type ``untyped``) so nothing is silently lost."""
    families: dict[str, dict] = {}
    current = None

    def fam(name: str) -> dict:
        return families.setdefault(
            name, {"help": None, "type": None, "samples": []})

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            f = fam(parts[2])
            f["help"] = parts[3] if len(parts) > 3 else ""
            current = parts[2]
        elif line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            f = fam(parts[2])
            f["type"] = parts[3].strip() if len(parts) > 3 else "untyped"
            current = parts[2]
        elif line.startswith("#"):
            continue
        else:
            m = _SAMPLE_RE.match(line)
            if not m:
                continue
            name, labels, value = m.group(1), m.group(2), m.group(3)
            owner = current if current and name.startswith(current) \
                else name
            fam(owner)["samples"].append((name, labels, value))
    return families


def merge_prometheus(per_replica: list[tuple[str, str]]) -> str:
    """Merge ``(replica_label, exposition_text)`` pairs into one text
    with ``replica=...`` injected as the first label of every sample;
    HELP/TYPE emitted once per family, all samples grouped under it."""
    merged: dict[str, dict] = {}
    order: list[str] = []
    for replica, text in per_replica:
        rl = f'replica="{_label_value(replica)}"'
        for name, f in parse_prometheus(text).items():
            m = merged.get(name)
            if m is None:
                m = merged[name] = {"help": f["help"], "type": f["type"],
                                    "samples": []}
                order.append(name)
            else:
                m["help"] = m["help"] or f["help"]
                m["type"] = m["type"] or f["type"]
            for sname, labels, value in f["samples"]:
                inner = labels[1:-1] if labels else ""
                inner = _INNER_REPLICA_RE.sub("exported_replica=", inner)
                lab = "{" + rl + ("," + inner if inner else "") + "}"
                m["samples"].append(f"{sname}{lab} {value}")
    out: list[str] = []
    for name in order:
        f = merged[name]
        if f["help"] is not None:
            out.append(f"# HELP {name} {f['help']}")
        out.append(f"# TYPE {name} {f['type'] or 'untyped'}")
        out.extend(f["samples"])
    return "\n".join(out) + "\n"


def fleet_perf(replicas: dict) -> dict:
    """Fleet performance-economics rollup for the federated JSON: mean
    MFU/MBU across replicas that reported one, and class chip-time
    summed fleet-wide with per-class shares — the block ``fleet_top``'s
    footer renders, computed once here instead of in every dashboard."""
    mfus: list[float] = []
    mbus: list[float] = []
    by_class: dict[str, float] = {}
    for entry in (replicas or {}).values():
        snap = entry.get("metrics") or {}
        for key, acc in (("mfu", mfus), ("mbu", mbus)):
            v = snap.get(key)
            if isinstance(v, (int, float)) and v > 0:
                acc.append(float(v))
        cc = snap.get("class_chip_ms")
        if isinstance(cc, dict):
            for k, v in cc.items():
                if isinstance(v, (int, float)):
                    by_class[k] = by_class.get(k, 0.0) + float(v)
    total = sum(by_class.values())
    return {
        "mfu_mean": round(sum(mfus) / len(mfus), 6) if mfus else None,
        "mbu_mean": round(sum(mbus) / len(mbus), 6) if mbus else None,
        "class_chip_ms": {k: round(v, 3)
                          for k, v in sorted(by_class.items())},
        "class_chip_share": {k: round(v / total, 4)
                             for k, v in sorted(by_class.items())}
        if total else {},
    }


class FleetScraper:
    """Concurrent scraper over the registry's full backend list."""

    def __init__(self, registry, *, timeout: float = SCRAPE_TIMEOUT):
        self.registry = registry
        self.timeout = float(timeout)
        self._lock = threading.Lock()
        # addr → last successful JSON metrics snapshot (ts, dict), kept
        # so a momentarily-unreachable replica is served stale-marked
        # instead of vanishing from the JSON federation
        self._last_good: dict[str, tuple[float, dict]] = {}

    # -- plumbing --------------------------------------------------------

    def _get(self, b, path: str, headers: dict | None = None):
        """(status, body_bytes) or None on any transport failure."""
        try:
            conn = http.client.HTTPConnection(b.host, b.port,
                                              timeout=self.timeout)
            try:
                conn.request("GET", path, headers=headers or {})
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                conn.close()
        except (OSError, http.client.HTTPException):
            return None

    def _fan_out(self, fn) -> list[tuple[object, object]]:
        """Run ``fn(backend)`` for every registered backend (ejected and
        retiring included) concurrently; returns ``[(backend, result)]``
        in registry order."""
        backends = list(self.registry.backends)
        if not backends:
            return []
        with ThreadPoolExecutor(
                max_workers=min(8, len(backends)),
                thread_name_prefix="fleet-scrape") as pool:
            results = list(pool.map(fn, backends))
        return list(zip(backends, results))

    def _mark(self, b, ok: bool) -> None:
        obs_metrics.FLEET_REPLICA_UP.set(b.addr, 1.0 if ok else 0.0)
        if not ok:
            obs_metrics.FLEET_SCRAPE_ERRORS.inc(b.addr)

    # -- metrics federation ----------------------------------------------

    def federated_json(self, router_metrics: dict | None = None) -> dict:
        """Fleet-scope JSON: the router's own registry plus every
        replica's, keyed by address, failures stale-marked."""
        t0 = time.perf_counter()

        def one(b):
            got = self._get(b, "/metrics")
            if got is None or got[0] != 200:
                return None
            try:
                return json.loads(got[1])
            except ValueError:
                return None

        replicas: dict[str, dict] = {}
        for b, snap in self._fan_out(one):
            ok = snap is not None
            self._mark(b, ok)
            entry = {"up": ok, "ejected": bool(b.ejected),
                     "retiring": bool(getattr(b, "retiring", False))}
            if ok:
                entry["metrics"] = snap
                with self._lock:
                    self._last_good[b.addr] = (time.time(), snap)
            else:
                with self._lock:
                    last = self._last_good.get(b.addr)
                if last is not None:
                    entry["stale"] = True
                    entry["stale_age_s"] = round(time.time() - last[0], 3)
                    entry["metrics"] = last[1]
            replicas[b.addr] = entry
        obs_metrics.FLEET_SCRAPE_SECONDS.observe(time.perf_counter() - t0)
        return {"scope": "fleet",
                "router": router_metrics or obs_metrics.snapshot_json(),
                "replicas": replicas,
                "perf": fleet_perf(replicas)}

    def federated_prometheus(self) -> str:
        """Fleet-scope Prometheus text: every sample — the router's own
        included — carries a ``replica`` label; a failed scrape shows up
        as ``dllama_fleet_replica_up{replica=...} 0`` (bumped *before*
        the router's own exposition is rendered, so the mark is in this
        very scrape, not the next one)."""
        t0 = time.perf_counter()

        def one(b):
            got = self._get(b, "/metrics?format=prometheus",
                            headers={"Accept": "text/plain"})
            if got is None or got[0] != 200:
                return None
            try:
                return got[1].decode("utf-8", "replace")
            except Exception:  # noqa: BLE001
                return None

        texts: list[tuple[str, str]] = []
        for b, text in self._fan_out(one):
            self._mark(b, text is not None)
            if text is not None:
                texts.append((b.addr, text))
        obs_metrics.FLEET_SCRAPE_SECONDS.observe(time.perf_counter() - t0)
        # the router's own registry renders AFTER the marks so
        # fleet_replica_up/scrape_errors reflect this fan-out
        texts.insert(0, ("router", obs_metrics.render_prometheus()))
        return merge_prometheus(texts)

    # -- event journals --------------------------------------------------

    def fleet_events(self, since: int | None = None) -> dict:
        """The router's journal plus every replica's, keyed by address.
        ``since`` applies to the *router* journal only — per-replica
        cursors live with the poller (each entry carries its own
        ``next_seq``)."""

        def one(b):
            got = self._get(b, "/debug/events")
            if got is None or got[0] != 200:
                return None
            try:
                return json.loads(got[1])
            except ValueError:
                return None

        replicas = {}
        for b, snap in self._fan_out(one):
            replicas[b.addr] = snap if snap is not None else {"up": False}
        return {"scope": "fleet",
                "router": obs_events.snapshot(since),
                "replicas": replicas}

    # -- cross-replica trace stitching -----------------------------------

    def fleet_trace(self, trace: str | None = None) -> dict:
        """One Perfetto timeline from every process's span ring.

        Each source exports ``raw()`` — spans in perf_counter seconds
        plus a ``(perf_now, wall_now)`` sample taken at export; the
        per-source offset shifts spans onto the shared wall-clock axis.
        The router is pid 1, each replica its own pid (named track);
        event-journal entries become instant-event markers on their
        process's track.  ``trace`` filters spans to one trace id
        (journal markers without a trace field — respawns, scale — are
        kept: they are the fleet context the filter exists to show)."""

        def one(b):
            spans = self._get(b, "/debug/trace?since=0")
            events = self._get(b, "/debug/events")

            def decode(got):
                if got is None or got[0] != 200:
                    return None
                try:
                    return json.loads(got[1])
                except ValueError:
                    return None
            return decode(spans), decode(events)

        sources: list[tuple[str, dict | None, dict | None]] = [
            ("router", obs_trace.TRACER.raw(), obs_events.snapshot())]
        scraped = self._fan_out(one)
        for b, (spans, events) in scraped:
            self._mark(b, spans is not None)
            sources.append((b.addr, spans, events))

        out: list[dict] = []
        fleet_meta: dict[str, dict] = {}
        for pid, (name, dump, journal) in enumerate(sources, start=1):
            fleet_meta[name] = {"up": dump is not None,
                                "spans": len((dump or {}).get("spans", ()))}
            out.append({"name": "process_name", "ph": "M", "pid": pid,
                        "args": {"name": name if name == "router"
                                 else f"replica {name}"}})
            if dump is None:
                continue
            offset = dump.get("wall_now", 0.0) - dump.get("perf_now", 0.0)
            tids: dict = {}
            for s in dump.get("spans", ()):
                if trace and s.get("trace") != trace:
                    continue
                raw_tid = s.get("tid", 0)
                if raw_tid not in tids:
                    tids[raw_tid] = len(tids) + 1
                    out.append({"name": "thread_name", "ph": "M",
                                "pid": pid, "tid": tids[raw_tid],
                                "args": {"name": f"{s.get('thread', '?')} "
                                                 f"({raw_tid})"}})
                args = dict(s.get("args") or {})
                if s.get("rid"):
                    args["request_id"] = s["rid"]
                if s.get("trace"):
                    args["trace_id"] = s["trace"]
                out.append({"name": s["name"], "cat": "dllama", "ph": "X",
                            "ts": round((s["ts"] + offset) * 1e6, 3),
                            "dur": round(s["dur"] * 1e6, 3),
                            "pid": pid, "tid": tids[raw_tid],
                            "args": args})
            for ev in (journal or {}).get("events", ()):
                ev_trace = ev.get("trace")
                if trace and ev_trace and ev_trace != trace:
                    continue
                args = {k: v for k, v in ev.items()
                        if k not in ("ts", "kind")}
                out.append({"name": f"event:{ev['kind']}", "cat": "fleet",
                            "ph": "i", "s": "p", "pid": pid, "tid": 0,
                            "ts": round(ev["ts"] * 1e6, 3), "args": args})
        return {"traceEvents": out, "displayTimeUnit": "ms",
                "fleet": fleet_meta}
