"""Replica registry: health probes, load scoring, hysteretic ejection.

Each backend replica is probed every ``probe_interval`` seconds with
``GET /health`` and scored from the response's ``capacity`` block
(server/api.py serves it precisely so the router never scrapes
Prometheus text).  Dispatch picks the eligible backend with the highest
score; the score is deliberately simple and monotone in "how much of
this replica is idle":

    free_slots − queue_depth − router_in_flight (+ a free-KV-pages tiebreak)

with large penalties for a ``degraded`` kernel-dispatch ledger and a
``violating`` SLO verdict, so a replica that fell off its fast matmul
path or is burning error budget only takes traffic when nothing
healthier can.

Ejection is hysteretic in both directions: ``eject_after`` consecutive
failures (probe or dispatch) before a backend stops receiving traffic,
``readmit_after`` consecutive healthy probes before it gets traffic
again.  One lucky probe does not un-eject a flapping replica, and one
lost packet does not eject a healthy one.  Draining replicas
(``status: "draining"``) are ineligible for dispatch but are NOT
ejected — drain is voluntary and the replica is still healthy enough
to finish and export its in-flight work.
"""

from __future__ import annotations

import http.client
import json
import threading
import time

from ..obs import events as obs_events, metrics as obs_metrics
from ..obs.log import get_logger

_log = get_logger("router.registry")

# score penalty that outweighs any realistic capacity signal: a
# degraded / SLO-violating replica only wins the pick when every
# alternative carries the same penalty
_PENALTY = 1e6


class Backend:
    """One replica's registry row.  Mutable fields are guarded by the
    owning :class:`Registry`'s lock."""

    def __init__(self, addr: str):
        self.addr = addr                  # "host:port" — also the metric label
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"--backends entry {addr!r} is not host:port")
        self.host, self.port = host, int(port)
        self.fail_streak = 0
        self.ok_streak = 0
        self.ejected = False
        self.retiring = False             # admission fence (elastic drain)
        self.last_health: dict | None = None
        self.last_probe_s: float | None = None  # EWMA probe RTT
        self.rtt_floor: float | None = None     # best EWMA ever seen
        self.in_flight = 0                # router-side active dispatches

    def rtt_degraded(self) -> bool:
        """Probe RTT has blown 10× past this backend's own baseline —
        the pre-hang signature (GC death spiral, device queue backing
        up).  The floor is clamped to 1 ms so a sub-millisecond loopback
        baseline cannot make normal jitter read as degradation, and the
        threshold to 50 ms so WAN-ish probes need a real excursion."""
        if self.last_probe_s is None or self.rtt_floor is None:
            return False
        return self.last_probe_s > max(10.0 * max(self.rtt_floor, 1e-3),
                                       0.05)

    def summary(self) -> dict:
        h = self.last_health or {}
        return {
            "addr": self.addr,
            "ejected": self.ejected,
            "retiring": self.retiring,
            "draining": h.get("status") == "draining",
            "fail_streak": self.fail_streak,
            "ok_streak": self.ok_streak,
            "in_flight": self.in_flight,
            "probe_s": self.last_probe_s,
            "rtt_degraded": self.rtt_degraded(),
            "capacity": h.get("capacity"),
            "degraded": h.get("degraded"),
            "slo": (h.get("slo") or {}).get("status") if h.get("slo")
            else None,
        }


class Registry:
    def __init__(self, addrs: list[str], *, probe_interval: float = 2.0,
                 eject_after: int = 3, readmit_after: int = 2,
                 probe_timeout: float = 5.0):
        if not addrs:
            raise ValueError("registry needs at least one backend")
        self.backends = [Backend(a) for a in addrs]
        self.probe_interval = float(probe_interval)
        self.eject_after = max(1, int(eject_after))
        self.readmit_after = max(1, int(readmit_after))
        self.probe_timeout = float(probe_timeout)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- probing -------------------------------------------------------
    def probe(self, b: Backend) -> bool:
        """One ``GET /health`` round trip; updates streaks and the
        latency gauge.  Returns True on a healthy (HTTP 200) answer."""
        t0 = time.monotonic()
        try:
            conn = http.client.HTTPConnection(b.host, b.port,
                                              timeout=self.probe_timeout)
            try:
                conn.request("GET", "/health")
                resp = conn.getresponse()
                body = resp.read()
                ok = resp.status == 200
                health = json.loads(body) if ok else None
            finally:
                conn.close()
        except (OSError, ValueError):
            ok, health = False, None
        rtt = time.monotonic() - t0
        with self._lock:
            if not ok:
                self._fail_locked(b, "probe")
                return False
            b.last_health = health
            # EWMA keeps the gauge stable across one slow GC pause but
            # tracking a genuinely slowing replica within a few probes
            b.last_probe_s = rtt if b.last_probe_s is None \
                else 0.7 * b.last_probe_s + 0.3 * rtt
            b.rtt_floor = b.last_probe_s if b.rtt_floor is None \
                else min(b.rtt_floor, b.last_probe_s)
            obs_metrics.ROUTER_BACKEND_LATENCY_S.set(
                b.addr, round(b.last_probe_s, 6))
            b.fail_streak = 0
            b.ok_streak += 1
            if b.ejected and b.ok_streak >= self.readmit_after:
                b.ejected = False
                obs_metrics.ROUTER_READMITS.inc(b.addr)
                obs_events.emit("readmit", replica=b.addr,
                                ok_streak=b.ok_streak)
                _log.info("backend %s re-admitted after %d healthy probes",
                          b.addr, b.ok_streak)
        return True

    def probe_all(self) -> None:
        # iterate a lock-held copy: the elastic controller adds and
        # removes backends at runtime from its own thread
        with self._lock:
            backends = list(self.backends)
        for b in backends:
            self.probe(b)

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            self.probe_all()

    def start(self) -> None:
        """Synchronous first probe round (dispatch decisions are never
        made blind), then the background probe thread."""
        self.probe_all()
        self._thread = threading.Thread(target=self._probe_loop,
                                        name="router-probe", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.probe_timeout + 1.0)

    # -- runtime membership (elastic pod) ------------------------------
    def add(self, addr: str) -> Backend:
        """Register a backend at runtime.  The newcomer starts with no
        health record, so dispatch ignores it until its first healthy
        probe — the same hysteretic admission an ejected backend earns
        re-entry through."""
        b = Backend(addr)
        with self._lock:
            if any(x.addr == addr for x in self.backends):
                raise ValueError(f"backend {addr} already registered")
            self.backends.append(b)
        _log.info("backend %s registered at runtime", addr)
        return b

    def remove(self, addr: str) -> Backend | None:
        """Drop a backend's row.  In-flight dispatches holding the
        Backend object finish normally; only future picks stop seeing
        it."""
        with self._lock:
            for i, b in enumerate(self.backends):
                if b.addr == addr:
                    del self.backends[i]
                    _log.info("backend %s removed from registry", addr)
                    return b
        return None

    def retire(self, addr: str) -> None:
        """Admission fence: the backend stops receiving NEW dispatches
        immediately (before its own /health flips to draining), but is
        not ejected — it stays a valid hand-off exporter while it
        drains."""
        with self._lock:
            for b in self.backends:
                if b.addr == addr:
                    b.retiring = True
                    obs_events.emit("retire", replica=addr)
                    return

    def get(self, addr: str) -> Backend | None:
        with self._lock:
            for b in self.backends:
                if b.addr == addr:
                    return b
        return None

    def score(self, b: Backend) -> float:
        """Public idle-ness score (elastic victim selection)."""
        with self._lock:
            return self._score(b)

    # -- dispatch feedback ---------------------------------------------
    def _fail_locked(self, b: Backend, why: str) -> None:
        b.ok_streak = 0
        b.fail_streak += 1
        if not b.ejected and b.fail_streak >= self.eject_after:
            b.ejected = True
            obs_metrics.ROUTER_EJECTIONS.inc(b.addr)
            obs_events.emit("eject", replica=b.addr, why=why,
                            fail_streak=b.fail_streak)
            _log.warning("backend %s EJECTED after %d consecutive %s "
                         "failures", b.addr, b.fail_streak, why)

    def record_failure(self, b: Backend, why: str = "dispatch") -> None:
        with self._lock:
            self._fail_locked(b, why)

    def force_eject(self, b: Backend, why: str) -> None:
        """Immediate ejection, bypassing the failure-streak hysteresis —
        for signals where waiting out ``eject_after`` probes would keep
        feeding streams to a replica known to be wedged (the router's
        stream-stall watchdog).  Re-admission stays hysteretic: the
        replica earns its way back with ``readmit_after`` healthy
        probes like any ejected backend."""
        with self._lock:
            b.ok_streak = 0
            b.fail_streak = max(b.fail_streak, self.eject_after)
            if not b.ejected:
                b.ejected = True
                obs_metrics.ROUTER_EJECTIONS.inc(b.addr)
                obs_events.emit("eject", replica=b.addr, why=why,
                                forced=True)
                _log.warning("backend %s EJECTED (%s)", b.addr, why)

    def record_success(self, b: Backend) -> None:
        # a served request proves liveness as well as a probe does, but
        # re-admission stays probe-driven (readmit_after applies to
        # probes only, so the hysteresis clock has one owner)
        with self._lock:
            b.fail_streak = 0

    def acquire(self, b: Backend) -> None:
        with self._lock:
            b.in_flight += 1

    def release(self, b: Backend) -> None:
        with self._lock:
            b.in_flight = max(0, b.in_flight - 1)

    # -- scoring -------------------------------------------------------
    @staticmethod
    def _score(b: Backend, interactive: bool = False) -> float:
        h = b.last_health or {}
        cap = h.get("capacity") or {}
        free_slots = cap.get("free_slots")
        score = float(free_slots if free_slots is not None else 0)
        score -= float(cap.get("queue_depth") or 0)
        score -= float(b.in_flight)
        # KV tiebreak: prefer the tiering view when the replica reports
        # one — resident free pages plus pages reclaimable by spilling
        # idle slots (kv_pressure.effective_free) — falling back to the
        # plain free list for pre-tiering replicas
        kvp = cap.get("kv_pressure") or {}
        free_pages = kvp.get("effective_free")
        if free_pages is None:
            free_pages = cap.get("free_kv_pages")
        if free_pages is not None:
            # tiebreak only: a page is worth far less than a slot
            score += min(float(free_pages), 1e5) * 1e-6
        if h.get("degraded"):
            score -= _PENALTY
        if b.rtt_degraded():
            # pre-hang signature: probes still answer (no failure streak
            # to eject on) but 10× slower than this backend's own
            # baseline — steer traffic away BEFORE the full stall
            score -= _PENALTY
        if (h.get("slo") or {}).get("status") == "violating" \
                and not interactive:
            # steer low-priority dispatch away from a replica that is
            # burning its SLO budget, but keep it fully eligible for
            # interactive traffic — the replica sheds batch/standard
            # itself, so interactive capacity there is real
            score -= _PENALTY
        return score

    def _eligible_locked(self, exclude, *, handoff: bool) -> list[Backend]:
        out = []
        for b in self.backends:
            if b in exclude or b.ejected or b.retiring \
                    or b.last_health is None:
                continue
            h = b.last_health
            if h.get("status") == "draining":
                continue
            if handoff and not (h.get("capacity") or {}).get("handoff"):
                continue
            out.append(b)
        return out

    def pick(self, exclude=(), priority: str | None = None
             ) -> Backend | None:
        """Least-loaded eligible backend, or None when the fleet has no
        capacity to offer (all ejected/draining/excluded)."""
        interactive = priority == "interactive"
        with self._lock:
            cands = self._eligible_locked(set(exclude), handoff=False)
            if not cands:
                return None
            return max(cands,
                       key=lambda b: self._score(b, interactive))

    def eligible_backends(self) -> list[Backend]:
        """Every backend dispatch would consider right now (elastic
        signal sampling reads their cached health blocks)."""
        with self._lock:
            return self._eligible_locked((), handoff=False)

    def handoff_peers(self, exclude=()) -> list[Backend]:
        """Eligible hand-off importers, best-scored first (the record is
        offered to each in turn; a geometry 409 moves to the next)."""
        with self._lock:
            cands = self._eligible_locked(set(exclude), handoff=True)
            return sorted(cands, key=self._score, reverse=True)

    def snapshot(self) -> dict:
        with self._lock:
            rows = [b.summary() for b in self.backends]
        avail = sum(1 for r in rows
                    if not r["ejected"] and not r["draining"]
                    and not r["retiring"] and r["capacity"] is not None)
        return {"backends": rows, "available": avail,
                "total": len(rows)}
