"""`.m` model-file format: reader + writer.

Byte-compatible with the reference's model format so that files produced by
the reference converters load directly:

* header parse mirrors ``Transformer::loadSpecFromFile``
  (/root/reference/src/transformer.cpp:12-125): magic ``0xA00ABCD``, an i32
  ``headerSize`` (total header bytes incl. magic+size), then (key, value)
  i32 pairs keyed by ``TransformerHeaderKey`` (transformer.hpp:10-25).
  Legacy magics ``0xABCD00``/``0xABCD01`` carry a fixed 9-int struct
  (transformer.cpp:27-42).
* tensor walk mirrors ``Transformer::loadRoot`` (transformer.cpp:428-487):
  embedding, then per layer q/k/v/wo, (router + per-expert up/gate/down |
  w1/w2/w3), rms_att, rms_ffn, (grok: rms_moe, rms_ffn2), then rms_final
  and wcls.  Matmul weights are stored row-major ``(d_out, n_in)`` in the
  model's weight float type; norm weights and the embedding are F32
  (transformer.cpp:213-218, 266-278).

Reading is mmap-backed and lazy: ``MFile.tensor(name)`` dequantizes one
tensor on demand, so sharded loading can stream straight to device without
materializing the full f32 model on host.
"""

from __future__ import annotations

import mmap
import os
import struct
from dataclasses import dataclass

import numpy as np

from .. import quants
from .integrity import ArtifactError, load_manifest_for, verify_bytes

MAGIC_V2 = 0xA00ABCD
LEGACY_MAGICS = (0xABCD00, 0xABCD01)

# TransformerArchType (transformer.hpp:39-43)
ARCH_LLAMA = 0xABCD00
ARCH_GROK1 = 0xABCD01
ARCH_MIXTRAL = 0xABCD02
ARCH_NAMES = {ARCH_LLAMA: "llama", ARCH_GROK1: "grok1", ARCH_MIXTRAL: "mixtral"}

# TransformerHiddenAct (transformer.hpp:45-48)
ACT_GELU = 0
ACT_SILU = 1

# TransformerHeaderKey (transformer.hpp:10-25)
KEY_VERSION = 0
KEY_ARCH_TYPE = 1
KEY_DIM = 2
KEY_HIDDEN_DIM = 3
KEY_N_LAYERS = 4
KEY_N_HEADS = 5
KEY_N_KV_HEADS = 6
KEY_N_EXPERTS = 7
KEY_N_ACTIVE_EXPERTS = 8
KEY_VOCAB_SIZE = 9
KEY_SEQ_LEN = 10
KEY_HIDDEN_ACT = 11
KEY_ROPE_THETA = 12
KEY_WEIGHTS_FLOAT_TYPE = 13


@dataclass
class ModelSpec:
    """Model hyperparameters — the reference's ``TransformerSpec``."""

    arch: int = ARCH_LLAMA
    dim: int = 0
    hidden_dim: int = 0
    n_layers: int = 0
    n_heads: int = 0
    n_kv_heads: int = 0
    n_experts: int = 0
    n_active_experts: int = 0
    vocab_size: int = 0
    seq_len: int = 0
    hidden_act: int = ACT_SILU
    rope_theta: float = 10000.0
    weights_ftype: int = quants.F32
    version: int = 1
    header_size: int = 0

    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    @property
    def kv_dim(self) -> int:
        return (self.dim * self.n_kv_heads) // self.n_heads

    @property
    def arch_name(self) -> str:
        return ARCH_NAMES.get(self.arch, hex(self.arch))

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


@dataclass
class TensorInfo:
    name: str
    shape: tuple[int, ...]  # logical row-major shape; matmuls are (d_out, n_in)
    ftype: int
    offset: int  # absolute byte offset in the file
    nbytes: int


def tensor_plan(spec: ModelSpec) -> list[TensorInfo]:
    """The fixed tensor order of a `.m` file (transformer.cpp:440-478).

    Offsets start right after the header.
    """
    w = spec.weights_ftype
    plan: list[TensorInfo] = []
    pos = spec.header_size

    def add(name: str, shape: tuple[int, ...], ftype: int):
        nonlocal pos
        d = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
        n = shape[-1]
        nbytes = quants.batch_bytes(ftype, n, d)
        plan.append(TensorInfo(name, shape, ftype, pos, nbytes))
        pos += nbytes

    add("token_embedding", (spec.vocab_size, spec.dim), quants.F32)
    for i in range(spec.n_layers):
        add(f"layers.{i}.wq", (spec.dim, spec.dim), w)
        add(f"layers.{i}.wk", (spec.kv_dim, spec.dim), w)
        add(f"layers.{i}.wv", (spec.kv_dim, spec.dim), w)
        add(f"layers.{i}.wo", (spec.dim, spec.dim), w)
        if spec.n_experts > 0:
            add(f"layers.{i}.moe_router", (spec.n_experts, spec.dim), w)
            for e in range(spec.n_experts):
                add(f"layers.{i}.experts.{e}.up", (spec.hidden_dim, spec.dim), w)
                add(f"layers.{i}.experts.{e}.gate", (spec.hidden_dim, spec.dim), w)
                add(f"layers.{i}.experts.{e}.down", (spec.dim, spec.hidden_dim), w)
        else:
            add(f"layers.{i}.w1", (spec.hidden_dim, spec.dim), w)
            add(f"layers.{i}.w2", (spec.dim, spec.hidden_dim), w)
            add(f"layers.{i}.w3", (spec.hidden_dim, spec.dim), w)
        add(f"layers.{i}.rms_att", (spec.dim,), quants.F32)
        add(f"layers.{i}.rms_ffn", (spec.dim,), quants.F32)
        if spec.arch == ARCH_GROK1:
            add(f"layers.{i}.rms_moe", (spec.dim,), quants.F32)
            add(f"layers.{i}.rms_ffn2", (spec.dim,), quants.F32)
    add("rms_final", (spec.dim,), quants.F32)
    add("wcls", (spec.vocab_size, spec.dim), w)
    return plan


def _read_exact(f, n: int, path, field: str) -> tuple[bytes, int]:
    """Read exactly ``n`` bytes or raise ArtifactError naming the offset —
    the loader-level replacement for letting ``struct.error`` escape on a
    truncated file."""
    off = f.tell()
    data = f.read(n)
    if len(data) != n:
        raise ArtifactError(path, field,
                            "file truncated mid-field",
                            offset=off, expected=f"{n} bytes",
                            got=f"{len(data)} bytes")
    return data, off


#: sanity ceilings for header-declared sizes.  A bit flip in a size field
#: must fail the parse, not drive a multi-minute tensor-plan walk or a
#: giant allocation; every bound sits far above any real model.
_SPEC_BOUNDS = {
    "dim": (1, 1 << 20),
    "hidden_dim": (1, 1 << 24),
    "n_layers": (1, 4096),
    "n_heads": (1, 4096),
    "n_kv_heads": (1, 4096),
    "n_experts": (0, 512),
    "n_active_experts": (0, 512),
    "vocab_size": (1, 1 << 24),
    "seq_len": (1, 1 << 24),
}


def validate_spec(spec: ModelSpec, path) -> ModelSpec:
    """Structural validation of a parsed header: range-check every field
    and the cross-field divisibility invariants the runtime assumes.
    Raises :class:`ArtifactError` naming the offending field."""
    for field, (lo, hi) in _SPEC_BOUNDS.items():
        v = getattr(spec, field)
        if not (lo <= v <= hi):
            raise ArtifactError(path, f"header field {field}",
                                "value out of range — corrupt header",
                                expected=f"{lo}..{hi}", got=v)
    if spec.arch not in ARCH_NAMES:
        raise ArtifactError(path, "header field arch",
                            "unknown architecture id",
                            expected=sorted(hex(a) for a in ARCH_NAMES),
                            got=hex(spec.arch))
    if spec.hidden_act not in (ACT_GELU, ACT_SILU):
        raise ArtifactError(path, "header field hidden_act",
                            "unknown activation id", expected="0|1",
                            got=spec.hidden_act)
    if spec.weights_ftype not in quants.FLOAT_TYPE_NAMES:
        raise ArtifactError(path, "header field weights_ftype",
                            "unknown weights float type",
                            expected=sorted(quants.FLOAT_TYPE_NAMES),
                            got=spec.weights_ftype)
    if not spec.rope_theta > 0:
        raise ArtifactError(path, "header field rope_theta",
                            "must be positive", got=spec.rope_theta)
    if spec.n_kv_heads > spec.n_heads:
        raise ArtifactError(path, "header field n_kv_heads",
                            "more KV heads than attention heads",
                            expected=f"<= {spec.n_heads}", got=spec.n_kv_heads)
    if spec.dim % spec.n_heads:
        raise ArtifactError(path, "header field n_heads",
                            "dim not divisible by n_heads",
                            expected=f"divisor of dim={spec.dim}",
                            got=spec.n_heads)
    if spec.n_heads % spec.n_kv_heads:
        raise ArtifactError(path, "header field n_kv_heads",
                            "n_heads not divisible by n_kv_heads (GQA)",
                            expected=f"divisor of n_heads={spec.n_heads}",
                            got=spec.n_kv_heads)
    if spec.n_active_experts > spec.n_experts:
        raise ArtifactError(path, "header field n_active_experts",
                            "more active experts than experts",
                            expected=f"<= {spec.n_experts}",
                            got=spec.n_active_experts)
    return spec


def read_spec(path: str | os.PathLike, weights_ftype: int | None = None) -> ModelSpec:
    """Parse + validate a `.m` header (transformer.cpp:12-125).

    Fully bounds-checked (beyond reference — ``loadSpecFromFile`` trusts
    its input): every read is length-checked, the declared header size is
    checked against the file, keys/values are range-checked, and any
    violation raises :class:`ArtifactError` with the file offset and field
    name — never ``struct.error``.

    ``weights_ftype`` mirrors the reference's mandatory
    ``--weights-float-type`` flag: legacy-magic files don't carry the weight
    float type, and v2 files may omit the key; the reference refuses to load
    in that case (`FUNK` check, transformer.cpp:80-81).
    """
    spec = ModelSpec()
    found_wft = False
    file_size = os.path.getsize(path)
    with open(path, "rb") as f:
        raw, _ = _read_exact(f, 4, path, "magic")
        (magic,) = struct.unpack("<i", raw)
        if magic in LEGACY_MAGICS:
            raw, off = _read_exact(f, 36, path, "legacy header")
            vals = struct.unpack("<9i", raw)
            spec.arch = magic
            (spec.dim, spec.hidden_dim, spec.n_layers, spec.n_heads,
             spec.n_kv_heads, spec.n_experts, spec.n_active_experts,
             spec.vocab_size, spec.seq_len) = vals
            spec.header_size = 4 + 36
        elif magic == MAGIC_V2:
            raw, off = _read_exact(f, 4, path, "headerSize")
            (header_size,) = struct.unpack("<i", raw)
            if header_size < 8 or (header_size - 8) % 8:
                raise ArtifactError(
                    path, "headerSize",
                    "must be 8 + a whole number of (key, value) i32 pairs",
                    offset=off, expected="8 + 8k", got=header_size)
            if header_size > file_size:
                raise ArtifactError(path, "headerSize",
                                    "header extends past end of file",
                                    offset=off, expected=f"<= {file_size}",
                                    got=header_size)
            spec.header_size = header_size
            body, body_off = _read_exact(f, header_size - 8, path, "header body")
            kv = struct.unpack(f"<{len(body) // 4}i", body)
            for i, (k, v) in enumerate(zip(kv[::2], kv[1::2])):
                pair_off = body_off + 8 * i
                if k == KEY_VERSION:
                    spec.version = v
                elif k == KEY_ARCH_TYPE:
                    spec.arch = v
                elif k == KEY_DIM:
                    spec.dim = v
                elif k == KEY_HIDDEN_DIM:
                    spec.hidden_dim = v
                elif k == KEY_N_LAYERS:
                    spec.n_layers = v
                elif k == KEY_N_HEADS:
                    spec.n_heads = v
                elif k == KEY_N_KV_HEADS:
                    spec.n_kv_heads = v
                elif k == KEY_N_EXPERTS:
                    spec.n_experts = v
                elif k == KEY_N_ACTIVE_EXPERTS:
                    spec.n_active_experts = v
                elif k == KEY_VOCAB_SIZE:
                    spec.vocab_size = v
                elif k == KEY_SEQ_LEN:
                    spec.seq_len = v
                elif k == KEY_HIDDEN_ACT:
                    spec.hidden_act = v
                elif k == KEY_ROPE_THETA:
                    spec.rope_theta = float(v)
                elif k == KEY_WEIGHTS_FLOAT_TYPE:
                    spec.weights_ftype = v
                    found_wft = True
                else:
                    raise ArtifactError(path, "header key",
                                        "unsupported .m header key",
                                        offset=pair_off,
                                        expected=f"0..{KEY_WEIGHTS_FLOAT_TYPE}",
                                        got=k)
        else:
            raise ArtifactError(path, "magic",
                                "unsupported model file magic",
                                offset=0,
                                expected=[hex(MAGIC_V2)] + [hex(m) for m in LEGACY_MAGICS],
                                got=hex(magic & 0xFFFFFFFF))
    # Precedence mirrors the reference: the header's WEIGHTS_FLOAT_TYPE key
    # overwrites the caller/CLI value (transformer.cpp:66-74 loop overwrites
    # the argument); the explicit argument only covers files lacking the key.
    if not found_wft:
        if weights_ftype is None:
            raise ArtifactError(
                path, "header field weights_ftype",
                "model file does not specify weights float type; pass weights_ftype "
                "(reference: 'Not specified weights float type', transformer.cpp:80-81)")
        spec.weights_ftype = weights_ftype
    return validate_spec(spec, path)


class MFile:
    """mmap-backed lazy `.m` reader with integrity checking.

    When a sidecar checksum manifest (``<path>.sum``, io/integrity.py,
    written by ``tools/checksum_model.py``) exists, the header digest is
    verified at open **always**, and each tensor's digest is verified on
    first read when ``verify=True`` (the CLI's ``--verify-weights``) —
    lazy, so sharded loading still streams without a full pre-pass, yet
    every byte the runtime consumes was checksummed.  ``verify=True``
    with no manifest is an error: silently skipping requested
    verification would defeat its purpose.
    """

    def __init__(self, path: str | os.PathLike, weights_ftype: int | None = None,
                 verify: bool = False):
        self.path = os.fspath(path)
        self.spec = read_spec(path, weights_ftype)
        self.verify_weights = verify
        self.manifest = load_manifest_for(self.path)
        self._verified: set[str] = set()
        if verify and self.manifest is None:
            raise ArtifactError(
                self.path, "manifest",
                "weight verification requested but no checksum manifest "
                f"found at {self.path}.sum (generate one with "
                "tools/checksum_model.py write)")
        self._f = open(self.path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        if self.manifest is not None:
            # header digest is always-on, and it runs BEFORE the tensor
            # plan is derived: every plan offset/size below comes from
            # header fields, so a flipped header must be caught here, not
            # surface as a downstream shape error
            if self.manifest["file_size"] != len(self._mm):
                raise ArtifactError(self.path, "file size",
                                    "size mismatch vs manifest",
                                    expected=self.manifest["file_size"],
                                    got=len(self._mm))
            verify_bytes(self.manifest["header"],
                         self._mm[:self.spec.header_size], self.path, "header")
        try:
            self.plan = tensor_plan(self.spec)
        except ValueError as e:
            # spec fields were individually in range but jointly impossible
            # (e.g. a flipped vocab_size that breaks quant block alignment)
            raise ArtifactError(
                self.path, "header",
                f"header describes an impossible tensor plan: {e}") from e
        self.by_name = {t.name: t for t in self.plan}
        end = self.plan[-1].offset + self.plan[-1].nbytes
        if len(self._mm) != end:
            raise ArtifactError(
                self.path, "file size",
                f"model file size mismatch: file={len(self._mm)} expected={end} "
                f"(reference errors the same way, transformer.cpp:480-484)",
                expected=end, got=len(self._mm))

    def close(self):
        try:
            self._mm.close()
        except BufferError:
            # zero-copy views handed out by raw() still reference the map;
            # it closes when the last view is collected
            pass
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def info(self, name: str) -> TensorInfo:
        """Plan entry for ``name``; unknown names raise ArtifactError
        listing what the file actually contains (never a bare KeyError)."""
        t = self.by_name.get(name)
        if t is None:
            sample = ", ".join(sorted(self.by_name)[:6])
            raise ArtifactError(
                self.path, f"tensor {name!r}",
                f"unknown tensor name; this {self.spec.arch_name} file has "
                f"{len(self.by_name)} tensors ({sample}, ...)")
        return t

    def raw(self, name: str) -> np.ndarray:
        """One tensor's packed file bytes (checksum-verified on first read
        under ``verify=True``).  The ``io.read_tensor`` fault point's
        ``corrupt`` action flips a byte of the returned buffer — the
        deterministic stand-in for storage corruption that lets drills
        prove the manifest catches it (runtime/faults.py)."""
        from ..runtime.faults import FAULTS
        t = self.info(name)
        buf = np.frombuffer(self._mm, dtype=np.uint8, count=t.nbytes,
                            offset=t.offset)
        if "corrupt" in FAULTS.fire("io.read_tensor"):
            buf = buf.copy()
            buf[0] ^= 0xFF
        if self.verify_weights and name not in self._verified:
            ent = self.manifest["tensors"].get(name)
            if ent is None:
                raise ArtifactError(self.path, f"tensor {name!r}",
                                    "tensor missing from checksum manifest "
                                    "(stale manifest? regenerate it)")
            if (ent["offset"], ent["nbytes"]) != (t.offset, t.nbytes):
                raise ArtifactError(
                    self.path, f"tensor {name!r}",
                    "manifest byte range disagrees with the file's tensor "
                    "plan (stale manifest? regenerate it)",
                    offset=t.offset,
                    expected=(ent["offset"], ent["nbytes"]),
                    got=(t.offset, t.nbytes))
            verify_bytes(ent, buf, self.path, f"tensor {name!r}")
            self._verified.add(name)
        return buf

    def tensor(self, name: str) -> np.ndarray:
        """Dequantize one tensor to f32 in its logical row-major shape."""
        t = self.info(name)
        n = int(np.prod(t.shape))
        return quants.dequantize_tensor(self.raw(name), t.ftype, n).reshape(t.shape)

    def q40_planes(self, name: str) -> tuple[np.ndarray, np.ndarray]:
        """Unpacked int8 values + per-block scales for a Q40 matmul tensor."""
        t = self.info(name)
        if t.ftype != quants.Q40:
            raise ValueError(f"{name} is not Q40")
        d = int(np.prod(t.shape[:-1]))
        return quants.q40_planes(self.raw(name), (d, t.shape[-1]))


def write_header(f, spec: ModelSpec) -> int:
    """Write a v2 `.m` header; returns its byte count
    (converter/writer.py:113-143 layout)."""
    pairs = [
        (KEY_VERSION, spec.version),
        (KEY_ARCH_TYPE, spec.arch),
        (KEY_DIM, spec.dim),
        (KEY_HIDDEN_DIM, spec.hidden_dim),
        (KEY_N_LAYERS, spec.n_layers),
        (KEY_N_HEADS, spec.n_heads),
        (KEY_N_KV_HEADS, spec.n_kv_heads),
        (KEY_N_EXPERTS, spec.n_experts),
        (KEY_N_ACTIVE_EXPERTS, spec.n_active_experts),
        (KEY_VOCAB_SIZE, spec.vocab_size),
        (KEY_SEQ_LEN, spec.seq_len),
        (KEY_HIDDEN_ACT, spec.hidden_act),
        (KEY_ROPE_THETA, int(spec.rope_theta)),
        (KEY_WEIGHTS_FLOAT_TYPE, spec.weights_ftype),
    ]
    data = b"".join(struct.pack("<ii", k, v) for k, v in pairs)
    f.write(struct.pack("<ii", MAGIC_V2, 8 + len(data)))
    f.write(data)
    return 8 + len(data)


class MFileWriter:
    """Streams tensors into a `.m` file in the canonical order."""

    def __init__(self, path: str | os.PathLike, spec: ModelSpec):
        self.spec = spec
        self._i = 0
        self._f = open(path, "wb")
        spec.header_size = write_header(self._f, spec)
        self.plan = tensor_plan(spec)

    def write_tensor(self, name: str, x: np.ndarray) -> None:
        expect = self.plan[self._i]
        if name != expect.name:
            raise ValueError(f"tensor order mismatch: got {name}, want {expect.name}")
        if tuple(x.shape) != tuple(expect.shape):
            raise ValueError(f"{name}: shape {x.shape} != {expect.shape}")
        self._f.write(quants.quantize_tensor(x, expect.ftype))
        self._i += 1

    def write_raw(self, name: str, raw: np.ndarray | bytes) -> None:
        """Write a tensor's already-encoded bytes (size-checked against the
        plan).  Lets large fixtures/benchmark models be synthesized at
        packed size with no f32 transit — the quantized analogue of the
        reference's direct block writes (writer.py:29-78)."""
        expect = self.plan[self._i]
        if name != expect.name:
            raise ValueError(f"tensor order mismatch: got {name}, want {expect.name}")
        n = int(np.prod(expect.shape))
        want = quants.batch_bytes(expect.ftype, n)
        raw = np.asarray(raw, np.uint8) if not isinstance(raw, bytes) else raw
        got = raw.nbytes if isinstance(raw, np.ndarray) else len(raw)
        if got != want:
            raise ValueError(f"{name}: raw payload {got} B != expected {want} B")
        self._f.write(raw.tobytes() if isinstance(raw, np.ndarray) else raw)
        self._i += 1

    def close(self):
        if self._i != len(self.plan):
            raise ValueError(f"file incomplete: {self._i}/{len(self.plan)} tensors written")
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
        else:
            self._f.close()
