"""Artifact integrity: typed loader errors + checksum manifests.

The reference engine mmaps whatever bytes it is handed
(``loadSpecFromFile``, transformer.cpp:12-125, does no bounds or
integrity checking), so a truncated or bit-flipped model file surfaces
as a cryptic ``struct.error``, a silently-garbage tensor, or NaN logits
minutes into decode.  This module is the common substrate for the
validated loaders (io/mfile.py, io/tfile.py) and the engine snapshot
format (runtime/snapshot.py):

* :class:`ArtifactError` — THE corruption exception.  Every loader-level
  failure names the file, the field being parsed, the byte offset, and
  expected-vs-got, so a bad artifact is diagnosable from the message
  alone.  Subclasses ``ValueError`` so pre-existing callers that caught
  ValueError keep working.
* **Checksum manifests** — a JSON sidecar (``<model>.m.sum``) carrying a
  crc32 per tensor byte-range plus a header digest, written by
  ``tools/checksum_model.py``.  ``MFile`` verifies the header digest
  always (when the sidecar exists) and tensor digests lazily on first
  read under ``--verify-weights``; ``read_tfile`` verifies a whole-file
  digest.  crc32 (zlib, stdlib) is the algorithm: this is corruption
  *detection* on trusted storage, not an adversarial MAC, and crc32
  streams at memory bandwidth with no dependencies.
* **Counters** — process-global verification counters exported verbatim
  at the API server's ``/metrics`` (docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import json
import os
import zlib

MANIFEST_FORMAT = "dllama-manifest"
MANIFEST_VERSION = 1
MANIFEST_SUFFIX = ".sum"


class ArtifactError(ValueError):
    """A model/tokenizer/snapshot artifact failed validation.

    Carries structured context (``path``, ``field``, ``offset``,
    ``expected``, ``got``) and renders it all into the message so the
    failure is diagnosable from a log line.  A ``ValueError`` subclass:
    the pre-integrity loaders raised bare ValueErrors and callers (tests,
    the CLI) match on that.
    """

    def __init__(self, path, field: str, message: str, *,
                 offset: int | None = None, expected=None, got=None):
        self.path = str(path) if path is not None else None
        self.field = field
        self.offset = offset
        self.expected = expected
        self.got = got
        loc = f" at byte {offset}" if offset is not None else ""
        detail = ""
        if expected is not None or got is not None:
            detail = f" (expected {expected!r}, got {got!r})"
        where = f"{self.path}: " if self.path else ""
        super().__init__(f"{where}{field}{loc}: {message}{detail}")


# -- verification counters (exported at /metrics) -------------------------
# Since PR 3 these live in the obs metric registry (one registry, two
# exposition formats — see dllama_tpu/obs/metrics.py); the three
# functions below keep the pre-registry call-site API.  Registered at
# obs import, so every key is present from boot (a counter that appears
# only after its first failure reads as "metric missing" to a dashboard,
# not "zero failures").

_INTEGRITY_KEYS = ("checksum_verified", "checksum_failures",
                   "numeric_faults", "snapshot_restores")


def _counter(name: str):
    from dllama_tpu.obs import metrics as _m
    return _m.REGISTRY.counter(name)


def bump_counter(name: str, n: int = 1) -> None:
    _counter(name).inc(n)


def counters() -> dict:
    """Snapshot of the process-global verification counters."""
    return {k: _counter(k).value for k in _INTEGRITY_KEYS}


def reset_counters() -> None:
    """Test isolation helper."""
    for k in _INTEGRITY_KEYS:
        _counter(k).reset()


# -- digests ---------------------------------------------------------------

def digest(data) -> int:
    """crc32 of a bytes-like object (numpy arrays accepted)."""
    return zlib.crc32(memoryview(data).cast("B")) & 0xFFFFFFFF


def _file_crc32(path, offset: int = 0, nbytes: int | None = None,
                chunk: int = 1 << 24) -> int:
    crc = 0
    remaining = nbytes
    with open(path, "rb") as f:
        f.seek(offset)
        while True:
            n = chunk if remaining is None else min(chunk, remaining)
            if n == 0:
                break
            buf = f.read(n)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
            if remaining is not None:
                remaining -= len(buf)
    return crc & 0xFFFFFFFF


# -- manifest build / write / load ----------------------------------------

def manifest_path_for(artifact_path) -> str:
    return os.fspath(artifact_path) + MANIFEST_SUFFIX


def build_manifest(path, weights_ftype: int | None = None) -> dict:
    """Build a manifest dict for an artifact.

    ``.m`` model files get a per-tensor manifest (header digest + one
    crc32 per tensor byte-range, in the canonical tensor-plan order);
    any other file (e.g. a ``.t`` tokenizer) gets a whole-file digest
    stored as its ``header`` entry — the lazy-verification granularity
    only matters for the multi-GB weights.  ``weights_ftype`` covers
    legacy ``.m`` files whose header omits the weight float type (the
    tensor byte-ranges depend on it).
    """
    from . import mfile  # lazy: mfile imports this module for ArtifactError

    path = os.fspath(path)
    size = os.path.getsize(path)
    man = {
        "format": MANIFEST_FORMAT,
        "version": MANIFEST_VERSION,
        "algorithm": "crc32",
        "file": os.path.basename(path),
        "file_size": size,
        "tensors": {},
    }
    with open(path, "rb") as f:
        magic_bytes = f.read(4)
    magic = int.from_bytes(magic_bytes, "little", signed=True) \
        if len(magic_bytes) == 4 else None
    if magic == mfile.MAGIC_V2 or magic in mfile.LEGACY_MAGICS:
        spec = mfile.read_spec(path, weights_ftype=weights_ftype)
        man["header"] = {"offset": 0, "nbytes": spec.header_size,
                         "crc32": _file_crc32(path, 0, spec.header_size)}
        for t in mfile.tensor_plan(spec):
            man["tensors"][t.name] = {
                "offset": t.offset, "nbytes": t.nbytes,
                "crc32": _file_crc32(path, t.offset, t.nbytes)}
    else:
        man["header"] = {"offset": 0, "nbytes": size,
                         "crc32": _file_crc32(path, 0, size)}
    return man


def write_manifest(path, manifest_path=None,
                   weights_ftype: int | None = None) -> str:
    """Build and write the sidecar manifest for ``path``; returns its path."""
    mp = manifest_path or manifest_path_for(path)
    man = build_manifest(path, weights_ftype=weights_ftype)
    tmp = mp + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(man, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, mp)
    return mp


def load_manifest(manifest_path) -> dict:
    """Load + validate a manifest file; raises ArtifactError when it is
    itself corrupt (a manifest that cannot be trusted must not silently
    disable verification)."""
    try:
        with open(manifest_path, "r", encoding="utf-8") as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ArtifactError(manifest_path, "manifest",
                            f"unreadable manifest: {e}") from e
    if not isinstance(man, dict) or man.get("format") != MANIFEST_FORMAT:
        raise ArtifactError(manifest_path, "manifest.format",
                            "not a dllama checksum manifest",
                            expected=MANIFEST_FORMAT,
                            got=man.get("format") if isinstance(man, dict) else type(man).__name__)
    if man.get("version") != MANIFEST_VERSION:
        raise ArtifactError(manifest_path, "manifest.version",
                            "unsupported manifest version",
                            expected=MANIFEST_VERSION, got=man.get("version"))
    if man.get("algorithm") != "crc32":
        raise ArtifactError(manifest_path, "manifest.algorithm",
                            "unsupported digest algorithm",
                            expected="crc32", got=man.get("algorithm"))
    for key in ("file_size", "header", "tensors"):
        if key not in man:
            raise ArtifactError(manifest_path, f"manifest.{key}",
                                "missing required manifest key")
    return man


def load_manifest_for(artifact_path) -> dict | None:
    """The artifact's sidecar manifest, or None when none exists."""
    mp = manifest_path_for(artifact_path)
    if not os.path.exists(mp):
        return None
    return load_manifest(mp)


def verify_bytes(entry: dict, data, path, field: str) -> None:
    """Verify a byte region against its manifest entry (crc32 + length).

    Bumps the process-global counters; raises :class:`ArtifactError`
    naming the region's file offset on any mismatch.
    """
    nbytes = memoryview(data).cast("B").nbytes
    if nbytes != entry["nbytes"]:
        bump_counter("checksum_failures")
        raise ArtifactError(path, field, "region size mismatch vs manifest",
                            offset=entry["offset"],
                            expected=entry["nbytes"], got=nbytes)
    got = digest(data)
    if got != entry["crc32"]:
        bump_counter("checksum_failures")
        raise ArtifactError(
            path, field, "checksum mismatch — artifact bytes are corrupt",
            offset=entry["offset"],
            expected=f"crc32={entry['crc32']:#010x}", got=f"crc32={got:#010x}")
    bump_counter("checksum_verified")


def verify_file(path, manifest: dict | None = None) -> int:
    """Fully verify an artifact against its manifest (every region).

    Returns the number of regions verified; raises ArtifactError on the
    first mismatch.  This is the eager path ``tools/checksum_model.py
    verify`` uses; ``MFile`` verifies the same regions lazily instead.
    """
    man = manifest if manifest is not None else load_manifest(manifest_path_for(path))
    size = os.path.getsize(path)
    if size != man["file_size"]:
        bump_counter("checksum_failures")
        raise ArtifactError(path, "file size", "size mismatch vs manifest",
                            expected=man["file_size"], got=size)
    regions = [("header", man["header"])]
    regions += [(f"tensor {name!r}", ent)
                for name, ent in man["tensors"].items()]
    for field, ent in regions:
        got = _file_crc32(path, ent["offset"], ent["nbytes"])
        if got != ent["crc32"]:
            bump_counter("checksum_failures")
            raise ArtifactError(
                path, field, "checksum mismatch — artifact bytes are corrupt",
                offset=ent["offset"],
                expected=f"crc32={ent['crc32']:#010x}", got=f"crc32={got:#010x}")
        bump_counter("checksum_verified")
    return len(regions)
