"""`.t` tokenizer-file format: reader + writer.

Byte-compatible with the reference tokenizer format
(`/root/reference/src/tokenizer.cpp:39-138` reader,
`converter/tokenizer-writer.py:47-59` writer):

* magic ``0x567124`` (v1) — i32 ``headerSize`` (total incl. magic+size),
  (key, value) i32 pairs keyed by ``TokenizerHeaderKey``
  (tokenizer.hpp:24-34); ``CHAT_TEMPLATE``/``CHAT_STOP`` values are byte
  lengths of strings that directly follow the header.
* magic ``0x567123`` (legacy) — fixed header
  ``{vocabSize, maxTokenLength, bosId, eosId, padId}`` (tokenizer.hpp:16-22).
* vocab body: per token, f32 score + i32 length + raw bytes.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field

MAGIC_V1 = 0x567124
MAGIC_LEGACY = 0x567123

# TokenizerHeaderKey (tokenizer.hpp:24-34)
TOK_VERSION = 0
TOK_VOCAB_SIZE = 1
MAX_TOKEN_LENGTH = 2
BOS_ID = 3
EOS_ID = 4
PAD_ID = 5
CHAT_EOS_ID = 6
CHAT_TEMPLATE = 7
CHAT_STOP = 8


@dataclass
class TokenizerData:
    vocab: list[bytes] = field(default_factory=list)
    scores: list[float] = field(default_factory=list)
    max_token_length: int = 0
    bos_id: int = -1
    eos_id: int = -1
    chat_eos_id: int = -1
    chat_template: str | None = None
    chat_stop: str | None = None

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)


def read_tfile(path: str | os.PathLike) -> TokenizerData:
    t = TokenizerData()
    with open(path, "rb") as f:
        (magic,) = struct.unpack("<i", f.read(4))
        if magic == MAGIC_LEGACY:
            vocab_size, t.max_token_length = struct.unpack("<II", f.read(8))
            t.bos_id, t.eos_id, _pad = struct.unpack("<iii", f.read(12))
        elif magic == MAGIC_V1:
            (header_size,) = struct.unpack("<i", f.read(4))
            body = f.read(header_size - 8)
            kv = struct.unpack(f"<{len(body) // 4}i", body)
            version = -1
            vocab_size = 0
            template_len = stop_len = 0
            for k, v in zip(kv[::2], kv[1::2]):
                if k == TOK_VERSION:
                    version = v
                elif k == TOK_VOCAB_SIZE:
                    vocab_size = v
                elif k == MAX_TOKEN_LENGTH:
                    t.max_token_length = v
                elif k == BOS_ID:
                    t.bos_id = v
                elif k == EOS_ID:
                    t.eos_id = v
                elif k == CHAT_EOS_ID:
                    t.chat_eos_id = v
                elif k == CHAT_TEMPLATE:
                    template_len = v
                elif k == CHAT_STOP:
                    stop_len = v
                elif k == PAD_ID:
                    pass  # ignored by the reference too (tokenizer.cpp:87)
                else:
                    raise ValueError(f"invalid tokenizer header key {k}")
            if version != 1:
                raise ValueError("old tokenizer version, please regenerate")
            if template_len > 0:
                t.chat_template = f.read(template_len).decode("utf-8", errors="replace")
            if stop_len > 0:
                t.chat_stop = f.read(stop_len).decode("utf-8", errors="replace")
        else:
            raise ValueError(f"invalid tokenizer file magic {magic:#x}")

        for _ in range(vocab_size):
            score, length = struct.unpack("<fi", f.read(8))
            t.scores.append(score)
            t.vocab.append(f.read(length))
    return t


def write_tfile(path: str | os.PathLike, t: TokenizerData) -> None:
    template = t.chat_template.encode("utf-8") if t.chat_template else b""
    stop = t.chat_stop.encode("utf-8") if t.chat_stop else b""
    pairs = [
        (TOK_VERSION, 1),
        (TOK_VOCAB_SIZE, t.vocab_size),
        (MAX_TOKEN_LENGTH, t.max_token_length or max((len(v) for v in t.vocab), default=0)),
        (BOS_ID, t.bos_id),
        (EOS_ID, t.eos_id),
    ]
    if t.chat_eos_id >= 0:
        pairs.append((CHAT_EOS_ID, t.chat_eos_id))
    if template:
        pairs.append((CHAT_TEMPLATE, len(template)))
    if stop:
        pairs.append((CHAT_STOP, len(stop)))
    data = b"".join(struct.pack("<ii", k, v) for k, v in pairs)
    with open(path, "wb") as f:
        f.write(struct.pack("<ii", MAGIC_V1, 8 + len(data)))
        f.write(data)
        f.write(template)
        f.write(stop)
        for score, piece in zip(t.scores, t.vocab):
            f.write(struct.pack("<fi", score, len(piece)))
            f.write(piece)
