"""`.t` tokenizer-file format: reader + writer.

Byte-compatible with the reference tokenizer format
(`/root/reference/src/tokenizer.cpp:39-138` reader,
`converter/tokenizer-writer.py:47-59` writer):

* magic ``0x567124`` (v1) — i32 ``headerSize`` (total incl. magic+size),
  (key, value) i32 pairs keyed by ``TokenizerHeaderKey``
  (tokenizer.hpp:24-34); ``CHAT_TEMPLATE``/``CHAT_STOP`` values are byte
  lengths of strings that directly follow the header.
* magic ``0x567123`` (legacy) — fixed header
  ``{vocabSize, maxTokenLength, bosId, eosId, padId}`` (tokenizer.hpp:16-22).
* vocab body: per token, f32 score + i32 length + raw bytes.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field

from .integrity import ArtifactError, load_manifest_for, verify_bytes

MAGIC_V1 = 0x567124
MAGIC_LEGACY = 0x567123

#: sanity ceilings: a bit-flipped length field must fail the parse, not
#: drive a giant read.  Far above any real tokenizer.
_MAX_VOCAB = 1 << 24
_MAX_TOKEN_BYTES = 1 << 16
_MAX_STR_BYTES = 1 << 20

# TokenizerHeaderKey (tokenizer.hpp:24-34)
TOK_VERSION = 0
TOK_VOCAB_SIZE = 1
MAX_TOKEN_LENGTH = 2
BOS_ID = 3
EOS_ID = 4
PAD_ID = 5
CHAT_EOS_ID = 6
CHAT_TEMPLATE = 7
CHAT_STOP = 8


@dataclass
class TokenizerData:
    vocab: list[bytes] = field(default_factory=list)
    scores: list[float] = field(default_factory=list)
    max_token_length: int = 0
    bos_id: int = -1
    eos_id: int = -1
    chat_eos_id: int = -1
    chat_template: str | None = None
    chat_stop: str | None = None

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)


def _read_exact(f, n: int, path, field: str) -> tuple[bytes, int]:
    off = f.tell()
    data = f.read(n)
    if len(data) != n:
        raise ArtifactError(path, field, "file truncated mid-field",
                            offset=off, expected=f"{n} bytes",
                            got=f"{len(data)} bytes")
    return data, off


def read_tfile(path: str | os.PathLike) -> TokenizerData:
    """Parse + validate a `.t` tokenizer file.

    Fully bounds-checked (beyond reference — ``Tokenizer::Tokenizer``
    trusts its input): every read is length-checked, every declared
    length/count is range-checked, trailing garbage is rejected, and any
    violation raises :class:`ArtifactError` with the file offset and
    field name — never ``struct.error``.  When a sidecar checksum
    manifest (``<path>.sum``) exists, the whole file is verified against
    it first, so even a flip inside a token's raw bytes (which no
    structural check can see) is caught.
    """
    path = os.fspath(path)
    man = load_manifest_for(path)
    file_size = os.path.getsize(path)
    if man is not None:
        if man["file_size"] != file_size:
            raise ArtifactError(path, "file size", "size mismatch vs manifest",
                                expected=man["file_size"], got=file_size)
        with open(path, "rb") as f:
            verify_bytes(man["header"], f.read(), path, "file")
    t = TokenizerData()
    with open(path, "rb") as f:
        raw, _ = _read_exact(f, 4, path, "magic")
        (magic,) = struct.unpack("<i", raw)
        if magic == MAGIC_LEGACY:
            raw, off = _read_exact(f, 8, path, "legacy header")
            vocab_size, t.max_token_length = struct.unpack("<II", raw)
            raw, _ = _read_exact(f, 12, path, "legacy header ids")
            t.bos_id, t.eos_id, _pad = struct.unpack("<iii", raw)
        elif magic == MAGIC_V1:
            raw, off = _read_exact(f, 4, path, "headerSize")
            (header_size,) = struct.unpack("<i", raw)
            if header_size < 8 or (header_size - 8) % 8:
                raise ArtifactError(
                    path, "headerSize",
                    "must be 8 + a whole number of (key, value) i32 pairs",
                    offset=off, expected="8 + 8k", got=header_size)
            if header_size > file_size:
                raise ArtifactError(path, "headerSize",
                                    "header extends past end of file",
                                    offset=off, expected=f"<= {file_size}",
                                    got=header_size)
            body, body_off = _read_exact(f, header_size - 8, path, "header body")
            kv = struct.unpack(f"<{len(body) // 4}i", body)
            version = -1
            vocab_size = 0
            template_len = stop_len = 0
            for i, (k, v) in enumerate(zip(kv[::2], kv[1::2])):
                pair_off = body_off + 8 * i
                if k == TOK_VERSION:
                    version = v
                elif k == TOK_VOCAB_SIZE:
                    vocab_size = v
                elif k == MAX_TOKEN_LENGTH:
                    t.max_token_length = v
                elif k == BOS_ID:
                    t.bos_id = v
                elif k == EOS_ID:
                    t.eos_id = v
                elif k == CHAT_EOS_ID:
                    t.chat_eos_id = v
                elif k == CHAT_TEMPLATE:
                    template_len = v
                elif k == CHAT_STOP:
                    stop_len = v
                elif k == PAD_ID:
                    pass  # ignored by the reference too (tokenizer.cpp:87)
                else:
                    raise ArtifactError(path, "header key",
                                        "invalid tokenizer header key",
                                        offset=pair_off,
                                        expected=f"0..{CHAT_STOP}", got=k)
            if version != 1:
                raise ArtifactError(path, "header field version",
                                    "old tokenizer version, please regenerate",
                                    expected=1, got=version)
            for field_name, v in (("chat_template length", template_len),
                                  ("chat_stop length", stop_len)):
                if not (0 <= v <= _MAX_STR_BYTES):
                    raise ArtifactError(path, f"header field {field_name}",
                                        "value out of range — corrupt header",
                                        expected=f"0..{_MAX_STR_BYTES}", got=v)
            if template_len > 0:
                raw, _ = _read_exact(f, template_len, path, "chat_template")
                t.chat_template = raw.decode("utf-8", errors="replace")
            if stop_len > 0:
                raw, _ = _read_exact(f, stop_len, path, "chat_stop")
                t.chat_stop = raw.decode("utf-8", errors="replace")
        else:
            raise ArtifactError(path, "magic",
                                "invalid tokenizer file magic", offset=0,
                                expected=[hex(MAGIC_V1), hex(MAGIC_LEGACY)],
                                got=hex(magic & 0xFFFFFFFF))

        if not (0 <= vocab_size <= _MAX_VOCAB):
            raise ArtifactError(path, "header field vocab_size",
                                "value out of range — corrupt header",
                                expected=f"0..{_MAX_VOCAB}", got=vocab_size)
        if not (0 <= t.max_token_length <= _MAX_TOKEN_BYTES):
            raise ArtifactError(path, "header field max_token_length",
                                "value out of range — corrupt header",
                                expected=f"0..{_MAX_TOKEN_BYTES}",
                                got=t.max_token_length)
        for i in range(vocab_size):
            raw, off = _read_exact(f, 8, path, f"vocab[{i}] score+length")
            score, length = struct.unpack("<fi", raw)
            if not (0 <= length <= _MAX_TOKEN_BYTES):
                raise ArtifactError(path, f"vocab[{i}] length",
                                    "token length out of range — corrupt vocab",
                                    offset=off + 4,
                                    expected=f"0..{_MAX_TOKEN_BYTES}", got=length)
            piece, _ = _read_exact(f, length, path, f"vocab[{i}] bytes")
            t.scores.append(score)
            t.vocab.append(piece)
        trailing = f.read(1)
        if trailing:
            raise ArtifactError(path, "end of file",
                                "trailing bytes after vocab — corrupt or "
                                "mis-sized file", offset=f.tell() - 1,
                                expected="EOF",
                                got=f"{file_size - f.tell() + 1} extra bytes")
    return t


def write_tfile(path: str | os.PathLike, t: TokenizerData) -> None:
    template = t.chat_template.encode("utf-8") if t.chat_template else b""
    stop = t.chat_stop.encode("utf-8") if t.chat_stop else b""
    pairs = [
        (TOK_VERSION, 1),
        (TOK_VOCAB_SIZE, t.vocab_size),
        (MAX_TOKEN_LENGTH, t.max_token_length or max((len(v) for v in t.vocab), default=0)),
        (BOS_ID, t.bos_id),
        (EOS_ID, t.eos_id),
    ]
    if t.chat_eos_id >= 0:
        pairs.append((CHAT_EOS_ID, t.chat_eos_id))
    if template:
        pairs.append((CHAT_TEMPLATE, len(template)))
    if stop:
        pairs.append((CHAT_STOP, len(stop)))
    data = b"".join(struct.pack("<ii", k, v) for k, v in pairs)
    with open(path, "wb") as f:
        f.write(struct.pack("<ii", MAGIC_V1, 8 + len(data)))
        f.write(data)
        f.write(template)
        f.write(stop)
        for score, piece in zip(t.scores, t.vocab):
            f.write(struct.pack("<fi", score, len(piece)))
            f.write(piece)
