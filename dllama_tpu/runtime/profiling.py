"""Profiler-derived compute-vs-collective attribution.

The reference's headline benchmark splits per-token time into I (inference)
and T (transfer) using task-type wall-clock accounting in its scheduler
(utils.cpp:189-192, printed at dllama.cpp:77-93).  On a TPU mesh the
inter-chip hops are XLA collectives *inside* the compiled program, so the
equivalent split needs the XLA profiler: this module traces a few engine
steps with ``jax.profiler`` and classifies device-op time into collective
vs compute from the xplane proto (SURVEY §5-tracing prescribes exactly
this profiler-derived attribution).

The heavy imports (tensorflow's xplane proto) happen lazily — profiling is
an opt-in diagnostic (`dllama inference --profile-split`), not a hot-path
dependency; without the proto available the caller gets ``None``.
"""

from __future__ import annotations

import glob
import re
import tempfile
from typing import Callable

# XLA HLO collective primitives (the ICI traffic the reference counts as T)
_COLLECTIVE = re.compile(
    r"all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all",
    re.IGNORECASE)
# HLO op names are lowercase dotted/dashed identifiers (fusion.3, dot.1,
# dynamic-update-slice); runtime/host events (Rendezvous, PjitFunction(...),
# "Wait: ...") are not op time and are excluded.
_HLO_NAME = re.compile(r"^[a-z][a-z0-9_.\-]*$")
# TPU device planes record full HLO instruction strings
# ('%fusion.3 = bf16[...]{...} fusion(...)'); the op name is the lhs.
_HLO_INSTR = re.compile(r"^%([A-Za-z0-9_.\-]+) =")


def _iter_op_events(path: str):
    """Yield (hlo_op_name, duration_ps) from every device plane of one
    xplane file — the shared walk under both the compute/collective split
    and per-op attribution (tools/profile_decode.py)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # lazy, heavy

    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        # TPU op time lives in '/device:TPU:N' planes; the CPU backend logs
        # ops into '/host:CPU'.  Skip pure-metadata planes.
        if not (plane.name.startswith("/device:") or plane.name == "/host:CPU"):
            continue
        md = {m.id: m.name for m in plane.event_metadata.values()}
        lines = plane.lines
        # TPU planes split events into 'XLA Modules' (whole program),
        # 'XLA Ops' (per-op), and 'Async XLA Ops' (a subset); only the
        # per-op line counts, the others would double-book the same time.
        op_lines = [ln for ln in lines if ln.name == "XLA Ops"]
        if op_lines:
            lines = op_lines
        elif plane.name == "/host:CPU":
            # the CPU backend records executed ops on the PjRt client
            # thread line; the 'python' and codegen-pass lines carry
            # host/compiler events whose names (simplification,
            # backend_compile_and_load, …) would otherwise pass the HLO
            # name filter and book compile time as op time
            # match any client-thread naming generation (TfrtCpuClient,
            # XLAPjRtCpuClient, ...)
            lines = [ln for ln in lines if "CpuClient" in ln.name]
        for line in lines:
            for ev in line.events:
                name = md.get(ev.metadata_id, "")
                m = _HLO_INSTR.match(name)
                if m:
                    name = m.group(1)
                elif not _HLO_NAME.match(name):
                    continue
                # control-flow wrappers nest their body ops' events inside
                # their own span on the same line — counting both would
                # double-book every loop body
                if name.split(".")[0] in ("while", "conditional", "call"):
                    continue
                yield name, ev.duration_ps


def op_times(trace_dir: str) -> dict[str, float]:
    """Sum device-plane op durations (ms) by op name over a trace dir."""
    times: dict[str, float] = {}
    for path in glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True):
        for name, ps in _iter_op_events(path):
            times[name] = times.get(name, 0.0) + ps / 1e9
    return times


def _parse_xspace(path: str) -> tuple[float, float]:
    """Returns (compute_ms, collective_ms) summed over all device planes."""
    compute_ps = 0
    collective_ps = 0
    for name, ps in _iter_op_events(path):
        if _COLLECTIVE.search(name):
            collective_ps += ps
        else:
            compute_ps += ps
    return compute_ps / 1e9, collective_ps / 1e9


def traced_op_times(step: Callable[[], None], steps: int = 1) -> dict[str, float] | None:
    """Trace ``steps`` calls of ``step()`` and return per-op device time
    (ms, summed over the calls and over every device in the mesh), or
    ``None`` when the xplane proto tooling is unavailable or the backend
    produced no trace files."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: F401
    except Exception:
        return None
    import jax

    with tempfile.TemporaryDirectory() as d:
        jax.profiler.start_trace(d)
        try:
            for _ in range(steps):
                step()
        finally:
            jax.profiler.stop_trace()
        files = glob.glob(d + "/**/*.xplane.pb", recursive=True)
        if not files:
            return None
        # an empty dict means the plane/line naming assumptions missed —
        # report unavailable rather than a plausible-looking zero split
        return op_times(d) or None


def split_op_times(times: dict[str, float]) -> tuple[float, float]:
    """Classify per-op times into (compute_ms, collective_ms) — the single
    home of the I/T classification used by both the CLI --profile-split
    and the bench's profile stage."""
    compute = sum(ms for op, ms in times.items() if not _COLLECTIVE.search(op))
    collective = sum(ms for op, ms in times.items() if _COLLECTIVE.search(op))
    return compute, collective


def summarize_split(times: dict[str, float], steps: int = 1) -> dict:
    """Per-step compute/collective summary of a per-op times dict — the
    single home of the averaging and percentage math (used by
    :func:`profiled_split`, the CLI's --profile-split, and the bench)."""
    compute_ms, collective_ms = split_op_times(times)
    compute_ms /= steps
    collective_ms /= steps
    total = compute_ms + collective_ms
    return {
        "compute_ms": compute_ms,
        "collective_ms": collective_ms,
        "collective_pct": 100.0 * collective_ms / total if total > 0 else 0.0,
    }


def top_ops(times: dict[str, float], k: int = 10,
            steps: int = 1) -> list[tuple[str, float]]:
    """The top-``k`` ops by device time as ``(name, per-step ms)`` — the
    one sort shared by the CLI's ``--profile-ops`` report and the
    server's ``POST /debug/profile``."""
    ranked = sorted(times.items(), key=lambda kv: -kv[1])[:k]
    return [(op, ms / steps) for op, ms in ranked]


def profiled_split(step: Callable[[], None], steps: int = 3) -> dict | None:
    """Trace ``steps`` calls of ``step()`` and attribute device-op time.

    Returns ``{"compute_ms", "collective_ms", "collective_pct"}`` with the
    ms values per step summed across every device in the mesh (divide by
    the device count for a per-chip figure), or ``None`` when the xplane
    proto tooling is unavailable.
    """
    times = traced_op_times(step, steps)
    if times is None:
        return None
    return summarize_split(times, steps)
