"""Engine-state snapshot file format: versioned, checksummed, validated.

The reference engine's only recovery primitive is restarting from an
empty context — a crashed or drained process loses all KV state and
every conversation re-prefills from zero.  This module gives the engine
a durable state file so a planned restart (deploy, reshard, preemption
drain) is a *warm* start: the KV cache, position clock, sampler RNG, and
ragged-batch offsets come back exactly, and continued decode is
token-identical to an uninterrupted run (tests/test_snapshot.py pins
this).

File layout (little-endian)::

    8 B   magic   b"DLSNAP02"
    4 B   u32     meta_len
    4 B   u32     crc32(meta || payload)
    meta_len B    meta JSON
    *     payload concatenated raw array bytes

Meta JSON: ``{"fingerprint", "pos", "chunk_counter", "arrays": [{"name",
"dtype", "shape", "nbytes"}, ...], "extra": {...}}``.  Arrays are stored
in meta order, back to back, in the payload.

Corruption policy mirrors io/integrity.py: every read is bounds-checked
and the crc32 covers meta *and* payload, so a truncated or bit-flipped
snapshot raises :class:`~dllama_tpu.io.integrity.ArtifactError` at load —
the server's restore path catches it and falls back to a cold start with
a logged reason, never a crash (a stale snapshot must not be able to
take the process down).  The ``fingerprint`` is the engine's config
fingerprint (model hyperparameters + batch + seq_len + cache layout);
restore refuses state from a differently-shaped engine.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time
import zlib

import numpy as np

from ..io.integrity import ArtifactError
from ..obs.log import get_logger

_log = get_logger("runtime.snapshot")

MAGIC = b"DLSNAP02"
# Per-request hand-off record: one in-flight request's KV pages +
# decode state, shipped over HTTP between replicas (never a file on
# disk).  Same header/crc/descriptor machinery as DLSNAP02, distinct
# magic so neither format can be fed to the other's loader.
REQ_MAGIC = b"DLREQ01\0"
# DLSNAP01 lacked the paged-KV state (page pool geometry in the
# fingerprint, page tables + radix-tree keys in the extras); restoring
# one silently would resurrect a contiguous cache under a paged engine.
# Old files are recognized and refused with a distinct message so the
# caller's cold-start fallback logs *why* rather than "corrupt".
_LEGACY_MAGICS = (b"DLSNAP01",)
_HEADER = struct.Struct("<8sII")  # magic, meta_len, crc32(meta || payload)
_MAX_META = 1 << 24


class SnapshotMismatch(ArtifactError):
    """A structurally valid snapshot that does not fit this engine
    (config fingerprint or array layout mismatch).  Distinct from plain
    corruption so callers can log "snapshot is from a different model",
    but still an ArtifactError: both mean "cold start"."""


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 et al. register via ml_dtypes (a jax dependency), not
        # the numpy namespace
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _encode(magic: bytes, *, fingerprint: str, pos: int, chunk_counter: int,
            arrays: dict[str, np.ndarray],
            extra: dict | None) -> tuple[bytes, bytes, list[bytes]]:
    """Serialize to ``(header, meta, blobs)`` — shared by the DLSNAP02
    file writer and the DLREQ01 in-memory encoder."""
    descs, blobs = [], []
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        blob = arr.tobytes()
        descs.append({"name": name, "dtype": str(arr.dtype),
                      "shape": list(arr.shape), "nbytes": len(blob)})
        blobs.append(blob)
    meta = json.dumps({
        "fingerprint": fingerprint, "pos": int(pos),
        "chunk_counter": int(chunk_counter), "arrays": descs,
        "extra": extra or {},
    }, sort_keys=True).encode("utf-8")
    crc = zlib.crc32(meta)
    for blob in blobs:
        crc = zlib.crc32(blob, crc)
    return _HEADER.pack(magic, len(meta), crc & 0xFFFFFFFF), meta, blobs


def _decode_body(label: str, body: bytes, meta_len: int,
                 crc_want: int) -> tuple[dict, dict[str, np.ndarray]]:
    """Validate and parse ``meta || payload`` (everything after the
    header).  Shared by :func:`load` and :func:`loads_request`."""
    if len(body) < meta_len:
        raise ArtifactError(label, "meta", "file truncated mid-field",
                            offset=_HEADER.size,
                            expected=f"{meta_len} bytes",
                            got=f"{len(body)} bytes")
    crc_got = zlib.crc32(body) & 0xFFFFFFFF
    if crc_got != crc_want:
        raise ArtifactError(label, "checksum",
                            "checksum mismatch — snapshot bytes are corrupt",
                            offset=_HEADER.size,
                            expected=f"crc32={crc_want:#010x}",
                            got=f"crc32={crc_got:#010x}")
    try:
        meta = json.loads(body[:meta_len].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ArtifactError(label, "meta", f"unreadable snapshot meta: {e}",
                            offset=_HEADER.size) from e
    for key in ("fingerprint", "pos", "chunk_counter", "arrays"):
        if key not in meta:
            raise ArtifactError(label, f"meta.{key}",
                                "missing required snapshot key")
    payload = body[meta_len:]
    arrays: dict[str, np.ndarray] = {}
    off = 0
    for d in meta["arrays"]:
        try:
            name, nbytes = d["name"], int(d["nbytes"])
            dt = _np_dtype(d["dtype"])
            shape = tuple(int(s) for s in d["shape"])
        except (KeyError, TypeError, ValueError, AttributeError) as e:
            raise ArtifactError(label, "meta.arrays",
                                f"bad array descriptor {d!r}: {e}") from e
        want = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        if nbytes != want:
            raise ArtifactError(label, f"array {name!r}",
                                "descriptor nbytes disagrees with dtype×shape",
                                expected=want, got=nbytes)
        if off + nbytes > len(payload):
            raise ArtifactError(label, f"array {name!r}",
                                "payload truncated",
                                offset=_HEADER.size + meta_len + off,
                                expected=f"{nbytes} bytes",
                                got=f"{len(payload) - off} bytes")
        arrays[name] = np.frombuffer(
            payload, dtype=dt, count=int(np.prod(shape, dtype=np.int64)),
            offset=off).reshape(shape)
        off += nbytes
    if off != len(payload):
        raise ArtifactError(label, "payload",
                            "trailing bytes after last array",
                            offset=_HEADER.size + meta_len + off,
                            expected="EOF", got=f"{len(payload) - off} extra bytes")
    return meta, arrays


def save(path: str | os.PathLike, *, fingerprint: str, pos: int,
         chunk_counter: int, arrays: dict[str, np.ndarray],
         extra: dict | None = None) -> str:
    """Write a snapshot atomically (tmp + rename): a crash mid-write
    leaves the previous snapshot (or none), never a torn file."""
    path = os.fspath(path)
    header, meta, blobs = _encode(MAGIC, fingerprint=fingerprint, pos=pos,
                                  chunk_counter=chunk_counter, arrays=arrays,
                                  extra=extra)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header)
        f.write(meta)
        for blob in blobs:
            f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _log.debug("snapshot_saved", extra={
        "path": path,
        "bytes": _HEADER.size + len(meta) + sum(len(b) for b in blobs),
        "pos": int(pos)})
    return path


def load(path: str | os.PathLike) -> tuple[dict, dict[str, np.ndarray]]:
    """Load and fully validate a snapshot; returns ``(meta, arrays)``.

    Raises :class:`ArtifactError` (with offset/field) on any corruption —
    bad magic, truncation, crc mismatch, or inconsistent array
    descriptors.  Fingerprint checking is the caller's job
    (:meth:`Engine.restore`): only the engine knows its own shape.
    """
    path = os.fspath(path)
    file_size = os.path.getsize(path)
    with open(path, "rb") as f:
        head = f.read(_HEADER.size)
        if len(head) != _HEADER.size:
            raise ArtifactError(path, "snapshot header",
                                "file truncated mid-field", offset=0,
                                expected=f"{_HEADER.size} bytes",
                                got=f"{len(head)} bytes")
        magic, meta_len, crc_want = _HEADER.unpack(head)
        if magic in _LEGACY_MAGICS:
            raise ArtifactError(path, "magic",
                                "superseded snapshot format — this build "
                                "writes DLSNAP02 (paged-KV state); delete "
                                "the old snapshot and cold-start",
                                offset=0, expected=MAGIC, got=magic)
        if magic != MAGIC:
            raise ArtifactError(path, "magic", "not a dllama snapshot",
                                offset=0, expected=MAGIC, got=magic)
        if not (2 <= meta_len <= min(_MAX_META, file_size)):
            raise ArtifactError(path, "meta_len",
                                "value out of range — corrupt snapshot",
                                offset=8, expected=f"2..{_MAX_META}",
                                got=meta_len)
        body = f.read()
    meta, arrays = _decode_body(path, body, meta_len, crc_want)
    _log.debug("snapshot_loaded", extra={
        "path": path, "bytes": file_size, "pos": int(meta["pos"])})
    return meta, arrays


def dumps_request(*, fingerprint: str, pos: int, chunk_counter: int,
                  arrays: dict[str, np.ndarray], extra: dict) -> bytes:
    """Serialize a per-request hand-off record (DLREQ01) to bytes.

    Same layout as a DLSNAP02 file but with :data:`REQ_MAGIC` and never
    written to disk — records travel as an HTTP octet-stream between a
    draining replica and the peer that resumes the request.  ``extra``
    carries the request's decode state (prompt/completion tokens,
    sampling params, slot counters); ``arrays`` carries the KV page
    slices and sampler RNG key.
    """
    header, meta, blobs = _encode(REQ_MAGIC, fingerprint=fingerprint,
                                  pos=pos, chunk_counter=chunk_counter,
                                  arrays=arrays, extra=extra)
    return b"".join([header, meta, *blobs])


def loads_request(blob: bytes,
                  label: str = "<handoff record>") -> tuple[dict, dict[str, np.ndarray]]:
    """Parse and fully validate a DLREQ01 record from bytes.

    Raises :class:`ArtifactError` on any corruption, exactly like
    :func:`load`; geometry/fingerprint checking stays with the importing
    scheduler, which knows its engine's shape.
    """
    if len(blob) < _HEADER.size:
        raise ArtifactError(label, "snapshot header",
                            "file truncated mid-field", offset=0,
                            expected=f"{_HEADER.size} bytes",
                            got=f"{len(blob)} bytes")
    magic, meta_len, crc_want = _HEADER.unpack(blob[:_HEADER.size])
    if magic != REQ_MAGIC:
        raise ArtifactError(label, "magic", "not a dllama hand-off record",
                            offset=0, expected=REQ_MAGIC, got=magic)
    if not (2 <= meta_len <= min(_MAX_META, len(blob))):
        raise ArtifactError(label, "meta_len",
                            "value out of range — corrupt record",
                            offset=8, expected=f"2..{_MAX_META}",
                            got=meta_len)
    return _decode_body(label, blob[_HEADER.size:], meta_len, crc_want)


def fingerprint(fields: dict) -> str:
    """Stable short digest of an engine-shape description (truncated
    sha256 of the sorted-key JSON)."""
    blob = json.dumps(fields, sort_keys=True).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:16]


class RecordStore:
    """TTL-bounded ``rid -> DLREQ01 bytes`` store.

    Two users, same failure mode: a draining replica parks export
    records for the router to claim (``--handoff-ttl``), and the router
    caches periodic checkpoints of in-flight streams
    (``--checkpoint-interval``).  In both cases an unclaimed record is a
    leak — the replica's drain waits on it, the router's cache grows
    without bound — so every read-side access sweeps expired entries
    first and reports each expiry through ``on_expire`` (the replica
    bumps ``dllama_handoff_expired_total`` there).

    ``ttl <= 0`` disables expiry, which makes the store a plain dict
    with a lock — the pre-TTL behavior, byte for byte.  The mapping
    surface (``pop``/``put``/``update``/``__len__``/``__bool__``/
    ``discard``) is intentionally the subset ``ApiState.handoff_records``
    callers already use, so the store is a drop-in replacement.
    """

    def __init__(self, ttl: float = 0.0, on_expire=None):
        self.ttl = float(ttl)
        self.on_expire = on_expire
        self._lock = threading.Lock()
        self._items: dict[str, tuple[bytes, float]] = {}

    def _sweep_locked(self) -> None:
        if self.ttl <= 0 or not self._items:
            return
        now = time.monotonic()
        dead = [rid for rid, (_, born) in self._items.items()
                if now - born > self.ttl]
        for rid in dead:
            del self._items[rid]
        if dead and self.on_expire is not None:
            for rid in dead:
                try:
                    self.on_expire(rid)
                except Exception:  # noqa: BLE001 — expiry is best-effort
                    _log.warning("record_expire_callback_failed",
                                 extra={"rid": rid})

    def put(self, rid: str, blob: bytes) -> None:
        with self._lock:
            self._items[rid] = (blob, time.monotonic())

    def update(self, records: dict) -> None:
        now = time.monotonic()
        with self._lock:
            for rid, blob in records.items():
                self._items[rid] = (blob, now)

    def pop(self, rid: str, default=None):
        with self._lock:
            self._sweep_locked()
            item = self._items.pop(rid, None)
        return item[0] if item is not None else default

    def get(self, rid: str, default=None):
        with self._lock:
            self._sweep_locked()
            item = self._items.get(rid)
        return item[0] if item is not None else default

    def discard(self, rid: str) -> None:
        with self._lock:
            self._items.pop(rid, None)

    def sweep(self) -> int:
        """Explicit expiry pass; returns how many records remain."""
        with self._lock:
            self._sweep_locked()
            return len(self._items)

    def __len__(self) -> int:
        with self._lock:
            self._sweep_locked()
            return len(self._items)

    def __bool__(self) -> bool:
        return len(self) > 0
