"""Inference engine: compiled prefill/decode + generation loop + stats.

Replaces the reference's execution layer (`Inference::infer` tasks.cpp:199-
210 + the per-token task-list walk): here a *whole decode step* — embed,
all layers, logits — is one XLA program with ``pos`` as a traced scalar, so
autoregression never recompiles, and the KV cache is a donated device
buffer updated in place.

Prefill is a separate bucketed program (prompt padded up to the next
bucket) that processes the whole prompt in one batched pass — the reference
feeds prompt tokens one at a time (dllama.cpp:53-58), which is parity-fine
but wastes the MXU; true prefill is the TPU-idiomatic replacement.

Stats keep the reference's per-token G/I/T contract (dllama.cpp:45-93,
`Inference::getStats` tasks.cpp:212-215): G = whole-step wall ms, I =
on-device compute ms, T = device→host transfer ms.  On the reference, T is
socket time between nodes; on a TPU mesh the inter-chip hops are XLA
collectives *inside* I (that's the point — T ≈ 0), so T here counts the
only remaining boundary: fetching logits for the host-side sampler.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.params import Params
from ..models.transformer import forward_last, init_kv_cache
from ..obs import dispatch as obs_dispatch, metrics as obs_metrics, \
    trace as obs_trace
from ..obs.log import get_logger
from ..parallel import sharding
from ..parallel.mesh import active_mesh, make_mesh, shard_map
from ..sampling import Sampler

_log = get_logger("runtime.engine")


def _hbm_reader(stat: str):
    """Bind a per-device memory_stats field to a labeled gauge: returns
    ``{device_id: bytes}`` at read time, or ``{}`` where the backend has
    no allocator stats (CPU, some emulators) — absence reads as no
    samples, never as zeros."""
    def read() -> dict:
        out: dict[str, float] = {}
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms and stat in ms:
                out[str(d.id)] = float(ms[stat])
        return out
    return read


# The obs package stays jax-free; the engine (which already owns the
# devices) donates the reader at import.  LabeledGauge calls it lazily at
# each /metrics read, so the gauges track live allocator state.
obs_metrics.HBM_BYTES_IN_USE.fn = _hbm_reader("bytes_in_use")
obs_metrics.HBM_BYTES_PEAK.fn = _hbm_reader("peak_bytes_in_use")


def _next_bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _unfuse(params: Params, cfg: ModelConfig) -> Params:
    """Split fused ``wqkv``/``w13`` tensors into per-projection weights for
    tensor-parallel placement (the fused layout is a single-chip launch
    optimization; its concat axis does not align with TP shard boundaries)."""
    from ..ops import q40, q8

    def split(w, sizes):
        if isinstance(w, (q40.QTensor, q8.Q8Tensor)):
            return q40.split_d(w, sizes)
        off, out = 0, []
        for s in sizes:
            out.append(w[..., :, off:off + s])
            off += s
        return out

    p = dict(params)
    if "wqkv" in p:
        dh = cfg.head_size
        p["wq"], p["wk"], p["wv"] = split(
            p.pop("wqkv"), [cfg.n_heads * dh, cfg.n_kv_heads * dh, cfg.n_kv_heads * dh])
    if "w13" in p:
        p["w1"], p["w3"] = split(p.pop("w13"), [cfg.hidden_dim, cfg.hidden_dim])
    return p


class ContextOverflow(ValueError):
    """The requested tokens do not fit the engine's context window.

    A dedicated type so the API server can map it to an HTTP 400 without
    masking unrelated ValueErrors as client errors (ADVICE r01)."""


class NumericFault(RuntimeError):
    """NaN/Inf detected in the logits under ``numeric_checks``.

    The reference has no numeric guard at all: a corrupt weight or a
    numerically-diverged KV cache surfaces as garbage *text* (or a
    sampler crash) minutes later, with no pointer back to the step that
    went bad.  With ``--numeric-checks`` the engine checks every
    host-fetched logits array and raises this instead, naming the step,
    the sequence position, and a hint — detection happens at the logits
    (the one tensor the host already sees each step, so the check costs
    no extra device→host traffic), which cannot name the layer that
    produced the NaN; the hint says what to bisect next.  The server
    maps it to a 500 and resets the engine (a NaN anywhere implies the
    KV cache may be poisoned)."""

    def __init__(self, step: str, pos: int, hint: str = ""):
        self.step = step
        self.pos = pos
        self.hint = hint
        msg = f"non-finite logits at {step}, pos={pos}"
        super().__init__(msg + (f" ({hint})" if hint else ""))


class StepTimeout(RuntimeError):
    """A device step exceeded the engine's watchdog deadline.

    The reference's failure shape here is a silent hang — a blocking
    socket ``read()`` with no timeout wedges the whole cluster
    (socket.cpp).  Our equivalent blocking edge is
    ``jax.block_until_ready`` on a step's outputs: a wedged device/tunnel
    would park the serving thread forever while it holds the engine
    mutex.  The watchdog (``step_timeout``, or ``DLLAMA_STEP_TIMEOUT``)
    turns that into a diagnosable exception naming the step and position
    so the server can answer 500 and keep serving."""


@dataclass
class StepStats:
    """Per-token timing + host↔device traffic, reference benchmark-mode
    contract (dllama.cpp:74-82: G/I/T ms and sent/recv kB columns —
    there the bytes are TCP traffic between nodes, socket.cpp:280-285;
    on a TPU mesh inter-chip traffic rides ICI inside the XLA program, so
    S/R count the only remaining boundary: host↔device transfers)."""
    generation_ms: float = 0.0  # G: total wall time for the token
    inference_ms: float = 0.0   # I: device execution
    transfer_ms: float = 0.0    # T: host<->device boundary
    sent_bytes: float = 0.0     # S: host → device (fractional per token when
    recv_bytes: float = 0.0     # R: device → host   averaged over a chunk)


@dataclass
class RunStats:
    # Running sums keep every avg_* property O(1); the per-token list is
    # retained for callers that want the full series (benchmarks, tests).
    tokens: list[StepStats] = field(default_factory=list)
    _g_sum: float = field(default=0.0, repr=False)
    _i_sum: float = field(default=0.0, repr=False)
    _t_sum: float = field(default=0.0, repr=False)
    _s_sum: float = field(default=0.0, repr=False)
    _r_sum: float = field(default=0.0, repr=False)

    def add(self, s: StepStats):
        self.tokens.append(s)
        self._g_sum += s.generation_ms
        self._i_sum += s.inference_ms
        self._t_sum += s.transfer_ms
        self._s_sum += s.sent_bytes
        self._r_sum += s.recv_bytes

    def _avg(self, total: float) -> float:
        return total / len(self.tokens) if self.tokens else 0.0

    @property
    def avg_generation_ms(self):
        return self._avg(self._g_sum)

    @property
    def avg_inference_ms(self):
        return self._avg(self._i_sum)

    @property
    def avg_transfer_ms(self):
        return self._avg(self._t_sum)

    @property
    def avg_sent_bytes(self):
        return self._avg(self._s_sum)

    @property
    def avg_recv_bytes(self):
        return self._avg(self._r_sum)

    @property
    def tokens_per_second(self):
        g = self.avg_generation_ms
        return 1000.0 / g if g > 0 else 0.0


class Engine:
    """Owns placed params, the KV cache, and the compiled step functions."""

    def __init__(self, cfg: ModelConfig, params: Params, mesh=None,
                 batch: int = 1, seq_len: int | None = None, kv_dtype=None,
                 timing_mode: str | None = None,
                 step_timeout: float | None = None,
                 numeric_checks: bool | None = None,
                 kv_pages: int = 0, kv_page_size: int = 16):
        self.batch = batch
        # decode watchdog (see StepTimeout); 0/None disables.  Env default
        # so a live server can arm it without a code path change.
        if step_timeout is None:
            step_timeout = float(os.environ.get("DLLAMA_STEP_TIMEOUT", "0"))
        self.step_timeout = step_timeout if step_timeout > 0 else None
        # opt-in NaN/Inf guard over every host-fetched logits array (see
        # NumericFault); env default mirrors the watchdog.  Off by
        # default: np.isfinite over (B, V) costs ~µs but the *policy*
        # (fail the request) should be a choice.
        if numeric_checks is None:
            numeric_checks = os.environ.get(
                "DLLAMA_NUMERIC_CHECKS", "") not in ("", "0", "false")
        self.numeric_checks = bool(numeric_checks)
        # I/T attribution source (VERDICT r04 Weak #1).  "device-ready":
        # block_until_ready marks end-of-execution and the remaining fetch
        # is T — correct on local backends.  "host-fetch": on a tunneled
        # remote backend (axon) block_until_ready returns at *dispatch*,
        # not completion, so splitting on it mis-attributes nearly all of
        # I into T; instead the whole step is timed at the host fetch
        # boundary (the only trustworthy clock edge) and reported as I
        # with T=0 — the xplane profiler supplies the real on-device
        # split (runtime/profiling.py; cmd_inference auto-profiles).
        if timing_mode is None:
            try:
                timing_mode = ("host-fetch"
                               if jax.devices()[0].platform == "axon"
                               else "device-ready")
            except Exception:
                timing_mode = "device-ready"
        if timing_mode not in ("device-ready", "host-fetch"):
            raise ValueError(f"unknown timing_mode {timing_mode!r}")
        self.timing_mode = timing_mode
        self.seq_len = min(seq_len or cfg.seq_len, cfg.seq_len)
        self.mesh = mesh if mesh is not None else make_mesh(tp=1, devices=jax.devices()[:1])
        tp = self.mesh.shape.get("tp", 1)
        if tp > 1:
            sharding.check_tp_constraint(cfg, tp)
            # the fused wqkv/w13 concat axis mixes q/k/v shard ranges under
            # tp — split back into per-projection tensors whose output axes
            # shard cleanly (RowMatmulSlice boundaries, commands.cpp:8-40)
            params = _unfuse(params, cfg)
        # Packed-Q40 matmul dispatch on a multi-device mesh runs the fused
        # Pallas kernel per shard under shard_map (ops/q40.py
        # _sharded_matmul) — no downgrade; weights whose shapes don't
        # divide the mesh evenly fall back per-tensor inside q40.matmul.
        self.sp = self.mesh.shape.get("sp", 1)
        if self.sp > 1 and self.seq_len % self.sp:
            raise ValueError(f"seq_len {self.seq_len} not divisible by sp={self.sp}")
        ep = self.mesh.shape.get("ep", 1)
        if ep > 1:
            if not cfg.is_moe:
                raise ValueError("ep>1 needs an MoE model (no expert axis to shard)")
            if cfg.n_experts % ep:
                raise ValueError(
                    f"n_experts {cfg.n_experts} not divisible by ep={ep}")
        self.cfg = cfg
        if os.environ.get("DLLAMA_Q40_LAYOUT", "") == "blocked":
            if self.mesh.size == 1:
                # tile-contiguous packed storage (ops/q40.py
                # BlockedQTensor): every dense Q40 weight's kernel tile
                # becomes one sequential HBM read — single-device decode
                # only; on a mesh the row-major layout keeps its
                # splitWeights-compatible sharding semantics
                from ..ops import q40
                params = q40.blocked_params(params)
            else:
                # requested layout silently kept row-major — that is a
                # degrade off the *requested* path, so it goes through the
                # ledger (warn-once structured log + labeled counter +
                # degraded flag), not scrollback
                obs_dispatch.record_degrade(
                    "q40", "blocked_ignored_mesh", warn_key=self.mesh.size,
                    mesh_size=self.mesh.size,
                    hint="blocked storage is single-device only; "
                         "row-major keeps sharding semantics")
        if self.mesh.shape.get("tp", 1) > 1 \
                and jax.default_backend() != "tpu" \
                and os.environ.get("DLLAMA_TP_REDUCE", "") != "psum":
            # tp serving off-TPU cannot take the fused collective-matmul
            # decode path (ops/q40.py _tp_ring_allreduce is built on
            # inter-chip RDMA): decode collectives degrade to plain
            # psum/GSPMD all-reduce.  Same ledger treatment as
            # blocked_ignored_mesh — the run still serves, but a bench
            # number from this configuration must not read as the fused
            # number
            obs_dispatch.record_degrade(
                "q40", "tp_psum", warn_key=jax.default_backend(),
                backend=jax.default_backend(),
                tp=self.mesh.shape.get("tp", 1),
                hint="fused collective-matmul decode is TPU-only; tp "
                     "collectives run as plain psum all-reduce")
        self.params = sharding.place_params(params, cfg, self.mesh)
        # kv_dtype "q8" (or int8) selects the quantized cache: int8 values
        # + per-position f32 scales — ~2× less cache HBM traffic and
        # residency than bf16, so max context per chip nearly doubles
        # (beyond reference; see models.transformer.init_kv_cache)
        kv_quant = kv_dtype == "q8" or (
            kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8)
        if kv_quant and self.sp > 1:
            raise ValueError("quantized KV cache is not supported on sp "
                             "meshes (shard-local sp cache writes are "
                             "dense); use sp=1 or a dense cache dtype")
        # sp>1 shards the cache's sequence axis: max context scales with
        # sp × per-chip HBM (capability the reference lacks, SURVEY §5);
        # the same sharding is pinned as jit out_shardings below so cache
        # placement and step outputs can never silently diverge
        self._cache_sh = sharding.kv_cache_sharding(
            self.mesh, "sp" if self.sp > 1 else None)
        # kv_pages > 0 replaces the per-slot contiguous cache with a paged
        # pool + per-slot page tables (ops/attention.py paged section):
        # memory is bounded by live tokens, not batch × seq_len, and the
        # scheduler's radix tree can share prompt-prefix pages across
        # requests.  Slot-serving only: the one-shot conversation/batch
        # paths keep contiguous addressing.
        self.paged = kv_pages > 0
        self.kv_pages = int(kv_pages)
        self.kv_page_size = int(kv_page_size)
        if self.paged:
            if self.sp > 1:
                raise ValueError("paged KV is not supported on sp meshes "
                                 "(sequence-sharded pools are not wired)")
            if self.kv_pages < 2:
                raise ValueError("kv_pages must be >= 2 (page 0 is the "
                                 "reserved scratch page)")
            if self.kv_page_size < 1:
                raise ValueError(f"kv_page_size must be >= 1, "
                                 f"got {self.kv_page_size}")
            # per-slot table width: enough logical pages to cover seq_len
            self.max_pages_per_slot = -(-self.seq_len // self.kv_page_size)
            from ..models.transformer import init_kv_pool
            # pool layout (L, P, Hkv, ps, Dh) is axis-compatible with the
            # contiguous cache spec: pages ride the batch ("dp") axis, the
            # page interior rides the sequence axis
            # --kv-quant int8: pool pages hold int8 values + per-position
            # f32 scale planes (the Q80 weight codec's trick applied to
            # pages); paged attention dequantizes after the int8-sized
            # page read, so cache HBM traffic and residency halve again
            # on top of paging
            self.cache = jax.device_put(
                init_kv_pool(cfg, self.kv_pages, self.kv_page_size,
                             dtype=None if kv_quant else kv_dtype,
                             quant=kv_quant),
                self._cache_sh)
            obs_metrics.KV_PAGE_CODEC.set(
                "int8" if kv_quant else str(self.cache.k.dtype), 1)
        else:
            self.cache = jax.device_put(
                init_kv_cache(cfg, batch, self.seq_len,
                              dtype=None if kv_quant else kv_dtype,
                              quant=kv_quant),
                self._cache_sh)
        self.pos = 0

        def step(params, cache, tokens, pos, last_index, offsets=None):
            return forward_last(params, cfg, tokens, cache, pos, last_index,
                                offsets=offsets)

        # Outputs that the host reads (logits, sampled tokens) are pinned
        # replicated while the cache keeps its mesh sharding: on a
        # multi-process mesh (parallel/distributed.py) a sharded output
        # spans non-addressable devices and cannot be fetched — replication
        # makes every fetch process-local (the gather rides ICI inside the
        # program, which is where inter-chip traffic belongs; T≈0 contract).
        self._rep = NamedSharding(self.mesh, P())
        # one compiled program per (batch, T-bucket); decode is bucket T=1
        self._step = jax.jit(step, donate_argnums=(1,),
                             out_shardings=(self._rep, self._cache_sh))
        if self.sp > 1:
            cfg_ring = cfg.with_(ring_prefill=True)

            def ring_step(params, cache, tokens, pos, last_index):
                return forward_last(params, cfg_ring, tokens, cache, pos, last_index)

            self._step_ring = jax.jit(ring_step, donate_argnums=(1,),
                                      out_shardings=(self._rep, self._cache_sh))
        self._chunk_fns: dict = {}
        # compile telemetry: step shapes that already built an executable
        # (self._step/_step_ring jit-compile per (batch, T-bucket) shape;
        # self._chunk_fns is its own executable cache) — lets _run tell a
        # recompile from a cache hit without reaching into jax internals
        self._compiled_steps: set = set()
        self._key = jax.random.PRNGKey(0)
        self._chunk_counter = 0
        # device-resident RNG chain for sampled slot dispatches: seeded
        # lazily off the host stream, then advanced by the key each
        # compiled chunk returns — sampled pure decode never syncs the
        # host for randomness (one-dispatch decode, ISSUE 20)
        self._dev_key: jax.Array | None = None
        # which sampling implementation owns this engine's draws; rides
        # snapshots/hand-off records so a sampled slot never resumes on
        # a replica whose stream would diverge
        self.sampling_path = os.environ.get(
            "DLLAMA_SAMPLING_PATH", "device").strip().lower() or "device"
        # collective-latency probe (probe_collective): compiled lazily on
        # first use, rate-limited host-side
        self._collective_fn = None
        self._collective_probe_t = 0.0
        self._offsets: jax.Array | None = None  # ragged-batch left padding

    # ------------------------------------------------------------------
    def reset(self):
        """Restart the sequence (new conversation); cache memory is reused."""
        self.pos = 0
        self._offsets = None

    # -- state snapshot/restore (runtime/snapshot.py format) -----------
    def config_fingerprint(self) -> str:
        """Short digest of everything that must match for a snapshot's
        state to be meaningful in this engine: model hyperparameters,
        batch, context length, and the cache's dtype/shape layout.  Mesh
        shape is deliberately excluded — KV *values* are placement-
        independent, so a snapshot taken on one mesh restores onto
        another (device_put reshards)."""
        from . import snapshot as snapfmt
        c = self.cfg
        fields = {
            "arch": c.arch, "dim": c.dim, "hidden_dim": c.hidden_dim,
            "n_layers": c.n_layers, "n_heads": c.n_heads,
            "n_kv_heads": c.n_kv_heads, "n_experts": c.n_experts,
            "n_active_experts": c.n_active_experts,
            "vocab_size": c.vocab_size, "hidden_act": c.hidden_act,
            "rope_theta": c.rope_theta,
            "batch": self.batch, "seq_len": self.seq_len,
            "cache": [[n, str(a.dtype), list(a.shape)]
                      for n, a in self._cache_arrays().items()],
            # pool geometry: a paged snapshot only means something in an
            # engine with the same page count/size (page ids are physical)
            "paged": [self.kv_pages, self.kv_page_size] if self.paged else None,
        }
        return snapfmt.fingerprint(fields)

    def _cache_arrays(self) -> dict:
        out = {"cache.k": self.cache.k, "cache.v": self.cache.v}
        if self.cache.quantized:
            out["cache.k_scale"] = self.cache.k_scale
            out["cache.v_scale"] = self.cache.v_scale
        return out

    def snapshot(self, path: str | os.PathLike,
                 extra: dict | None = None,
                 extra_arrays: dict | None = None) -> str:
        """Serialize the engine's conversation state (KV cache, position,
        sampler RNG stream, ragged offsets) to a versioned, checksummed
        file (runtime/snapshot.py).  Atomic; returns the path.  ``extra``
        is caller JSON carried in the snapshot meta and handed back by
        :meth:`restore` (the API server stores its conversation cache
        there so a warm restart resumes chats, not just KV bytes);
        ``extra_arrays`` are caller numpy arrays stored alongside the
        cache (the paged scheduler persists its page tables this way) and
        handed back via :attr:`restored_arrays`."""
        from . import snapshot as snapfmt
        arrays = {n: np.asarray(a) for n, a in self._cache_arrays().items()}
        arrays["rng_key"] = np.asarray(self._key)
        if self._dev_key is not None:
            arrays["rng_dev_key"] = np.asarray(self._dev_key)
        meta_extra = dict(extra or {})
        meta_extra.setdefault("sampling_path", self.sampling_path)
        if self._offsets is not None:
            arrays["offsets"] = np.asarray(self._offsets)
            meta_extra["has_offsets"] = True
        for n, a in (extra_arrays or {}).items():
            if n in arrays:
                raise ValueError(f"extra array name {n!r} collides")
            arrays[n] = np.asarray(a)
        return snapfmt.save(path, fingerprint=self.config_fingerprint(),
                            pos=self.pos, chunk_counter=self._chunk_counter,
                            arrays=arrays, extra=meta_extra)

    def restore(self, path: str | os.PathLike) -> dict:
        """Restore state saved by :meth:`snapshot`.

        Raises :class:`~dllama_tpu.io.integrity.ArtifactError` on
        corruption and its :class:`~dllama_tpu.runtime.snapshot.
        SnapshotMismatch` subclass when the snapshot came from a
        differently-shaped engine — the caller (server boot) catches
        ArtifactError and cold-starts.  On success the continued decode
        stream is token-identical to never having restarted
        (tests/test_snapshot.py); returns the snapshot's ``extra`` dict."""
        from ..io.integrity import bump_counter
        from ..models.transformer import KVCache
        from . import snapshot as snapfmt
        meta, arrays = snapfmt.load(path)
        want_fp = self.config_fingerprint()
        if meta["fingerprint"] != want_fp:
            raise snapfmt.SnapshotMismatch(
                path, "fingerprint",
                "snapshot is from a differently-configured engine",
                expected=want_fp, got=meta["fingerprint"])
        cache_np = {}
        for name, cur in self._cache_arrays().items():
            arr = arrays.get(name)
            if arr is None:
                raise snapfmt.SnapshotMismatch(
                    path, f"array {name!r}", "missing cache array")
            if tuple(arr.shape) != tuple(cur.shape) or \
                    str(arr.dtype) != str(np.asarray(cur).dtype):
                raise snapfmt.SnapshotMismatch(
                    path, f"array {name!r}",
                    "cache array layout mismatch",
                    expected=f"{np.asarray(cur).dtype}{tuple(cur.shape)}",
                    got=f"{arr.dtype}{tuple(arr.shape)}")
            cache_np[name] = arr
        pos = int(meta["pos"])
        if not (0 <= pos <= self.seq_len):
            raise snapfmt.SnapshotMismatch(
                path, "pos", "restored position outside the context window",
                expected=f"0..{self.seq_len}", got=pos)
        snap_sp = meta.get("extra", {}).get("sampling_path")
        if snap_sp is not None and snap_sp != self.sampling_path:
            # a sampled stream drawn on one path cannot continue on the
            # other without silently changing the distribution — refuse
            # (absent flag = pre-ISSUE-20 snapshot, greedy-safe either way)
            raise snapfmt.SnapshotMismatch(
                path, "sampling_path",
                "snapshot sampled on a different sampling path",
                expected=self.sampling_path, got=snap_sp)
        if self.cache.quantized:
            cache = KVCache(cache_np["cache.k"], cache_np["cache.v"],
                            cache_np["cache.k_scale"], cache_np["cache.v_scale"])
        else:
            cache = KVCache(cache_np["cache.k"], cache_np["cache.v"])
        self.cache = jax.device_put(cache, self._cache_sh)
        self.pos = pos
        self._chunk_counter = int(meta["chunk_counter"])
        self._key = jnp.asarray(arrays["rng_key"]) if "rng_key" in arrays \
            else jax.random.PRNGKey(0)
        self._dev_key = jnp.asarray(arrays["rng_dev_key"]) \
            if "rng_dev_key" in arrays else None
        self._offsets = jnp.asarray(arrays["offsets"]) \
            if meta.get("extra", {}).get("has_offsets") else None
        # caller arrays saved via snapshot(extra_arrays=...) — e.g. the
        # paged scheduler's page tables — handed back out-of-band
        known = set(self._cache_arrays()) | {"rng_key", "rng_dev_key",
                                             "offsets"}
        self.restored_arrays = {n: a for n, a in arrays.items()
                                if n not in known}
        bump_counter("snapshot_restores")
        return dict(meta.get("extra", {}))

    # -- per-request KV hand-off (DLREQ01, runtime/snapshot.py) ---------
    def handoff_fingerprint(self) -> str:
        """Geometry digest for per-request KV hand-off.

        Looser than :meth:`config_fingerprint`: a request's pages mean
        the same thing on any replica with the same model, context
        window, and page shape/dtype — batch width and pool *size* are
        deliberately excluded (the importer allocates its own physical
        pages), so a 4-slot and an 8-slot replica can exchange requests
        as long as their page geometry matches."""
        from . import snapshot as snapfmt
        if not self.paged:
            raise ValueError("per-request hand-off needs a paged KV cache "
                             "(kv_pages > 0)")
        c = self.cfg
        k = self.cache.k
        fields = {
            "arch": c.arch, "dim": c.dim, "hidden_dim": c.hidden_dim,
            "n_layers": c.n_layers, "n_heads": c.n_heads,
            "n_kv_heads": c.n_kv_heads, "n_experts": c.n_experts,
            "n_active_experts": c.n_active_experts,
            "vocab_size": c.vocab_size, "hidden_act": c.hidden_act,
            "rope_theta": c.rope_theta, "seq_len": self.seq_len,
            # page shape (Hkv, ps, Dh) + dtype, not pool page count; the
            # codec is explicit so int8-paged vs dense records reject
            # cleanly even where the raw dtype string would coincide
            "page": [str(k.dtype), list(k.shape[2:])],
            "codec": "int8" if self.cache.quantized else "dense",
            "handoff": 1,
        }
        return snapfmt.fingerprint(fields)

    def set_rng(self, key_np, chunk_counter: int, dev_key_np=None) -> None:
        """Rebase the sampler RNG stream (hand-off import: continue the
        exporting replica's draw sequence instead of this process's).
        ``dev_key_np`` rebases the device-resident sampling chain too, so
        a preempted sampled slot resumes with an identical distribution;
        None resets the chain to re-seed off the host stream."""
        self._key = jnp.asarray(key_np)
        self._chunk_counter = int(chunk_counter)
        self._dev_key = None if dev_key_np is None else jnp.asarray(dev_key_np)

    def _next_dev_key(self) -> jax.Array:
        """Current device RNG chain head, seeding it from the host stream
        on first use (fold_in keeps legacy greedy snapshots byte-stable:
        the host stream itself never advances differently)."""
        if self._dev_key is None:
            self._dev_key = jax.random.fold_in(self._key,
                                               self._chunk_counter)
            self._chunk_counter += 1
        return self._dev_key

    def probe_collective(self, min_interval_s: float = 0.5) -> float | None:
        """Time one tp all-reduce of a decode-width (1, dim) partial sum
        across this engine's mesh and feed ``engine_collective_ms``.

        The in-step collective (the fused ring or its psum fallback) is
        fused inside a compiled program, so its latency is not separable
        host-side; this probe dispatches the same-shape reduce as its own
        program — real devices, real ICI path — which is the per-step
        collective cost the fused-reduce work targets.  Rate-limited
        (callers may invoke per burst), no-op on tp==1 meshes; the first
        call compiles outside the timed window.  Returns the measured
        milliseconds, or None when skipped."""
        tp = self.mesh.shape.get("tp", 1)
        if tp <= 1:
            return None
        now = time.monotonic()
        if now - self._collective_probe_t < min_interval_s:
            return None
        if self._collective_fn is None:
            fn = jax.jit(shard_map(
                lambda v: jax.lax.psum(v, "tp"), mesh=self.mesh,
                in_specs=P(None, "tp"), out_specs=P(None, None),
                check_vma=False))
            x = jax.device_put(
                jnp.zeros((1, self.cfg.dim), jnp.float32),
                NamedSharding(self.mesh, P(None, "tp")))
            jax.block_until_ready(fn(x))  # compile, uncounted
            self._collective_fn = (fn, x)
        fn, x = self._collective_fn
        t0 = time.perf_counter()
        jax.block_until_ready(fn(x))
        ms = (time.perf_counter() - t0) * 1e3
        self._collective_probe_t = now
        obs_metrics.ENGINE_COLLECTIVE_MS.observe(ms)
        return ms

    def read_pool_pages(self, pages) -> dict[str, np.ndarray]:
        """Copy the given physical pages out of the paged pool to host
        numpy, all layers at once: shape ``(L, n, Hkv, ps, Dh)`` (plus the
        ``(L, n, Hkv, ps, 1)`` scale planes for an int8 pool).  Used by
        the scheduler's drain-time export and the spill path."""
        return {k: h.wait() for k, h in
                self.read_pool_pages_async(pages).items()}

    def read_pool_pages_async(self, pages) -> dict:
        """Start device-to-host copies of the given physical pages and
        return ``{name: handle}`` where ``handle.wait()`` yields the host
        ndarray.  The gather is enqueued on the device stream behind
        whatever is already in flight and ``copy_to_host_async`` makes
        the D2H transfer non-blocking — the spill path issues the copies,
        does its host-side bookkeeping, and only ``wait()``s right before
        freeing the pages, so the transfer hides behind the next dispatch
        burst."""
        idx = jnp.asarray(np.asarray(pages, np.int32))

        class _Handle:
            def __init__(self, dev):
                self._dev = dev
                try:
                    dev.copy_to_host_async()
                except Exception:
                    pass  # backend without async D2H: wait() still works

            def wait(self):
                return np.asarray(self._dev)

        out = {"pages.k": _Handle(self.cache.k[:, idx]),
               "pages.v": _Handle(self.cache.v[:, idx])}
        if self.cache.quantized:
            out["pages.k_scale"] = _Handle(self.cache.k_scale[:, idx])
            out["pages.v_scale"] = _Handle(self.cache.v_scale[:, idx])
        return out

    def write_pool_pages(self, pages, arrays: dict[str, np.ndarray]) -> None:
        """Write exported page slices (from :meth:`read_pool_pages` on a
        peer) into this engine's pool at the given physical page ids.
        One transient pool copy — acceptable at hand-off import time,
        which is off the steady-state decode path."""
        from ..models.transformer import KVCache
        idx = jnp.asarray(np.asarray(pages, np.int32))
        new_k = self.cache.k.at[:, idx].set(
            jnp.asarray(arrays["pages.k"], self.cache.k.dtype))
        new_v = self.cache.v.at[:, idx].set(
            jnp.asarray(arrays["pages.v"], self.cache.v.dtype))
        if self.cache.quantized:
            new_ks = self.cache.k_scale.at[:, idx].set(
                jnp.asarray(arrays["pages.k_scale"],
                            self.cache.k_scale.dtype))
            new_vs = self.cache.v_scale.at[:, idx].set(
                jnp.asarray(arrays["pages.v_scale"],
                            self.cache.v_scale.dtype))
            cache = KVCache(new_k, new_v, new_ks, new_vs)
        else:
            cache = KVCache(new_k, new_v)
        self.cache = jax.device_put(cache, self._cache_sh)

    def _sync(self, arrays, what: str) -> list[str]:
        """Block until ``arrays`` are device-ready — THE engine's blocking
        edge — under the watchdog, firing the ``engine.device_step`` fault
        point first (runtime/faults.py).  Returns the fault actions that
        ask the call site to transform its value (``nan``).

        With ``step_timeout`` set, the wait runs on a helper thread and a
        wait that outlives the deadline raises :class:`StepTimeout` (the
        helper is a daemon; a truly wedged runtime leaks one parked
        thread, which is the price of the caller staying responsive).
        """
        from .faults import FAULTS

        def wait() -> list[str]:
            actions = FAULTS.fire("engine.device_step")
            jax.block_until_ready(arrays)
            return actions

        if not self.step_timeout:
            return wait()
        import threading
        box: dict = {}

        def run():
            try:
                box["actions"] = wait()
            except BaseException as e:  # surfaced below, on the caller
                box["error"] = e

        t = threading.Thread(target=run, daemon=True,
                             name=f"dllama-step-watchdog({what})")
        t.start()
        t.join(self.step_timeout)
        if t.is_alive():
            raise StepTimeout(
                f"{what} did not become ready within {self.step_timeout}s "
                f"(pos={self.pos}, batch={self.batch}, mesh={dict(self.mesh.shape)})")
        if "error" in box:
            raise box["error"]
        return box["actions"]

    def _numeric_guard(self, host_logits: np.ndarray, step: str) -> np.ndarray:
        """Check a host-fetched logits array for NaN/Inf (``numeric_checks``
        mode; see :class:`NumericFault`).  Fires the ``engine.numeric``
        fault point first — its ``nan`` action poisons the checked array so
        the fault path is testable without real corruption.  Guards cover
        every host-logits step (prefill, single-token decode, ragged
        prefill); the on-device chunked decode loop only ships token ids
        to the host, so a divergence there surfaces at the next
        host-logits step (the following turn's prefill) — the bounded
        blind spot is documented in docs/ROBUSTNESS.md."""
        if not self.numeric_checks:
            return host_logits
        from .faults import FAULTS
        from ..io.integrity import bump_counter
        if "nan" in FAULTS.fire("engine.numeric"):
            host_logits = np.full_like(host_logits, np.nan)
        if not np.isfinite(host_logits).all():
            bump_counter("numeric_faults")
            bad = int(np.size(host_logits) - np.count_nonzero(
                np.isfinite(host_logits)))
            raise NumericFault(
                step, self.pos,
                hint=f"{bad}/{host_logits.size} non-finite logits; detection "
                     "is at the output logits (no layer attribution) — "
                     "bisect with --verify-weights and a dense kv cache")
        return host_logits

    def _note_executable(self, fresh: bool, compile_s: float | None = None,
                         key=None):
        """Feed the compile-telemetry metrics for one executable lookup:
        a recompile (with its first-call wall time, where the caller has a
        clean boundary) or a cache hit, plus the live-executable gauge."""
        if fresh:
            obs_metrics.ENGINE_RECOMPILES.inc()
            if compile_s is not None:
                obs_metrics.ENGINE_COMPILE_S.observe(compile_s)
            _log.info("compile", extra={
                "key": repr(key),
                "compile_s": None if compile_s is None
                else round(compile_s, 3)})
        else:
            obs_metrics.ENGINE_CACHE_HITS.inc()
        obs_metrics.ENGINE_LIVE_EXECUTABLES.set(
            len(self._compiled_steps) + len(self._chunk_fns))

    def _run(self, tokens_np: np.ndarray, last_index: int,
             offsets: jax.Array | None = None) -> tuple[np.ndarray, StepStats]:
        if self.paged:
            raise ValueError("paged engine is slot-only: the pool has no "
                             "contiguous per-row addressing; drive it via "
                             "slot_step / the slot scheduler")
        stats = StepStats()
        t0 = time.perf_counter()
        # from-scratch prefill on an sp mesh → blockwise ring attention with
        # the tokens (and therefore all activations) sharded on the
        # sequence axis: per-chip activation memory scales 1/sp, which is
        # what lets a prompt longer than one chip's HBM prefill at all
        use_ring = (self.sp > 1 and self.pos == 0 and tokens_np.shape[1] > 1
                    and tokens_np.shape[1] % self.sp == 0)
        # jit compiles per input shape: a shape first seen here is a fresh
        # XLA executable, whose first-call wall (t1 - t0) is dominated by
        # trace + compile — that's what the compile histogram records
        step_key = ("ring" if use_ring else "step",
                    tokens_np.shape, offsets is not None)
        fresh_exec = step_key not in self._compiled_steps
        with active_mesh(self.mesh):  # read at trace time (first call)
            if use_ring:
                toks = jax.device_put(
                    tokens_np, NamedSharding(self.mesh, P("dp", "sp")))
                logits, self.cache = self._step_ring(
                    self.params, self.cache, toks,
                    jnp.int32(self.pos), jnp.int32(last_index))
            else:
                logits, self.cache = self._step(
                    self.params, self.cache, jnp.asarray(tokens_np),
                    jnp.int32(self.pos), jnp.int32(last_index), offsets)
        fired = self._sync(logits, "prefill/decode step")
        t1 = time.perf_counter()
        if fresh_exec:
            self._compiled_steps.add(step_key)
        self._note_executable(fresh_exec, (t1 - t0) if fresh_exec else None,
                              key=step_key)
        host_logits = np.asarray(logits)  # (B, V)
        if "nan" in fired:  # injected device fault: poisoned logits
            host_logits = np.full_like(host_logits, np.nan)
        host_logits = self._numeric_guard(
            host_logits, "prefill" if tokens_np.shape[1] > 1 else "decode")
        t2 = time.perf_counter()
        if self.timing_mode == "host-fetch":
            # the ready marker fired at dispatch, not completion: only the
            # fetch edge is real — report the whole step as I (see __init__)
            stats.inference_ms = (t2 - t0) * 1000
            stats.transfer_ms = 0.0
        else:
            stats.inference_ms = (t1 - t0) * 1000
            stats.transfer_ms = (t2 - t1) * 1000
        stats.generation_ms = (t2 - t0) * 1000
        stats.sent_bytes = tokens_np.nbytes + 8  # token ids + pos/last scalars
        stats.recv_bytes = host_logits.nbytes
        phase = "prefill" if tokens_np.shape[1] > 1 else "decode_step"
        obs_trace.record(phase, t0, t2, pos=self.pos,
                         n_tokens=int(tokens_np.shape[1]))
        obs_metrics.ENGINE_GENERATION_MS.observe(stats.generation_ms)
        obs_metrics.ENGINE_INFERENCE_MS.observe(stats.inference_ms)
        obs_metrics.ENGINE_TRANSFER_MS.observe(stats.transfer_ms)
        obs_metrics.HOST_DEVICE_SENT_BYTES.observe(stats.sent_bytes)
        obs_metrics.HOST_DEVICE_RECV_BYTES.observe(stats.recv_bytes)
        return host_logits, stats

    def prefill(self, prompt_tokens: list[int]) -> tuple[np.ndarray, StepStats]:
        """Process the whole prompt; returns logits for its last token."""
        n = len(prompt_tokens)
        if n == 0:
            raise ValueError("empty prompt")
        if self.pos + n > self.seq_len:
            raise ContextOverflow(
                f"prompt of {n} exceeds seq_len {self.seq_len} at pos {self.pos}")
        # the padded bucket must also fit the cache: dynamic_update_slice
        # clamps out-of-range starts *backwards*, which would silently
        # overwrite valid KV history near the end of context
        bucket = max(n, min(_next_bucket(n), self.seq_len - self.pos))
        toks = np.zeros((self.batch, bucket), np.int32)
        toks[:, :n] = prompt_tokens
        logits, stats = self._run(toks, n - 1)
        self.pos += n
        _log.info("prefill", extra={
            "n_tokens": n, "pos": self.pos,
            "generation_ms": round(stats.generation_ms, 3)})
        return logits, stats

    def prefill_ragged(self, prompts: list[list[int]]
                       ) -> tuple[np.ndarray, StepStats]:
        """Prefill B *distinct* prompts left-padded to one bucket.

        Beyond reference (the reference fixes batch=1, tasks.cpp:199-210).
        Each prompt is right-aligned so every row's last real token lands
        on the shared index ``longest-1``; ``offsets[r] = longest -
        len(prompt_r)`` is kept on the engine and threaded into every
        subsequent decode step (per-row RoPE positions + attention key
        floors).  Rows see exactly the keys/angles they would see alone,
        so greedy decode matches the single-stream output per row.

        Like single-stream :meth:`prefill`, the token array pads up to a
        compile bucket but ``pos`` advances only to ``longest`` — the pad
        tail's garbage KV sits beyond the live region and the first
        decode steps overwrite it.  Lockstep caveat: the whole batch
        shares one position clock starting at ``longest``, so a short row
        batched with a much longer one has ``longest - len(prompt_r)``
        fewer context slots than it would alone; parity with the
        single-stream run holds while the requested steps fit that
        budget.
        """
        if len(prompts) != self.batch:
            raise ValueError(f"{len(prompts)} prompts for batch={self.batch}")
        if any(len(p) == 0 for p in prompts):
            raise ValueError("empty prompt")
        if self.sp > 1:
            raise ValueError("ragged batches are not supported on sp meshes "
                             "(sequence-sharded cache); use sp=1")
        if self.pos != 0:
            raise ValueError("ragged prefill starts a fresh batch; call reset()")
        longest = max(len(p) for p in prompts)
        if longest > self.seq_len:
            raise ContextOverflow(
                f"prompt of {longest} exceeds seq_len {self.seq_len}")
        bucket = max(longest, min(_next_bucket(longest), self.seq_len))
        toks = np.zeros((self.batch, bucket), np.int32)
        offsets = np.zeros((self.batch,), np.int32)
        for r, p in enumerate(prompts):
            toks[r, longest - len(p):longest] = p
            offsets[r] = longest - len(p)
        self._offsets = jnp.asarray(offsets)
        logits, stats = self._run(toks, longest - 1, offsets=self._offsets)
        self.pos = longest
        return logits, stats

    def decode_one(self, token: int) -> tuple[np.ndarray, StepStats]:
        """One autoregressive step at the current position."""
        if self.pos >= self.seq_len:
            raise ContextOverflow(f"position {self.pos} at seq_len limit {self.seq_len}")
        toks = np.full((self.batch, 1), token, np.int32)
        logits, stats = self._run(toks, 0)
        self.pos += 1
        return logits, stats

    # ------------------------------------------------------------------
    def _chunk_fn(self, steps: int, temperature: float, topp: float):
        """Compiled on-device K-step generation loop (runtime/decode_loop.py)."""
        from .decode_loop import decode_chunk
        key = (steps, float(temperature), float(topp))
        fresh = key not in self._chunk_fns
        if fresh:
            cfg = self.cfg
            self._chunk_fns[key] = jax.jit(
                lambda p, c, tok, pos, k, off=None: decode_chunk(
                    p, cfg, c, tok, pos, k,
                    steps=steps, temperature=key[1], topp=key[2], offsets=off),
                donate_argnums=(1,),
                # tokens/scalars replicated for process-local fetch; cache
                # keeps its sharding (see __init__)
                out_shardings=(self._rep, self._cache_sh,
                               self._rep, self._rep, self._rep))
        # compile seconds are observed at the first *call* (the dispatch
        # sites), where jit actually traces + compiles; here only the
        # recompile/cache-hit decision exists
        self._note_executable(fresh, key=("chunk",) + key)
        return self._chunk_fns[key]

    def generate_stream(self, prompt_tokens: list[int], steps: int, *,
                        temperature: float = 0.0, topp: float = 0.9,
                        seed: int | None = 0, eos_ids: tuple[int, ...] = (),
                        chunk: int = 16):
        """High-throughput generation: sampling and the decode loop run on
        device; token ids stream back in chunks.

        Yields ``(token_id, StepStats)``.  Prompt tokens are echoed first
        (reference generate-mode contract, dllama.cpp:45-93); the per-token
        stats of a chunk are the chunk averages.

        ``seed=None`` continues the engine's existing RNG stream instead of
        restarting it — multi-turn chat seeds once per session and lets the
        stream advance across turns, like the reference's single Sampler
        whose xorshift state persists for the process (app.cpp:33,
        dllama.cpp:196-203; VERDICT r04 Weak #6).
        """
        steps = min(steps, self.seq_len - self.pos)
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
            self._chunk_counter = 0

        logits, pstats = self.prefill(prompt_tokens[:])
        for i, t in enumerate(prompt_tokens):
            yield t, pstats if i == len(prompt_tokens) - 1 else StepStats()
        produced = len(prompt_tokens)
        if produced >= steps:
            return

        # one RNG stream per generation: the first token samples from the
        # fetched prefill logits with the *same* JAX counter-based PRNG the
        # on-device chunks use (fold_in of the seed key), so a fixed seed
        # corresponds to exactly one stream (ADVICE r01: previously token 1
        # came from the host xorshift Sampler and the rest from JAX)
        from .decode_loop import device_sample
        sub = jax.random.fold_in(self._key, self._chunk_counter)
        self._chunk_counter += 1
        token = int(np.asarray(device_sample(
            jnp.asarray(logits), sub, temperature, topp))[0])
        # prefill cost was already attributed to the last prompt token; this
        # token only cost a sample over the fetched logits
        yield token, StepStats()
        produced += 1
        if token in eos_ids:
            return

        # Pipelined chunk dispatch: chunk N+1 is enqueued — fed the
        # on-device last token the chunk fn returns — BEFORE chunk N's ids
        # are fetched, so the host dispatch/RPC bubble (measured ~2.3
        # ms/token over the axon tunnel at chunk 32, docs/PERF.md)
        # overlaps device execution.  Token streams are bit-identical to
        # the serial schedule (same compiled fn, same inputs; only host
        # scheduling changes).  An EOS that lands mid-chunk discards the
        # one speculative in-flight chunk: its cache writes sit past the
        # rewound position (dead rows, overwritten later, same overshoot
        # invariant as within-chunk EOS) and its RNG tick is rolled back.
        def dispatch(in_tok_dev, done):
            # ``done`` counts tokens EXPECTED by prior dispatches (not yet
            # necessarily fetched) so a speculative chunk never overshoots
            # the requested steps
            k = min(chunk, steps - done, self.seq_len - self.pos)
            fresh = (k, float(temperature), float(topp)) not in self._chunk_fns
            fn = self._chunk_fn(k, temperature, topp)
            sub = jax.random.fold_in(self._key, self._chunk_counter)
            self._chunk_counter += 1
            p0 = self.pos
            # host→device bytes actually crossing for THIS dispatch: the
            # pos scalar + folded key always; the token array only when it
            # comes from the host (first chunk) — later chunks feed the
            # device-carried last token, which never touches the host
            sent = 12 + (in_tok_dev.nbytes
                         if isinstance(in_tok_dev, np.ndarray) else 0)
            t0 = time.perf_counter()
            with active_mesh(self.mesh):
                toks_dev, self.cache, last_dev, _pos, _key = fn(
                    self.params, self.cache, jnp.asarray(in_tok_dev),
                    jnp.int32(p0), sub)
            if fresh:
                # jit's first call blocks through trace + XLA compile
                # before the async dispatch returns — this wall is the
                # compile cost the histogram tracks
                obs_metrics.ENGINE_COMPILE_S.observe(time.perf_counter() - t0)
            self.pos = p0 + k
            return k, p0, toks_dev, last_dev, t0, sent

        if produced >= steps or self.pos >= self.seq_len:
            return  # nothing left to dispatch (e.g. max_tokens == 1)
        pending = dispatch(np.full((self.batch,), token, np.int32), produced)
        expected = produced
        boundary = None
        try:
            while pending is not None:
                k, p0, toks_dev, last_dev, t0, sent = pending
                expected += k
                pending = dispatch(last_dev, expected) \
                    if expected < steps and self.pos < self.seq_len else None
                self._sync(toks_dev, f"decode chunk at pos {p0}")
                t1 = time.perf_counter()
                toks = np.asarray(toks_dev)[:, 0]  # (k,)
                t2 = time.perf_counter()
                # steady-state chunk wall = boundary to boundary (this
                # chunk was dispatched before the PREVIOUS fetch returned)
                g0 = t0 if boundary is None else max(boundary, t0)
                boundary = t2
                if self.timing_mode == "host-fetch":
                    i_ms, t_ms = (t2 - g0) * 1000 / k, 0.0  # see __init__
                else:
                    i_ms, t_ms = (t1 - g0) * 1000 / k, (t2 - t1) * 1000 / k
                # chunk averages: each of the k tokens carries 1/k of the
                # chunk's wall/device/boundary cost (labeled in the CLI)
                per = StepStats(
                    generation_ms=(t2 - g0) * 1000 / k,
                    inference_ms=i_ms,
                    transfer_ms=t_ms,
                    sent_bytes=sent / k,
                    recv_bytes=toks.nbytes / k)
                obs_trace.record("decode_chunk", g0, t2, pos=p0, k=k)
                obs_metrics.ENGINE_GENERATION_MS.observe(per.generation_ms)
                obs_metrics.ENGINE_INFERENCE_MS.observe(per.inference_ms)
                obs_metrics.ENGINE_TRANSFER_MS.observe(per.transfer_ms)
                obs_metrics.HOST_DEVICE_SENT_BYTES.observe(sent)
                obs_metrics.HOST_DEVICE_RECV_BYTES.observe(toks.nbytes)
                _log.debug("decode_chunk", extra={
                    "pos": p0, "k": k,
                    "generation_ms": round(per.generation_ms, 3)})
                for j, tk in enumerate(toks.tolist()):
                    token = int(tk)
                    yield token, per
                    produced += 1
                    if token in eos_ids:
                        # rewind past the unconsumed overshoot so a
                        # following turn prefills at the right position
                        # (masked rows are never attended and get
                        # overwritten); the finally below returns the
                        # speculative chunk's RNG tick
                        self.pos = p0 + j + 1
                        return
                    if produced >= steps:
                        return
        finally:
            # Reached on EOS return AND when the consumer abandons the
            # generator (stop-string break in drain_generation →
            # GeneratorExit): a speculative in-flight chunk is dead rows
            # past the live position, and its unconsumed RNG tick is
            # returned so a later turn's sampled stream is
            # schedule-independent of the pipelining.
            if pending is not None:
                self._chunk_counter -= 1

    def generate_batch(self, prompts: list[list[int]], steps: int, *,
                       temperature: float = 0.0, topp: float = 0.9,
                       seed: int | None = 0,
                       eos_ids: tuple[int, ...] = (), chunk: int = 16
                       ) -> list[list[int]]:
        """Decode B *distinct* prompts in lockstep on one mesh.

        Beyond reference — the reference fixes batch=1 per cluster
        (tasks.cpp:199-210); this is the TPU throughput lever that needs
        no extra chips: the decode matmuls amortize one weight read over
        B rows.  Returns B token lists, each ``prompts[r]`` followed by
        its continuation, truncated per row at ``steps`` total tokens or
        the row's EOS.  Greedy (temperature 0) rows match the
        single-stream ``generate_stream`` output token for token while
        the steps fit the shared position budget (the clock starts at the
        longest prompt's length — see :meth:`prefill_ragged`); sampled
        rows are reproducible from ``seed`` but draw from a different
        PRNG stream than a batch-1 run.

        Rows that finish early stay in the lockstep batch (their cache
        rows keep advancing with ignored tokens) until every row is done
        — the batch is one-shot, not a continuable conversation; the
        per-row bookkeeping an incremental server needs lives in
        server/api.py.
        """
        if steps <= 0:
            raise ValueError("steps must be positive")
        steps = min(steps, self.seq_len)  # same clamp as the stream core
        outs = [list(p) for p in prompts]
        done = [len(o) >= steps for o in outs]
        for row_tokens in self.generate_batch_stream(
                prompts, steps, temperature=temperature, topp=topp,
                seed=seed, chunk=chunk):
            for r, t in enumerate(row_tokens.tolist()):
                if done[r]:
                    continue
                outs[r].append(int(t))
                if int(t) in eos_ids or len(outs[r]) >= steps:
                    done[r] = True
            if all(done):
                break
        return outs

    def generate_batch_stream(self, prompts: list[list[int]], steps: int, *,
                              temperature: float = 0.0, topp: float = 0.9,
                              seed: int | None = 0, chunk: int = 16):
        """The lockstep core of :meth:`generate_batch`, as a generator:
        yields one ``(B,)`` int32 array per decoded step, every row's
        sampled token, as each on-device chunk lands.  EOS/length policy
        belongs to the consumer (generate_batch truncates per row; the
        API server streams per-row deltas with its own stop detectors) —
        finished rows keep decoding in lockstep and their later tokens
        are simply ignored.  The stream ends at ``steps`` total yields or
        the context window, whichever first (every row's per-prompt cap
        lies below ``steps``, see generate_batch); consumers that want
        fewer tokens stop iterating (both built-in consumers break when
        every row is done).  Abandoning the generator mid-batch is fine:
        the batch is one-shot, not a continuable conversation."""
        from .decode_loop import device_sample
        if steps <= 0:
            raise ValueError("steps must be positive")
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
            self._chunk_counter = 0

        logits, _ = self.prefill_ragged(prompts)  # validates batch/sp/pos
        sub = jax.random.fold_in(self._key, self._chunk_counter)
        self._chunk_counter += 1
        tok_vec = np.asarray(device_sample(
            jnp.asarray(logits), sub, temperature, topp))  # (B,)
        yield tok_vec

        # depth-1 pipelined dispatch, mirroring generate_stream: chunk N+1
        # is enqueued on the device-carried last row of tokens before
        # chunk N's fetch, overlapping the host dispatch bubble with
        # device execution.  Consumers break when every row is done
        # (GeneratorExit) — the finally returns the speculative chunk's
        # RNG tick; its cache rows are dead (the batch is one-shot and
        # reset() precedes reuse).
        def dispatch(in_tok, done):
            # ``done`` = steps already covered by prior dispatches, so a
            # speculative chunk never runs past the consumer's budget
            k = min(chunk, steps - done, self.seq_len - self.pos)
            fresh = (k, float(temperature), float(topp)) not in self._chunk_fns
            fn = self._chunk_fn(k, temperature, topp)
            sub = jax.random.fold_in(self._key, self._chunk_counter)
            self._chunk_counter += 1
            tc = time.perf_counter()
            with active_mesh(self.mesh):
                toks_dev, self.cache, last_dev, _pos, _key = fn(
                    self.params, self.cache, jnp.asarray(in_tok, jnp.int32),
                    jnp.int32(self.pos), sub, self._offsets)
            if fresh:  # first call blocks through trace + compile
                obs_metrics.ENGINE_COMPILE_S.observe(time.perf_counter() - tc)
            self.pos += k
            return k, toks_dev, last_dev

        expected = 1  # the prefill-sample step already yielded
        if expected >= steps or self.pos >= self.seq_len:
            return
        pending = dispatch(tok_vec, expected)
        try:
            while pending is not None:
                k, toks_dev, last_dev = pending
                expected += k
                pending = dispatch(last_dev, expected) \
                    if expected < steps and self.pos < self.seq_len else None
                t0 = time.perf_counter()
                self._sync(toks_dev, "batch decode chunk")
                obs_trace.record("decode_chunk", t0, time.perf_counter(),
                                 pos=self.pos - k, k=k, batch=True)
                toks = np.asarray(toks_dev)  # (k, B)
                for j in range(toks.shape[0]):
                    yield toks[j]
        finally:
            if pending is not None:
                self._chunk_counter -= 1

    # ------------------------------------------------------------------
    def slot_step_async(self, tokens_np: np.ndarray | None,
                        pos_rows_np: np.ndarray, n_valid_np: np.ndarray, *,
                        temps_np: np.ndarray, topps_np: np.ndarray,
                        topks_np: np.ndarray | None = None,
                        steps: int = 1,
                        page_tables_np: np.ndarray | None = None,
                        vocab_mask_np: np.ndarray | None = None,
                        feed_dev=None) -> "SlotDispatch":
        """Enqueue one continuous-batching dispatch over the
        slot-addressable batch WITHOUT blocking on the result: row ``r``
        consumes its first ``n_valid_np[r]`` tokens of ``tokens_np``
        (B, T) at its own cache positions ``pos_rows_np[r]..``, then
        ``steps - 1`` pure decode steps run on device
        (decode_loop.slot_chunk).  Returns a :class:`SlotDispatch`
        completion handle holding the sampled-id futures; call
        ``.wait()`` for the host (steps, B) array.

        This is the primitive the slot scheduler (runtime/scheduler.py)
        drives: a joining request's prefill chunk and its neighbors'
        decode tokens share one dispatch, and a freed slot is reused by
        just handing its row position 0 again — the previous occupant's
        stale KV sits above the new request's causal ceiling (see
        ops.attention.slot_gqa_attention_at), so per-slot reset costs
        nothing.

        ``feed_dev`` is the device-resident feedback path: pass a prior
        dispatch's ``last_dev`` (B,) and the new dispatch consumes it
        directly as its T=1 token column — the sampled tokens never
        visit the host on the input side, eliminating the
        device→host→device round trip per pure-decode dispatch (the
        paper's T ≈ 0 overlap goal applied to the host boundary).  With
        ``feed_dev`` set, ``tokens_np`` must be None.

        Deliberately does NOT touch ``self.pos`` / ``self._offsets``:
        the one-shot conversation/batch paths and the slot path can share
        one engine as long as their uses don't overlap in time (the
        scheduler's ``exclusive()`` guarantees that), and the scheduler
        tracks every slot's clock host-side.  Compiled per
        ``(T, steps, all-greedy, fused-attention mode, mask presence)``;
        temperature/top-p/top-k ride in as (B,) arrays so heterogeneous
        requests share one program — a feed-fed dispatch shares the T=1
        executable with a host-fed one.  Sampled dispatches draw from the
        device-resident key chain (:meth:`_next_dev_key`) and the chunk
        returns the advanced key, so sampled ``feed_dev`` decode runs
        with zero host round trips.  ``vocab_mask_np`` is the optional
        (V,) or (B, V) boolean keep-mask (grammar seam, identity today).

        On a paged engine ``page_tables_np`` (B, max_pages) int32 is
        required: reads/writes indirect through it into the pool
        (decode_loop.slot_chunk).  Its shape is static per engine, so it
        rides the same compile buckets as one extra operand.
        """
        from .decode_loop import slot_chunk
        if self.sp > 1:
            raise ValueError("slot serving is not supported on sp meshes "
                             "(sequence-sharded cache); use sp=1")
        if self.cache.quantized and not self.paged:
            raise ValueError("slot serving needs a dense or paged-int8 KV "
                             "cache (contiguous per-row quantized writes "
                             "are not wired)")
        if self.paged and page_tables_np is None:
            raise ValueError("paged engine: slot_step needs page_tables_np")
        if not self.paged and page_tables_np is not None:
            raise ValueError("page tables passed to a contiguous engine")
        if feed_dev is not None:
            if tokens_np is not None:
                raise ValueError("feed_dev replaces tokens_np; pass one")
            t = 1
        elif tokens_np is None:
            raise ValueError("slot step needs tokens_np or feed_dev")
        else:
            t = int(tokens_np.shape[1])
        if steps < 1:
            raise ValueError("steps must be positive")
        # dynamic_update_slice clamps out-of-range starts backwards, which
        # would silently overwrite valid history — refuse instead.  (The
        # paged write path clamps into the scratch page rather than
        # backwards, but the logical-position budget is the same.)
        hi = max(int(np.max(pos_rows_np)) + t,
                 int(np.max(pos_rows_np + n_valid_np)) + (steps - 1))
        if hi > self.seq_len:
            raise ContextOverflow(
                f"slot step would write position {hi - 1} past seq_len "
                f"{self.seq_len}; retire rows at the context edge first")
        greedy = bool(np.all(temps_np == 0.0))
        from ..ops.attention import fused_mode
        has_mask = vocab_mask_np is not None
        key = ("slot_paged" if self.paged else "slot", t, steps, greedy,
               fused_mode() if self.paged else "", has_mask)
        fresh = key not in self._chunk_fns
        if fresh:
            cfg = self.cfg
            if self.paged:
                self._chunk_fns[key] = jax.jit(
                    lambda p, c, tok, pr, nv, k, tm, tp, tk, ptab, vm=None:
                    slot_chunk(
                        p, cfg, c, tok, pr, nv, k, tm, tp, tk,
                        steps=steps, greedy=greedy, page_table=ptab,
                        vocab_mask=vm),
                    donate_argnums=(1,),
                    out_shardings=(self._rep, self._cache_sh, self._rep,
                                   self._rep))
            else:
                self._chunk_fns[key] = jax.jit(
                    lambda p, c, tok, pr, nv, k, tm, tp, tk, vm=None:
                    slot_chunk(
                        p, cfg, c, tok, pr, nv, k, tm, tp, tk,
                        steps=steps, greedy=greedy, vocab_mask=vm),
                    donate_argnums=(1,),
                    out_shardings=(self._rep, self._cache_sh, self._rep,
                                   self._rep))
        self._note_executable(fresh, key=key)
        fn = self._chunk_fns[key]
        sub = self._next_dev_key()
        t0 = time.perf_counter()
        if feed_dev is not None:
            tok_arr = jnp.asarray(feed_dev, jnp.int32)[:, None]  # on device
        else:
            tok_arr = jnp.asarray(tokens_np, jnp.int32)
        if topks_np is None:
            topks_np = np.zeros(len(pos_rows_np), np.int32)
        args = (self.params, self.cache, tok_arr,
                jnp.asarray(pos_rows_np, jnp.int32),
                jnp.asarray(n_valid_np, jnp.int32), sub,
                jnp.asarray(temps_np, jnp.float32),
                jnp.asarray(topps_np, jnp.float32),
                jnp.asarray(topks_np, jnp.int32))
        if self.paged:
            args = args + (jnp.asarray(page_tables_np, jnp.int32),)
        if has_mask:
            args = args + (jnp.asarray(vocab_mask_np, bool),)
        with active_mesh(self.mesh):
            toks_dev, self.cache, last_dev, self._dev_key = fn(*args)
        return SlotDispatch(self, toks_dev, last_dev, t=t, steps=steps,
                            fresh=fresh, enqueued_at=t0)

    def slot_step(self, tokens_np: np.ndarray, pos_rows_np: np.ndarray,
                  n_valid_np: np.ndarray, *, temps_np: np.ndarray,
                  topps_np: np.ndarray,
                  topks_np: np.ndarray | None = None, steps: int = 1,
                  page_tables_np: np.ndarray | None = None,
                  vocab_mask_np: np.ndarray | None = None) -> np.ndarray:
        """Synchronous :meth:`slot_step_async`: enqueue and immediately
        wait.  Returns the sampled ids (steps, B)."""
        return self.slot_step_async(
            tokens_np, pos_rows_np, n_valid_np, temps_np=temps_np,
            topps_np=topps_np, topks_np=topks_np, steps=steps,
            page_tables_np=page_tables_np,
            vocab_mask_np=vocab_mask_np).wait()

    def slot_verify_async(self, tokens_np: np.ndarray,
                          pos_rows_np: np.ndarray, n_valid_np: np.ndarray, *,
                          temps_np: np.ndarray, topps_np: np.ndarray,
                          topks_np: np.ndarray | None = None,
                          page_tables_np: np.ndarray | None = None,
                          vocab_mask_np: np.ndarray | None = None
                          ) -> "SlotVerifyDispatch":
        """Enqueue one ragged slot-VERIFY dispatch (the batched,
        per-slot generalization of :meth:`_verify_fn`'s single-stream
        verify window): row ``r`` feeds its previous sample plus
        ``n_valid_np[r] - 1`` proposed draft tokens at positions
        ``pos_rows_np[r]..``, and the landed result carries the model's
        prediction at every fed position plus the per-row count of
        accepted leading drafts (decode_loop.slot_verify_chunk).

        A row with ``n_valid`` 1 carries no proposal and rides the burst
        as one plain decode step — the scheduler mixes proposing and
        non-proposing slots freely in a single dispatch, so one slot
        speculating never stalls a neighbor.  Rejected drafts wrote KV
        above their row's accepted ceiling; those entries are dead under
        the causal-ceiling masking (or redirected harmlessly in paged
        mode) exactly like slot-reuse garbage, so rejection truncates
        that row only and costs nothing to undo.

        Compiled per ``(T, all-greedy)``; the verified next-token row
        ``last_dev`` stays device-resident on the handle so a caller can
        feed it onward like :meth:`slot_step_async`'s ``feed_dev``.
        Same engine-state discipline as ``slot_step_async``: slot clocks
        stay host-side with the scheduler; ``self.pos`` is untouched.
        """
        from .decode_loop import slot_verify_chunk
        if self.sp > 1:
            raise ValueError("slot serving is not supported on sp meshes "
                             "(sequence-sharded cache); use sp=1")
        if self.cache.quantized and not self.paged:
            raise ValueError("slot serving needs a dense or paged-int8 KV "
                             "cache (contiguous per-row quantized writes "
                             "are not wired)")
        if self.paged and page_tables_np is None:
            raise ValueError("paged engine: slot_verify needs page_tables_np")
        if not self.paged and page_tables_np is not None:
            raise ValueError("page tables passed to a contiguous engine")
        t = int(tokens_np.shape[1])
        if t < 2:
            raise ValueError("slot_verify needs T >= 2 (a previous sample "
                             "plus at least one proposal column)")
        if int(np.max(n_valid_np)) > t:
            raise ValueError("n_valid exceeds the verify window width")
        # every fed column writes KV at pos..pos+T-1 (invalid columns land
        # above the ceiling / in the scratch page), so the whole window
        # must fit — same refusal as slot_step_async
        hi = int(np.max(pos_rows_np)) + t
        if hi > self.seq_len:
            raise ContextOverflow(
                f"slot verify would write position {hi - 1} past seq_len "
                f"{self.seq_len}; retire rows at the context edge first")
        greedy = bool(np.all(temps_np == 0.0))
        from ..ops.attention import fused_mode
        has_mask = vocab_mask_np is not None
        key = ("slot_verify_paged" if self.paged else "slot_verify",
               t, greedy, fused_mode() if self.paged else "", has_mask)
        fresh = key not in self._chunk_fns
        if fresh:
            cfg = self.cfg
            if self.paged:
                self._chunk_fns[key] = jax.jit(
                    lambda p, c, tok, pr, nv, k, tm, tp, tk, ptab, vm=None:
                    slot_verify_chunk(p, cfg, c, tok, pr, nv, k, tm, tp, tk,
                                      greedy=greedy, page_table=ptab,
                                      vocab_mask=vm),
                    donate_argnums=(1,),
                    out_shardings=(self._rep, self._cache_sh,
                                   self._rep, self._rep, self._rep))
            else:
                self._chunk_fns[key] = jax.jit(
                    lambda p, c, tok, pr, nv, k, tm, tp, tk, vm=None:
                    slot_verify_chunk(p, cfg, c, tok, pr, nv, k, tm, tp, tk,
                                      greedy=greedy, vocab_mask=vm),
                    donate_argnums=(1,),
                    out_shardings=(self._rep, self._cache_sh,
                                   self._rep, self._rep, self._rep))
        self._note_executable(fresh, key=key)
        fn = self._chunk_fns[key]
        sub = self._next_dev_key()
        t0 = time.perf_counter()
        if topks_np is None:
            topks_np = np.zeros(len(pos_rows_np), np.int32)
        args = (self.params, self.cache, jnp.asarray(tokens_np, jnp.int32),
                jnp.asarray(pos_rows_np, jnp.int32),
                jnp.asarray(n_valid_np, jnp.int32), sub,
                jnp.asarray(temps_np, jnp.float32),
                jnp.asarray(topps_np, jnp.float32),
                jnp.asarray(topks_np, jnp.int32))
        if self.paged:
            args = args + (jnp.asarray(page_tables_np, jnp.int32),)
        if has_mask:
            args = args + (jnp.asarray(vocab_mask_np, bool),)
        with active_mesh(self.mesh):
            preds_dev, self.cache, accepted_dev, last_dev, self._dev_key = \
                fn(*args)
        return SlotVerifyDispatch(self, preds_dev, accepted_dev, last_dev,
                                  t=t, fresh=fresh, enqueued_at=t0)

    # ------------------------------------------------------------------
    def score_batch(self, sequences: list[list[int]], top_k: int = 0
                    ) -> tuple[np.ndarray, np.ndarray | None, np.ndarray | None]:
        """Teacher-force B sequences through ONE left-padded ragged forward
        and return per-token log-probabilities (beyond reference — the
        API's ``logprobs``).

        Returns ``(token_lp, top_ids, top_lp)``: ``token_lp[r, j]`` is
        ``log P(sequences[r][j+1] | prefix)`` for ``j+1 < len(seq_r)``
        (position 0 has no conditional; rows are right-aligned in a
        bucketed width, so entry ``j`` lives at padded column
        ``tok_lp.shape[1] - len(seq_r) + j``; pad columns hold garbage —
        callers slice by their own lengths).  With ``top_k`` the
        per-position top-k alternative ids and log-probs come back too.
        Scoring runs on a scratch cache copy (no donation) and leaves the
        engine's conversation state untouched except ``reset()``.
        """
        from ..models.transformer import forward, init_kv_cache
        if self.sp > 1:
            raise ValueError("score_batch is not supported on sp meshes")
        if self.paged:
            raise ValueError("paged engine is slot-only; scoring needs a "
                             "contiguous scratch cache")
        if len(sequences) != self.batch:
            raise ValueError(f"{len(sequences)} sequences for batch={self.batch}")
        if any(len(s) < 2 for s in sequences):
            raise ValueError("scoring needs ≥2 tokens per sequence")
        longest = max(len(s) for s in sequences)
        if longest > self.seq_len:
            raise ContextOverflow(
                f"sequence of {longest} exceeds seq_len {self.seq_len}")
        # bucket the padded length so a serving loop compiles one scoring
        # program per bucket, not one per distinct request length (extra
        # left-padding is invisible: offsets grow, masks/RoPE follow)
        bucket = max(longest, min(_next_bucket(longest), self.seq_len))
        toks = np.zeros((self.batch, bucket), np.int32)
        offsets = np.zeros((self.batch,), np.int32)
        for r, s in enumerate(sequences):
            toks[r, bucket - len(s):] = s
            offsets[r] = bucket - len(s)
        key = ("score", bucket, top_k)
        fresh_score = key not in self._chunk_fns
        if fresh_score:
            cfg = self.cfg

            def score(p, c, tk, off):
                logits, _ = forward(p, cfg, tk, c, jnp.int32(0), offsets=off)
                lg = logits.astype(jnp.float32)
                # normalize via a (B, T) logsumexp instead of materializing
                # a second full-vocab log_softmax buffer next to the logits
                lse = jax.scipy.special.logsumexp(lg, axis=-1)  # (B, T)
                # log P of the NEXT fed token, at the position producing it
                nxt = jnp.roll(tk, -1, axis=1)  # (B, T); last col garbage
                tok_lp = jnp.take_along_axis(
                    lg, nxt[..., None], axis=-1)[..., 0] - lse  # (B, T)
                if top_k > 0:
                    tl, ti = jax.lax.top_k(lg, top_k)  # (B, T, k)
                    return tok_lp, ti.astype(jnp.int32), tl - lse[..., None]
                return tok_lp, None, None

            # one replicated sharding as a pytree prefix covers however
            # many array outputs the top_k variant returns
            self._chunk_fns[key] = jax.jit(score, out_shardings=self._rep)
        tc = time.perf_counter()
        with active_mesh(self.mesh):
            cache = init_kv_cache(self.cfg, self.batch, bucket,
                                  dtype=self.cache.k.dtype
                                  if not self.cache.quantized else None)
            tok_lp, ti, tl = self._chunk_fns[key](
                self.params, cache, jnp.asarray(toks), jnp.asarray(offsets))
        self._note_executable(
            fresh_score,
            (time.perf_counter() - tc) if fresh_score else None, key=key)
        return (np.asarray(tok_lp),
                None if ti is None else np.asarray(ti),
                None if tl is None else np.asarray(tl))

    # ------------------------------------------------------------------
    def _verify_fn(self, t: int):
        """Compiled T-token verification step returning ALL positions'
        logits (B, T, V) — the speculative-decoding workhorse."""
        from ..models.transformer import forward
        key = ("verify", t)
        fresh = key not in self._chunk_fns
        if fresh:
            cfg = self.cfg

            def verify(p, c, toks, pos):
                logits, c = forward(p, cfg, toks, c, pos)
                # argmax ON DEVICE: only T int32 ids cross the host
                # boundary, not (T, V) logits — the same boundary
                # discipline as the decode chunk (a 128k vocab would
                # otherwise ship ~4 MB per window over the tunnel)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), c

            self._chunk_fns[key] = jax.jit(
                verify, donate_argnums=(1,),
                out_shardings=(self._rep, self._cache_sh))
        self._note_executable(fresh, key=key)
        return self._chunk_fns[key]

    def generate_pld(self, prompt_tokens: list[int], steps: int, *,
                     ngram: int = 2, k: int = 7,
                     eos_ids: tuple[int, ...] = ()) -> list[int]:
        """Greedy decode with prompt-lookup speculation (beyond reference).

        Draft-model-free speculative decoding: propose the ``k`` tokens
        that followed the most recent occurrence of the current ``ngram``
        suffix earlier in the sequence, then verify the whole window in
        ONE ``T=k+1`` forward.  Decode is weight-bandwidth-bound, so a
        verify step reads the weights once for up to ``k+1`` accepted
        tokens — on repetitive continuations (summarization, code, quoted
        context) this multiplies tokens/weight-read by the acceptance
        rate.  Rejected proposals cost nothing extra: the cache rows they
        wrote sit beyond the live prefix (``pos`` only advances over
        accepted tokens) and are overwritten by the next window, exactly
        like bucketed-prefill padding.

        Output is the vanilla greedy stream (tests pin ``generate_pld ==
        generate_stream`` token for token on the CPU test mesh): every
        emitted token is an argmax of the model distribution at its
        position — speculation only changes how many positions one
        dispatch verifies.  Hardware caveat: the ``T=k+1`` forward may
        reduce bf16 matmuls in a different order than the ``T=1`` decode
        forward, so an argmax near-tie can resolve differently on a real
        chip; both streams are valid greedy decodes of the model, but
        bit-identity across the two is only guaranteed where reduction
        order matches.
        """
        return list(self.generate_pld_stream(prompt_tokens, steps,
                                             ngram=ngram, k=k,
                                             eos_ids=eos_ids))

    def generate_pld_stream(self, prompt_tokens: list[int], steps: int, *,
                            ngram: int = 2, k: int = 7,
                            eos_ids: tuple[int, ...] = ()):
        """Generator core of :meth:`generate_pld`: yields the prompt echo,
        then each verified token as its window lands — so the CLI streams
        text during speculation exactly like plain greedy decode."""
        if self.batch != 1:
            raise ValueError("speculative decode is single-stream (batch=1)")
        if self.sp > 1:
            raise ValueError("speculative decode is not supported on sp meshes")
        if self.paged:
            raise ValueError("paged engine is slot-only; speculative decode "
                             "uses contiguous addressing")
        steps = min(steps, self.seq_len - self.pos)
        out = list(prompt_tokens)
        # latest-occurrence n-gram index, maintained incrementally: O(1)
        # lookup per window instead of an O(context) rescan (the host
        # would otherwise idle the device at exactly the long-context
        # lengths speculation targets).  Value = position AFTER the match;
        # only positions ≤ len(out)-1 are indexed, so a lookup never
        # matches the current suffix against itself (the continuation
        # would be empty).
        index: dict[tuple, int] = {}
        indexed = ngram - 1

        def extend_index():
            nonlocal indexed
            hi = len(out) - 1
            for p in range(max(indexed + 1, ngram), hi + 1):
                index[tuple(out[p - ngram:p])] = p
            indexed = max(indexed, hi)

        logits, _ = self.prefill(prompt_tokens[:])
        yield from out
        if len(out) >= steps:
            return  # the prompt always echoes whole (stream contract)
        cur = int(np.asarray(logits)[0].argmax())
        out.append(cur)
        yield cur
        if cur in eos_ids:
            return

        def propose() -> list[int]:
            """Continuation after the latest earlier occurrence of the
            current ngram-suffix; zeros when none (wrong guesses merely
            verify short)."""
            if len(out) > ngram:
                i = index.get(tuple(out[-ngram:]))
                if i is not None:
                    cand = out[i:i + k]
                    return cand + [0] * (k - len(cand))
            return [0] * k

        fn = self._verify_fn(k + 1)
        while len(out) < steps and self.pos + k + 1 <= self.seq_len:
            extend_index()
            window = np.asarray([[cur] + propose()], np.int32)  # (1, k+1)
            p0 = self.pos
            with active_mesh(self.mesh):
                preds_dev, self.cache = fn(
                    self.params, self.cache, jnp.asarray(window),
                    jnp.int32(p0))
            preds = np.asarray(preds_dev)[0]  # (k+1,) int32
            accepted = 0
            while accepted < k and window[0, accepted + 1] == preds[accepted]:
                accepted += 1
            # every verified position's argmax is a true greedy token: the
            # `accepted` matching proposals plus the model's own next token
            emit = [int(t) for t in preds[:accepted + 1]]
            base = len(out)
            out.extend(emit)
            # the window's first `accepted+1` fed tokens are now part of
            # the sequence; rows written beyond that are dead (never
            # attended: the causal mask reads s_idx <= pos)
            self.pos = p0 + accepted + 1
            cur = emit[-1]
            for j, t in enumerate(emit):
                yield t
                if t in eos_ids or base + j + 1 >= steps:
                    del out[base + j + 1:]
                    self.pos = p0 + j + 1
                    return
        # tail: plain single-token steps when the window no longer fits
        while len(out) < steps and self.pos < self.seq_len:
            logits, _ = self.decode_one(cur)
            cur = int(np.asarray(logits)[0].argmax())
            out.append(cur)
            yield cur
            if cur in eos_ids:
                break

    def generate(self, prompt_tokens: list[int], steps: int, sampler: Sampler,
                 eos_ids: tuple[int, ...] = (), prefill_single_token: bool = False):
        """Yield ``(token_id, stats)`` for up to ``steps`` generated tokens.

        Mirrors the reference generate loop (dllama.cpp:17-93): prompt
        tokens are consumed first (emitted with their stats but not
        sampled), then sampled tokens stream out until ``steps`` tokens
        total, seq_len, or an EOS id.  ``prefill_single_token=True``
        reproduces the reference's token-at-a-time prefill for parity
        testing.
        """
        steps = min(steps, self.seq_len - self.pos)
        produced = 0
        if prefill_single_token:
            logits = None
            for t in prompt_tokens:
                logits, stats = self.decode_one(t)
                produced += 1
                yield t, stats
                if produced >= steps:
                    return
        else:
            logits, stats = self.prefill(prompt_tokens[:])
            produced += len(prompt_tokens)
            for i, t in enumerate(prompt_tokens):
                yield t, stats if i == len(prompt_tokens) - 1 else StepStats()
            if produced >= steps:
                return

        token = int(sampler.sample(logits[0]))
        stats = StepStats()  # prefill cost already attributed above
        while True:
            yield token, stats
            produced += 1
            if produced >= steps or self.pos >= self.seq_len or token in eos_ids:
                return
            logits, stats = self.decode_one(token)
            token = int(sampler.sample(logits[0]))


class SlotDispatch:
    """Completion handle for one in-flight :meth:`Engine.slot_step_async`
    dispatch.

    ``tokens_dev`` is the (steps, B) sampled-id future; ``last_dev`` the
    (B,) final sampled row, kept device-resident so the next pure-decode
    dispatch can consume it via ``feed_dev`` without any host transfer.
    ``fresh`` reports whether this dispatch minted a new XLA executable —
    the scheduler uses it to keep trace+compile walls out of its
    step-time EMA.  ``wait()`` is the blocking edge (idempotent): it runs
    :meth:`Engine._sync` (fault point + step watchdog), feeds the compile
    histogram on a fresh executable, stamps the engine's
    ``last_slot_dispatch_ms``, and returns the tokens as one host array —
    the single device→host transfer a dispatch pays.
    """

    __slots__ = ("_engine", "tokens_dev", "last_dev", "t", "steps",
                 "fresh", "enqueued_at", "ready_at", "_out")

    def __init__(self, engine, tokens_dev, last_dev, *, t: int, steps: int,
                 fresh: bool, enqueued_at: float):
        self._engine = engine
        self.tokens_dev = tokens_dev
        self.last_dev = last_dev
        self.t = t
        self.steps = steps
        self.fresh = fresh
        self.enqueued_at = enqueued_at  # perf_counter at enqueue
        self.ready_at: float | None = None
        self._out: np.ndarray | None = None

    def wait(self) -> np.ndarray:
        """Block until the dispatch lands; returns the (steps, B) ids."""
        if self._out is not None:
            return self._out
        eng = self._engine
        eng._sync(self.tokens_dev, "slot step")
        t1 = time.perf_counter()
        self.ready_at = t1
        if self.fresh:  # first call blocked through trace + compile
            obs_metrics.ENGINE_COMPILE_S.observe(t1 - self.enqueued_at)
        # enqueue→ready span, read by the scheduler's slot timeline
        # (obs/flight.py); for an overlapped dispatch it includes the
        # predecessor still executing, so it bounds device time from above
        eng.last_slot_dispatch_ms = (t1 - self.enqueued_at) * 1e3
        obs_trace.record("slot_step", self.enqueued_at, t1,
                         t=self.t, steps=self.steps)
        self._out = np.asarray(self.tokens_dev)  # (steps, B)
        return self._out


class SlotVerifyDispatch:
    """Completion handle for one in-flight
    :meth:`Engine.slot_verify_async` dispatch.

    ``preds_dev`` (B, T) holds the model's prediction at every fed
    position, ``accepted_dev`` (B,) the per-row count of leading drafts
    that matched, and ``last_dev`` (B,) the verified next token
    (``preds[r, accepted[r]]``) kept device-resident for onward feeding.
    ``wait()`` mirrors :class:`SlotDispatch.wait` — fault point + step
    watchdog via :meth:`Engine._sync`, compile-histogram feed on a fresh
    executable, ``last_slot_dispatch_ms`` — and returns
    ``(preds, accepted)`` as host arrays in one boundary crossing.
    """

    __slots__ = ("_engine", "preds_dev", "accepted_dev", "last_dev", "t",
                 "fresh", "enqueued_at", "ready_at", "_out")

    def __init__(self, engine, preds_dev, accepted_dev, last_dev, *,
                 t: int, fresh: bool, enqueued_at: float):
        self._engine = engine
        self.preds_dev = preds_dev
        self.accepted_dev = accepted_dev
        self.last_dev = last_dev
        self.t = t
        self.fresh = fresh
        self.enqueued_at = enqueued_at  # perf_counter at enqueue
        self.ready_at: float | None = None
        self._out: tuple[np.ndarray, np.ndarray] | None = None

    def wait(self) -> tuple[np.ndarray, np.ndarray]:
        """Block until the verify lands; returns ``(preds (B, T),
        accepted (B,))`` as host int32 arrays."""
        if self._out is not None:
            return self._out
        eng = self._engine
        eng._sync(self.preds_dev, "slot verify")
        t1 = time.perf_counter()
        self.ready_at = t1
        if self.fresh:  # first call blocked through trace + compile
            obs_metrics.ENGINE_COMPILE_S.observe(t1 - self.enqueued_at)
        eng.last_slot_dispatch_ms = (t1 - self.enqueued_at) * 1e3
        obs_trace.record("slot_verify", self.enqueued_at, t1, t=self.t)
        self._out = (np.asarray(self.preds_dev),
                     np.asarray(self.accepted_dev))
        return self._out
