"""Deterministic fault injection for the serving/runtime path.

The reference engine's failure modes are untestable by construction — a
stalled socket blocks the whole cluster (socket.cpp blocking read loop)
and there is no way to *make* a socket stall on demand, so degraded-mode
behavior is only ever exercised by production incidents.  This module is
the antidote for the TPU port: a process-global registry of named **fault
points** compiled into the hot paths, each a no-op until a test (or the
``DLLAMA_FAULTS`` environment variable) arms it.

Fault points (the arming side never needs code changes to add more —
``fire()`` takes any name — but these are the ones instrumented today):

* ``server.read_body``      — before the HTTP handler reads the request
  body (server/api.py); a ``raise:TimeoutError`` here is a stalled client.
* ``server.emit_delta``     — before each SSE/stream delta write; a
  ``disconnect`` here is a client that went away mid-stream.
* ``engine.device_step``    — at every device-step synchronization point
  (Engine._sync: prefill, decode chunk fetch, batch chunk fetch); a
  ``delay`` here is a slow/hung device step, ``nan`` poisons the logits.
* ``distributed.initialize``— before ``jax.distributed.initialize``
  (parallel/distributed.py); a ``raise:ConnectionError`` here is the
  coordinator not being up yet (the *normal* case under the reference's
  "workers first, then root" start-order contract).
* ``io.read_tensor``        — on every ``MFile.raw`` tensor read
  (io/mfile.py); ``corrupt`` flips a byte of the returned buffer — the
  deterministic stand-in for storage corruption the checksum manifest
  must catch.
* ``spec.propose``          — in the speculative-decoding proposer
  (runtime/spec.py) before drafts are returned; ``corrupt`` replaces
  every slot's draft with adversarial tokens chosen to never match the
  target model — the reject-storm worst case for the verify path.
* ``engine.numeric``        — at the engine's logits numeric guard
  (runtime/engine.py, ``--numeric-checks``); ``nan`` poisons the checked
  logits so the ``NumericFault`` path is testable without real
  corruption.
* ``kv.spill``              — in the KV tiering path (runtime/
  scheduler.py, ``_spill_slot_locked``) before a victim slot's pages
  move to the host pool; a ``delay`` here is a slow D2H drain (the
  spilled consumer's stall window), a ``raise`` aborts the spill and
  the grow ladder falls back to preemption — honest queueing either
  way, never wrong bytes.
* ``sched.host_fanout``     — in the slot scheduler's token fanout
  (runtime/scheduler.py) after a dispatch lands; a ``delay`` here
  widens the host gap the overlapped pipeline must hide.
* ``pod.respawn``           — in the serve-pod supervisor
  (router/pod.py) before a dead/hung replica is respawned; a
  ``raise``/``delay`` here is a respawn that fails or stalls, the
  injectable stand-in for "the replacement process cannot start"
  (exec failure, device still held by the corpse).  The supervisor
  treats a raising respawn as another death in the crash-loop window.

Spec grammar (``DLLAMA_FAULTS`` or :meth:`FaultRegistry.install`)::

    spec     := entry ("," entry)*
    entry    := point "=" action [":" arg] ["@" skip] ["x" times]
    action   := "delay" | "raise" | "disconnect" | "nan" | "corrupt"

* ``delay:SECONDS``  — sleep that long at the point.
* ``raise:ExcName[:message]`` — raise the named exception (one of
  ``ConnectionError, TimeoutError, BrokenPipeError, ConnectionResetError,
  OSError, RuntimeError, ValueError``; default :class:`FaultInjected`).
* ``disconnect``     — raise ``BrokenPipeError`` (a dead peer).
* ``nan``            — ask the call site to poison its value (the site
  reads the action list ``fire()`` returns; ``engine.device_step`` and
  ``engine.numeric`` honor it, by NaN-filling the fetched logits).
* ``corrupt``        — ask the call site to corrupt its value
  (``io.read_tensor`` flips a byte; ``spec.propose`` swaps the drafts
  for adversarial tokens).
* ``@skip``          — stay dormant for the first ``skip`` hits (fire
  starting on hit ``skip+1``).
* ``xtimes``         — fire at most ``times`` times, then go dormant
  (default: every hit after ``skip``).

Example: ``DLLAMA_FAULTS="engine.device_step=delay:0.5@2x3"`` sleeps
500 ms on device-step hits 3, 4 and 5 only.

Everything is deterministic: hit counters, not randomness, decide when a
fault fires, so a test that arms ``disconnect@1`` sees the disconnect on
exactly the second delta every run.  The registry is thread-safe (the
threaded API server fires points from request threads).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from ..obs.log import get_logger

_log = get_logger("runtime.faults")


class FaultInjected(RuntimeError):
    """Default exception for a ``raise`` action with no exception name."""


#: exceptions a ``raise:`` action may name — the set the serving paths
#: classify (connection-ish retried/mapped, the rest surfaced as bugs)
_EXCEPTIONS: dict[str, type[BaseException]] = {
    "ConnectionError": ConnectionError,
    "TimeoutError": TimeoutError,
    "socket.timeout": TimeoutError,  # alias since 3.10
    "BrokenPipeError": BrokenPipeError,
    "ConnectionResetError": ConnectionResetError,
    "OSError": OSError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "FaultInjected": FaultInjected,
}

_ACTIONS = ("delay", "raise", "disconnect", "nan", "corrupt")


@dataclass
class Fault:
    """One armed fault: where, what, and its deterministic firing window."""
    point: str
    action: str
    arg: str | None = None
    skip: int = 0            # dormant for the first `skip` hits
    times: int | None = None  # fire at most this many times (None = forever)
    hits: int = 0
    fired: int = 0

    def should_fire(self) -> bool:
        if self.hits <= self.skip:
            return False
        return self.times is None or self.fired < self.times

    def perform(self) -> str | None:
        """Execute the side effect; returns the action name for call sites
        that transform values (``nan``) rather than raise/sleep."""
        if self.action == "delay":
            time.sleep(float(self.arg or 0.0))
            return None
        if self.action == "raise":
            name, _, msg = (self.arg or "FaultInjected").partition(":")
            exc = _EXCEPTIONS.get(name, FaultInjected)
            raise exc(msg or f"injected fault at {self.point}")
        if self.action == "disconnect":
            raise BrokenPipeError(f"injected disconnect at {self.point}")
        return self.action  # "nan": the call site applies it


def parse_spec(spec: str) -> list[Fault]:
    """Parse the ``DLLAMA_FAULTS`` grammar into :class:`Fault` objects.

    Raises ``ValueError`` with the offending entry on any malformed spec —
    a silently dropped fault would make a drill pass vacuously.
    """
    import re
    pat = re.compile(r"^(?P<point>[\w.]+)=(?P<action>[a-z]+)"
                     r"(?::(?P<arg>.+?))?(?:@(?P<skip>\d+))?"
                     r"(?:x(?P<times>\d+))?$")
    faults = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        m = pat.match(entry)
        if not m:
            raise ValueError(
                f"bad fault entry {entry!r}: expected "
                "point=action[:arg][@skip][xtimes]")
        action, arg = m["action"], m["arg"]
        skip = int(m["skip"] or 0)
        times = int(m["times"]) if m["times"] else None
        if action not in _ACTIONS:
            raise ValueError(
                f"bad fault entry {entry!r}: unknown action {action!r} "
                f"(expected one of {', '.join(_ACTIONS)})")
        if action == "raise" and arg:
            name = arg.partition(":")[0]
            if name not in _EXCEPTIONS:
                raise ValueError(
                    f"bad fault entry {entry!r}: unknown exception {name!r}")
        faults.append(Fault(m["point"], action, arg, skip, times))
    return faults


class FaultRegistry:
    """Process-global, test-controllable set of armed faults."""

    def __init__(self):
        self._lock = threading.Lock()
        self._faults: list[Fault] = []

    # -- arming ---------------------------------------------------------
    def install(self, spec: str | Fault | list[Fault]) -> None:
        """Arm faults from a spec string, one Fault, or a list (additive)."""
        if isinstance(spec, str):
            new = parse_spec(spec)
        elif isinstance(spec, Fault):
            new = [spec]
        else:
            new = list(spec)
        with self._lock:
            self._faults.extend(new)

    def install_env(self, env: dict | None = None) -> bool:
        """Arm from ``DLLAMA_FAULTS`` if set; returns True when it was."""
        spec = (env or os.environ).get("DLLAMA_FAULTS", "")
        if not spec:
            return False
        self.install(spec)
        return True

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()

    def active(self) -> bool:
        with self._lock:
            return bool(self._faults)

    def snapshot(self) -> list[Fault]:
        with self._lock:
            return [Fault(f.point, f.action, f.arg, f.skip, f.times,
                          f.hits, f.fired) for f in self._faults]

    # -- the hot-path hook ----------------------------------------------
    def fire(self, point: str) -> list[str]:
        """Hit ``point``: every armed fault there advances its counter and,
        if inside its firing window, performs its action.  Raising actions
        raise from here; the returned list carries value-transform actions
        (``nan``) for the call site.  A registry with nothing armed is a
        single locked list check — cheap enough for per-chunk paths.
        """
        due = []
        with self._lock:
            if not self._faults:
                return []
            for f in self._faults:
                if f.point != point:
                    continue
                f.hits += 1
                if f.should_fire():
                    f.fired += 1
                    due.append(f)
        actions = []
        for f in due:  # perform outside the lock: delay/raise must not block
            _log.info("fault_fired", extra={
                "point": f.point, "action": f.action, "arg": f.arg,
                "fired": f.fired})
            a = f.perform()  # other points, and raise escapes here
            if a is not None:
                actions.append(a)
        return actions


#: THE process-global registry every instrumented call site fires into.
#: ``DLLAMA_FAULTS`` arms it at import so the same spec drives a live
#: server (``python -m dllama_tpu.server.api``), the CLI, and the tests.
FAULTS = FaultRegistry()
FAULTS.install_env()


class injected:
    """``with injected("point=action"):`` — arm for a block, then disarm.

    ``__exit__`` clears the WHOLE registry rather than only what it armed:
    test isolation wants a clean slate, and tests never arm faults they
    don't own.
    """

    def __init__(self, spec: str):
        self.spec = spec

    def __enter__(self) -> FaultRegistry:
        FAULTS.install(self.spec)
        return FAULTS

    def __exit__(self, *exc) -> None:
        FAULTS.clear()
