"""Per-slot draft proposers for speculative decoding under the slot
scheduler.

The engine already proves exact speculation in lockstep mode
(:meth:`Engine.generate_pld`): propose K tokens, verify the whole window
in one T=K+1 forward, accept the leading match — every emitted token is
a true greedy token, speculation only changes how many positions one
weight read verifies (Leviathan et al. 2023).  This module is the
serving-side half: a :class:`Proposer` maintains per-slot draft state
for the continuous-batching scheduler (runtime/scheduler.py), which
collects proposals after each dispatch lands and turns the next dispatch
into a ragged verify burst (:meth:`Engine.slot_verify_async`).

Two implementations:

* :class:`PromptLookupProposer` — Saxena's prompt-lookup decoding: a
  per-slot latest-occurrence n-gram index over prompt + produced
  tokens, maintained incrementally at land time (the same O(1)-lookup
  structure as ``generate_pld_stream``, one instance per slot).  Zero
  extra model cost; wins on repetitive continuations (summarization,
  code, quoted context).
* :class:`DraftModelProposer` — a second, smaller :class:`Engine` (a
  tiny-llama drafting for a llama2-7b target) whose slot-aligned KV is
  kept in sync by replaying accepted tokens.  Draft rows ride the same
  causal-ceiling contract as the target: tokens the verifier rejected
  left stale draft KV above the synced ceiling, masked until real
  tokens overwrite them, so rejection needs no rollback on either
  model.

Contract with the scheduler: ``sync`` is called once per landed
dispatch per live decode slot (idempotent; a ``rid`` change rebuilds
from scratch — slot reuse, hand-off import, un-park), ``propose`` is
called with the slots wanting drafts this round, and ``reset`` at every
flush point (retire, cancel, preemption park, hand-off export).  A
proposer never sees a mid-prefill slot and never blocks correctness:
wrong or absent drafts merely verify short, the emitted stream is the
model's own greedy output either way (tests/test_spec.py pins byte
parity against ``--spec off``).

The ``spec.propose`` fault point (runtime/faults.py) supports the
``spec_reject_storm`` drill: the ``corrupt`` action replaces every
proposal with adversarial tokens, collapsing the accept ratio while the
served bytes stay identical.
"""

from __future__ import annotations

import numpy as np

from .faults import FAULTS

# pre-feed width for draft-model catch-up (prompt replay, resumed
# requests): bounded so the drafting dispatch rides a handful of
# power-of-two compile shapes, like the scheduler's prefill chunks
_DRAFT_CHUNK = 32


class Proposer:
    """Per-slot draft state + proposal generation; see module docstring.

    Subclasses keep whatever per-slot state they need in
    ``self._states`` keyed by slot index and implement
    :meth:`_propose_one` / :meth:`propose`.
    """

    #: label for metrics (``sched_spec_accepted_total{proposer=...}``)
    name = "base"

    def __init__(self, vocab: int):
        self.vocab = max(2, int(vocab))
        self._states: dict[int, object] = {}

    # -- scheduler-facing API ------------------------------------------
    def sync(self, slot: int, rid: str, prompt: list[int],
             emitted: list[int]) -> None:
        """Bring slot ``slot``'s state up to date with the request's
        full sequence (prompt + emitted completion).  Called at land
        time with the freshly fanned-out tokens appended; a ``rid``
        change (slot reuse, import, resume) rebuilds from scratch."""
        raise NotImplementedError

    def propose(self, want: dict[int, int]) -> dict[int, list[int]]:
        """Return up to ``want[slot]`` draft tokens per requested slot.
        Slots may be omitted from the result (no candidate continuation
        is a valid answer — the row decodes normally)."""
        raise NotImplementedError

    def reset(self, slot: int) -> None:
        """Drop slot ``slot``'s state (flush point: retire, cancel,
        park, export).  In-flight drafts die here — they are never
        exported and never outlive the request that seeded them."""
        self._states.pop(slot, None)

    def reset_all(self) -> None:
        self._states.clear()

    # -- fault injection -----------------------------------------------
    def _storm(self, want: dict[int, int],
               props: dict[int, list[int]]) -> dict[int, list[int]]:
        """``spec.propose`` fault point: the ``corrupt`` action swaps
        every wanted slot's proposal for adversarial tokens (off-by-one
        from the last real token, so they near-never match the model's
        argmax) — the reject-storm drill's worst case."""
        if "corrupt" in FAULTS.fire("spec.propose"):
            for slot, k in want.items():
                st = self._states.get(slot)
                seq = getattr(st, "seq", None) or [0]
                props[slot] = [int((seq[-1] + 1 + j) % self.vocab)
                               for j in range(k)]
        return props


class _PLDState:
    __slots__ = ("rid", "seq", "n_prompt", "index", "indexed")

    def __init__(self, rid, seq, n_prompt, ngram):
        self.rid = rid
        self.seq = seq                 # prompt + emitted, grown in place
        self.n_prompt = n_prompt
        self.index: dict[tuple, int] = {}  # ngram -> position AFTER match
        self.indexed = ngram - 1


class PromptLookupProposer(Proposer):
    """Prompt-lookup drafts: the continuation after the latest earlier
    occurrence of the current ``ngram``-suffix in this slot's own
    sequence.  Same index discipline as ``generate_pld_stream`` — only
    positions ``<= len(seq) - 1`` are indexed, so a lookup never matches
    the suffix against itself."""

    name = "pld"

    def __init__(self, *, ngram: int = 2, vocab: int = 1 << 30):
        super().__init__(vocab)
        self.ngram = max(1, int(ngram))

    def sync(self, slot, rid, prompt, emitted):
        st = self._states.get(slot)
        if st is None or st.rid != rid:
            self._states[slot] = _PLDState(rid, list(prompt) + list(emitted),
                                           len(prompt), self.ngram)
            return
        st.seq.extend(emitted[len(st.seq) - st.n_prompt:])

    def _extend_index(self, st: _PLDState) -> None:
        hi = len(st.seq) - 1
        for p in range(max(st.indexed + 1, self.ngram), hi + 1):
            st.index[tuple(st.seq[p - self.ngram:p])] = p
        st.indexed = max(st.indexed, hi)

    def propose(self, want):
        props: dict[int, list[int]] = {}
        for slot, k in want.items():
            st = self._states.get(slot)
            if st is None or len(st.seq) <= self.ngram or k < 1:
                continue
            self._extend_index(st)
            i = st.index.get(tuple(st.seq[-self.ngram:]))
            if i is None:
                continue
            cand = st.seq[i:i + k]
            if cand:
                props[slot] = [int(t) for t in cand]
        return self._storm(want, props)


class _DraftState:
    __slots__ = ("rid", "seq", "n_prompt", "synced", "fed", "drafted")

    def __init__(self, rid, seq, n_prompt):
        self.rid = rid
        self.seq = seq
        self.n_prompt = n_prompt
        self.synced = 0     # seq tokens whose draft KV is valid
        self.fed = 0        # seq tokens the last drafting forward consumed
        self.drafted: list[int] = []  # tokens drafted by that forward


class DraftModelProposer(Proposer):
    """Drafts from a second, smaller engine sharing the target's slot
    geometry (same ``batch``; contiguous KV — the draft pool is tiny).

    Sync-by-replay: each slot tracks ``synced``, the count of sequence
    tokens whose draft KV is valid.  At propose time the unsynced delta
    (accepted tokens the draft has not consumed — after admission, the
    whole prompt) is fed through the draft in one ragged slot dispatch,
    then ``k`` greedy draft steps run on device.  Draft tokens the
    verifier later rejects leave stale draft KV above ``synced`` —
    masked by the causal ceiling exactly like target-side rejection, so
    a miss costs nothing to undo on either model.  Rows whose delta
    cannot fit the draft context stop proposing (and re-ride as inert
    neighbors); everyone else drafts in the same batched dispatch."""

    name = "draft"

    def __init__(self, engine):
        super().__init__(engine.cfg.vocab_size)
        if getattr(engine, "paged", False):
            raise ValueError("draft engine must be contiguous (the draft "
                             "KV pool is slot-aligned, not paged)")
        if engine.sp > 1:
            raise ValueError("draft engine must be sp=1")
        self.engine = engine

    def sync(self, slot, rid, prompt, emitted):
        st = self._states.get(slot)
        if st is None or st.rid != rid:
            self._states[slot] = _DraftState(
                rid, list(prompt) + list(emitted), len(prompt))
            return
        new = emitted[len(st.seq) - st.n_prompt:]
        st.seq.extend(new)
        if st.drafted:
            # the drafting forward wrote KV for drafted[:-1] (the last
            # draft was sampled but never fed back); credit the leading
            # drafts the verifier actually kept
            m = 0
            while m < len(new) and m < len(st.drafted) \
                    and new[m] == st.drafted[m]:
                m += 1
            st.synced = st.fed + min(m, len(st.drafted) - 1)
            st.drafted = []

    def propose(self, want):
        eng = self.engine
        b, L = eng.batch, eng.seq_len
        rows = []  # (slot, state, delta, k)
        for slot, k in sorted(want.items()):
            st = self._states.get(slot)
            if st is None or k < 1 or slot >= b:
                continue
            delta = st.seq[st.synced:]
            # conservative room check: delta feed (+ bucket padding) and
            # the k draft steps must all fit the draft context
            if not delta \
                    or st.synced + len(delta) + k + _DRAFT_CHUNK > L:
                continue
            rows.append((slot, st, delta, k))
        if not rows:
            return self._storm(want, {})
        k = max(r[3] for r in rows)
        temps = np.zeros((b,), np.float32)
        topps = np.full((b,), 0.9, np.float32)

        def base_rows(t):
            """Ride-along positions for rows not fed this dispatch:
            each live draft row parks at its own ceiling (garbage
            written above ``synced`` is overwritten before it is ever
            attendable — the slot-reuse invariant); a row too close to
            the context edge abandons its draft state instead."""
            pos = np.zeros((b,), np.int32)
            for s, st in list(self._states.items()):
                if s >= b:
                    continue
                if st.synced + t > L:
                    st.synced, st.fed, st.drafted = 0, 0, []
                pos[s] = st.synced
            return pos

        off = {slot: 0 for slot, *_ in rows}
        # pre-feed long deltas (prompt replay / resume catch-up) in
        # fixed-width chunks, always leaving >= 1 token so the drafting
        # dispatch below has a window to sample from
        while max(len(d) - off[s] for s, _, d, _ in rows) > _DRAFT_CHUNK:
            t = _DRAFT_CHUNK
            tokens = np.zeros((b, t), np.int32)
            nv = np.ones((b,), np.int32)
            pos = base_rows(t)
            for slot, st, delta, _ in rows:
                c = min(t, len(delta) - off[slot] - 1)
                if c < 1:
                    continue
                tokens[slot, :c] = delta[off[slot]:off[slot] + c]
                nv[slot] = c
                pos[slot] = st.synced + off[slot]
                off[slot] += c
            eng.slot_step(tokens, pos, nv, temps_np=temps, topps_np=topps,
                          steps=1)
        rem = {s: len(d) - off[s] for s, _, d, _ in rows}
        t = 1 << max(0, max(rem.values()) - 1).bit_length()
        tokens = np.zeros((b, t), np.int32)
        nv = np.ones((b,), np.int32)
        pos = base_rows(t)
        for slot, st, delta, _ in rows:
            tokens[slot, :rem[slot]] = delta[off[slot]:]
            nv[slot] = rem[slot]
            pos[slot] = st.synced + off[slot]
        toks = eng.slot_step(tokens, pos, nv, temps_np=temps,
                             topps_np=topps, steps=k)  # (k, b)
        props: dict[int, list[int]] = {}
        for slot, st, delta, kw in rows:
            drafts = [int(toks[j, slot]) for j in range(k)]
            st.fed = st.synced + len(delta)
            st.drafted = drafts
            props[slot] = drafts[:kw]
        return self._storm(want, props)


def make_proposer(mode: str, engine, draft_engine=None) -> Proposer | None:
    """Build the proposer for ``--spec``: ``off`` → None, ``pld`` →
    prompt lookup over the target's vocab, ``draft`` → draft-model
    speculation (requires ``draft_engine``)."""
    if mode in (None, "", "off"):
        return None
    if mode == "pld":
        return PromptLookupProposer(vocab=engine.cfg.vocab_size)
    if mode == "draft":
        if draft_engine is None:
            raise ValueError("--spec draft needs --draft-model")
        return DraftModelProposer(draft_engine)
    raise ValueError(f"unknown speculation mode {mode!r}")
