"""Continuous-batching slot scheduler over the slot-addressable batch
engine.

The server's engine mutex serializes whole *requests*: while one stream
decodes, every other admitted request waits, even though a lockstep batch
step prices B rows at roughly one weight read (runtime/engine.py
``generate_batch_stream``).  Iteration-level scheduling (Orca, OSDI'22;
vLLM's slot form, SOSP'23) moves the admission boundary from the request
to the *decode step*: this scheduler owns the ``--batch-slots`` engine and
drives :meth:`Engine.slot_step` from one daemon thread, admitting a new
request into any free slot between steps and retiring finished ones
without disturbing their neighbors.

Mechanics per dispatch:

* every active slot is either **prefilling** (its prompt feeds in chunks
  of ``--sched-prefill-chunk`` tokens, interleaved with its neighbors'
  decode tokens in the same mixed forward — bounding the inter-token
  latency a join adds to running streams) or **decoding** (feeds its
  previous sample);
* when *no* slot is mid-prefill, decode runs in on-device bursts
  (``steps > 1`` inside one XLA program, decode_chunk's amortization);
  with work waiting in the queue the burst is clamped so a finishing
  stream frees its slot within ``--sched-max-wait-ms``;
* a freed slot is reused by handing its row position 0 again — the
  previous occupant's stale KV sits above the newcomer's causal ceiling
  (ops/attention.py ``slot_gqa_attention_at``), so per-slot reset is
  free and the cache is never zeroed.

Each submitted request gets a :class:`Ticket` — a thread-safe token
stream the HTTP handler consumes.  Cancellation (client disconnect, stop
string, deadline) flips a flag the loop honors at the next step
boundary, freeing the slot mid-generation.  A dispatch failure
(StepTimeout, device fault) retires every active slot with the error on
its ticket and the loop keeps serving — the write-before-visible
invariant makes any cache garbage from the failed step unobservable.

Greedy determinism contract: a temperature-0 request produces the same
tokens whichever slot it lands in and whatever its neighbors are doing
(tests/test_scheduler.py pins this).  Sampled requests draw from the
engine's shared counter-based RNG stream, so their draws depend on
co-scheduling — per-request seeds are not reproducible here (use the
mutex path for that); this is the standard continuous-batching trade.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from collections import deque

import numpy as np

from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics, trace as obs_trace
from ..obs.log import get_logger, new_request_id, request_id_var
from .pagepool import PagePool, PagePoolExhausted, RadixTree

_log = get_logger("runtime.scheduler")

_DONE = object()  # ticket stream terminator


class SchedulerClosed(RuntimeError):
    """submit() after begin_drain()/close(): no new work is admitted."""


class SchedulerSaturated(RuntimeError):
    """submit() with the wait queue at its bound (the server maps this to
    429, same as mutex-path admission)."""


class Ticket:
    """One request's handle: a bounded-latency token stream plus the
    finish verdict.  Produced by the scheduler thread, consumed by the
    HTTP handler thread; ``cancel`` may be called from either side."""

    def __init__(self, prompt, max_new, temperature, top_p, eos_ids,
                 deadline):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.eos_ids = tuple(eos_ids)
        self.deadline = deadline  # time.monotonic() or None
        self.finish: str | None = None  # stop/length/timeout/aborted/error/handoff
        self.error: BaseException | None = None
        self.slot: int | None = None
        self.submitted_at = time.monotonic()
        # hand-off state (runtime/snapshot.py DLREQ01): the server parks
        # its stop strings here so a drain-time export can ship them, and
        # every emitted completion token is kept so the importing replica
        # can rebuild the full decode/stop-scan state
        self.stop: list[str] = []
        self.emitted: list[int] = []
        # the submitting thread's X-Request-Id rides the ticket onto the
        # scheduler thread, where the contextvar is not set — spans, logs
        # and the flight record all stamp this one grep-able ID
        self.rid: str = request_id_var.get() or new_request_id()
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._cancel: str | None = None
        self._on_cancel = None  # scheduler wakeup, bound at submit

    def cancel(self, reason: str = "aborted") -> None:
        """Ask the scheduler to retire this request at the next step
        boundary (idempotent).  Safe before admission: a queued ticket is
        dropped without ever occupying a slot."""
        if self._cancel is None and self.finish is None:
            self._cancel = reason
            if self._on_cancel is not None:
                self._on_cancel()

    def tokens(self):
        """Yield completion token ids until the request retires.  After
        the generator ends, ``finish`` holds the verdict; a scheduler-side
        failure re-raises here on the consumer's thread."""
        while True:
            item = self._q.get()
            if item is _DONE:
                break
            yield item
        if self.error is not None:
            raise self.error


class _Slot:
    __slots__ = ("ticket", "pos", "fed", "produced", "last", "pages",
                 "prefix_tokens", "inserted")

    def __init__(self):
        self.ticket: Ticket | None = None
        self.pos = 0        # this row's cache clock
        self.fed = 0        # prompt tokens consumed so far
        self.produced = 0   # completion tokens emitted
        self.last = 0       # previous sample (decode feedback)
        self.pages: list[int] = []   # paged mode: owned pool pages
        self.prefix_tokens = 0       # prompt tokens bound from the radix tree
        self.inserted = False        # prompt pages handed to the tree yet?


class SlotScheduler:
    """Owns the batch engine; see the module docstring.  ``max_queue``
    bounds requests waiting for a slot (beyond it submit() raises
    :class:`SchedulerSaturated`)."""

    def __init__(self, engine, *, prefill_chunk: int = 16,
                 max_wait_ms: float = 50.0, decode_burst: int = 16,
                 max_queue: int = 32, prefix_reuse: bool = True):
        if engine.sp > 1:
            raise ValueError("slot scheduling is not supported on sp meshes")
        if engine.cache.quantized:
            raise ValueError("slot scheduling needs a dense KV cache")
        self.engine = engine
        self.slots = [_Slot() for _ in range(engine.batch)]
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.max_wait_ms = float(max_wait_ms)
        self.decode_burst = max(1, int(decode_burst))
        self.max_queue = max(1, int(max_queue))
        # paged engine (engine.kv_pages > 0): the scheduler owns the page
        # bookkeeping — a refcounted PagePool plus (prefix_reuse) a radix
        # tree that turns repeated prompt prefixes into shared pages
        # (runtime/pagepool.py).  Pages are reserved at admission for the
        # whole request (prompt + budget), so a dispatch can never fail on
        # allocation and exhaustion surfaces as queueing → 429.
        self.paged = bool(getattr(engine, "paged", False))
        self.pool: PagePool | None = None
        self.prefix_cache: RadixTree | None = None
        if self.paged:
            self.pool = PagePool(engine.kv_pages, engine.kv_page_size)
            if prefix_reuse:
                self.prefix_cache = RadixTree(self.pool)
            self._page_tables = np.zeros(
                (engine.batch, engine.max_pages_per_slot), np.int32)
            obs_metrics.KV_PAGES_TOTAL.set(self.pool.capacity)
            obs_metrics.KV_PAGES_IN_USE.set(0)
        self._queue: deque[Ticket] = deque()
        self._cond = threading.Condition()
        # serializes engine cache access between the dispatch loop (whose
        # jit step donates the cache buffer) and the hand-off export/
        # import paths, which read/write pool pages from other threads.
        # Scoped strictly around the device calls — never held while
        # taking self._cond, so the two locks cannot deadlock.
        self._engine_lock = threading.Lock()
        self._draining = False
        self._stop = False
        self._idle = threading.Event()  # set while paused with empty slots
        self._paused = 0
        self._step_ms_ema: float | None = None
        # goodput accounting: every ms between the first and the latest
        # dispatch lands in exactly one component (see obs/metrics.py)
        self._first_dispatch_at: float | None = None   # perf_counter
        self._last_dispatch_end: float | None = None   # perf_counter
        self._idle_accum = 0.0     # seconds slept in _cond.wait since last dispatch
        self._comp = {"prefill": 0.0, "decode": 0.0, "pad": 0.0,
                      "host_gap": 0.0, "idle": 0.0}
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dllama-slot-scheduler")
        self._thread.start()

    # -- submission-side API -------------------------------------------
    def submit(self, prompt: list[int], max_new: int, *,
               temperature: float = 0.0, top_p: float = 0.9,
               eos_ids: tuple[int, ...] = (),
               deadline: float | None = None) -> Ticket:
        """Queue one request; returns its :class:`Ticket` immediately.
        ``deadline`` is a ``time.monotonic()`` instant (the server's
        per-request deadline); an expired request retires with finish
        ``timeout`` and whatever tokens it produced."""
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError("max_new must be positive")
        if self.pool is not None:
            # a request whose full reservation exceeds the pool would wait
            # forever — that is a sizing error, not transient saturation
            need = min(len(prompt) + max_new, self.engine.seq_len)
            n_pages = -(-need // self.pool.page_size)
            if n_pages > self.pool.capacity:
                from .engine import ContextOverflow
                raise ContextOverflow(
                    f"request needs {n_pages} KV pages but the pool has "
                    f"{self.pool.capacity}; raise --kv-pages or shorten "
                    "the request")
        t = Ticket(prompt, max_new, temperature, top_p, eos_ids, deadline)
        with self._cond:
            if self._stop or self._draining:
                raise SchedulerClosed("scheduler is draining")
            # admission runs on the scheduler thread, so just-submitted
            # tickets sit in the queue for one beat even when slots are
            # free — the bound is on work beyond what free slots will
            # immediately absorb, not on that scheduling gap
            free = sum(1 for s in self.slots if s.ticket is None)
            if len(self._queue) >= self.max_queue + (0 if self._paused
                                                     else free):
                raise SchedulerSaturated(
                    f"{len(self._queue)} requests already waiting")
            t._on_cancel = self._wake
            self._queue.append(t)
            self._cond.notify_all()
        obs_flight.submit(t.rid, n_prompt=len(t.prompt), max_new=t.max_new,
                          temperature=t.temperature, source="scheduler")
        return t

    def occupancy(self) -> dict:
        """Live state for /health and the over-n error body."""
        with self._cond:
            active = sum(1 for s in self.slots if s.ticket is not None)
            out = {"slots": len(self.slots), "active": active,
                   "queued": len(self._queue)}
            if self.pool is not None:
                out["kv_pages_total"] = self.pool.capacity
                out["kv_pages_free"] = self.pool.available
                if self.prefix_cache is not None:
                    out["prefix_nodes"] = len(self.prefix_cache)
            return out

    def begin_drain(self, deadline: float | None) -> None:
        """Stop admitting new submissions and clamp every in-flight and
        queued request's deadline — drain then *waits* for the slots via
        the handlers consuming their tickets."""
        with self._cond:
            self._draining = True
            for t in list(self._queue):
                t.deadline = min(t.deadline, deadline) \
                    if (t.deadline and deadline) else (t.deadline or deadline)
            for s in self.slots:
                if s.ticket is not None:
                    t = s.ticket
                    t.deadline = min(t.deadline, deadline) \
                        if (t.deadline and deadline) else (t.deadline or deadline)
            self._cond.notify_all()

    def close(self, timeout: float = 5.0) -> None:
        """Stop the loop; any still-live tickets retire as ``aborted`` so
        no consumer blocks forever."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)

    @contextlib.contextmanager
    def exclusive(self):
        """Park the scheduler and wait until every slot has retired, so
        the caller may run one-shot batch-engine work (list-prompt
        lockstep, n>1 fan-out, logprobs scoring) that resets the shared
        cache.  Admission pauses; queued requests keep their place."""
        with self._cond:
            self._paused += 1
            self._cond.notify_all()
        self._idle.wait()
        try:
            yield
        finally:
            with self._cond:
                self._paused -= 1
                if self._paused == 0:
                    self._idle.clear()
                self._cond.notify_all()

    def _wake(self):
        with self._cond:
            self._cond.notify_all()

    # -- paged state snapshot/restore (runtime/snapshot.py DLSNAP02) ----
    def snapshot_paged(self, path, extra: dict | None = None) -> str:
        """Persist the paged serving state: the pool KV arrays ride the
        engine snapshot, the page tables go as an extra array, and the
        radix tree's token keys + page ids go in the JSON meta.  Call
        with no live slots (drain or ``exclusive()`` first) — snapshots
        of mid-flight requests are not meaningful."""
        if self.pool is None:
            raise ValueError("snapshot_paged on a non-paged scheduler")
        with self._cond:
            if self._active():
                raise RuntimeError("snapshot_paged with live slots; "
                                   "drain first")
            meta = dict(extra or {})
            meta["radix"] = (self.prefix_cache.export()
                             if self.prefix_cache is not None else [])
            return self.engine.snapshot(
                path, extra=meta,
                extra_arrays={"page_tables": self._page_tables.copy()})

    def restore_paged(self, path) -> dict:
        """Restore :meth:`snapshot_paged` state.  The engine validates
        format/fingerprint (pool geometry is part of the fingerprint, so
        a mismatched geometry raises SnapshotMismatch and the caller
        cold-starts); the pool and radix tree are rebuilt from the
        snapshot's tree keys, re-claiming their pages."""
        if self.pool is None:
            raise ValueError("restore_paged on a non-paged scheduler")
        with self._cond:
            if self._active():
                raise RuntimeError("restore_paged with live slots")
            extra = self.engine.restore(path)
            arrs = getattr(self.engine, "restored_arrays", {})
            pt = arrs.get("page_tables")
            if pt is not None and pt.shape == self._page_tables.shape:
                self._page_tables[:] = pt
            self.pool = PagePool(self.engine.kv_pages,
                                 self.engine.kv_page_size)
            if self.prefix_cache is not None:
                self.prefix_cache = RadixTree(self.pool)
                self.prefix_cache.restore(extra.get("radix") or [])
            obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
            return extra

    # -- per-request KV hand-off (runtime/snapshot.py DLREQ01) ----------
    def _export_slot_locked(self, slot_idx: int) -> bytes:
        """Serialize one live slot to a DLREQ01 record (caller holds
        ``self._cond``).  The record carries the slot's written KV pages
        (positions ``[0, pos)``), the full prompt + completion token ids,
        sampling params, remaining deadline, and the engine's sampler RNG
        stream — everything a geometry-compatible peer needs to resume
        decode without re-prefilling."""
        import math

        s = self.slots[slot_idx]
        t = s.ticket
        ps = self.pool.page_size
        n_data = math.ceil(s.pos / ps)
        deadline_left = None
        if t.deadline is not None:
            deadline_left = max(t.deadline - time.monotonic(), 0.0)
        # pages may contain stale values above pos (an in-flight dispatch
        # whose fanout never ran) — harmless, the importer's causal
        # ceiling masks them exactly like slot reuse does
        with self._engine_lock:
            arrays = self.engine.read_pool_pages(s.pages[:n_data])
            arrays["rng_key"] = np.asarray(self.engine._key)
            chunk_counter = self.engine._chunk_counter
        from . import snapshot as snapfmt
        return snapfmt.dumps_request(
            fingerprint=self.engine.handoff_fingerprint(),
            pos=s.pos, chunk_counter=chunk_counter, arrays=arrays,
            extra={
                "rid": t.rid, "prompt": list(t.prompt),
                "completion": list(t.emitted), "max_new": t.max_new,
                "temperature": t.temperature, "top_p": t.top_p,
                "eos_ids": list(t.eos_ids), "stop": list(t.stop),
                "deadline_left": deadline_left,
                "fed": s.fed, "produced": s.produced, "last": s.last,
            })

    def handoff_export_all(self) -> dict[str, bytes]:
        """Drain-time hand-off: export every live slot to a DLREQ01
        record keyed by request id and retire it with finish
        ``handoff``; queued (never-admitted) tickets retire ``handoff``
        with no record — the router re-submits those from scratch, which
        is idempotent because nothing was ever streamed."""
        if self.pool is None:
            return {}
        records: dict[str, bytes] = {}
        with self._cond:
            for i in self._active():
                t = self.slots[i].ticket
                try:
                    records[t.rid] = self._export_slot_locked(i)
                except Exception as e:
                    # an unexportable slot degrades to a plain drain
                    # abort for that request; the fleet must not lose
                    # the other slots over it
                    _log.error("handoff export failed", extra={
                        "rid": t.rid, "error": repr(e)})
                self._retire(i, "handoff")
            while self._queue:
                self._fail_ticket(self._queue.popleft(), "handoff")
            self._cond.notify_all()
        if records:
            _log.info("handoff export", extra={"requests": len(records)})
        return records

    def import_request(self, blob: bytes) -> tuple[Ticket, dict]:
        """Re-bind an exported request (DLREQ01 bytes) into a free slot:
        allocate this pool's own physical pages, write the exported page
        slices into them, and resume the slot's clocks exactly where the
        exporter stopped — continued greedy decode is byte-identical to
        never having moved (tests/test_handoff.py pins this).

        Raises :class:`~dllama_tpu.io.integrity.ArtifactError` on a
        corrupt record, :class:`SnapshotMismatch` on incompatible
        geometry, :class:`SchedulerSaturated` when no slot/pages are
        free, :class:`SchedulerClosed` when this replica is itself
        draining.  Returns ``(ticket, record_extra)``.

        The exporter's sampler RNG stream is restored only when this
        scheduler has no other live work — the engine RNG is shared
        across slots, so rebasing it under co-scheduled requests would
        perturb their draws.  Greedy (temperature-0) requests do not
        consume the stream and hand off byte-identically regardless.
        """
        from . import snapshot as snapfmt

        if self.pool is None:
            raise ValueError("hand-off import needs a paged scheduler "
                             "(--kv-pages)")
        meta, arrays = snapfmt.loads_request(blob)
        eng = self.engine
        want = eng.handoff_fingerprint()
        if meta["fingerprint"] != want:
            raise snapfmt.SnapshotMismatch(
                "<handoff record>", "fingerprint",
                "record is from a replica with incompatible geometry",
                expected=want, got=meta["fingerprint"])
        extra = dict(meta.get("extra", {}))
        prompt = [int(x) for x in extra.get("prompt") or []]
        completion = [int(x) for x in extra.get("completion") or []]
        pos = int(meta["pos"])
        max_new = int(extra.get("max_new", 1))
        fed = int(extra.get("fed", 0))
        produced = int(extra.get("produced", len(completion)))
        if not prompt or max_new < 1 or not (0 <= pos <= eng.seq_len) \
                or not (0 <= fed <= len(prompt)) or produced < 0:
            raise snapfmt.SnapshotMismatch(
                "<handoff record>", "extra",
                "inconsistent request state in hand-off record")
        ps = self.pool.page_size
        n_data = -(-pos // ps)
        pk, pv = arrays.get("pages.k"), arrays.get("pages.v")
        kvshape = eng.cache.k.shape
        want_shape = (kvshape[0], n_data) + tuple(kvshape[2:])
        for name, arr in (("pages.k", pk), ("pages.v", pv)):
            if arr is None or tuple(arr.shape) != want_shape:
                raise snapfmt.SnapshotMismatch(
                    "<handoff record>", f"array {name!r}",
                    "page payload does not match the record position",
                    expected=str(want_shape),
                    got="missing" if arr is None else str(arr.shape))
        need = min(len(prompt) + max_new, eng.seq_len)
        n_total = -(-need // ps)
        if n_total > self.pool.capacity:
            from .engine import ContextOverflow
            raise ContextOverflow(
                f"request needs {n_total} KV pages but the pool has "
                f"{self.pool.capacity}")
        deadline = None
        if extra.get("deadline_left") is not None:
            deadline = time.monotonic() + float(extra["deadline_left"])
        with self._cond:
            if self._stop or self._draining:
                raise SchedulerClosed("scheduler is draining")
            slot_idx = next((i for i, s in enumerate(self.slots)
                             if s.ticket is None), None)
            if slot_idx is None:
                raise SchedulerSaturated("no free slot for hand-off import")
            try:
                pages = self.pool.alloc(n_total)
            except PagePoolExhausted:
                pages = None
                if self.prefix_cache is not None:
                    self.prefix_cache.evict(n_total - self.pool.available)
                    try:
                        pages = self.pool.alloc(n_total)
                    except PagePoolExhausted:
                        pass
            if pages is None:
                raise SchedulerSaturated(
                    "no free KV pages for hand-off import")
            others = any(s.ticket is not None for s in self.slots)
            with self._engine_lock:
                if n_data:
                    eng.write_pool_pages(pages[:n_data],
                                         {"pages.k": pk, "pages.v": pv})
                if not others and not self._queue and "rng_key" in arrays:
                    eng.set_rng(arrays["rng_key"],
                                int(meta["chunk_counter"]))
            t = Ticket(prompt, max_new,
                       float(extra.get("temperature", 0.0)),
                       float(extra.get("top_p", 0.9)),
                       tuple(int(e) for e in extra.get("eos_ids") or ()),
                       deadline)
            t.rid = str(extra.get("rid") or t.rid)
            t.stop = [str(x) for x in extra.get("stop") or []]
            t.emitted = list(completion)
            t._on_cancel = self._wake
            s = self.slots[slot_idx]
            s.ticket = t
            s.pages = pages
            s.prefix_tokens = 0
            # prompt pages become radix-shareable once prefill completes;
            # a decode-phase import never re-inserts (alignment with the
            # exporter's shared prefixes is unknowable here)
            s.inserted = fed >= len(prompt)
            s.pos = pos
            s.fed = fed
            s.produced = produced
            s.last = int(extra.get("last", 0))
            t.slot = slot_idx
            row = self._page_tables[slot_idx]
            row[:] = 0
            row[:len(pages)] = pages
            obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
            obs_metrics.SCHED_SLOT_JOINS.inc(slot_idx)
            self._cond.notify_all()
        obs_flight.submit(t.rid, n_prompt=len(prompt), max_new=max_new,
                          temperature=t.temperature, source="handoff")
        obs_flight.admit(t.rid, slot=slot_idx, queued_ms=0.0,
                         prefix_reused=0)
        ctx = request_id_var.set(t.rid)
        try:
            _log.info("handoff import", extra={
                "slot": slot_idx, "pos": pos, "produced": produced,
                "pages": len(pages)})
        finally:
            request_id_var.reset(ctx)
        return t, extra

    # -- scheduler thread ----------------------------------------------
    def _retire(self, slot_idx: int, reason: str,
                error: BaseException | None = None) -> None:
        s = self.slots[slot_idx]
        t = s.ticket
        if t is None:
            return
        t.finish = reason
        t.error = error
        s.ticket = None
        if self.pool is not None and s.pages:
            # drop this slot's references; pages the radix tree retained
            # stay live (and reusable by the next matching prompt)
            self.pool.decref(s.pages)
            s.pages = []
            self._page_tables[slot_idx][:] = 0
            obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
        obs_metrics.SCHED_SLOT_RETIRES.inc(slot_idx, reason)
        now = time.monotonic()
        obs_trace.record("sched_retire", now, now, rid=t.rid, slot=slot_idx,
                         reason=reason, produced=s.produced)
        # the log record factory stamps the contextvar, so bind the
        # ticket's ID around the call (this thread serves many requests)
        ctx = request_id_var.set(t.rid)
        try:
            _log.info("slot retire", extra={
                "slot": slot_idx, "reason": reason, "produced": s.produced})
        finally:
            request_id_var.reset(ctx)
        obs_flight.retire(t.rid, reason, produced=s.produced, pos=s.pos,
                          error=repr(error) if error is not None else None)
        t._q.put(_DONE)

    def _fail_ticket(self, t: Ticket, reason: str,
                     error: BaseException | None = None) -> None:
        t.finish = reason
        t.error = error
        obs_flight.retire(t.rid, reason, produced=0,
                          error=repr(error) if error is not None else None)
        t._q.put(_DONE)

    def _bind_pages(self, slot_idx: int, t: Ticket) -> bool:
        """Paged admission: match the prompt against the radix tree, then
        reserve every page the request can ever touch (matched prefix +
        fresh pages through ``min(len(prompt) + max_new, seq_len)``).
        Full reservation up front is what keeps exhaustion out of the
        dispatch path — a request that cannot get its pages stays queued
        (False), it never fails mid-decode.  Caller holds the lock."""
        pool = self.pool
        ps = pool.page_size
        prompt = t.prompt
        matched, shared = 0, []
        if self.prefix_cache is not None:
            matched, shared = self.prefix_cache.match(prompt)
            # always leave ≥1 prompt token to feed: the forward over the
            # suffix is what produces the first sampled token.  The dropped
            # block is re-prefilled into a fresh page; the tree keeps its
            # copy (first writer wins on a later insert).
            while matched >= len(prompt):
                matched -= ps
                shared = shared[:-1]
        # shared pages are referenced BEFORE any allocation/eviction so the
        # evictor (which only frees tree-only pages) cannot free a page
        # this admission just matched
        pool.incref(shared)
        need_len = min(len(prompt) + t.max_new, self.engine.seq_len)
        fresh = -(-need_len // ps) - len(shared)
        try:
            new_pages = pool.alloc(fresh)
        except PagePoolExhausted:
            new_pages = None
            if self.prefix_cache is not None:
                self.prefix_cache.evict(fresh - pool.available)
                try:
                    new_pages = pool.alloc(fresh)
                except PagePoolExhausted:
                    pass
        if new_pages is None:
            pool.decref(shared)
            if not getattr(t, "_page_deferred", False):
                t._page_deferred = True
                obs_metrics.KV_POOL_EXHAUSTED.inc()
                ctx = request_id_var.set(t.rid)
                try:
                    _log.info("kv pool exhausted", extra={
                        "need_pages": fresh, "free": pool.available})
                finally:
                    request_id_var.reset(ctx)
            return False
        s = self.slots[slot_idx]
        s.pages = list(shared) + new_pages
        s.prefix_tokens = matched
        s.inserted = False
        # the slot's page-table row: reserved pages first, scratch page 0
        # everywhere else (unreserved entries absorb overshoot writes)
        row = self._page_tables[slot_idx]
        row[:] = 0
        row[:len(s.pages)] = s.pages
        if matched:
            obs_metrics.PREFIX_HITS.inc()
            obs_metrics.PREFIX_TOKENS_REUSED.inc(matched)
            obs_flight.phase(t.rid, "prefix_reuse", tokens=matched,
                             pages=len(shared))
        obs_metrics.KV_PAGES_IN_USE.set(pool.in_use)
        return True

    def _admit_locked(self, now: float) -> None:
        """Move queued tickets into free slots (caller holds the lock)."""
        for i, s in enumerate(self.slots):
            if s.ticket is not None or not self._queue:
                continue
            t = self._queue.popleft()
            if t._cancel is not None:
                self._fail_ticket(t, t._cancel)
                continue
            if t.deadline is not None and now >= t.deadline:
                self._fail_ticket(t, "timeout")
                continue
            if self.pool is not None and not self._bind_pages(i, t):
                # pool exhausted: the ticket keeps its place at the head
                # of the queue and admission stops for this round —
                # retirements free pages and the next pass retries
                self._queue.appendleft(t)
                break
            s.ticket = t
            # paged with a prefix hit: the matched tokens are already in
            # the cache (shared pages), so the clock starts past them and
            # prefill covers only the suffix.  Otherwise both start at 0
            # (_bind_pages sets prefix_tokens; it stays 0 when contiguous).
            s.pos = s.fed = s.prefix_tokens
            s.produced = 0
            s.last = 0
            t.slot = i
            queued_ms = round((now - t.submitted_at) * 1e3, 3)
            obs_metrics.SCHED_SLOT_JOINS.inc(i)
            obs_trace.record("sched_admit", t.submitted_at, now, rid=t.rid,
                             slot=i, queued_ms=queued_ms,
                             n_prompt=len(t.prompt),
                             prefix_reused=s.prefix_tokens)
            ctx = request_id_var.set(t.rid)
            try:
                _log.info("slot join", extra={
                    "slot": i, "n_prompt": len(t.prompt),
                    "queued_ms": queued_ms,
                    "prefix_reused": s.prefix_tokens})
            finally:
                request_id_var.reset(ctx)
            obs_flight.admit(t.rid, slot=i, queued_ms=queued_ms,
                             prefix_reused=s.prefix_tokens)
            obs_metrics.QUEUE_WAIT.observe(max(now - t.submitted_at, 0.0))

    def _active(self) -> list[int]:
        return [i for i, s in enumerate(self.slots) if s.ticket is not None]

    def _account(self, component: str, ms: float) -> None:
        self._comp[component] += ms
        obs_metrics.SCHED_STEP_TIME_MS.inc(component, n=ms)

    def _slot_entries(self, active, prefset, rid_by_slot, emitted) -> list:
        out = []
        for i in range(len(self.slots)):
            if i in rid_by_slot:
                out.append({"slot": i,
                            "phase": "prefill" if i in prefset else "decode",
                            "tokens": emitted.get(i, 0),
                            "request_id": rid_by_slot[i]})
            else:
                out.append({"slot": i, "phase": "pad", "tokens": 0})
        return out

    def wall_window(self) -> tuple[float, float] | None:
        """``perf_counter`` bounds of the accounted span (first dispatch
        start → latest dispatch end); the goodput components sum to this
        interval by construction.  None before the first dispatch."""
        if self._first_dispatch_at is None or self._last_dispatch_end is None:
            return None
        return self._first_dispatch_at, self._last_dispatch_end

    def _run(self) -> None:
        try:
            while True:
                with self._cond:
                    now = time.monotonic()
                    # honor cancels/deadlines first so their slots free up
                    for i in self._active():
                        t = self.slots[i].ticket
                        if t._cancel is not None:
                            self._retire(i, t._cancel)
                        elif t.deadline is not None and now >= t.deadline:
                            self._retire(i, "timeout")
                    for t in [q for q in self._queue
                              if q._cancel is not None
                              or (q.deadline is not None and now >= q.deadline)]:
                        self._queue.remove(t)
                        self._fail_ticket(t, t._cancel or "timeout")
                    if not self._paused:
                        self._admit_locked(now)
                    active = self._active()
                    queued = len(self._queue)
                    obs_metrics.SCHED_SLOTS_OCCUPIED.set(len(active))
                    obs_metrics.SCHED_QUEUE_DEPTH.set(queued)
                    if self._stop:
                        return
                    if not active:
                        if self._paused:
                            self._idle.set()
                        # parked: submissions/cancels/close notify; the
                        # short timeout re-checks queued deadlines.  The
                        # slept time is "idle" in the goodput decomposition
                        # (the remainder of an inter-dispatch gap is
                        # host_gap — true scheduling overhead)
                        w0 = time.perf_counter()
                        self._cond.wait(0.1)
                        self._idle_accum += time.perf_counter() - w0
                        continue
                self._dispatch(active, queued)
        except BaseException as e:  # loop must not die silently
            _log.error("scheduler loop failed", extra={"error": repr(e)})
            raise
        finally:
            with self._cond:
                for i in self._active():
                    self._retire(i, "aborted")
                while self._queue:
                    self._fail_ticket(self._queue.popleft(), "aborted")
                self._idle.set()

    def _dispatch(self, active: list[int], queued: int) -> None:
        eng = self.engine
        b = eng.batch
        slots = self.slots
        prefilling = [i for i in active
                      if slots[i].fed < len(slots[i].ticket.prompt)]
        room = min(eng.seq_len - slots[i].pos for i in active)
        # both dispatch dimensions ride the compile key (engine.slot_step
        # caches per (T, steps, greedy)), so each is rounded down to a
        # power of two: transient values — a neighbor 3 tokens from its
        # prompt end, a row 2 tokens from its budget — would otherwise
        # mint one-off executables (PR-4 compile telemetry made that
        # visible).  O(log chunk × log burst) shapes total, each reusable.
        if prefilling:
            # mixed step: prefill chunks ride along with the decode rows'
            # single tokens; steps=1 keeps every row's clock advancing by
            # its own n_valid
            t_width = min(self.prefill_chunk, room,
                          max(len(slots[i].ticket.prompt) - slots[i].fed
                              for i in prefilling))
            t_width = 1 << (t_width.bit_length() - 1)
            steps = 1
        else:
            # pure decode: burst on device, clamped so (a) no row outruns
            # the context edge and (b) queued work waits at most
            # ~max_wait_ms for the next admission boundary.  A row that
            # hits its token budget mid-burst retires and the fanout
            # discards its overrun — cheaper than letting per-row budget
            # minima pick the burst size (lockstep rows share the cost of
            # the longest-running neighbor either way)
            t_width = 1
            steps = min(self.decode_burst, room)
            if queued and self._step_ms_ema:
                steps = min(steps, max(
                    1, int(self.max_wait_ms / self._step_ms_ema)))
            steps = max(1, steps)
            steps = 1 << (steps.bit_length() - 1)

        tokens = np.zeros((b, t_width), np.int32)
        n_valid = np.ones((b,), np.int32)
        pos_rows = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        topps = np.full((b,), 0.9, np.float32)
        for i in active:
            s = slots[i]
            pos_rows[i] = s.pos
            temps[i] = s.ticket.temperature
            topps[i] = s.ticket.top_p
            if s.fed < len(s.ticket.prompt):
                c = min(t_width, len(s.ticket.prompt) - s.fed)
                tokens[i, :c] = s.ticket.prompt[s.fed:s.fed + c]
                n_valid[i] = c
            else:
                tokens[i, 0] = s.last

        obs_metrics.SCHED_BATCH_EFFICIENCY.set(len(active) / b)
        prefset = set(prefilling)
        rid_by_slot = {i: slots[i].ticket.rid for i in active}
        fed_by_slot = {i: int(n_valid[i]) for i in prefilling}

        # inter-dispatch gap: idle (slept waiting for work) vs host_gap
        # (token fanout, admission, array prep — the overhead ROADMAP
        # item 3's on-device burst would amortize)
        tp0 = time.perf_counter()
        host_gap_ms = idle_ms = 0.0
        if self._last_dispatch_end is None:
            self._first_dispatch_at = tp0
        else:
            gap_ms = max(tp0 - self._last_dispatch_end, 0.0) * 1e3
            idle_ms = min(self._idle_accum * 1e3, gap_ms)
            host_gap_ms = gap_ms - idle_ms
            self._account("idle", idle_ms)
            self._account("host_gap", host_gap_ms)
            obs_metrics.SCHED_HOST_GAP_MS.observe(host_gap_ms)
        self._idle_accum = 0.0

        t0 = time.monotonic()
        error = None
        try:
            with self._engine_lock:
                out = eng.slot_step(tokens, pos_rows, n_valid,
                                    temps_np=temps, topps_np=topps,
                                    steps=steps,
                                    page_tables_np=self._page_tables
                                    if self.paged else None)
        except Exception as e:
            error = e
        tp1 = time.perf_counter()
        self._last_dispatch_end = tp1
        wall_ms = (tp1 - tp0) * 1e3
        # split the dispatch wall by row occupancy: every row rode the
        # same lockstep step, so a row's share IS wall * rows/b
        n_pref, n_act = len(prefilling), len(active)
        self._account("prefill", wall_ms * n_pref / b)
        self._account("decode", wall_ms * (n_act - n_pref) / b)
        self._account("pad", wall_ms * (b - n_act) / b)
        busy = self._comp["prefill"] + self._comp["decode"]
        total = sum(self._comp.values())
        if total > 0:
            obs_metrics.SCHED_GOODPUT_RATIO.set(busy / total)

        if error is not None:
            # a failed dispatch poisons at most this step: retire every
            # active slot with the error and keep serving — stale cache
            # garbage sits above future occupants' causal ceilings
            _log.error("slot dispatch failed", extra={"error": repr(error)})
            obs_flight.TIMELINE.record_step(
                ts=tp0, wall_ms=wall_ms, host_gap_ms=host_gap_ms,
                idle_ms=idle_ms, steps=steps, t_width=t_width, error=True,
                slots=self._slot_entries(active, prefset, rid_by_slot, {}))
            with self._cond:
                for i in self._active():
                    self._retire(i, "error", error=error)
            return
        step_ms = wall_ms / steps
        self._step_ms_ema = step_ms if self._step_ms_ema is None \
            else 0.8 * self._step_ms_ema + 0.2 * step_ms
        obs_trace.record("sched_step", t0, time.monotonic(),
                         active=len(active), queued=queued,
                         t=t_width, steps=steps,
                         rids=sorted(rid_by_slot.values()))

        emitted = dict.fromkeys(active, 0)
        # the whole fanout holds _cond (re-entrant with the _retire calls
        # below): slot clocks (pos/fed/produced/last) and the ticket's
        # emitted list must never be observable half-advanced by the
        # hand-off exporter, which snapshots them from another thread
        with self._cond:
            self._fanout(active, steps, out, n_valid, emitted)

        # flight phases + timeline entry for this dispatch (after the
        # fanout so the emitted-token counts are final; a row retired
        # mid-burst still gets its last burst recorded)
        for i in active:
            rid = rid_by_slot[i]
            if i in prefset:
                # a completing chunk also emits the first sampled token —
                # recorded as ``emitted`` on the chunk, not a zero-wall
                # synthetic burst
                obs_flight.phase(rid, "prefill_chunk",
                                 tokens=fed_by_slot[i], ms=wall_ms,
                                 pos=int(pos_rows[i]), emitted=emitted[i])
            else:
                obs_flight.phase(rid, "decode_burst", steps=steps,
                                 tokens=emitted[i], wall_ms=wall_ms,
                                 step_ms=step_ms)
        obs_flight.TIMELINE.record_step(
            ts=tp0, wall_ms=wall_ms,
            device_ms=getattr(eng, "last_slot_dispatch_ms", None),
            host_gap_ms=host_gap_ms, idle_ms=idle_ms, steps=steps,
            t_width=t_width,
            slots=self._slot_entries(active, prefset, rid_by_slot, emitted))

    def _fanout(self, active: list[int], steps: int, out, n_valid,
                emitted: dict[int, int]) -> None:
        """Distribute one dispatch's sampled tokens to their tickets and
        advance the slot clocks.  Caller holds ``self._cond``."""
        eng = self.engine
        slots = self.slots
        for j in range(steps):
            for i in active:
                s = slots[i]
                t = s.ticket
                if t is None:  # retired earlier this burst
                    continue
                tok = int(out[j, i])
                if j == 0 and s.fed < len(t.prompt):
                    s.fed += int(n_valid[i])
                    s.pos += int(n_valid[i])
                    if s.fed < len(t.prompt):
                        continue  # mid-prefill: sample not meaningful yet
                    # prefill just completed: this sample IS the first
                    # completion token — fall through to emit it.  The
                    # prompt's full pages are now entirely written and will
                    # never be rewritten (the clock only moves forward), so
                    # this is the moment they become shareable.
                    if self.prefix_cache is not None and not s.inserted:
                        s.inserted = True
                        ps = self.pool.page_size
                        n_full = len(t.prompt) // ps
                        if n_full:
                            self.prefix_cache.insert(
                                t.prompt[:n_full * ps], s.pages[:n_full])
                else:
                    s.pos += 1
                s.last = tok
                if tok in t.eos_ids:
                    with self._cond:
                        self._retire(i, "stop")
                    continue
                s.produced += 1
                emitted[i] += 1
                t.emitted.append(tok)
                t._q.put(tok)
                if s.produced >= t.max_new or s.pos >= eng.seq_len:
                    with self._cond:
                        self._retire(i, "length")
