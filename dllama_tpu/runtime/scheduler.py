"""Continuous-batching slot scheduler over the slot-addressable batch
engine.

The server's engine mutex serializes whole *requests*: while one stream
decodes, every other admitted request waits, even though a lockstep batch
step prices B rows at roughly one weight read (runtime/engine.py
``generate_batch_stream``).  Iteration-level scheduling (Orca, OSDI'22;
vLLM's slot form, SOSP'23) moves the admission boundary from the request
to the *decode step*: this scheduler owns the ``--batch-slots`` engine and
drives :meth:`Engine.slot_step` from one daemon thread, admitting a new
request into any free slot between steps and retiring finished ones
without disturbing their neighbors.

Mechanics per dispatch:

* every active slot is either **prefilling** (its prompt feeds in chunks
  of ``--sched-prefill-chunk`` tokens, interleaved with its neighbors'
  decode tokens in the same mixed forward — bounding the inter-token
  latency a join adds to running streams) or **decoding** (feeds its
  previous sample);
* when *no* slot is mid-prefill, decode runs in on-device bursts
  (``steps > 1`` inside one XLA program, decode_chunk's amortization);
  with work waiting in the queue the burst is clamped so a finishing
  stream frees its slot within ``--sched-max-wait-ms``;
* a freed slot is reused by handing its row position 0 again — the
  previous occupant's stale KV sits above the newcomer's causal ceiling
  (ops/attention.py ``slot_gqa_attention_at``), so per-slot reset is
  free and the cache is never zeroed;
* with ``overlap`` (default on) steady-state decode runs as a two-deep
  pipeline: while dispatch N's tokens land and fan out host-side,
  dispatch N+1 is already enqueued on device, fed by N's on-device
  last-token row (``Engine.slot_step_async``'s ``feed_dev`` — no
  device→host→device round trip).  Every *flush point* — a queued
  ticket awaiting admission, slot retire, cancel/deadline,
  ``exclusive()`` parking, hand-off export/import, drain — falls back
  to synchronous dispatch: the pipelined dispatch is landed and
  discarded, its KV writes sit above every surviving row's position
  (masked by the causal ceiling exactly like slot reuse), and greedy
  output stays byte-identical with overlap on or off;
* with a ``spec`` proposer armed (runtime/spec.py, ``--spec``), each
  greedy decode slot drafts up to ``spec_k`` tokens after a burst
  lands, and the next dispatch is a ragged VERIFY burst
  (``Engine.slot_verify_async``): proposing rows feed their drafts,
  no-proposal rows ride as plain decode steps, and each row emits its
  accepted leading drafts plus one bonus token — all re-derived from
  the target model's own argmax, so greedy output is byte-identical
  with speculation on or off.  Rejection truncates that row only
  (stale KV above its accepted ceiling is slot-reuse garbage), and
  every flush point above drops pending drafts the same way it drops a
  pipelined dispatch: drafts never survive a retire, park, or export.
  Speculation supersedes burst pipelining while armed (a verify
  window's content depends on the previous dispatch's landed tokens,
  so there is nothing token-independent to pipeline); the verify
  burst's multi-token yield is what amortizes the host gap instead.

Each submitted request gets a :class:`Ticket` — a thread-safe token
stream the HTTP handler consumes.  Cancellation (client disconnect, stop
string, deadline) flips a flag the loop honors at the next step
boundary, freeing the slot mid-generation.  A dispatch failure
(StepTimeout, device fault) retires every active slot with the error on
its ticket and the loop keeps serving — the write-before-visible
invariant makes any cache garbage from the failed step unobservable.

Greedy determinism contract: a temperature-0 request produces the same
tokens whichever slot it lands in and whatever its neighbors are doing
(tests/test_scheduler.py pins this).  Sampled requests draw from the
engine's shared counter-based RNG stream, so their draws depend on
co-scheduling — per-request seeds are not reproducible here (use the
mutex path for that); this is the standard continuous-batching trade.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from collections import deque

import numpy as np

from ..obs import cost as obs_cost, dispatch as obs_dispatch
from ..obs import events as obs_events, flight as obs_flight
from ..obs import metrics as obs_metrics, trace as obs_trace
from ..obs.log import get_logger, new_request_id, request_id_var
from .faults import FAULTS
from .pagepool import PagePool, PagePoolExhausted, RadixTree

_log = get_logger("runtime.scheduler")

_DONE = object()  # ticket stream terminator

# multi-tenant QoS classes (lower level = more important).  The wire
# names ride the OpenAI surface (body ``priority`` / X-Dllama-Priority);
# the scheduler orders admission by level and preempts strictly
# lower-priority slots for a higher-priority arrival.
PRIORITY_LEVELS = {"interactive": 0, "standard": 1, "batch": 2}
PRIORITY_NAMES = {v: k for k, v in PRIORITY_LEVELS.items()}


class SchedulerClosed(RuntimeError):
    """submit() after begin_drain()/close(): no new work is admitted."""


class SchedulerSaturated(RuntimeError):
    """submit() with the wait queue at its bound (the server maps this to
    429, same as mutex-path admission)."""


class Ticket:
    """One request's handle: a bounded-latency token stream plus the
    finish verdict.  Produced by the scheduler thread, consumed by the
    HTTP handler thread; ``cancel`` may be called from either side."""

    def __init__(self, prompt, max_new, temperature, top_p, eos_ids,
                 deadline, priority: int = 1, top_k: int = 0):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.top_k = int(top_k)
        self.eos_ids = tuple(eos_ids)
        self.deadline = deadline  # time.monotonic() or None
        # finish: stop/length/timeout/aborted/error/handoff/preempted
        self.finish: str | None = None
        self.error: BaseException | None = None
        self.slot: int | None = None
        self.submitted_at = time.monotonic()
        # QoS: priority level (PRIORITY_LEVELS), how many times this
        # request has been evicted to the parked area, and the total time
        # it spent parked (ms) — all three ride DLREQ01 hand-offs
        self.priority = int(priority)
        self.preempt_count = 0
        self.parked_ms = 0.0
        # KV tiering: total ms this request's pages sat in the host spill
        # pool (the stall the flight record surfaces as ``spill_ms``)
        self.spill_ms = 0.0
        # hand-off state (runtime/snapshot.py DLREQ01): the server parks
        # its stop strings here so a drain-time export can ship them, and
        # every emitted completion token is kept so the importing replica
        # can rebuild the full decode/stop-scan state
        self.stop: list[str] = []
        self.emitted: list[int] = []
        # the submitting thread's X-Request-Id rides the ticket onto the
        # scheduler thread, where the contextvar is not set — spans, logs
        # and the flight record all stamp this one grep-able ID
        self.rid: str = request_id_var.get() or new_request_id()
        # speculative decoding: draft tokens proposed for / accepted by
        # this request's verify bursts (flight record + /debug/requests)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._cancel: str | None = None
        self._on_cancel = None  # scheduler wakeup, bound at submit

    def cancel(self, reason: str = "aborted") -> None:
        """Ask the scheduler to retire this request at the next step
        boundary (idempotent).  Safe before admission: a queued ticket is
        dropped without ever occupying a slot."""
        if self._cancel is None and self.finish is None:
            self._cancel = reason
            if self._on_cancel is not None:
                self._on_cancel()

    def tokens(self):
        """Yield completion token ids until the request retires.  After
        the generator ends, ``finish`` holds the verdict; a scheduler-side
        failure re-raises here on the consumer's thread."""
        while True:
            item = self._q.get()
            if item is _DONE:
                break
            yield item
        if self.error is not None:
            raise self.error


class _Slot:
    __slots__ = ("ticket", "pos", "fed", "produced", "last", "pages",
                 "prefix_tokens", "inserted", "budget", "spilled",
                 "active_at")

    def __init__(self):
        self.ticket: Ticket | None = None
        self.pos = 0        # this row's cache clock
        self.fed = 0        # prompt tokens consumed so far
        self.produced = 0   # completion tokens emitted
        self.last = 0       # previous sample (decode feedback)
        self.pages: list[int] = []   # paged mode: owned pool pages
        self.prefix_tokens = 0       # prompt tokens bound from the radix tree
        self.inserted = False        # prompt pages handed to the tree yet?
        # KV tiering (--kv-reserve optimistic): the page ceiling this
        # request can ever need, the non-resident flag (pages spilled to
        # the host pool; the slot sits out dispatches until they page
        # back in), and the victim-ranking clock (monotonic of the last
        # token this slot advanced — idle-longest spills first)
        self.budget = 0
        self.spilled = False
        self.active_at = 0.0


class _Parked:
    """One preempted request: its live Ticket (the consumer is still
    blocked on the stream — parking is invisible beyond a stall) plus the
    DLREQ01 record that resumes it, held in RAM or spilled to
    ``--preempt-spill-dir``."""

    __slots__ = ("ticket", "blob", "path", "parked_at")

    def __init__(self, ticket, blob, path, parked_at):
        self.ticket = ticket
        self.blob = blob          # bytes, or None when spilled to disk
        self.path = path          # spill file, or None when in RAM
        self.parked_at = parked_at


class _Pending:
    """One in-flight dispatch: the engine's completion handle plus the
    host-side view frozen at enqueue time — who rode it, at what clocks,
    with which sampling params.  The pipeline in
    :meth:`SlotScheduler._dispatch` keeps at most one of these beyond
    the dispatch it is currently landing (depth 2)."""

    __slots__ = ("handle", "error", "active", "tickets", "steps",
                 "t_width", "n_valid", "temps", "topps", "topks", "prefset",
                 "rid_by_slot", "fed_by_slot", "pos_rows", "enq_tp",
                 "t0_mono", "host_gap_ms", "idle_ms", "overlapped",
                 "queued", "verify", "proposed_by_slot")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


class SlotScheduler:
    """Owns the batch engine; see the module docstring.  ``max_queue``
    bounds requests waiting for a slot (beyond it submit() raises
    :class:`SchedulerSaturated`)."""

    def __init__(self, engine, *, prefill_chunk: int = 16,
                 max_wait_ms: float = 50.0, decode_burst: int = 16,
                 max_queue: int = 32, prefix_reuse: bool = True,
                 overlap: bool = True, preempt: bool = True,
                 preempt_age_ms: float = 5000.0, preempt_cap: int = 3,
                 parked_max: int | None = None,
                 spill_dir: str | None = None,
                 spec=None, spec_k: int = 4,
                 kv_reserve: str = "full", spill_headroom: int = 16,
                 host_pool_mb: float = 64.0):
        if engine.sp > 1:
            raise ValueError("slot scheduling is not supported on sp meshes")
        if engine.cache.quantized and not getattr(engine, "paged", False):
            raise ValueError("slot scheduling needs a dense or paged-int8 "
                             "KV cache")
        if kv_reserve not in ("full", "optimistic"):
            raise ValueError(f"kv_reserve must be 'full' or 'optimistic', "
                             f"got {kv_reserve!r}")
        self.engine = engine
        self.slots = [_Slot() for _ in range(engine.batch)]
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.max_wait_ms = float(max_wait_ms)
        self.decode_burst = max(1, int(decode_burst))
        self.max_queue = max(1, int(max_queue))
        # paged engine (engine.kv_pages > 0): the scheduler owns the page
        # bookkeeping — a refcounted PagePool plus (prefix_reuse) a radix
        # tree that turns repeated prompt prefixes into shared pages
        # (runtime/pagepool.py).  Pages are reserved at admission for the
        # whole request (prompt + budget), so a dispatch can never fail on
        # allocation and exhaustion surfaces as queueing → 429.
        self.paged = bool(getattr(engine, "paged", False))
        self.pool: PagePool | None = None
        self.prefix_cache: RadixTree | None = None
        # KV tiering (runtime/kvtier.py): under ``optimistic`` reservation
        # admission binds only ceil((prompt + spill_headroom)/page) pages
        # and slots grow page-by-page between dispatch rounds; a grow that
        # finds the pool empty spills the idle-longest neighbor's pages to
        # the bytes-bounded host pool and pages them back in on demand.
        # ``full`` keeps today's whole-request reservation (spill never
        # engages — every slot is always resident).
        self.kv_reserve = kv_reserve
        self.optimistic = self.paged and kv_reserve == "optimistic"
        self.spill_headroom = max(0, int(spill_headroom))
        self.host_pool = None
        self._spilled: dict[int, dict] = {}   # slot -> spill bookkeeping
        self._page_nbytes = 0
        if self.paged:
            self.pool = PagePool(engine.kv_pages, engine.kv_page_size)
            if prefix_reuse:
                self.prefix_cache = RadixTree(self.pool)
            self._page_tables = np.zeros(
                (engine.batch, engine.max_pages_per_slot), np.int32)
            from .kvtier import HostPagePool
            self.host_pool = HostPagePool(
                int(float(host_pool_mb) * (1 << 20)))
            cache = engine.cache
            planes = (cache.k, cache.v) + (
                (cache.k_scale, cache.v_scale) if cache.quantized else ())
            self._page_nbytes = sum(
                int(np.prod(a.shape[:1] + a.shape[2:])) * a.dtype.itemsize
                for a in planes)
            obs_metrics.KV_PAGES_TOTAL.set(self.pool.capacity)
            obs_metrics.KV_PAGES_IN_USE.set(0)
        self._queue: deque[Ticket] = deque()
        # QoS preemption (paged mode only — the DLREQ01 export path is
        # the eviction mechanism).  Aging bounds starvation: a queued
        # ticket's effective level drops one class per preempt_age_ms
        # waited.  preempt_cap bounds per-request churn; parked_max
        # bounds the spill area — beyond either, the victim retires with
        # honest finish "preempted" instead of parking.
        self.preempt = bool(preempt)
        self.preempt_age_ms = float(preempt_age_ms)
        self.preempt_cap = max(0, int(preempt_cap))
        self.parked_max = self.max_queue if parked_max is None \
            else max(0, int(parked_max))
        self.spill_dir = spill_dir
        self._parked: list[_Parked] = []
        self._cond = threading.Condition()
        # serializes engine cache access between the dispatch loop (whose
        # jit step donates the cache buffer) and the hand-off export/
        # import paths, which read/write pool pages from other threads.
        # Scoped strictly around the device calls — never held while
        # taking self._cond, so the two locks cannot deadlock.
        self._engine_lock = threading.Lock()
        self._draining = False
        self._stop = False
        self._idle = threading.Event()  # set while paused with empty slots
        self._paused = 0
        self._step_ms_ema: float | None = None
        # overlapped-dispatch pipeline (see module docstring).  All
        # fields are mutated on the scheduler thread only; _inflight_n
        # is additionally read under _cond by _flushed() waiters, and
        # _flush_req is written by them.
        self.overlap = bool(overlap)
        self._inflight_n = 0     # pipelined dispatches on device
        self._flush_req = 0      # >0: flush requested, pipelining blocked
        self._depth = 0          # dispatches enqueued but not yet landed
        # speculative decoding (runtime/spec.py): proposer instance (or
        # None = off) and per-slot pending drafts collected at land time,
        # each tagged with the ticket it was drafted for so a re-bound
        # slot can never consume a predecessor's drafts.  All spec state
        # is host-side and scheduler-thread-only; flush points clear it.
        self.spec = spec
        self.spec_k = max(1, int(spec_k))
        self._proposals: dict[int, tuple[Ticket, list[int]]] = {}
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._n_dispatched = 0
        self._n_overlapped = 0
        self._park_wakeups = 0   # parked-wait iterations (idle test hook)
        # goodput accounting: every ms between the first and the latest
        # dispatch lands in exactly one component (see obs/metrics.py)
        self._first_dispatch_at: float | None = None   # perf_counter
        self._last_dispatch_end: float | None = None   # perf_counter
        self._idle_accum = 0.0     # seconds slept in _cond.wait since last dispatch
        self._comp = {"prefill": 0.0, "decode": 0.0, "pad": 0.0,
                      "host_gap": 0.0, "idle": 0.0}
        # roofline cost attribution (obs/cost.py): analytic FLOPs/bytes
        # per landed dispatch, pro-rated across occupied rows.  None when
        # the engine shape could not be modeled — serving never depends
        # on the accounting.
        self.cost_model = obs_cost.model_from_engine(engine)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dllama-slot-scheduler")
        self._thread.start()

    # -- submission-side API -------------------------------------------
    def submit(self, prompt: list[int], max_new: int, *,
               temperature: float = 0.0, top_p: float = 0.9,
               top_k: int = 0, eos_ids: tuple[int, ...] = (),
               deadline: float | None = None,
               priority: int = 1) -> Ticket:
        """Queue one request; returns its :class:`Ticket` immediately.
        ``deadline`` is a ``time.monotonic()`` instant (the server's
        per-request deadline); an expired request retires with finish
        ``timeout`` and whatever tokens it produced.  ``priority`` is a
        :data:`PRIORITY_LEVELS` level: admission is priority-ordered and
        a higher-priority arrival may preempt lower-priority slots."""
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError("max_new must be positive")
        if self.pool is not None:
            # a request whose full reservation exceeds the pool would wait
            # forever — that is a sizing error, not transient saturation
            need = min(len(prompt) + max_new, self.engine.seq_len)
            n_pages = -(-need // self.pool.page_size)
            if n_pages > self.pool.capacity:
                from .engine import ContextOverflow
                raise ContextOverflow(
                    f"request needs {n_pages} KV pages but the pool has "
                    f"{self.pool.capacity}; raise --kv-pages or shorten "
                    "the request")
        t = Ticket(prompt, max_new, temperature, top_p, eos_ids, deadline,
                   priority=max(0, min(max(PRIORITY_NAMES), int(priority))),
                   top_k=top_k)
        with self._cond:
            if self._stop or self._draining:
                raise SchedulerClosed("scheduler is draining")
            # admission runs on the scheduler thread, so just-submitted
            # tickets sit in the queue for one beat even when slots are
            # free — the bound is on work beyond what free slots will
            # immediately absorb, not on that scheduling gap
            free = sum(1 for s in self.slots if s.ticket is None)
            if len(self._queue) >= self.max_queue + (0 if self._paused
                                                     else free):
                raise SchedulerSaturated(
                    f"{len(self._queue)} requests already waiting")
            t._on_cancel = self._wake
            self._queue.append(t)
            self._cond.notify_all()
        obs_flight.submit(t.rid, n_prompt=len(t.prompt), max_new=t.max_new,
                          temperature=t.temperature, source="scheduler",
                          priority=PRIORITY_NAMES.get(t.priority, "standard"))
        return t

    def occupancy(self) -> dict:
        """Live state for /health and the over-n error body."""
        with self._cond:
            active = sum(1 for s in self.slots if s.ticket is not None)
            out = {"slots": len(self.slots), "active": active,
                   "queued": len(self._queue),
                   "parked": len(self._parked)}
            if self.pool is not None:
                out["kv_pages_total"] = self.pool.capacity
                out["kv_pages_free"] = self.pool.available
                if self.prefix_cache is not None:
                    out["prefix_nodes"] = len(self.prefix_cache)
                # tiering pressure for the fleet router: resident free
                # pages plus what one spill pass could free into the host
                # pool — the capacity a new request can actually claim
                owned = sum(len(s.pages) for s in self.slots
                            if s.ticket is not None and not s.spilled)
                headroom = 0
                if self.host_pool is not None and self._page_nbytes:
                    headroom = max(0, self.host_pool.capacity_bytes
                                   - self.host_pool.bytes_used) \
                        // self._page_nbytes
                spillable = min(owned, headroom) if self.optimistic else 0
                eng = self.engine
                out["kv_pressure"] = {
                    "reserve": self.kv_reserve,
                    "resident_free": self.pool.available,
                    "spillable": spillable,
                    "effective_free": self.pool.available + spillable,
                    "host_pool_bytes": self.host_pool.bytes_used
                    if self.host_pool is not None else 0,
                    "spilled_slots": len(self._spilled),
                    "codec": "int8" if eng.cache.quantized
                    else str(eng.cache.k.dtype),
                }
            return out

    def begin_drain(self, deadline: float | None) -> None:
        """Stop admitting new submissions and clamp every in-flight and
        queued request's deadline — drain then *waits* for the slots via
        the handlers consuming their tickets."""
        with self._cond:
            self._draining = True
            for t in list(self._queue):
                t.deadline = min(t.deadline, deadline) \
                    if (t.deadline and deadline) else (t.deadline or deadline)
            for s in self.slots:
                if s.ticket is not None:
                    t = s.ticket
                    t.deadline = min(t.deadline, deadline) \
                        if (t.deadline and deadline) else (t.deadline or deadline)
            for e in self._parked:
                t = e.ticket
                t.deadline = min(t.deadline, deadline) \
                    if (t.deadline and deadline) else (t.deadline or deadline)
            self._cond.notify_all()

    def drain_with_export(self, deadline: float | None) -> dict[str, bytes]:
        """Bulk drain entry point: stop admissions, clamp every ticket's
        deadline, and export every live slot as a DLREQ01 record in one
        call — the shape a fleet-level drain (SIGTERM, elastic
        scale-down, live reshape) actually wants, so callers cannot
        forget one half.  Returns the records keyed by request id;
        ``{}`` when the scheduler has no paged KV pool (nothing
        exportable — the drain still runs)."""
        self.begin_drain(deadline)
        return self.handoff_export_all()

    def close(self, timeout: float = 5.0) -> None:
        """Stop the loop; any still-live tickets retire as ``aborted`` so
        no consumer blocks forever."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)

    @contextlib.contextmanager
    def exclusive(self):
        """Park the scheduler and wait until every slot has retired, so
        the caller may run one-shot batch-engine work (list-prompt
        lockstep, n>1 fan-out, logprobs scoring) that resets the shared
        cache.  Admission pauses; queued requests keep their place."""
        with self._cond:
            self._paused += 1
            self._cond.notify_all()
        self._idle.wait()
        try:
            yield
        finally:
            with self._cond:
                self._paused -= 1
                if self._paused == 0:
                    self._idle.clear()
                self._cond.notify_all()

    def _wake(self):
        with self._cond:
            self._cond.notify_all()

    # -- pipeline flush ------------------------------------------------
    @contextlib.contextmanager
    def _flushed(self):
        """Hold the dispatch pipeline empty: block new pipelining, wait
        for any in-flight pipelined dispatch to land (it is discarded
        at the flush point), then yield with ``self._cond`` held and
        zero dispatches in flight.  The DLREQ01 exporter runs inside
        this window so its snapshots never observe a half-landed
        burst."""
        with self._cond:
            self._flush_req += 1
            self._cond.notify_all()
            try:
                if not self._cond.wait_for(
                        lambda: self._inflight_n == 0, timeout=60.0):
                    _log.error("pipeline flush timed out", extra={
                        "inflight": self._inflight_n})
                yield
            finally:
                self._flush_req -= 1
                self._cond.notify_all()

    def flush(self) -> None:
        """Synchronize the dispatch pipeline: returns only once zero
        dispatches are in flight.  Speculation resumes immediately
        after."""
        with self._flushed():
            pass

    # -- paged state snapshot/restore (runtime/snapshot.py DLSNAP02) ----
    def snapshot_paged(self, path, extra: dict | None = None) -> str:
        """Persist the paged serving state: the pool KV arrays ride the
        engine snapshot, the page tables go as an extra array, and the
        radix tree's token keys + page ids go in the JSON meta.  Call
        with no live slots (drain or ``exclusive()`` first) — snapshots
        of mid-flight requests are not meaningful."""
        if self.pool is None:
            raise ValueError("snapshot_paged on a non-paged scheduler")
        with self._cond:
            if self._active():
                raise RuntimeError("snapshot_paged with live slots; "
                                   "drain first")
            meta = dict(extra or {})
            meta["radix"] = (self.prefix_cache.export()
                             if self.prefix_cache is not None else [])
            return self.engine.snapshot(
                path, extra=meta,
                extra_arrays={"page_tables": self._page_tables.copy()})

    def restore_paged(self, path) -> dict:
        """Restore :meth:`snapshot_paged` state.  The engine validates
        format/fingerprint (pool geometry is part of the fingerprint, so
        a mismatched geometry raises SnapshotMismatch and the caller
        cold-starts); the pool and radix tree are rebuilt from the
        snapshot's tree keys, re-claiming their pages."""
        if self.pool is None:
            raise ValueError("restore_paged on a non-paged scheduler")
        with self._cond:
            if self._active():
                raise RuntimeError("restore_paged with live slots")
            extra = self.engine.restore(path)
            arrs = getattr(self.engine, "restored_arrays", {})
            pt = arrs.get("page_tables")
            if pt is not None and pt.shape == self._page_tables.shape:
                self._page_tables[:] = pt
            self.pool = PagePool(self.engine.kv_pages,
                                 self.engine.kv_page_size)
            if self.prefix_cache is not None:
                self.prefix_cache = RadixTree(self.pool)
                self.prefix_cache.restore(extra.get("radix") or [])
            # spill records describe the pre-restore pool; drop them
            if self.host_pool is not None:
                self.host_pool.clear()
            self._spilled.clear()
            obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
            return extra

    # -- per-request KV hand-off (runtime/snapshot.py DLREQ01) ----------
    def _export_slot_locked(self, slot_idx: int) -> bytes:
        """Serialize one live slot to a DLREQ01 record (caller holds
        ``self._cond``).  The record carries the slot's written KV pages
        (positions ``[0, pos)``), the full prompt + completion token ids,
        sampling params, remaining deadline, and the engine's sampler RNG
        stream — everything a geometry-compatible peer needs to resume
        decode without re-prefilling."""
        import math

        s = self.slots[slot_idx]
        t = s.ticket
        ps = self.pool.page_size
        n_data = math.ceil(s.pos / ps)
        deadline_left = None
        if t.deadline is not None:
            deadline_left = max(t.deadline - time.monotonic(), 0.0)
        # pages may contain stale values above pos (an in-flight dispatch
        # whose fanout never ran) — harmless, the importer's causal
        # ceiling masks them exactly like slot reuse does.  A spilled
        # slot's pages are not resident: its record is built from the
        # host-pool copy (page order there is the slot's logical order).
        if s.spilled:
            rec = self.host_pool.get(self._spill_key(slot_idx))
            if rec is None:
                raise RuntimeError(
                    f"slot {slot_idx} marked spilled but its host-pool "
                    "record is missing")
            arrays = {name: np.asarray(a[:, :n_data])
                      for name, a in rec[0].items()}
            with self._engine_lock:
                arrays["rng_key"] = np.asarray(self.engine._key)
                if self.engine._dev_key is not None:
                    arrays["rng_dev_key"] = np.asarray(self.engine._dev_key)
                chunk_counter = self.engine._chunk_counter
        else:
            with self._engine_lock:
                arrays = self.engine.read_pool_pages(s.pages[:n_data])
                arrays["rng_key"] = np.asarray(self.engine._key)
                if self.engine._dev_key is not None:
                    arrays["rng_dev_key"] = np.asarray(self.engine._dev_key)
                chunk_counter = self.engine._chunk_counter
        from . import snapshot as snapfmt
        return snapfmt.dumps_request(
            fingerprint=self.engine.handoff_fingerprint(),
            pos=s.pos, chunk_counter=chunk_counter, arrays=arrays,
            extra={
                "rid": t.rid, "prompt": list(t.prompt),
                "completion": list(t.emitted), "max_new": t.max_new,
                "temperature": t.temperature, "top_p": t.top_p,
                "top_k": t.top_k,
                "sampling_path": self.engine.sampling_path,
                "eos_ids": list(t.eos_ids), "stop": list(t.stop),
                "deadline_left": deadline_left,
                "fed": s.fed, "produced": s.produced, "last": s.last,
                "priority": t.priority, "preempt_count": t.preempt_count,
                "parked_ms": t.parked_ms, "spill_ms": t.spill_ms,
                "trace_id": obs_trace.trace_of(t.rid),
            })

    def handoff_export_all(self) -> dict[str, bytes]:
        """Drain-time hand-off: export every live slot to a DLREQ01
        record keyed by request id and retire it with finish
        ``handoff``; queued (never-admitted) tickets retire ``handoff``
        with no record — the router re-submits those from scratch, which
        is idempotent because nothing was ever streamed."""
        if self.pool is None:
            return {}
        records: dict[str, bytes] = {}
        # _flushed() lands-and-discards any in-flight pipelined
        # dispatch before yielding, so every snapshot below observes
        # step-boundary state only (acceptance: zero in-flight here).
        # Token speculation flushes too: the export path runs _retire
        # (via handoff) which drops the slot's pending drafts, so a
        # DLREQ01 record never carries speculative state
        with self._flushed():
            for i in self._active():
                t = self.slots[i].ticket
                try:
                    records[t.rid] = self._export_slot_locked(i)
                except Exception as e:
                    # an unexportable slot degrades to a plain drain
                    # abort for that request; the fleet must not lose
                    # the other slots over it
                    _log.error("handoff export failed", extra={
                        "rid": t.rid, "error": repr(e)})
                self._retire(i, "handoff")
            # parked (preempted) requests already ARE their own DLREQ01
            # records — ship them as-is so a peer resumes them too
            for e in list(self._parked):
                t = e.ticket
                try:
                    blob = e.blob
                    if blob is None:
                        with open(e.path, "rb") as f:
                            blob = f.read()
                    records[t.rid] = blob
                except Exception as exc:
                    _log.error("handoff export of parked record failed",
                               extra={"rid": t.rid, "error": repr(exc)})
                self._drop_parked_locked(e)
                self._fail_ticket(t, "handoff")
            while self._queue:
                self._fail_ticket(self._queue.popleft(), "handoff")
            self._cond.notify_all()
        if records:
            _log.info("handoff export", extra={"requests": len(records)})
            for rid in records:
                obs_events.emit("handoff", direction="export", rid=rid,
                                trace=obs_trace.trace_of(rid))
        return records

    def checkpoint_export(self, rid: str) -> bytes | None:
        """Non-destructive DLREQ01 snapshot of ONE live slot, keyed by
        request id — the proactive-checkpoint twin of
        :meth:`handoff_export_all`.  The slot keeps decoding afterwards;
        the record is a point-in-time copy the router caches so a
        replica that later dies *ungracefully* can be resumed from the
        checkpoint instead of paying a full re-prefill.

        Runs inside :meth:`_flushed` so the snapshot only ever observes
        step-boundary state (same invariant as the drain exporter).  A
        resumed checkpoint is allowed to be stale: the importer's
        ``emitted_chars`` cursor re-decodes the tokens between the
        checkpoint and what the client already saw and emits nothing
        until the cursor is passed, so greedy byte-parity holds for any
        checkpoint age.  Returns ``None`` when the request is not in a
        live slot (queued, parked, or already retired)."""
        if self.pool is None:
            return None
        with self._flushed():
            for i in self._active():
                t = self.slots[i].ticket
                if t is not None and t.rid == rid:
                    return self._export_slot_locked(i)
        return None

    def import_request(self, blob: bytes) -> tuple[Ticket, dict]:
        """Re-bind an exported request (DLREQ01 bytes) into a free slot:
        allocate this pool's own physical pages, write the exported page
        slices into them, and resume the slot's clocks exactly where the
        exporter stopped — continued greedy decode is byte-identical to
        never having moved (tests/test_handoff.py pins this).

        Raises :class:`~dllama_tpu.io.integrity.ArtifactError` on a
        corrupt record, :class:`SnapshotMismatch` on incompatible
        geometry, :class:`SchedulerSaturated` when no slot/pages are
        free, :class:`SchedulerClosed` when this replica is itself
        draining.  Returns ``(ticket, record_extra)``.

        The exporter's sampler RNG stream is restored only when this
        scheduler has no other live work — the engine RNG is shared
        across slots, so rebasing it under co-scheduled requests would
        perturb their draws.  Greedy (temperature-0) requests do not
        consume the stream and hand off byte-identically regardless.
        """
        from . import snapshot as snapfmt

        if self.pool is None:
            raise ValueError("hand-off import needs a paged scheduler "
                             "(--kv-pages)")
        meta, arrays = snapfmt.loads_request(blob)
        eng = self.engine
        want = eng.handoff_fingerprint()
        if meta["fingerprint"] != want:
            raise snapfmt.SnapshotMismatch(
                "<handoff record>", "fingerprint",
                "record is from a replica with incompatible geometry",
                expected=want, got=meta["fingerprint"])
        extra = dict(meta.get("extra", {}))
        rec_sp = extra.get("sampling_path")
        if rec_sp is not None and rec_sp != eng.sampling_path:
            # the record's sampled stream was drawn by a different
            # sampling implementation — resuming here would silently
            # change the distribution (absent flag = legacy record,
            # accepted for compatibility)
            raise snapfmt.SnapshotMismatch(
                "<handoff record>", "sampling_path",
                "record sampled on a different sampling path",
                expected=eng.sampling_path, got=str(rec_sp))
        prompt = [int(x) for x in extra.get("prompt") or []]
        completion = [int(x) for x in extra.get("completion") or []]
        pos = int(meta["pos"])
        max_new = int(extra.get("max_new", 1))
        fed = int(extra.get("fed", 0))
        produced = int(extra.get("produced", len(completion)))
        if not prompt or max_new < 1 or not (0 <= pos <= eng.seq_len) \
                or not (0 <= fed <= len(prompt)) or produced < 0:
            raise snapfmt.SnapshotMismatch(
                "<handoff record>", "extra",
                "inconsistent request state in hand-off record")
        ps = self.pool.page_size
        n_data = -(-pos // ps)
        # the record must carry exactly this pool's page planes: values
        # always, per-position scale planes iff the pool is int8 — an
        # int8 record into a dense pool (or vice versa) already failed
        # the fingerprint above, this validates shape against position
        page_names = ["pages.k", "pages.v"]
        if eng.cache.quantized:
            page_names += ["pages.k_scale", "pages.v_scale"]
        page_arrays: dict = {}
        for name in page_names:
            ref = getattr(eng.cache, name.split(".", 1)[1])
            arr = arrays.get(name)
            want_shape = (ref.shape[0], n_data) + tuple(ref.shape[2:])
            if arr is None or tuple(arr.shape) != want_shape:
                raise snapfmt.SnapshotMismatch(
                    "<handoff record>", f"array {name!r}",
                    "page payload does not match the record position",
                    expected=str(want_shape),
                    got="missing" if arr is None else str(arr.shape))
            page_arrays[name] = arr
        need = min(len(prompt) + max_new, eng.seq_len)
        n_total = -(-need // ps)
        if n_total > self.pool.capacity:
            from .engine import ContextOverflow
            raise ContextOverflow(
                f"request needs {n_total} KV pages but the pool has "
                f"{self.pool.capacity}")
        deadline = None
        if extra.get("deadline_left") is not None:
            deadline = time.monotonic() + float(extra["deadline_left"])
        with self._cond:
            if self._stop or self._draining:
                raise SchedulerClosed("scheduler is draining")
            slot_idx = next((i for i, s in enumerate(self.slots)
                             if s.ticket is None), None)
            if slot_idx is None:
                raise SchedulerSaturated("no free slot for hand-off import")
            try:
                pages = self.pool.alloc(n_total)
            except PagePoolExhausted:
                pages = None
                if self.prefix_cache is not None:
                    self.prefix_cache.evict(n_total - self.pool.available)
                    try:
                        pages = self.pool.alloc(n_total)
                    except PagePoolExhausted:
                        pass
            if pages is None:
                raise SchedulerSaturated(
                    "no free KV pages for hand-off import")
            others = any(s.ticket is not None for s in self.slots)
            with self._engine_lock:
                if n_data:
                    eng.write_pool_pages(pages[:n_data], page_arrays)
                if not others and not self._queue and "rng_key" in arrays:
                    eng.set_rng(arrays["rng_key"],
                                int(meta["chunk_counter"]),
                                dev_key_np=arrays.get("rng_dev_key"))
            t = Ticket(prompt, max_new,
                       float(extra.get("temperature", 0.0)),
                       float(extra.get("top_p", 0.9)),
                       tuple(int(e) for e in extra.get("eos_ids") or ()),
                       deadline, top_k=int(extra.get("top_k", 0)))
            t.rid = str(extra.get("rid") or t.rid)
            # re-establish the fleet trace context on the importing
            # replica: every span this scheduler records for the resumed
            # request (rid-stamped) joins the exporter's trace id, so a
            # migrated request is ONE trace across both rings
            if extra.get("trace_id"):
                obs_trace.set_trace(t.rid, str(extra["trace_id"]))
            t.stop = [str(x) for x in extra.get("stop") or []]
            t.emitted = list(completion)
            t.priority = int(extra.get("priority", 1))
            t.preempt_count = int(extra.get("preempt_count", 0))
            t.parked_ms = float(extra.get("parked_ms", 0.0))
            t.spill_ms = float(extra.get("spill_ms", 0.0))
            t._on_cancel = self._wake
            s = self.slots[slot_idx]
            s.ticket = t
            s.pages = pages
            s.budget = n_total
            s.spilled = False
            s.active_at = time.monotonic()
            s.prefix_tokens = 0
            # prompt pages become radix-shareable once prefill completes;
            # a decode-phase import never re-inserts (alignment with the
            # exporter's shared prefixes is unknowable here)
            s.inserted = fed >= len(prompt)
            s.pos = pos
            s.fed = fed
            s.produced = produced
            s.last = int(extra.get("last", 0))
            t.slot = slot_idx
            row = self._page_tables[slot_idx]
            row[:] = 0
            row[:len(pages)] = pages
            obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
            obs_metrics.SCHED_SLOT_JOINS.inc(slot_idx)
            self._cond.notify_all()
        obs_flight.submit(t.rid, n_prompt=len(prompt), max_new=max_new,
                          temperature=t.temperature, source="handoff",
                          priority=PRIORITY_NAMES.get(t.priority, "standard"))
        obs_flight.admit(t.rid, slot=slot_idx, queued_ms=0.0,
                         prefix_reused=0)
        ctx = request_id_var.set(t.rid)
        try:
            _log.info("handoff import", extra={
                "slot": slot_idx, "pos": pos, "produced": produced,
                "pages": len(pages)})
        finally:
            request_id_var.reset(ctx)
        obs_events.emit("handoff", direction="import", rid=t.rid,
                        slot=slot_idx, pos=pos, produced=produced,
                        trace=obs_trace.trace_of(t.rid))
        return t, extra

    # -- scheduler thread ----------------------------------------------
    def _retire(self, slot_idx: int, reason: str,
                error: BaseException | None = None) -> None:
        s = self.slots[slot_idx]
        t = s.ticket
        if t is None:
            return
        t.finish = reason
        t.error = error
        if self.pool is not None:
            # a spilled slot owns no pages; its host-pool record dies
            # with the request (dropped while the ticket is still bound
            # so the spilled interval lands on its spill_ms clock)
            self._drop_spilled_locked(slot_idx)
        s.ticket = None
        # flush point for speculation: pending drafts die with the slot
        # and the proposer forgets its per-slot state (a later occupant
        # rebuilds from its own prompt)
        self._proposals.pop(slot_idx, None)
        if self.spec is not None:
            self.spec.reset(slot_idx)
        if self.pool is not None and s.pages:
            # drop this slot's references; pages the radix tree retained
            # stay live (and reusable by the next matching prompt)
            self.pool.decref(s.pages)
            s.pages = []
            self._page_tables[slot_idx][:] = 0
            obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
        obs_metrics.SCHED_SLOT_RETIRES.inc(slot_idx, reason)
        now = time.monotonic()
        obs_trace.record("sched_retire", now, now, rid=t.rid, slot=slot_idx,
                         reason=reason, produced=s.produced)
        # the log record factory stamps the contextvar, so bind the
        # ticket's ID around the call (this thread serves many requests)
        ctx = request_id_var.set(t.rid)
        try:
            _log.info("slot retire", extra={
                "slot": slot_idx, "reason": reason, "produced": s.produced})
        finally:
            request_id_var.reset(ctx)
        obs_flight.retire(t.rid, reason, produced=s.produced, pos=s.pos,
                          error=repr(error) if error is not None else None,
                          preempt_count=t.preempt_count or None,
                          parked_ms=round(t.parked_ms, 3)
                          if t.parked_ms else None,
                          spill_ms=round(t.spill_ms, 3)
                          if t.spill_ms else None,
                          spec_proposed=t.spec_proposed or None,
                          spec_accepted=t.spec_accepted
                          if t.spec_proposed else None)
        t._q.put(_DONE)

    def _fail_ticket(self, t: Ticket, reason: str,
                     error: BaseException | None = None) -> None:
        t.finish = reason
        t.error = error
        obs_flight.retire(t.rid, reason, produced=0,
                          error=repr(error) if error is not None else None)
        t._q.put(_DONE)

    def _bind_pages(self, slot_idx: int, t: Ticket) -> bool:
        """Paged admission: match the prompt against the radix tree, then
        reserve pages.  Under ``full`` reservation that is every page the
        request can ever touch (matched prefix + fresh pages through
        ``min(len(prompt) + max_new, seq_len)``) — exhaustion stays out
        of the dispatch path because a request that cannot get its pages
        stays queued (False), it never fails mid-decode.  Under
        ``optimistic`` only ``ceil((prompt + spill_headroom)/page)`` is
        bound here; the slot grows page-by-page between dispatch rounds
        (:meth:`_tier_round_locked`'s ladder: alloc → radix evict →
        spill → park), so over-commit degrades to queueing either way.
        Caller holds the lock."""
        pool = self.pool
        ps = pool.page_size
        prompt = t.prompt
        matched, shared = 0, []
        if self.prefix_cache is not None:
            matched, shared = self.prefix_cache.match(prompt)
            # always leave ≥1 prompt token to feed: the forward over the
            # suffix is what produces the first sampled token.  The dropped
            # block is re-prefilled into a fresh page; the tree keeps its
            # copy (first writer wins on a later insert).
            while matched >= len(prompt):
                matched -= ps
                shared = shared[:-1]
        # shared pages are referenced BEFORE any allocation/eviction so the
        # evictor (which only frees tree-only pages) cannot free a page
        # this admission just matched
        pool.incref(shared)
        need_len = min(len(prompt) + t.max_new, self.engine.seq_len)
        if self.optimistic:
            reserve_len = min(len(prompt) + self.spill_headroom, need_len)
        else:
            reserve_len = need_len
        fresh = -(-reserve_len // ps) - len(shared)
        try:
            new_pages = pool.alloc(fresh)
        except PagePoolExhausted:
            new_pages = None
            if self.prefix_cache is not None:
                self.prefix_cache.evict(fresh - pool.available)
                try:
                    new_pages = pool.alloc(fresh)
                except PagePoolExhausted:
                    pass
        if new_pages is None:
            pool.decref(shared)
            if not getattr(t, "_page_deferred", False):
                t._page_deferred = True
                obs_metrics.KV_POOL_EXHAUSTED.inc()
                ctx = request_id_var.set(t.rid)
                try:
                    _log.info("kv pool exhausted", extra={
                        "need_pages": fresh, "free": pool.available})
                finally:
                    request_id_var.reset(ctx)
            return False
        s = self.slots[slot_idx]
        s.pages = list(shared) + new_pages
        s.prefix_tokens = matched
        s.inserted = False
        # full-reservation page count: the growth ceiling under
        # optimistic mode (and trivially == len(s.pages) under full)
        s.budget = -(-need_len // ps)
        s.spilled = False
        s.active_at = time.monotonic()
        # the slot's page-table row: reserved pages first, scratch page 0
        # everywhere else (unreserved entries absorb overshoot writes)
        row = self._page_tables[slot_idx]
        row[:] = 0
        row[:len(s.pages)] = s.pages
        if matched:
            obs_metrics.PREFIX_HITS.inc()
            obs_metrics.PREFIX_TOKENS_REUSED.inc(matched)
            obs_flight.phase(t.rid, "prefix_reuse", tokens=matched,
                             pages=len(shared))
        obs_metrics.KV_PAGES_IN_USE.set(pool.in_use)
        return True

    def _eff_level(self, t: Ticket, now: float) -> int:
        """Effective priority level after aging: a waiting ticket climbs
        one class per ``preempt_age_ms`` waited, bounding starvation of
        batch traffic behind a steady interactive stream.  ``<= 0``
        disables aging."""
        lvl = t.priority
        if self.preempt_age_ms > 0:
            lvl -= int((now - t.submitted_at) * 1e3 / self.preempt_age_ms)
        return lvl

    def _admit_locked(self, now: float) -> None:
        """Move waiting work into free slots in priority order (caller
        holds the lock).  Candidates come from two places — the submit
        queue and the parked (preempted) area; the best effective level
        wins, parked beating queued on ties (they were admitted once
        already).  A candidate that cannot get a slot or pages may
        preempt a strictly lower-priority victim; otherwise admission
        stops for the round (head-of-line keeps its place)."""
        while True:
            best = None  # (sort key, kind, ticket, parked entry)
            for t in self._queue:
                k = (self._eff_level(t, now), 1, t.submitted_at)
                if best is None or k < best[0]:
                    best = (k, "queued", t, None)
            for e in self._parked:
                k = (self._eff_level(e.ticket, now), 0,
                     e.ticket.submitted_at)
                if best is None or k < best[0]:
                    best = (k, "parked", e.ticket, e)
            if best is None:
                return
            _, kind, t, entry = best
            if t._cancel is not None or (t.deadline is not None
                                         and now >= t.deadline):
                if kind == "queued":
                    self._queue.remove(t)
                else:
                    self._drop_parked_locked(entry)
                self._fail_ticket(t, t._cancel or "timeout")
                continue
            free = next((i for i, s in enumerate(self.slots)
                         if s.ticket is None), None)
            if free is None:
                if self._preempt_for_locked(t, now, "no_free_slot"):
                    continue
                return
            if kind == "parked":
                if self._unpark_locked(free, entry, now):
                    continue
                if self._preempt_for_locked(t, now, "pool_exhausted"):
                    continue
                return
            if self.pool is not None and not self._bind_pages(free, t):
                # pool exhausted: evict a lower-priority slot if one
                # exists, else the ticket keeps its place at the head of
                # the order and admission stops for this round —
                # retirements free pages and the next pass retries
                if self._preempt_for_locked(t, now, "pool_exhausted"):
                    continue
                return
            self._queue.remove(t)
            s = self.slots[free]
            s.ticket = t
            # paged with a prefix hit: the matched tokens are already in
            # the cache (shared pages), so the clock starts past them and
            # prefill covers only the suffix.  Otherwise both start at 0
            # (_bind_pages sets prefix_tokens; it stays 0 when contiguous).
            s.pos = s.fed = s.prefix_tokens
            s.produced = 0
            s.last = 0
            t.slot = free
            queued_ms = round((now - t.submitted_at) * 1e3, 3)
            obs_metrics.SCHED_SLOT_JOINS.inc(free)
            obs_trace.record("sched_admit", t.submitted_at, now, rid=t.rid,
                             slot=free, queued_ms=queued_ms,
                             n_prompt=len(t.prompt),
                             prefix_reused=s.prefix_tokens,
                             priority=PRIORITY_NAMES.get(t.priority,
                                                         t.priority))
            ctx = request_id_var.set(t.rid)
            try:
                _log.info("slot join", extra={
                    "slot": free, "n_prompt": len(t.prompt),
                    "queued_ms": queued_ms,
                    "prefix_reused": s.prefix_tokens,
                    "priority": PRIORITY_NAMES.get(t.priority, t.priority)})
            finally:
                request_id_var.reset(ctx)
            obs_flight.admit(t.rid, slot=free, queued_ms=queued_ms,
                             prefix_reused=s.prefix_tokens)
            obs_metrics.QUEUE_WAIT.observe(max(now - t.submitted_at, 0.0))

    # -- QoS preemption (export → park → re-admit) ---------------------
    def _preempt_for_locked(self, t: Ticket, now: float,
                            reason: str) -> bool:
        """Evict the lowest-priority longest-remaining slot so ``t`` can
        admit.  Raw (un-aged) priorities gate eviction — an aged batch
        ticket outranks newer batch arrivals for admission but never
        evicts standard work.  Admission runs only between dispatch
        rounds (``_dispatch``'s zero-in-flight invariant), so the export
        below observes step-boundary state only; ``_inflight_n`` is
        checked anyway as a belt-and-braces guard.  Returns False when
        preemption is off, the scheduler is unpaged, or no strictly
        lower-priority victim exists."""
        if not self.preempt or self.pool is None or self._inflight_n:
            return False
        victims = [i for i, s in enumerate(self.slots)
                   if s.ticket is not None and s.ticket.priority > t.priority]
        if not victims:
            return False
        victim = max(victims, key=lambda i: (
            self.slots[i].ticket.priority,
            self.slots[i].ticket.max_new - self.slots[i].produced))
        self._preempt_locked(victim, reason, now)
        return True

    def _preempt_locked(self, slot_idx: int, reason: str,
                        now: float) -> None:
        """Evict one slot through the DLREQ01 export path: snapshot it,
        park the record (RAM, or ``spill_dir``), free its pages, and
        leave the ticket live — the streaming consumer sees only a
        stall.  Over the per-request cap or with the parked area full,
        the victim retires instead with honest finish ``preempted`` and
        whatever tokens it produced."""
        s = self.slots[slot_idx]
        t = s.ticket
        # flush point: pending drafts are discarded BEFORE the export so
        # a DLREQ01 record never carries speculative state — the resumed
        # slot re-drafts from its own (exact) accepted stream
        self._proposals.pop(slot_idx, None)
        if self.spec is not None:
            self.spec.reset(slot_idx)
        obs_metrics.SCHED_PREEMPTIONS.inc(reason)
        obs_trace.record("sched_preempt", now, time.monotonic(), rid=t.rid,
                         slot=slot_idx, reason=reason, produced=s.produced,
                         priority=PRIORITY_NAMES.get(t.priority, t.priority))
        if t.preempt_count >= self.preempt_cap \
                or len(self._parked) >= self.parked_max:
            self._retire(slot_idx, "preempted")
            return
        try:
            blob = self._export_slot_locked(slot_idx)
        except Exception as e:
            # an unexportable slot cannot be parked — honest truncation
            _log.error("preempt export failed", extra={
                "rid": t.rid, "error": repr(e)})
            self._retire(slot_idx, "preempted")
            return
        path = None
        if self.spill_dir is not None:
            import os
            try:
                os.makedirs(self.spill_dir, exist_ok=True)
                path = os.path.join(self.spill_dir, f"{t.rid}.dlreq")
                with open(path, "wb") as f:
                    f.write(blob)
                blob = None
            except OSError as e:
                path = None  # spill failed: keep the record in RAM
                _log.error("preempt spill failed; keeping record in RAM",
                           extra={"rid": t.rid, "error": repr(e)})
        t.preempt_count += 1
        self._parked.append(_Parked(t, blob, path, now))
        # a spilled victim parks from its host-pool copy (the export
        # above read it); the record is now redundant with the DLREQ01
        # blob — drop it while the ticket is still bound
        self._drop_spilled_locked(slot_idx)
        s.ticket = None
        t.slot = None
        if s.pages:
            self.pool.decref(s.pages)
            s.pages = []
            self._page_tables[slot_idx][:] = 0
            obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
        obs_metrics.SCHED_PREEMPT_PARKED.set(len(self._parked))
        ctx = request_id_var.set(t.rid)
        try:
            _log.info("slot preempt", extra={
                "slot": slot_idx, "reason": reason, "produced": s.produced,
                "preempt_count": t.preempt_count,
                "spilled": path is not None})
        finally:
            request_id_var.reset(ctx)
        obs_flight.phase(t.rid, "preempted", slot=slot_idx, reason=reason,
                         produced=s.produced,
                         preempt_count=t.preempt_count)
        obs_events.emit("preempt", rid=t.rid, slot=slot_idx, reason=reason,
                        produced=s.produced, spilled=path is not None,
                        trace=obs_trace.trace_of(t.rid))

    def _unpark_locked(self, slot_idx: int, entry: _Parked,
                       now: float) -> bool:
        """Re-admit a parked request into ``slot_idx``, re-binding its
        ORIGINAL ticket — the consumer is still blocked on the stream,
        so resumption is invisible beyond the stall.  Continued greedy
        decode is byte-identical to never having been preempted
        (tests/test_qos.py pins this against a solo oracle).  Returns
        True when the entry was consumed (resumed, or failed on an
        unreadable record), False when pages are unavailable and it must
        stay parked."""
        from . import snapshot as snapfmt

        eng = self.engine
        t = entry.ticket
        try:
            blob = entry.blob
            if blob is None:
                with open(entry.path, "rb") as f:
                    blob = f.read()
            meta, arrays = snapfmt.loads_request(blob)
        except Exception as e:
            _log.error("parked record unreadable; request cannot resume",
                       extra={"rid": t.rid, "error": repr(e)})
            self._drop_parked_locked(entry)
            self._fail_ticket(t, "preempted")
            return True
        ps = self.pool.page_size
        pos = int(meta["pos"])
        n_data = -(-pos // ps)
        need = min(len(t.prompt) + t.max_new, eng.seq_len)
        n_total = -(-need // ps)
        if self.optimistic:
            # resume with the written pages plus headroom (same shape as
            # optimistic admission); growth resumes page-by-page
            n_alloc = max(n_data,
                          -(-min(pos + self.spill_headroom, need) // ps))
        else:
            n_alloc = n_total
        # the full ladder applies: resuming a parked request may spill
        # an idle neighbor to make room (round boundary — safe)
        pages = self._alloc_ladder_locked(n_alloc)
        if pages is None:
            return False
        extra = dict(meta.get("extra", {}))
        others = any(s.ticket is not None for s in self.slots)
        with self._engine_lock:
            if n_data:
                eng.write_pool_pages(pages[:n_data],
                                     {"pages.k": arrays["pages.k"],
                                      "pages.v": arrays["pages.v"]})
            if not others and not self._queue and "rng_key" in arrays:
                eng.set_rng(arrays["rng_key"], int(meta["chunk_counter"]),
                            dev_key_np=arrays.get("rng_dev_key"))
        s = self.slots[slot_idx]
        s.ticket = t
        s.pages = pages
        s.prefix_tokens = 0
        s.inserted = int(extra.get("fed", 0)) >= len(t.prompt)
        s.budget = n_total
        s.spilled = False
        s.active_at = now
        s.pos = pos
        s.fed = int(extra.get("fed", 0))
        s.produced = int(extra.get("produced", len(t.emitted)))
        s.last = int(extra.get("last", 0))
        t.slot = slot_idx
        row = self._page_tables[slot_idx]
        row[:] = 0
        row[:len(pages)] = pages
        parked_ms = round((now - entry.parked_at) * 1e3, 3)
        t.parked_ms += parked_ms
        self._drop_parked_locked(entry)
        obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
        obs_metrics.SCHED_SLOT_JOINS.inc(slot_idx)
        obs_trace.record("sched_resume", entry.parked_at, now, rid=t.rid,
                         slot=slot_idx, parked_ms=parked_ms, pos=pos,
                         priority=PRIORITY_NAMES.get(t.priority, t.priority))
        ctx = request_id_var.set(t.rid)
        try:
            _log.info("slot resume", extra={
                "slot": slot_idx, "pos": pos, "produced": s.produced,
                "parked_ms": parked_ms})
        finally:
            request_id_var.reset(ctx)
        obs_flight.phase(t.rid, "resumed", slot=slot_idx,
                         parked_ms=parked_ms, pos=pos)
        obs_events.emit("resume", rid=t.rid, slot=slot_idx,
                        parked_ms=parked_ms, pos=pos,
                        trace=obs_trace.trace_of(t.rid))
        return True

    def _drop_parked_locked(self, entry: _Parked) -> None:
        with contextlib.suppress(ValueError):
            self._parked.remove(entry)
        if entry.path is not None:
            import os
            with contextlib.suppress(OSError):
                os.remove(entry.path)
        obs_metrics.SCHED_PREEMPT_PARKED.set(len(self._parked))

    def _sweep_parked_locked(self, now: float) -> None:
        for e in list(self._parked):
            t = e.ticket
            if t._cancel is not None:
                self._drop_parked_locked(e)
                self._fail_ticket(t, t._cancel)
            elif t.deadline is not None and now >= t.deadline:
                self._drop_parked_locked(e)
                self._fail_ticket(t, "timeout")

    # -- KV tiering (optimistic growth → spill → page-in) --------------
    def _spill_key(self, slot_idx: int):
        """Host-pool key for one slot's spill record: the (slot, rid)
        pair, so a slot re-bound to a new ticket can never collide with
        a stale record of its previous occupant."""
        return (slot_idx, self.slots[slot_idx].ticket.rid)

    def _drop_spilled_locked(self, slot_idx: int) -> None:
        """Forget a slot's spill record (retire / park / page-in), and
        charge the spilled interval to the ticket's ``spill_ms`` clock.
        Idempotent — a no-op for slots with no record."""
        rec = self._spilled.pop(slot_idx, None)
        if rec is None:
            return
        s = self.slots[slot_idx]
        if s.ticket is not None:
            s.ticket.spill_ms += (time.monotonic() - rec["since"]) * 1e3
        if self.host_pool is not None:
            self.host_pool.drop(rec["key"])
        s.spilled = False

    def _spill_slot_locked(self, slot_idx: int) -> bool:
        """Move one slot's resident pages to the host pool (caller holds
        ``self._cond``; zero dispatches in flight — the round-boundary
        invariant _dispatch provides).  The page payload is read through
        the engine's async D2H path, stored whole in the host pool, and
        only THEN are the device pages released — a refused or failed
        spill leaves the slot fully resident, so the ladder can fall
        back to preemption without replaying anything."""
        from . import kvtier

        s = self.slots[slot_idx]
        t = s.ticket
        n = len(s.pages)
        if (self.host_pool is None or not n
                or not self.host_pool.would_fit(n * self._page_nbytes)):
            return False
        FAULTS.fire("kv.spill")
        with self._engine_lock:
            handles = self.engine.read_pool_pages_async(s.pages)
        arrays = {k: h.wait() for k, h in handles.items()}
        key = self._spill_key(slot_idx)
        if not self.host_pool.put(key, arrays, {"pos": s.pos}):
            return False
        self.pool.decref(s.pages)
        s.pages = []
        s.spilled = True
        self._page_tables[slot_idx][:] = 0
        now = time.monotonic()
        self._spilled[slot_idx] = {"key": key, "since": now, "n_pages": n}
        obs_metrics.KV_PAGES_SPILLED.inc(n)
        obs_metrics.KV_SPILL_BYTES.inc(kvtier.arrays_nbytes(arrays))
        obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
        ctx = request_id_var.set(t.rid)
        try:
            _log.info("kv spill", extra={"slot": slot_idx, "pages": n,
                                         "pos": s.pos})
        finally:
            request_id_var.reset(ctx)
        obs_flight.phase(t.rid, "kv_spill", slot=slot_idx, pages=n)
        return True

    def _spill_one_locked(self, exclude: int | None = None) -> bool:
        """Pick the best spill victim (idle-longest, index tie-break —
        kvtier.rank_victims) among active resident slots and spill it.
        ``exclude`` protects the slot the ladder is growing — spilling
        the grower to feed the grower would livelock."""
        from . import kvtier

        cands = [(i, self.slots[i].active_at) for i in self._active()
                 if i != exclude and not self.slots[i].spilled
                 and self.slots[i].pages]
        for idx in kvtier.rank_victims(cands):
            if self._spill_slot_locked(idx):
                return True
        return False

    def _alloc_ladder_locked(self, n: int, exclude: int | None = None,
                             allow_spill: bool = True):
        """Allocate ``n`` pages, escalating through the reclaim ladder:
        free list → radix-tree eviction (cold shared prefixes) → host
        spill of idle slots.  Returns the page list or None — the caller
        decides the fallback (queue the admission, park the slot).  Each
        rung only frees pages no slot row references, so recycled pages
        are safe even under an in-flight pipelined dispatch; the spill
        rung additionally reads device state and is round-boundary only
        (callers pass ``allow_spill=False`` mid-flight)."""
        if n <= 0:
            return []
        pool = self.pool
        try:
            return pool.alloc(n)
        except PagePoolExhausted:
            pass
        if self.prefix_cache is not None:
            self.prefix_cache.evict(n - pool.available)
            try:
                return pool.alloc(n)
            except PagePoolExhausted:
                pass
        if allow_spill and self.host_pool is not None:
            while pool.available < n:
                if not self._spill_one_locked(exclude):
                    return None
            try:
                return pool.alloc(n)
            except PagePoolExhausted:  # pragma: no cover - defensive
                return None
        return None

    def _grow_slot_locked(self, slot_idx: int, target_pos: int,
                          allow_spill: bool = True) -> bool:
        """Ensure ``slot_idx`` owns every page the write of token
        positions ``[0, target_pos)`` touches, growing through the
        reclaim ladder.  Growth MUST land before the dispatch that
        writes past the reserved prefix — unreserved page-table entries
        hold scratch page 0, which absorbs (and silently discards)
        overshoot writes.  Clamped to the slot's full-reservation budget
        so optimistic never holds more than full mode would."""
        s = self.slots[slot_idx]
        ps = self.pool.page_size
        need = min(-(-int(target_pos) // ps), s.budget)
        extra = need - len(s.pages)
        if extra <= 0:
            return True
        pages = self._alloc_ladder_locked(extra, exclude=slot_idx,
                                          allow_spill=allow_spill)
        if pages is None:
            return False
        s.pages.extend(pages)
        self._page_tables[slot_idx][:len(s.pages)] = s.pages
        obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
        return True

    def _try_page_in_locked(self) -> None:
        """Bring spilled slots back to residency, oldest spill first
        (FIFO — the longest-stalled consumer un-stalls first).  Runs
        before admission so freed pages prefer slots that already hold
        tickets over fresh admissions.  The ladder runs WITHOUT the
        spill rung here: paging one slot in by spilling another would
        ping-pong."""
        order = sorted(self._spilled.items(),
                       key=lambda kv: (kv[1]["since"], kv[0]))
        for slot_idx, rec in order:
            s = self.slots[slot_idx]
            pages = self._alloc_ladder_locked(rec["n_pages"],
                                              allow_spill=False)
            if pages is None:
                return
            got = self.host_pool.pop(rec["key"])
            if got is None:  # pragma: no cover - defensive
                self._spilled.pop(slot_idx, None)
                s.spilled = False
                self.pool.decref(pages)
                continue
            arrays, _meta = got
            with self._engine_lock:
                self.engine.write_pool_pages(pages, arrays)
            s.pages = list(pages)
            s.spilled = False
            row = self._page_tables[slot_idx]
            row[:] = 0
            row[:len(pages)] = pages
            t = s.ticket
            stalled_ms = (time.monotonic() - rec["since"]) * 1e3
            t.spill_ms += stalled_ms
            self._spilled.pop(slot_idx, None)
            obs_metrics.KV_PAGES_PAGED_IN.inc(len(pages))
            obs_metrics.KV_PAGES_IN_USE.set(self.pool.in_use)
            ctx = request_id_var.set(t.rid)
            try:
                _log.info("kv page-in", extra={
                    "slot": slot_idx, "pages": len(pages),
                    "stalled_ms": round(stalled_ms, 3)})
            finally:
                request_id_var.reset(ctx)
            obs_flight.phase(t.rid, "kv_pagein", slot=slot_idx,
                             pages=len(pages),
                             stalled_ms=round(stalled_ms, 3))

    def _tier_round_locked(self, now: float) -> None:
        """Between-rounds tiering pass (caller holds ``self._cond``,
        zero dispatches in flight): grow every active resident slot to
        cover the widest write the next dispatch can issue.  A slot the
        ladder cannot make room for parks (``kv_pressure``) — the same
        honest-queueing degradation as admission-time exhaustion."""
        if not self.optimistic:
            return
        reach = max(self.prefill_chunk, self.decode_burst,
                    (self.spec_k + 1) if self.spec is not None else 1)
        for i in self._active():
            s = self.slots[i]
            if s.spilled:
                continue
            target = min(s.pos + reach, int(self.engine.seq_len))
            if not self._grow_slot_locked(i, target, allow_spill=True):
                self._preempt_locked(i, "kv_pressure", now)

    def _active(self) -> list[int]:
        return [i for i, s in enumerate(self.slots) if s.ticket is not None]

    def _account(self, component: str, ms: float) -> None:
        self._comp[component] += ms
        obs_metrics.SCHED_STEP_TIME_MS.inc(component, n=ms)

    def _slot_entries(self, active, prefset, rid_by_slot, emitted) -> list:
        out = []
        for i in range(len(self.slots)):
            if i in rid_by_slot:
                out.append({"slot": i,
                            "phase": "prefill" if i in prefset else "decode",
                            "tokens": emitted.get(i, 0),
                            "request_id": rid_by_slot[i]})
            else:
                out.append({"slot": i, "phase": "pad", "tokens": 0})
        return out

    def wall_window(self) -> tuple[float, float] | None:
        """``perf_counter`` bounds of the accounted span (first dispatch
        start → latest dispatch end); the goodput components sum to this
        interval by construction.  None before the first dispatch."""
        if self._first_dispatch_at is None or self._last_dispatch_end is None:
            return None
        return self._first_dispatch_at, self._last_dispatch_end

    def _run(self) -> None:
        try:
            while True:
                with self._cond:
                    now = time.monotonic()
                    # honor cancels/deadlines first so their slots free up
                    for i in self._active():
                        t = self.slots[i].ticket
                        if t._cancel is not None:
                            self._retire(i, t._cancel)
                        elif t.deadline is not None and now >= t.deadline:
                            self._retire(i, "timeout")
                    for t in [q for q in self._queue
                              if q._cancel is not None
                              or (q.deadline is not None and now >= q.deadline)]:
                        self._queue.remove(t)
                        self._fail_ticket(t, t._cancel or "timeout")
                    self._sweep_parked_locked(now)
                    if self.paged and self._spilled:
                        # spilled slots rejoin before fresh admissions:
                        # they hold live tickets whose consumers are
                        # stalled, so freed pages go to them first
                        self._try_page_in_locked()
                    if not self._paused:
                        self._admit_locked(now)
                    if self.paged and self.optimistic:
                        self._tier_round_locked(now)
                    real_active = self._active()
                    # a spilled slot holds a ticket but no pages — it
                    # must sit out the dispatch (its page-table row is
                    # all scratch) until _try_page_in_locked restores it
                    active = [i for i in real_active
                              if not self.slots[i].spilled]
                    queued = len(self._queue)
                    obs_metrics.SCHED_SLOTS_OCCUPIED.set(len(active))
                    obs_metrics.SCHED_QUEUE_DEPTH.set(queued)
                    if self._stop:
                        return
                    if not active:
                        if self._paused and not real_active:
                            self._idle.set()
                        # parked: submissions/cancels/close notify_all
                        # immediately, so the timeout only has to cover
                        # the earliest *queued* deadline (a paused
                        # scheduler holds its queue), capped at 0.5s —
                        # the old fixed 0.1s poll burned ~10 wakeups/s
                        # doing nothing.  The slept time is "idle" in
                        # the goodput decomposition (the remainder of an
                        # inter-dispatch gap is host_gap — true
                        # scheduling overhead)
                        timeout = 0.5
                        dls = [t.deadline for t in self._queue
                               if t.deadline is not None]
                        dls += [e.ticket.deadline for e in self._parked
                                if e.ticket.deadline is not None]
                        if dls:
                            timeout = min(timeout,
                                          max(min(dls) - now, 0.0))
                        w0 = time.perf_counter()
                        self._cond.wait(timeout)
                        self._park_wakeups += 1
                        self._idle_accum += time.perf_counter() - w0
                        continue
                self._dispatch(active, queued)
        except BaseException as e:  # loop must not die silently
            _log.error("scheduler loop failed", extra={"error": repr(e)})
            raise
        finally:
            with self._cond:
                for i in self._active():
                    self._retire(i, "aborted")
                while self._queue:
                    self._fail_ticket(self._queue.popleft(), "aborted")
                for e in list(self._parked):
                    self._drop_parked_locked(e)
                    self._fail_ticket(e.ticket, "aborted")
                self._idle.set()

    def _dispatch(self, active: list[int], queued: int) -> None:
        """Run one dispatch round — and, with ``overlap`` on, keep a
        second dispatch enqueued on device while the first one's tokens
        land and fan out (a two-deep pipeline).  INVARIANT: zero
        dispatches are in flight when this returns, so admission,
        ``exclusive()``, drain and hand-off export all still happen at a
        plain step boundary."""
        cur = self._enqueue_first(active, queued)
        while True:
            nxt = None
            if cur.error is None and self.overlap:
                nxt = self._maybe_pipeline(cur)
            ok = self._land_and_fanout(cur)
            if not ok or nxt is None:
                if nxt is not None:
                    self._abandon(nxt)
                return
            survivors = self._pipeline_verdict(nxt)
            if survivors is None:
                self._abandon(nxt)
                return
            cur = nxt

    def _enqueue_first(self, active: list[int], queued: int) -> _Pending:
        """Build and enqueue the round's first (host-fed) dispatch.
        Does not block on the device — the returned handle's tokens are
        still in flight."""
        eng = self.engine
        b = eng.batch
        slots = self.slots
        prefilling = [i for i in active
                      if slots[i].fed < len(slots[i].ticket.prompt)]
        room = min(eng.seq_len - slots[i].pos for i in active)
        # consume the slots' pending draft proposals (runtime/spec.py).
        # Proposals are valid for exactly the next dispatch after the
        # burst that produced them — decode rows advance every dispatch —
        # so they are popped unconditionally here and re-validated:
        # identity-checked against the slot's *current* ticket (retire /
        # park / import all rebind), dropped whole when a prefilling row
        # joins (the mixed step has no verify shape) or the context edge
        # is closer than a full verify window (flush, not truncate: the
        # proposer re-drafts next round from exact state either way)
        props: dict[int, list[int]] = {}
        if self.spec is not None:
            with self._cond:
                pend, self._proposals = self._proposals, {}
            if not prefilling and room >= self.spec_k + 1:
                for i, (tk, d) in pend.items():
                    if i in active and slots[i].ticket is tk and d:
                        props[i] = d
        # both dispatch dimensions ride the compile key (engine.slot_step
        # caches per (T, steps, greedy)), so each is rounded down to a
        # power of two: transient values — a neighbor 3 tokens from its
        # prompt end, a row 2 tokens from its budget — would otherwise
        # mint one-off executables (PR-4 compile telemetry made that
        # visible).  O(log chunk × log burst) shapes total, each reusable.
        if props:
            # ragged verify burst: a fixed T = spec_k + 1 window (one
            # compile key per spec_k), rows with proposals feed
            # [last, d_1..d_k] and rows without ride along as plain
            # single-token decode (n_valid 1) — one slot speculating
            # never stalls a neighbor that has nothing to propose
            t_width = self.spec_k + 1
            steps = 1
        elif prefilling:
            # mixed step: prefill chunks ride along with the decode rows'
            # single tokens; steps=1 keeps every row's clock advancing by
            # its own n_valid
            t_width = min(self.prefill_chunk, room,
                          max(len(slots[i].ticket.prompt) - slots[i].fed
                              for i in prefilling))
            t_width = 1 << (t_width.bit_length() - 1)
            steps = 1
        else:
            # pure decode: burst on device, clamped so (a) no row outruns
            # the context edge and (b) queued work waits at most
            # ~max_wait_ms for the next admission boundary.  A row that
            # hits its token budget mid-burst retires and the fanout
            # discards its overrun — cheaper than letting per-row budget
            # minima pick the burst size (lockstep rows share the cost of
            # the longest-running neighbor either way)
            t_width = 1
            steps = min(self.decode_burst, room)
            if queued and self._step_ms_ema:
                steps = min(steps, max(
                    1, int(self.max_wait_ms / self._step_ms_ema)))
            steps = max(1, steps)
            steps = 1 << (steps.bit_length() - 1)

        tokens = np.zeros((b, t_width), np.int32)
        n_valid = np.ones((b,), np.int32)
        pos_rows = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        topps = np.full((b,), 0.9, np.float32)
        topks = np.zeros((b,), np.int32)
        for i in active:
            s = slots[i]
            pos_rows[i] = s.pos
            temps[i] = s.ticket.temperature
            topps[i] = s.ticket.top_p
            topks[i] = s.ticket.top_k
            if s.fed < len(s.ticket.prompt):
                c = min(t_width, len(s.ticket.prompt) - s.fed)
                tokens[i, :c] = s.ticket.prompt[s.fed:s.fed + c]
                n_valid[i] = c
            else:
                tokens[i, 0] = s.last
                d = props.get(i)
                if d is not None:
                    tokens[i, 1:1 + len(d)] = d
                    n_valid[i] = 1 + len(d)

        obs_metrics.SCHED_BATCH_EFFICIENCY.set(len(active) / b)
        prefset = set(prefilling)
        rid_by_slot = {i: slots[i].ticket.rid for i in active}
        fed_by_slot = {i: int(n_valid[i]) for i in prefilling}
        tickets = {i: slots[i].ticket for i in active}

        # inter-dispatch gap: idle (slept waiting for work) vs host_gap
        # (token fanout, admission, array prep — the overhead the
        # overlapped pipeline exists to hide)
        tp0 = time.perf_counter()
        host_gap_ms = idle_ms = 0.0
        if self._last_dispatch_end is None:
            self._first_dispatch_at = tp0
        else:
            gap_ms = max(tp0 - self._last_dispatch_end, 0.0) * 1e3
            idle_ms = min(self._idle_accum * 1e3, gap_ms)
            host_gap_ms = gap_ms - idle_ms
            self._account("idle", idle_ms)
            self._account("host_gap", host_gap_ms)
            obs_metrics.SCHED_HOST_GAP_MS.observe(host_gap_ms)
        self._idle_accum = 0.0

        handle, error = None, None
        try:
            with self._engine_lock:
                if props:
                    handle = eng.slot_verify_async(
                        tokens, pos_rows, n_valid, temps_np=temps,
                        topps_np=topps, topks_np=topks,
                        page_tables_np=self._page_tables
                        if self.paged else None)
                else:
                    handle = eng.slot_step_async(
                        tokens, pos_rows, n_valid, temps_np=temps,
                        topps_np=topps, topks_np=topks, steps=steps,
                        page_tables_np=self._page_tables
                        if self.paged else None)
        except Exception as e:
            error = e
        if handle is not None:
            self._depth += 1
            obs_metrics.SCHED_INFLIGHT_DEPTH.set(self._depth)
        return _Pending(handle=handle, error=error, active=list(active),
                        tickets=tickets, steps=steps, t_width=t_width,
                        n_valid=n_valid, temps=temps, topps=topps,
                        topks=topks,
                        prefset=prefset, rid_by_slot=rid_by_slot,
                        fed_by_slot=fed_by_slot, pos_rows=pos_rows,
                        enq_tp=tp0, t0_mono=time.monotonic(),
                        host_gap_ms=host_gap_ms, idle_ms=idle_ms,
                        overlapped=False, queued=queued,
                        verify=bool(props),
                        proposed_by_slot={i: len(d)
                                          for i, d in props.items()})

    def _maybe_pipeline(self, cur: _Pending) -> _Pending | None:
        """While ``cur`` is still in flight, speculate on the next burst:
        enqueue the next pure-decode dispatch fed by ``cur``'s on-device
        last-token row.  ("Speculate" here is dispatch pipelining — a
        guess that no flush point interrupts the round — not token
        speculation; that is the ``spec`` proposer's job.)  Returns None
        at any pipeline flush point — queued admission pending, drain /
        pause / flush request, cancel or expired deadline, a row still
        mid-prefill after ``cur``, a hand-off import, no context room —
        and the round then completes synchronously."""
        if self.spec is not None:
            # token speculation supersedes burst pipelining: a verify
            # window's *content* (the draft tokens) depends on the
            # previous dispatch's landed tokens, so the next dispatch
            # cannot be built while ``cur`` is in flight.  The verify
            # burst's multi-token yield amortizes the host gap instead.
            return None
        eng = self.engine
        slots = self.slots
        b = eng.batch
        with self._cond:
            if (self._stop or self._draining or self._paused
                    or self._flush_req or self._queue or self._parked):
                return None
            now = time.monotonic()
            pos2 = np.zeros((b,), np.int32)
            budget = 0
            for j in range(b):
                s = slots[j]
                t = s.ticket
                if j not in cur.tickets:
                    if t is not None:
                        return None   # hand-off import mid-round
                    continue
                if t is None or t is not cur.tickets[j]:
                    return None       # slot re-bound under us
                if t._cancel is not None or (t.deadline is not None
                                             and now >= t.deadline):
                    return None
                nv = int(cur.n_valid[j])
                if s.fed < len(t.prompt) and s.fed + nv < len(t.prompt):
                    return None       # still mid-prefill after cur
                pos2[j] = s.pos + nv + (cur.steps - 1)
                made = 1 if j in cur.prefset else cur.steps
                budget = max(budget, t.max_new - (s.produced + made))
            if budget < 1:
                # every row hits its token budget during ``cur``: unlike
                # the sync path (which only learns a row retired after
                # the burst lands), the pipelined dispatch knows its
                # predecessor's yield up front, so the all-overrun burst
                # is avoidable waste, not a shape-count trade
                return None
            room = min(int(eng.seq_len) - int(pos2[i])
                       for i in cur.active)
            if room < 1:
                return None
            # sized exactly like the sync burst (mid-burst retirement
            # overrun stays cheaper than minting tail shapes), so the
            # overlap on/off A/B compares dispatch pipelining alone
            steps2 = max(1, min(self.decode_burst, room))
            steps2 = 1 << (steps2.bit_length() - 1)
            if self.paged and self.optimistic:
                # pipelined chains are unbounded per round (cur = nxt
                # loops), so the round-start grow cannot cover them:
                # each burst grows its rows here.  No spill rung — a
                # D2H page read would order behind the in-flight
                # dispatch; radix eviction stays safe mid-flight (it
                # only frees pages no slot row references)
                for j in cur.active:
                    if not self._grow_slot_locked(
                            j, int(pos2[j]) + steps2, allow_spill=False):
                        return None
            # the import path rewrites _page_tables under _cond; freeze
            # a copy so the enqueue below (outside the lock) cannot
            # observe a half-written row
            ptab = self._page_tables.copy() if self.paged else None
            # reserve the in-flight count before releasing the lock so a
            # concurrent _flushed() waiter sees this dispatch coming
            self._inflight_n += 1
        handle, err = None, None
        try:
            with self._engine_lock:
                handle = eng.slot_step_async(
                    None, pos2, np.ones((b,), np.int32),
                    temps_np=cur.temps, topps_np=cur.topps,
                    topks_np=cur.topks, steps=steps2,
                    page_tables_np=ptab, feed_dev=cur.handle.last_dev)
        except Exception as e:
            err = e
        if err is not None:
            with self._cond:
                self._inflight_n -= 1
                self._cond.notify_all()
            _log.error("pipelined enqueue failed; round completes "
                       "synchronously", extra={"error": repr(err)})
            return None
        self._depth += 1
        obs_metrics.SCHED_INFLIGHT_DEPTH.set(self._depth)
        return _Pending(handle=handle, error=None,
                        active=list(cur.active), tickets=dict(cur.tickets),
                        steps=steps2, t_width=1,
                        n_valid=np.ones((b,), np.int32),
                        temps=cur.temps, topps=cur.topps, topks=cur.topks,
                        prefset=set(),
                        rid_by_slot=dict(cur.rid_by_slot), fed_by_slot={},
                        pos_rows=pos2, enq_tp=time.perf_counter(),
                        t0_mono=time.monotonic(), host_gap_ms=0.0,
                        idle_ms=0.0, overlapped=True, queued=0)

    def _attribute_cost(self, cur: _Pending, wall_ms: float) -> None:
        """Analytic roofline attribution for one landed dispatch
        (obs/cost.py): ledger FLOPs/bytes counters by (codec, path,
        phase), a cost block on every riding request's flight record,
        per-class chip-time, and the MFU/MBU gauges.

        A row's chip-time share is ``wall_ms / batch`` — summed over the
        occupied rows of every dispatch that is exactly the busy
        (prefill + decode) goodput component, so per-request chip time
        telescopes the same way the goodput clock does (pad rows' share
        is capacity waste, attributed to nobody).  FLOPs/bytes use each
        row's own useful tokens; the per-pass weight read is split
        evenly across occupied rows (that IS the batching
        amortization)."""
        cm = self.cost_model
        if cm is None or not cur.active:
            return
        rows = []
        for i in cur.active:
            if i in cur.prefset:
                rows.append(("prefill", int(cur.pos_rows[i]),
                             int(cur.n_valid[i])))
            elif cur.verify and (cur.proposed_by_slot or {}).get(i):
                rows.append(("verify", int(cur.pos_rows[i]),
                             int(cur.n_valid[i])))
            else:
                # plain decode rows advance cur.steps tokens (1 inside a
                # mixed or verify dispatch)
                rows.append(("decode", int(cur.pos_rows[i]),
                             int(cur.steps)))
        out = cm.dispatch_cost(rows, steps=cur.steps)
        obs_dispatch.record_cost(out["entries"])
        obs_cost.TRACKER.note(out["flops"], out["hbm_bytes"], wall_ms)
        mfu, mbu = obs_cost.TRACKER.mfu(), obs_cost.TRACKER.mbu()
        if mfu is not None:
            obs_metrics.MFU.set(mfu)
        if mbu is not None:
            obs_metrics.MBU.set(mbu)
        chip_ms = wall_ms / self.engine.batch
        for i, rc in zip(cur.active, out["per_row"]):
            pages = len(self.slots[i].pages) if self.paged else 0
            obs_flight.cost(cur.rid_by_slot.get(i), chip_ms=chip_ms,
                            flops=rc["flops"], hbm_bytes=rc["hbm_bytes"],
                            kv_page_ms=pages * wall_ms)
            t = (cur.tickets or {}).get(i)
            cls = PRIORITY_NAMES.get(getattr(t, "priority", 1), "standard")
            obs_metrics.CLASS_CHIP_MS.inc(cls, n=chip_ms)

    def _land_and_fanout(self, cur: _Pending) -> bool:
        """Block until ``cur``'s tokens land, charge the goodput clock,
        and fan the tokens out to their tickets.  Returns False when the
        dispatch errored (every active slot retires with the error and
        the pipeline round ends)."""
        eng = self.engine
        b = eng.batch
        tw = time.perf_counter()
        error, out = cur.error, None
        if error is None:
            try:
                out = cur.handle.wait()
            except Exception as e:
                error = e
        tp1 = time.perf_counter()
        prev_end = self._last_dispatch_end
        self._last_dispatch_end = tp1
        if cur.handle is not None:
            self._depth -= 1
            obs_metrics.SCHED_INFLIGHT_DEPTH.set(self._depth)
        self._n_dispatched += 1
        if cur.overlapped:
            self._n_overlapped += 1
            with self._cond:
                self._inflight_n -= 1
                self._cond.notify_all()
        obs_metrics.SCHED_OVERLAP_RATIO.set(
            self._n_overlapped / self._n_dispatched)

        n_pref, n_act = len(cur.prefset), len(cur.active)
        hidden_ms = 0.0
        if cur.overlapped:
            # this dispatch was enqueued while its predecessor was still
            # in flight, so the span [previous land end, this land end]
            # is the wall it owns.  The host-side share (predecessor
            # fanout + bookkeeping before wait() was called) is *hidden*
            # when the land actually had to wait — the device was still
            # computing underneath it — and *exposed* when the land
            # returned immediately (the host was the bottleneck after
            # all).  Either way every ms lands in exactly one goodput
            # component, preserving the telescoping-sum contract.
            host_ms = max(tw - prev_end, 0.0) * 1e3
            wait_ms = max(tp1 - tw, 0.0) * 1e3
            if wait_ms >= 0.1:
                hidden_ms = host_ms
                exposed_ms = 0.0
                wall_ms = host_ms + wait_ms
            else:
                exposed_ms = host_ms
                wall_ms = wait_ms
            if exposed_ms:
                self._account("host_gap", exposed_ms)
                obs_metrics.SCHED_HOST_GAP_MS.observe(exposed_ms)
            if hidden_ms:
                obs_metrics.SCHED_HOST_GAP_HIDDEN_MS.inc(hidden_ms)
            ts0 = prev_end
            gap_exposed, gap_idle = exposed_ms, 0.0
        else:
            wall_ms = (tp1 - cur.enq_tp) * 1e3
            ts0 = cur.enq_tp
            gap_exposed, gap_idle = cur.host_gap_ms, cur.idle_ms
        # split the dispatch wall by row occupancy: every row rode the
        # same lockstep step, so a row's share IS wall * rows/b
        self._account("prefill", wall_ms * n_pref / b)
        self._account("decode", wall_ms * (n_act - n_pref) / b)
        self._account("pad", wall_ms * (b - n_act) / b)
        busy = self._comp["prefill"] + self._comp["decode"]
        total = sum(self._comp.values())
        if total > 0:
            obs_metrics.SCHED_GOODPUT_RATIO.set(busy / total)

        if error is not None:
            # a failed dispatch poisons at most this round: retire every
            # active slot with the error and keep serving — stale cache
            # garbage sits above future occupants' causal ceilings
            _log.error("slot dispatch failed", extra={"error": repr(error)})
            obs_flight.TIMELINE.record_step(
                ts=ts0, wall_ms=wall_ms, host_gap_ms=gap_exposed,
                idle_ms=gap_idle, steps=cur.steps, t_width=cur.t_width,
                error=True, overlapped=cur.overlapped,
                hidden_host_ms=hidden_ms,
                slots=self._slot_entries(cur.active, cur.prefset,
                                         cur.rid_by_slot, {}))
            with self._cond:
                for i in self._active():
                    self._retire(i, "error", error=error)
            return False
        self._note_step_time(wall_ms, cur.steps, cur.handle.fresh)
        if self.engine.mesh.shape.get("tp", 1) > 1:
            # sample the mesh's all-reduce latency alongside real decode
            # traffic (rate-limited inside probe_collective) so the
            # engine_collective_ms histogram reflects the serving mesh
            # under load, not an idle microbenchmark
            with self._engine_lock:
                self.engine.probe_collective()
        if cur.verify:
            preds, accepted = out
            n_prop = sum(cur.proposed_by_slot.values())
            n_acc = sum(int(accepted[i]) for i in cur.proposed_by_slot)
            obs_trace.record("sched_verify", cur.t0_mono, time.monotonic(),
                             active=n_act, queued=cur.queued,
                             t=cur.t_width, proposed=n_prop, accepted=n_acc,
                             rids=sorted(cur.rid_by_slot.values()))
        else:
            obs_trace.record("sched_step", cur.t0_mono, time.monotonic(),
                             active=n_act, queued=cur.queued,
                             t=cur.t_width, steps=cur.steps,
                             overlapped=cur.overlapped,
                             rids=sorted(cur.rid_by_slot.values()))

        FAULTS.fire("sched.host_fanout")
        emitted = dict.fromkeys(cur.active, 0)
        # the whole fanout holds _cond (re-entrant with the _retire calls
        # below): slot clocks (pos/fed/produced/last) and the ticket's
        # emitted list must never be observable half-advanced by the
        # hand-off exporter, which snapshots them from another thread
        with self._cond:
            if cur.verify:
                self._fanout_verify(cur.active, preds, accepted,
                                    cur.proposed_by_slot, emitted)
            else:
                self._fanout(cur.active, cur.steps, out, cur.n_valid,
                             emitted)

        # flight phases + timeline entry for this dispatch (after the
        # fanout so the emitted-token counts are final; a row retired
        # mid-burst still gets its last burst recorded)
        step_ms = wall_ms / cur.steps
        for i in cur.active:
            rid = cur.rid_by_slot[i]
            if i in cur.prefset:
                # a completing chunk also emits the first sampled token —
                # recorded as ``emitted`` on the chunk, not a zero-wall
                # synthetic burst
                obs_flight.phase(rid, "prefill_chunk",
                                 tokens=cur.fed_by_slot[i], ms=wall_ms,
                                 pos=int(cur.pos_rows[i]),
                                 emitted=emitted[i])
            elif cur.verify:
                obs_flight.phase(rid, "verify_burst",
                                 proposed=cur.proposed_by_slot.get(i, 0),
                                 accepted=int(accepted[i]),
                                 tokens=emitted[i], wall_ms=wall_ms)
            else:
                obs_flight.phase(rid, "decode_burst", steps=cur.steps,
                                 tokens=emitted[i], wall_ms=wall_ms,
                                 step_ms=step_ms)
        self._attribute_cost(cur, wall_ms)
        obs_flight.TIMELINE.record_step(
            ts=ts0, wall_ms=wall_ms,
            device_ms=getattr(eng, "last_slot_dispatch_ms", None),
            host_gap_ms=gap_exposed, idle_ms=gap_idle, steps=cur.steps,
            t_width=cur.t_width, overlapped=cur.overlapped,
            hidden_host_ms=hidden_ms,
            slots=self._slot_entries(cur.active, cur.prefset,
                                     cur.rid_by_slot, emitted))
        if self.spec is not None:
            self._collect_proposals()
        return True

    def _pipeline_verdict(self, nxt: _Pending) -> list[int] | None:
        """After ``nxt``'s predecessor landed and fanned out with
        ``nxt`` still in flight: decide whether ``nxt``'s tokens may
        be emitted.  Returns the surviving slot list, or None for a hard
        flush (``nxt`` must be discarded).  A slot that merely retired
        in the predecessor's fanout (EOS / budget) survives row-wise
        removal — the burst computed its row for nothing, which is
        cheaper than flushing the whole pipeline."""
        slots = self.slots
        with self._cond:
            if (self._stop or self._draining or self._paused
                    or self._flush_req or self._queue or self._parked):
                return None
            now = time.monotonic()
            survivors = []
            for j in range(len(slots)):
                s = slots[j]
                if j not in nxt.tickets:
                    if s.ticket is not None:
                        return None   # import bound a slot mid-pipeline
                    continue
                t = s.ticket
                if t is None:
                    continue          # retired by the predecessor's fanout
                if t is not nxt.tickets[j]:
                    return None       # slot re-bound (import into freed row)
                if t._cancel is not None or (t.deadline is not None
                                             and now >= t.deadline):
                    return None       # honor the step boundary, like sync
                survivors.append(j)
            if not survivors:
                return None
            nxt.active = survivors
            nxt.tickets = {j: nxt.tickets[j] for j in survivors}
            nxt.rid_by_slot = {j: nxt.rid_by_slot[j] for j in survivors}
            return survivors

    def _abandon(self, nxt: _Pending) -> None:
        """Land and discard an in-flight pipelined dispatch at a flush
        point.  No slot clock ever advanced for it and its tokens are
        never emitted, so greedy output is byte-identical to never
        having pipelined: its KV writes all sit above every surviving
        row's position — masked by the causal ceiling and rewritten
        identically by the synchronous redo dispatch, exactly like slot
        reuse.  The sampler RNG tick it consumed is not rewound: sampled
        draws are co-scheduling-dependent by contract (module
        docstring); greedy rows never touch the stream."""
        try:
            nxt.handle.wait()
        except Exception as e:
            # the discarded dispatch owns its own failure — nothing was
            # emitted from it; the next live dispatch re-probes the device
            _log.error("discarded in-flight dispatch failed", extra={
                "error": repr(e)})
        tp1 = time.perf_counter()
        prev_end = self._last_dispatch_end
        self._last_dispatch_end = tp1
        self._depth -= 1
        obs_metrics.SCHED_INFLIGHT_DEPTH.set(self._depth)
        self._n_dispatched += 1
        self._n_overlapped += 1
        obs_metrics.SCHED_OVERLAP_RATIO.set(
            self._n_overlapped / self._n_dispatched)
        with self._cond:
            self._inflight_n -= 1
            self._cond.notify_all()
        wall_ms = max(tp1 - prev_end, 0.0) * 1e3
        # burned device capacity, not goodput
        self._account("pad", wall_ms)
        obs_metrics.SCHED_OVERLAP_DISCARDS.inc()
        obs_flight.TIMELINE.record_step(
            ts=prev_end, wall_ms=wall_ms, steps=nxt.steps, t_width=1,
            overlapped=True, discarded=True,
            slots=self._slot_entries([], set(), {}, {}))

    def _note_step_time(self, wall_ms: float, steps: int,
                        fresh: bool) -> None:
        """Fold one dispatch's per-step wall into the EMA that clamps
        burst size under queue pressure — except fresh-compile
        dispatches, whose trace+compile seconds would poison the EMA and
        pin bursts near 1 for dozens of dispatches after every new
        compile key."""
        if fresh:
            return
        step_ms = wall_ms / max(1, steps)
        self._step_ms_ema = step_ms if self._step_ms_ema is None \
            else 0.8 * self._step_ms_ema + 0.2 * step_ms

    def _fanout(self, active: list[int], steps: int, out, n_valid,
                emitted: dict[int, int]) -> None:
        """Distribute one dispatch's sampled tokens to their tickets and
        advance the slot clocks.  Caller holds ``self._cond``."""
        eng = self.engine
        slots = self.slots
        now = time.monotonic()
        for i in active:
            # the spill victim clock: a slot that took part in this
            # dispatch was active now, whatever it emitted
            slots[i].active_at = now
        for j in range(steps):
            for i in active:
                s = slots[i]
                t = s.ticket
                if t is None:  # retired earlier this burst
                    continue
                tok = int(out[j, i])
                if j == 0 and s.fed < len(t.prompt):
                    s.fed += int(n_valid[i])
                    s.pos += int(n_valid[i])
                    if s.fed < len(t.prompt):
                        continue  # mid-prefill: sample not meaningful yet
                    # prefill just completed: this sample IS the first
                    # completion token — fall through to emit it.  The
                    # prompt's full pages are now entirely written and will
                    # never be rewritten (the clock only moves forward), so
                    # this is the moment they become shareable.
                    if self.prefix_cache is not None and not s.inserted:
                        s.inserted = True
                        ps = self.pool.page_size
                        n_full = len(t.prompt) // ps
                        if n_full:
                            self.prefix_cache.insert(
                                t.prompt[:n_full * ps], s.pages[:n_full])
                else:
                    s.pos += 1
                s.last = tok
                if tok in t.eos_ids:
                    with self._cond:
                        self._retire(i, "stop")
                    continue
                s.produced += 1
                emitted[i] += 1
                t.emitted.append(tok)
                t._q.put(tok)
                if s.produced >= t.max_new or s.pos >= eng.seq_len:
                    with self._cond:
                        self._retire(i, "length")

    def _fanout_verify(self, active: list[int], preds, accepted,
                       proposed_by_slot: dict[int, int],
                       emitted: dict[int, int]) -> None:
        """Distribute one verify dispatch's tokens and advance the slot
        clocks.  Row ``i`` emits ``preds[i, :accepted[i]+1]`` — every
        token is the model's own (argmax) prediction, so the stream is
        byte-identical to plain decode; the drafts only chose how many
        positions one dispatch got to check.  A rejection truncates that
        row alone (its clock advances by its own accepted count; the
        rejected tail's KV sits above the new position, dead under the
        causal ceiling).  EOS or budget mid-window retires the row and
        discards the rest of its window, exactly like a decode burst.
        Caller holds ``self._cond``."""
        eng = self.engine
        slots = self.slots
        now = time.monotonic()
        for i in active:
            s = slots[i]
            s.active_at = now
            t = s.ticket
            if t is None:  # retired between enqueue and land
                continue
            a = int(accepted[i])
            k = proposed_by_slot.get(i, 0)
            if k:
                t.spec_proposed += k
                t.spec_accepted += a
                self._spec_proposed += k
                self._spec_accepted += a
                obs_metrics.SCHED_SPEC_PROPOSED.inc(k)
                if a:
                    obs_metrics.SCHED_SPEC_ACCEPTED.inc(self.spec.name, n=a)
            for tok in (int(preds[i, j]) for j in range(a + 1)):
                s.pos += 1
                s.last = tok
                if tok in t.eos_ids:
                    self._retire(i, "stop")
                    break
                s.produced += 1
                emitted[i] += 1
                t.emitted.append(tok)
                t._q.put(tok)
                if s.produced >= t.max_new or s.pos >= eng.seq_len:
                    self._retire(i, "length")
                    break
        if self._spec_proposed:
            obs_metrics.SCHED_SPEC_ACCEPT_RATIO.set(
                self._spec_accepted / self._spec_proposed)

    def _collect_proposals(self) -> None:
        """After a dispatch fans out: let each live, greedy, decode-phase
        slot draft up to ``spec_k`` tokens for the *next* dispatch.  Runs
        on the scheduler thread only; slot clocks are stable here.  The
        proposer call happens outside ``_cond`` (a draft-model proposer
        dispatches its own engine), so storage re-validates ticket
        identity — a slot parked or retired mid-draft simply loses its
        proposal, which the consume-time check would also have caught."""
        spec = self.spec
        eng = self.engine
        slots = self.slots
        want: dict[int, int] = {}
        with self._cond:
            if (self._stop or self._draining or self._paused
                    or self._flush_req or self._queue or self._parked):
                # a flush point (or pending admission, which makes the
                # next dispatch a mixed prefill step) is imminent:
                # drafting now would be discarded at consume — refuse
                # speculation instead of wasting proposer work
                return
            now = time.monotonic()
            tick = {}
            for i in self._active():
                s = slots[i]
                t = s.ticket
                if (t.temperature != 0.0 or s.fed < len(t.prompt)
                        or t._cancel is not None
                        or (t.deadline is not None and now >= t.deadline)):
                    continue
                if eng.seq_len - s.pos < self.spec_k + 1:
                    # the verify window is fixed at spec_k + 1 columns no
                    # matter how few tokens this row drafts, so a row
                    # that close to the context edge cannot ride one
                    continue
                # drafting past the token budget is pure waste (the
                # fanout discards the overrun as the row retires), so k
                # is clamped to remaining-budget - 1: the window's bonus
                # token is the one that lands exactly on the budget
                k = min(self.spec_k, t.max_new - s.produced - 1)
                if k < 1:
                    continue
                spec.sync(i, t.rid, t.prompt, t.emitted)
                want[i] = k
                tick[i] = t
        if not want:
            return
        props = spec.propose(want)
        with self._cond:
            for i, d in props.items():
                t = slots[i].ticket
                if t is None or t is not tick.get(i):
                    continue
                d = d[:want[i]]
                if d:
                    self._proposals[i] = (t, d)
