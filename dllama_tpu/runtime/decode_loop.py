"""On-device generation loop: K decode steps + sampling in one XLA program.

The reference's decode loop crosses the host boundary every token — logits
to the host sampler, the sampled token back to the cluster
(`generate` dllama.cpp:53-72, `Sampler::sample` tokenizer.cpp:384-407).
On a tunneled/remote TPU that round trip costs ~100 ms, dwarfing the
~20 ms device step.  Here the whole sample→embed→forward chain runs inside
a ``lax.scan``: one dispatch yields a chunk of K tokens and only the int32
token ids cross the boundary.

Sampling parity: greedy (temperature 0) is exact argmax, identical to the
reference.  Temperature/top-p uses the JAX counter-based PRNG instead of
the reference's xorshift stream — same distribution, different stream; the
host Sampler (sampling.py) remains available for bit-exact parity runs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.transformer import (KVCache, forward_last, forward_slots,
                                  forward_slots_all)
from ..ops.kernels import softmax_f32


def device_sample(logits: jax.Array, key: jax.Array, temperature: float,
                  topp: float) -> jax.Array:
    """Sample token ids (B,) from logits (B, V) on device.

    Mirrors Sampler::sample's three modes (tokenizer.cpp:384-407):
    temperature 0 → argmax; top-p outside (0,1) → plain multinomial;
    otherwise nucleus sampling.  ``temperature``/``topp`` are static so each
    mode compiles to its own minimal program.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    probs = softmax_f32(logits / temperature)  # (B, V)
    if topp <= 0.0 or topp >= 1.0:
        return jax.random.categorical(key, jnp.log(probs), axis=-1).astype(jnp.int32)

    # nucleus: sort descending, keep the smallest prefix with mass > topp
    # (tokenizer.cpp:328-369 semantics), renormalize, sample within it
    sorted_probs, sorted_idx = jax.lax.top_k(probs, probs.shape[-1])
    cum = jnp.cumsum(sorted_probs, axis=-1)
    keep = (cum - sorted_probs) < topp  # include the first token crossing topp
    filtered = jnp.where(keep, sorted_probs, 0.0)
    choice = jax.random.categorical(key, jnp.log(filtered), axis=-1)  # index into sorted order
    return jnp.take_along_axis(sorted_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)


def decode_chunk(params, cfg: ModelConfig, cache: KVCache, token: jax.Array,
                 pos: jax.Array, key: jax.Array, *, steps: int,
                 temperature: float, topp: float,
                 offsets: jax.Array | None = None):
    """Generate ``steps`` tokens starting from ``token`` (B,) at ``pos``.

    Returns (tokens (steps, B), cache, last_token, new_pos, key).  The
    caller jits this with ``steps``/``temperature``/``topp`` static and the
    cache donated.  Every batch row carries its own token and samples its
    own next token; ``offsets`` (B,) is the ragged-batch left-padding
    vector threaded to the forward pass (per-row RoPE positions and
    attention key floors) so distinct streams decode in lockstep.
    """

    def body(carry, _):
        cache, token, pos, key = carry
        logits, cache = forward_last(params, cfg, token[:, None], cache, pos,
                                     jnp.int32(0), offsets=offsets)
        key, sub = jax.random.split(key)
        nxt = device_sample(logits, sub, temperature, topp)
        return (cache, nxt, pos + 1, key), nxt

    (cache, last, pos, key), toks = jax.lax.scan(
        body, (cache, token, pos, key), None, length=steps)
    return toks, cache, last, pos, key


def device_sample_rows(logits: jax.Array, key: jax.Array, temps: jax.Array,
                       topps: jax.Array, greedy: bool) -> jax.Array:
    """Per-row-parameter sampling (B, V) → (B,) for continuous-batching
    slots: rows belong to *different requests*, so temperature/top-p
    arrive as (B,) traced arrays rather than static floats — one compiled
    program serves any mix of per-request settings.  Rows with
    temperature 0 take the exact argmax (same op as device_sample's
    greedy mode, so a slot stream is byte-identical to a solo greedy
    run); ``greedy`` is static and compiles an all-greedy batch down to
    the argmax alone.
    """
    arg = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if greedy:
        return arg
    t = jnp.maximum(temps, 1e-6)[:, None]
    probs = softmax_f32(logits / t)  # (B, V)
    # vectorized nucleus (device_sample semantics per row); top-p outside
    # (0, 1) degrades to plain multinomial by widening the kept prefix to
    # the whole vocab
    sorted_probs, sorted_idx = jax.lax.top_k(probs, probs.shape[-1])
    cum = jnp.cumsum(sorted_probs, axis=-1)
    tp = jnp.where((topps > 0.0) & (topps < 1.0), topps, 1.0)[:, None]
    keep = (cum - sorted_probs) < tp
    filtered = jnp.where(keep, sorted_probs, 0.0)
    choice = jax.random.categorical(key, jnp.log(filtered), axis=-1)
    sampled = jnp.take_along_axis(sorted_idx, choice[:, None],
                                  axis=-1)[:, 0].astype(jnp.int32)
    return jnp.where(temps == 0.0, arg, sampled)


def slot_chunk(params, cfg: ModelConfig, cache: KVCache, tokens: jax.Array,
               pos_rows: jax.Array, n_valid: jax.Array, key: jax.Array,
               temps: jax.Array, topps: jax.Array, *, steps: int,
               greedy: bool, page_table: jax.Array | None = None):
    """One continuous-batching dispatch: a mixed prefill/decode forward
    over (B, T) slot rows, then ``steps - 1`` pure decode steps — all one
    XLA program, so slot serving keeps decode_chunk's amortization (only
    (steps, B) int32 ids cross the host boundary).

    Row ``r`` consumes its first ``n_valid[r]`` tokens at positions
    ``pos_rows[r]..``; its first output token is sampled from its last
    valid position, and each subsequent step feeds every row its own
    previous sample.  The scheduler uses ``steps > 1`` (a decode burst)
    only when no slot is mid-prefill; free rows ride along at position 0
    and their samples are discarded host-side.

    Returns (tokens (steps, B), cache, last (B,)).  ``last`` is the
    final sampled row — the same values as ``tokens[-1]``, surfaced as
    its own output so a pipelined caller can feed it straight into the
    next dispatch as a device array (no device→host→device round trip
    in pure decode).  The caller advances per-slot positions host-side
    (``pos += n_valid``, then +1 per extra step).

    ``page_table`` (B, max_pages) switches the cache to a paged pool:
    pages are pre-reserved at admission for the whole request (prompt +
    budget), so the table is constant across the chunk and rides the
    compiled program as one extra int32 operand.
    """
    logits, cache = forward_slots(params, cfg, tokens, cache, pos_rows,
                                  n_valid, page_table=page_table)
    key, sub = jax.random.split(key)
    first = device_sample_rows(logits, sub, temps, topps, greedy)
    pos_rows = pos_rows + n_valid

    def body(carry, _):
        cache, tok, pos_rows, key = carry
        logits, cache = forward_slots(params, cfg, tok[:, None], cache,
                                      pos_rows, jnp.ones_like(pos_rows),
                                      page_table=page_table)
        key, sub = jax.random.split(key)
        nxt = device_sample_rows(logits, sub, temps, topps, greedy)
        return (cache, nxt, pos_rows + 1, key), nxt

    if steps > 1:
        (cache, last, _, _), rest = jax.lax.scan(
            body, (cache, first, pos_rows, key), None, length=steps - 1)
        toks = jnp.concatenate([first[None], rest], axis=0)
    else:
        toks, last = first[None], first
    return toks, cache, last


def slot_verify_chunk(params, cfg: ModelConfig, cache: KVCache,
                      tokens: jax.Array, pos_rows: jax.Array,
                      n_valid: jax.Array, key: jax.Array, temps: jax.Array,
                      topps: jax.Array, *, greedy: bool,
                      page_table: jax.Array | None = None):
    """One ragged slot-verify dispatch (speculative decoding's verify
    side, Leviathan et al. 2023 greedy rule): row ``r`` feeds
    ``[last_token, d_1..d_{n_valid[r]-1}]`` — its previous sample plus
    its proposed draft tokens — and gets back the model's prediction at
    every fed position plus the count of leading drafts that matched.

    Returns ``(preds (B, T), cache, accepted (B,), last (B,))``:

    * ``preds[r, j]`` is the true next token after ``tokens[r, :j+1]``
      (argmax for greedy rows, so every emitted token is byte-identical
      to plain decode); the caller emits ``preds[r, :accepted[r]+1]`` —
      the matched drafts re-derived from the model's own argmax, plus
      the one bonus token the verify forward gives for free.
    * ``accepted[r]`` counts the leading ``preds``-matching drafts,
      clamped to ``n_valid[r] - 1`` so a no-proposal row (``n_valid``
      1) degrades to one plain decode step — one slot speculating never
      perturbs a neighbor that isn't.
    * ``last[r] = preds[r, accepted[r]]`` stays device-resident so a
      pipelined caller could feed it onward like slot_chunk's ``last``.

    Rows with temperature > 0 never carry proposals (the scheduler only
    drafts for greedy rows); their position-0 prediction is drawn with
    their own sampling params so riding a verify burst is equivalent to
    riding a decode burst.  KV rows written for rejected drafts sit
    above the row's accepted ceiling — dead by the same causal-ceiling
    masking that makes slot reuse free.
    """
    logits, cache = forward_slots_all(params, cfg, tokens, cache, pos_rows,
                                      n_valid, page_table=page_table)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, T)
    if not greedy:
        key, sub = jax.random.split(key)
        first = device_sample_rows(logits[:, 0], sub, temps, topps, greedy)
        preds = preds.at[:, 0].set(first)
    t = tokens.shape[1]
    # leading-match count: draft j (fed at column j+1) is accepted iff it
    # equals the model's prediction at column j and every earlier draft
    # was accepted too — cumprod turns the match mask into leading-ones
    ok = (tokens[:, 1:] == preds[:, :-1]) \
        & (jnp.arange(t - 1)[None, :] < (n_valid - 1)[:, None])
    accepted = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
    accepted = accepted.astype(jnp.int32)  # (B,)
    last = jnp.take_along_axis(preds, accepted[:, None], axis=1)[:, 0]
    return preds, cache, accepted, last
