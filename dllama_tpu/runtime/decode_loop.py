"""On-device generation loop: K decode steps + sampling in one XLA program.

The reference's decode loop crosses the host boundary every token — logits
to the host sampler, the sampled token back to the cluster
(`generate` dllama.cpp:53-72, `Sampler::sample` tokenizer.cpp:384-407).
On a tunneled/remote TPU that round trip costs ~100 ms, dwarfing the
~20 ms device step.  Here the whole sample→embed→forward chain runs inside
a ``lax.scan``: one dispatch yields a chunk of K tokens and only the int32
token ids cross the boundary.

Sampling parity: greedy (temperature 0) is exact argmax, identical to the
reference.  Temperature/top-k/top-p runs ``sampling.sample_on_device`` —
a branch-for-branch mirror of the host reference's decision rules driven
by one uniform coin per (row, step), so a fixed coin picks the same token
as ``sampling.sample_with_coin`` on the host.  The *coin stream* comes
from the engine's device-resident JAX key (threefry), not the reference's
xorshift; the host Sampler (sampling.py) remains available for bit-exact
parity runs against the reference stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.transformer import (KVCache, forward_last, forward_slots,
                                  forward_slots_all)
from ..sampling import sample_on_device


def _record_sample_dev(rows: int) -> None:
    # trace-time ledger entry (once per compiled call site, like the
    # matmul/attention paths): the sampled stage ran on device, no host
    # round trip
    from ..obs import dispatch as obs_dispatch
    obs_dispatch.record_dispatch("sample", "sample-dev", rows=rows)


def device_sample(logits: jax.Array, key: jax.Array, temperature: float,
                  topp: float, topk: int = 0,
                  mask: jax.Array | None = None) -> jax.Array:
    """Sample token ids (B,) from logits (B, V) on device.

    Mirrors Sampler::sample's modes (tokenizer.cpp:384-407): temperature
    0 → argmax; top-p outside (0,1) → plain multinomial; otherwise
    nucleus sampling — all via :func:`sampling.sample_on_device`, the
    coin-based host mirror.  ``temperature``/``topp``/``topk`` are
    static so each mode compiles to its own minimal program; ``mask`` is
    the optional vocab keep-mask (grammar seam, identity today).
    """
    if temperature == 0.0:
        if mask is not None:
            logits = jnp.where(jnp.asarray(mask).astype(bool), logits,
                               -jnp.inf)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    b = logits.shape[0]
    _record_sample_dev(b)
    coins = jax.random.uniform(key, (b,), jnp.float32)
    return sample_on_device(
        logits, coins,
        jnp.full((b,), temperature, jnp.float32),
        jnp.full((b,), topp, jnp.float32),
        jnp.full((b,), topk, jnp.int32), mask=mask)


def decode_chunk(params, cfg: ModelConfig, cache: KVCache, token: jax.Array,
                 pos: jax.Array, key: jax.Array, *, steps: int,
                 temperature: float, topp: float,
                 offsets: jax.Array | None = None):
    """Generate ``steps`` tokens starting from ``token`` (B,) at ``pos``.

    Returns (tokens (steps, B), cache, last_token, new_pos, key).  The
    caller jits this with ``steps``/``temperature``/``topp`` static and the
    cache donated.  Every batch row carries its own token and samples its
    own next token; ``offsets`` (B,) is the ragged-batch left-padding
    vector threaded to the forward pass (per-row RoPE positions and
    attention key floors) so distinct streams decode in lockstep.
    """

    def body(carry, _):
        cache, token, pos, key = carry
        logits, cache = forward_last(params, cfg, token[:, None], cache, pos,
                                     jnp.int32(0), offsets=offsets)
        key, sub = jax.random.split(key)
        nxt = device_sample(logits, sub, temperature, topp)
        return (cache, nxt, pos + 1, key), nxt

    (cache, last, pos, key), toks = jax.lax.scan(
        body, (cache, token, pos, key), None, length=steps)
    return toks, cache, last, pos, key


def device_sample_rows(logits: jax.Array, key: jax.Array, temps: jax.Array,
                       topps: jax.Array, greedy: bool,
                       topks: jax.Array | None = None,
                       mask: jax.Array | None = None) -> jax.Array:
    """Per-row-parameter sampling (B, V) → (B,) for continuous-batching
    slots: rows belong to *different requests*, so temperature/top-p/
    top-k arrive as (B,) traced arrays rather than static floats — one
    compiled program serves any mix of per-request settings.  Rows with
    temperature 0 take the exact argmax (same op as device_sample's
    greedy mode, so a slot stream is byte-identical to a solo greedy
    run); ``greedy`` is static and compiles an all-greedy batch down to
    the argmax alone (no coin drawn, no key consumed).  Sampled rows run
    :func:`sampling.sample_on_device` — the coin-based mirror of the
    host reference, one uniform coin per row from ``key``.  ``mask`` is
    the optional vocab keep-mask (grammar seam, identity today).
    """
    if greedy:
        if mask is not None:
            logits = jnp.where(jnp.asarray(mask).astype(bool), logits,
                               -jnp.inf)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    b = logits.shape[0]
    _record_sample_dev(b)
    coins = jax.random.uniform(key, (b,), jnp.float32)
    if topks is None:
        topks = jnp.zeros((b,), jnp.int32)
    return sample_on_device(logits, coins, temps, topps, topks, mask=mask)


def slot_chunk(params, cfg: ModelConfig, cache: KVCache, tokens: jax.Array,
               pos_rows: jax.Array, n_valid: jax.Array, key: jax.Array,
               temps: jax.Array, topps: jax.Array,
               topks: jax.Array | None = None, *, steps: int,
               greedy: bool, page_table: jax.Array | None = None,
               vocab_mask: jax.Array | None = None):
    """One continuous-batching dispatch: a mixed prefill/decode forward
    over (B, T) slot rows, then ``steps - 1`` pure decode steps — all one
    XLA program, so slot serving keeps decode_chunk's amortization (only
    (steps, B) int32 ids cross the host boundary).

    Row ``r`` consumes its first ``n_valid[r]`` tokens at positions
    ``pos_rows[r]..``; its first output token is sampled from its last
    valid position, and each subsequent step feeds every row its own
    previous sample.  The scheduler uses ``steps > 1`` (a decode burst)
    only when no slot is mid-prefill; free rows ride along at position 0
    and their samples are discarded host-side.

    Returns (tokens (steps, B), cache, last (B,), key).  ``last`` is the
    final sampled row — the same values as ``tokens[-1]``, surfaced as
    its own output so a pipelined caller can feed it straight into the
    next dispatch as a device array (no device→host→device round trip
    in pure decode).  ``key`` is the advanced device RNG key: sampled
    chunks split one sub-key per step and return the chain tail, so the
    engine can thread it into the next dispatch without a host round
    trip (greedy chunks return it untouched — no coin was drawn).  The
    caller advances per-slot positions host-side (``pos += n_valid``,
    then +1 per extra step).

    ``page_table`` (B, max_pages) switches the cache to a paged pool:
    pages are pre-reserved at admission for the whole request (prompt +
    budget), so the table is constant across the chunk and rides the
    compiled program as one extra int32 operand.
    """
    logits, cache = forward_slots(params, cfg, tokens, cache, pos_rows,
                                  n_valid, page_table=page_table)
    if not greedy:
        key, sub = jax.random.split(key)
    else:
        sub = key
    first = device_sample_rows(logits, sub, temps, topps, greedy, topks,
                               vocab_mask)
    pos_rows = pos_rows + n_valid

    def body(carry, _):
        cache, tok, pos_rows, key = carry
        logits, cache = forward_slots(params, cfg, tok[:, None], cache,
                                      pos_rows, jnp.ones_like(pos_rows),
                                      page_table=page_table)
        if not greedy:
            key, sub = jax.random.split(key)
        else:
            sub = key
        nxt = device_sample_rows(logits, sub, temps, topps, greedy, topks,
                                 vocab_mask)
        return (cache, nxt, pos_rows + 1, key), nxt

    if steps > 1:
        (cache, last, _, key), rest = jax.lax.scan(
            body, (cache, first, pos_rows, key), None, length=steps - 1)
        toks = jnp.concatenate([first[None], rest], axis=0)
    else:
        toks, last = first[None], first
    return toks, cache, last, key


def slot_verify_chunk(params, cfg: ModelConfig, cache: KVCache,
                      tokens: jax.Array, pos_rows: jax.Array,
                      n_valid: jax.Array, key: jax.Array, temps: jax.Array,
                      topps: jax.Array, topks: jax.Array | None = None,
                      *, greedy: bool, page_table: jax.Array | None = None,
                      vocab_mask: jax.Array | None = None):
    """One ragged slot-verify dispatch (speculative decoding's verify
    side, Leviathan et al. 2023 greedy rule): row ``r`` feeds
    ``[last_token, d_1..d_{n_valid[r]-1}]`` — its previous sample plus
    its proposed draft tokens — and gets back the model's prediction at
    every fed position plus the count of leading drafts that matched.

    Returns ``(preds (B, T), cache, accepted (B,), last (B,), key)``
    (``key`` advanced one split for sampled batches, untouched for
    greedy — same chain contract as :func:`slot_chunk`):

    * ``preds[r, j]`` is the true next token after ``tokens[r, :j+1]``
      (argmax for greedy rows, so every emitted token is byte-identical
      to plain decode); the caller emits ``preds[r, :accepted[r]+1]`` —
      the matched drafts re-derived from the model's own argmax, plus
      the one bonus token the verify forward gives for free.
    * ``accepted[r]`` counts the leading ``preds``-matching drafts,
      clamped to ``n_valid[r] - 1`` so a no-proposal row (``n_valid``
      1) degrades to one plain decode step — one slot speculating never
      perturbs a neighbor that isn't.
    * ``last[r] = preds[r, accepted[r]]`` stays device-resident so a
      pipelined caller could feed it onward like slot_chunk's ``last``.

    Rows with temperature > 0 never carry proposals (the scheduler only
    drafts for greedy rows); their position-0 prediction is drawn with
    their own sampling params so riding a verify burst is equivalent to
    riding a decode burst.  KV rows written for rejected drafts sit
    above the row's accepted ceiling — dead by the same causal-ceiling
    masking that makes slot reuse free.
    """
    logits, cache = forward_slots_all(params, cfg, tokens, cache, pos_rows,
                                      n_valid, page_table=page_table)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, T)
    if not greedy:
        key, sub = jax.random.split(key)
        first = device_sample_rows(logits[:, 0], sub, temps, topps, greedy,
                                   topks, vocab_mask)
        preds = preds.at[:, 0].set(first)
    t = tokens.shape[1]
    # leading-match count: draft j (fed at column j+1) is accepted iff it
    # equals the model's prediction at column j and every earlier draft
    # was accepted too — cumprod turns the match mask into leading-ones
    ok = (tokens[:, 1:] == preds[:, :-1]) \
        & (jnp.arange(t - 1)[None, :] < (n_valid - 1)[:, None])
    accepted = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
    accepted = accepted.astype(jnp.int32)  # (B,)
    last = jnp.take_along_axis(preds, accepted[:, None], axis=1)[:, 0]
    return preds, cache, accepted, last, key
