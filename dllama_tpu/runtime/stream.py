"""Shared generation-stream consumption for the chat CLI and the API server.

One state machine (prompt-echo skip, EOS/stop-string detection with
held-back partial matches, end-of-budget flush, KV overshoot rewind) so the
two front ends cannot drift: the pos-rewind arithmetic interacts with
``Engine.generate_stream``'s own eos-id rewind and the on-device chunk
overshoot, and must stay identical in both.
"""

from __future__ import annotations

from typing import Callable

from ..obs.log import get_logger
from ..tokenizer.eos import EOS, MAYBE_EOS, EosDetector

_log = get_logger("runtime.stream")


def drain_generation(engine, tokenizer, detector: EosDetector, stream,
                     n_prompt: int, prompt_end: int,
                     on_delta: Callable[[str], None]) -> tuple[str, int, bool]:
    """Consume ``stream`` (an Engine.generate_stream iterator), calling
    ``on_delta(text)`` as text becomes safe to emit.

    Returns ``(reply, n_completion, ended_by_eos)``.  On return,
    ``engine.pos`` has been rewound past any chunk-overshoot tokens that
    were sampled after a stop string — they were never part of the reply
    and must not condition later turns.
    """
    content: list[str] = []
    prev = tokenizer.bos_id
    n_completion = 0
    ended_by_eos = False
    for i, (token, _) in enumerate(stream):
        if i < n_prompt:  # prompt tokens are echoed first (engine contract)
            prev = token
            continue
        n_completion += 1
        # Per-piece decode, NOT an incremental UTF-8 decoder: the
        # EosDetector's stop arithmetic is character-position-based per
        # piece, and a decoder that carries dangling bytes into the next
        # piece shifts those positions (an eos piece would swallow the
        # carried replacement char; a stop piece's trailing fragment would
        # flush AFTER the truncation point).  The cost is cosmetic: a
        # codepoint split across byte-fallback tokens renders as one
        # U+FFFD per fragment here.  The batched completions stream
        # (server/api.py complete_batch_stream) reassembles those — its
        # stop logic is buffer-based, so the carry is safe there.
        piece = tokenizer.decode_piece(prev, token).decode("utf-8", errors="replace")
        prev = token
        res = detector.append(token, piece)
        if res == MAYBE_EOS:
            continue  # hold back a potential partial stop-string match
        delta = detector.get_delta()
        if delta:
            content.append(delta)
            on_delta(delta)
        detector.clear()
        if res == EOS:
            ended_by_eos = True
            break
    if not ended_by_eos:
        # budget exhausted with a partial stop-string match held back —
        # it was real text, flush it
        delta = detector.get_delta()
        if delta:
            content.append(delta)
            on_delta(delta)
    # One position convention for every stop kind (ADVICE r01): the last
    # consumed token — eos id, stop-string tail, or the final budgeted
    # token — was sampled but never fed to the model, so the cache holds
    # prompt + (n_completion − 1) positions.  The engine's internal eos-id
    # rewind and the natural end-of-stream accounting already land there;
    # this clamp brings the abandoned-mid-chunk (stop-string) case in line.
    engine.pos = min(engine.pos, prompt_end + max(n_completion - 1, 0))
    _log.info("decode", extra={
        "n_prompt": n_prompt, "n_completion": n_completion,
        "ended_by_eos": ended_by_eos, "pos": engine.pos})
    return "".join(content), n_completion, ended_by_eos
