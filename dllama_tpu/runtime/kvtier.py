"""KV memory tiering: pinned host-RAM spill pool + victim policy.

The paged pool (runtime/pagepool.py) bounds KV memory by *reserved*
pages, and under ``--kv-reserve full`` reservation is worst-case:
``ceil((len + max_new)/page)`` pages at admission, most of which short
requests never touch.  Optimistic reservation (``--kv-reserve
optimistic``) admits with only ``ceil((prompt_len + spill_headroom)/
page)`` pages and grows slots page-by-page at decode time — which means
a mid-decode grow can find the pool empty while neighbors sit on pages
they are not actively extending.  This module supplies the two pieces
the scheduler's grow ladder needs beyond ``RadixTree.evict``:

* :class:`HostPagePool` — a bytes-bounded host-RAM store for spilled
  page payloads (values + scale planes for int8 pages), keyed by slot.
  ``put`` refuses rather than grows past ``--kv-host-pool-mb``: a spill
  that cannot be stored falls back to the preempt/park path, so
  over-commit always degrades to queueing, never to lost bytes.
* :func:`rank_victims` — the deterministic eviction order: idle-longest
  slot first (oldest last-activity clock), slot index as the tie-break.
  Determinism matters the same way it does for the page allocator's
  ascending free-list: byte-parity drills must see the same spill
  pattern every run.

The device-to-host copies themselves are the engine's job
(``Engine.read_pool_pages_async``: a device-side gather enqueued behind
the in-flight dispatch, then a non-blocking ``copy_to_host_async`` — the
transfer hides behind whatever the device is already running, and
``wait()`` only blocks if the host got there first).
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs.log import get_logger

_log = get_logger("runtime.kvtier")


def arrays_nbytes(arrays: dict) -> int:
    """Total payload bytes of one spill record's array dict."""
    return sum(int(np.asarray(a).nbytes) for a in arrays.values())


class HostPagePool:
    """Bytes-bounded ``key -> {name: ndarray}`` store for spilled KV.

    One record per spilled slot (the slot's whole resident working set
    moves together — pages page back in as a unit when the slot rejoins
    the dispatch).  The capacity check happens *before* the put, so a
    refused spill leaves the pool untouched and the caller's pages still
    resident; ``capacity_bytes <= 0`` disables spilling entirely (every
    put refuses), which is the ``--kv-host-pool-mb 0`` escape hatch.
    """

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        self._records: dict = {}
        self._bytes = 0

    # -- capacity ----------------------------------------------------------
    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def would_fit(self, nbytes: int) -> bool:
        with self._lock:
            return self._bytes + int(nbytes) <= self.capacity_bytes

    # -- records -----------------------------------------------------------
    def put(self, key, arrays: dict, meta: dict | None = None) -> bool:
        """Store one spill record; returns False (pool unchanged) when it
        would not fit or the key is already present (a double spill of
        the same slot is a caller bug surfaced as a refusal, not silent
        clobbering of bytes a resume still needs)."""
        nbytes = arrays_nbytes(arrays)
        with self._lock:
            if key in self._records:
                return False
            if self._bytes + nbytes > self.capacity_bytes:
                return False
            self._records[key] = ({k: np.asarray(v) for k, v in
                                   arrays.items()}, dict(meta or {}), nbytes)
            self._bytes += nbytes
        obs_metrics.KV_HOST_POOL_BYTES.set(self.bytes_used)
        return True

    def get(self, key):
        """Peek a record without removing it: ``(arrays, meta)`` or None."""
        with self._lock:
            rec = self._records.get(key)
            return (rec[0], rec[1]) if rec is not None else None

    def pop(self, key):
        """Remove and return ``(arrays, meta)`` or None."""
        with self._lock:
            rec = self._records.pop(key, None)
            if rec is not None:
                self._bytes -= rec[2]
        if rec is not None:
            obs_metrics.KV_HOST_POOL_BYTES.set(self.bytes_used)
            return rec[0], rec[1]
        return None

    def drop(self, key) -> None:
        self.pop(key)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._bytes = 0
        obs_metrics.KV_HOST_POOL_BYTES.set(0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._records


def rank_victims(candidates) -> list:
    """Order spill candidates: idle-longest first, index tie-break.

    ``candidates`` is an iterable of ``(slot_idx, last_activity)`` where
    ``last_activity`` is the slot's monotonic clock of its most recent
    dispatch participation (``_Slot.active_at``).  The oldest clock — the
    slot that
    has gone longest without producing — is the cheapest to stall, so it
    spills first.  Ties (same clock, e.g. slots admitted in the same
    dispatch) break by ascending slot index, keeping the order a pure
    function of scheduler state.
    """
    return [idx for idx, _ in
            sorted(candidates, key=lambda c: (c[1], c[0]))]
