"""Host-side paged-KV bookkeeping: a refcounted page pool and a radix
prefix tree over it.

The device side (ops/attention.py paged section) only sees int32 page
tables; everything about *which* physical page backs *which* logical
block of *which* request lives here, on the scheduler thread.  Two
structures:

* :class:`PagePool` — the allocator.  Physical page 0 is permanently
  pinned as the scratch page (invalid writes are redirected there, see
  ``paged_write_indices``); pages 1..n-1 carry refcounts so a page can
  be owned by several slots (shared prefix) plus the prefix tree at
  once, and returns to the free list only when the last reference drops.

* :class:`RadixTree` — SGLang-style prefix cache, one node per
  page-sized token block.  After a request's prefill completes, its
  full prompt-covered pages are inserted keyed by their token blocks
  (the tree takes its own reference).  A later prompt that walks the
  same token blocks binds the cached pages copy-free and prefills only
  its suffix.  Eviction drops least-recently-used leaves whose pages
  nothing else references, so the tree never steals memory from live
  requests.

Correctness of sharing rests on two invariants kept by the scheduler:
slot RoPE clocks always start at absolute position 0 (so a prefix's KV
is bit-identical no matter which request computed it), and only *whole*
pages are shared with fresh tail pages allocated per request (so shared
pages are never written after insertion).
"""

from __future__ import annotations


class PagePoolExhausted(RuntimeError):
    """No free pages for an allocation; the caller defers admission."""


class PagePool:
    """Refcounted allocator over ``n_pages`` physical KV pages.

    Page 0 is the scratch page: pinned with one permanent reference,
    never handed out, never freed.  Allocation hands out the lowest
    free page ids first (deterministic tests; locality is irrelevant —
    pages are gathered by id anyway).
    """

    def __init__(self, n_pages: int, page_size: int):
        if n_pages < 2:
            raise ValueError("paged pool needs >= 2 pages (page 0 is scratch)")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._refs = [0] * self.n_pages
        self._refs[0] = 1  # scratch, pinned forever
        # stack popping ascending ids: reversed so .pop() yields 1, 2, …
        self._free = list(range(self.n_pages - 1, 0, -1))

    @property
    def capacity(self) -> int:
        """Usable pages (excludes the scratch page)."""
        return self.n_pages - 1

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    def alloc(self, n: int) -> list[int]:
        """Allocate ``n`` fresh pages (refcount 1 each) or raise
        :class:`PagePoolExhausted` without allocating any."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise PagePoolExhausted(
                f"need {n} pages, {len(self._free)} free of {self.capacity}")
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        return out

    def incref(self, pages) -> None:
        """Add a reference to already-live pages (prefix sharing)."""
        for p in pages:
            if self._refs[p] <= 0:
                raise RuntimeError(f"incref on dead page {p}")
            self._refs[p] += 1

    def decref(self, pages) -> None:
        """Drop one reference per page; pages reaching zero return to the
        free list."""
        for p in pages:
            if p == 0:
                raise RuntimeError("decref on scratch page 0")
            if self._refs[p] <= 0:
                raise RuntimeError(f"decref on dead page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)

    def claim(self, page: int) -> None:
        """Allocate a *specific* free page id (snapshot restore rebuilding
        the prefix tree's ownership)."""
        if page == 0:
            raise RuntimeError("cannot claim scratch page 0")
        try:
            self._free.remove(page)
        except ValueError:
            raise RuntimeError(f"claim of non-free page {page}") from None
        self._refs[page] = 1

    def check(self) -> None:
        """Invariant audit (tests, fault drills): refcounts non-negative,
        scratch pinned, the free list exactly the zero-ref pages, no
        duplicates."""
        if self._refs[0] < 1:
            raise AssertionError("scratch page 0 lost its pin")
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate pages on the free list")
        if 0 in free:
            raise AssertionError("scratch page 0 on the free list")
        for p in range(1, self.n_pages):
            if self._refs[p] < 0:
                raise AssertionError(f"negative refcount on page {p}")
            if (self._refs[p] == 0) != (p in free):
                raise AssertionError(
                    f"page {p}: refs={self._refs[p]} vs free={p in free}")


class _Node:
    __slots__ = ("block", "page", "children", "last_used")

    def __init__(self, block: tuple, page: int):
        self.block = block
        self.page = page
        self.children: dict = {}
        self.last_used = 0


class RadixTree:
    """Prefix cache keyed on page-sized token blocks.

    Each node owns exactly one KV page holding that block's keys/values
    and carries one pool reference for as long as it stays in the tree.
    Matching walks full blocks only (a partial block's KV cannot be
    shared — the page would still be written by its owner); recency is a
    monotonic clock bumped on every match/insert touch, giving the
    evictor an LRU order without wall-clock time.
    """

    def __init__(self, pool: PagePool):
        self.pool = pool
        self.page_size = pool.page_size
        self._children: dict = {}  # root's children: {token-block: _Node}
        self._clock = 0
        self._n_nodes = 0

    def __len__(self) -> int:
        return self._n_nodes

    def _blocks(self, tokens) -> list[tuple]:
        ps = self.page_size
        n_full = len(tokens) // ps
        return [tuple(tokens[i * ps:(i + 1) * ps]) for i in range(n_full)]

    def match(self, tokens) -> tuple[int, list[int]]:
        """Longest cached prefix of ``tokens`` in whole blocks: returns
        (matched token count, the pages backing it, root-first).  Touches
        matched nodes' recency but takes NO pool references — the caller
        increfs the pages it decides to bind (before any further
        allocation, so eviction cannot race the hit)."""
        self._clock += 1
        children = self._children
        pages: list[int] = []
        for blk in self._blocks(tokens):
            nd = children.get(blk)
            if nd is None:
                break
            nd.last_used = self._clock
            pages.append(nd.page)
            children = nd.children
        return len(pages) * self.page_size, pages

    def insert(self, tokens, pages) -> int:
        """Retain ``tokens``' full blocks backed by ``pages`` (parallel
        lists, root-first).  Existing nodes are kept (first writer wins —
        the prefix KV is identical by construction, see module docstring);
        new nodes take a pool reference on their page.  Returns the number
        of newly retained pages."""
        self._clock += 1
        children = self._children
        added = 0
        for blk, page in zip(self._blocks(tokens), pages):
            nd = children.get(blk)
            if nd is None:
                nd = _Node(blk, page)
                self.pool.incref([page])
                children[blk] = nd
                self._n_nodes += 1
                added += 1
            nd.last_used = self._clock
            children = nd.children
        return added

    def evict(self, n_pages: int) -> int:
        """Free at least ``n_pages`` pages by dropping LRU *leaf* nodes
        whose pages only the tree references (live requests are never
        robbed).  Returns the number actually freed (may be less when
        everything else is shared or interior)."""
        freed = 0
        while freed < n_pages:
            victim_parent = victim_key = victim = None
            stack = [(self._children, k, nd) for k, nd in self._children.items()]
            while stack:
                parent, key, nd = stack.pop()
                if nd.children:
                    stack.extend((nd.children, k, c)
                                 for k, c in nd.children.items())
                    continue
                # leaf: evictable only if the tree holds the last reference
                if self.pool._refs[nd.page] == 1 and (
                        victim is None or nd.last_used < victim.last_used):
                    victim_parent, victim_key, victim = parent, key, nd
            if victim is None:
                break
            del victim_parent[victim_key]
            self._n_nodes -= 1
            self.pool.decref([victim.page])
            freed += 1
        return freed

    def drop_all(self) -> int:
        """Release every retained page (scheduler close/reset)."""
        freed = 0

        def walk(children):
            nonlocal freed
            for nd in children.values():
                walk(nd.children)
                self.pool.decref([nd.page])
                freed += 1

        walk(self._children)
        self._children = {}
        self._n_nodes = 0
        return freed

    # -- snapshot plumbing (runtime/snapshot.py DLSNAP02) -------------------

    def export(self) -> list:
        """JSON-serializable nested form: [[block tokens], page, children]."""
        def walk(children):
            return [[list(nd.block), nd.page, walk(nd.children)]
                    for nd in children.values()]

        return walk(self._children)

    def restore(self, data: list) -> None:
        """Rebuild from :meth:`export` output against a *fresh* pool whose
        page contents were restored out-of-band (the pool arrays ride the
        engine snapshot): claims each node's page from the free list."""
        if self._children:
            raise RuntimeError("restore into a non-empty prefix tree")

        def walk(children, items):
            for block, page, kids in items:
                self.pool.claim(page)
                nd = _Node(tuple(block), int(page))
                nd.last_used = self._clock
                children[tuple(block)] = nd
                self._n_nodes += 1
                walk(nd.children, kids)

        self._clock += 1
        walk(self._children, data)
