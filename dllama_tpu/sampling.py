"""Token sampler: greedy argmax / temperature / top-p (nucleus).

Behavior-compatible with the reference ``Sampler``
(/root/reference/src/tokenizer.cpp:294-415), including the xorshift RNG
(`utils.cpp:53-64`) so that fixed-seed runs are reproducible against the
reference.  The host path is vectorized numpy; ``sample_on_device`` is a
jit-friendly variant that keeps the vocab-size logits on the TPU and
transfers only the chosen token id per step.
"""

from __future__ import annotations

import numpy as np


def xorshift_u32(state: int) -> tuple[int, int]:
    """xorshift RNG step (utils.cpp:53-58). Returns (new_state, value)."""
    state &= 0xFFFFFFFFFFFFFFFF
    state ^= (state >> 12)
    state ^= (state << 25) & 0xFFFFFFFFFFFFFFFF
    state ^= (state >> 27)
    value = ((state * 0x2545F4914F6CDD1D) & 0xFFFFFFFFFFFFFFFF) >> 32
    return state, value


def xorshift_f32(state: int) -> tuple[int, float]:
    """Uniform [0, 1) float (utils.cpp:61-64: top 8 bits discarded / 2^24)."""
    state, value = xorshift_u32(state)
    return state, (value >> 8) / 16777216.0


def softmax(x: np.ndarray) -> np.ndarray:
    x = x - x.max()
    e = np.exp(x)
    return e / e.sum()


def sample_mult(probs: np.ndarray, coin: float) -> int:
    """Multinomial via CDF walk (tokenizer.cpp:307-318)."""
    cdf = np.cumsum(probs)
    idx = int(np.searchsorted(cdf, coin, side="right"))
    return min(idx, len(probs) - 1)


def sample_topp(probs: np.ndarray, topp: float, coin: float) -> int:
    """Nucleus sampling (tokenizer.cpp:328-369).

    Keeps candidates with p ≥ (1-topp)/(n-1), sorts descending, truncates at
    cumulative > topp, then samples within the truncated mass.
    """
    n = len(probs)
    cutoff = (1.0 - topp) / (n - 1)
    idx = np.nonzero(probs >= cutoff)[0]
    if len(idx) == 0:
        # degenerate near-uniform distribution: nothing survives the cutoff
        # (reference hits UB here, tokenizer.cpp:344-347); sample plainly
        return sample_mult(probs, coin)
    # stable sort descending by prob; ties keep index order like qsort's
    # comparator returning 0 for equals (implementation-defined but stable
    # here for determinism)
    order = idx[np.argsort(-probs[idx], kind="stable")]
    p = probs[order]
    cum = np.cumsum(p)
    over = np.nonzero(cum > topp)[0]
    last = int(over[0]) if len(over) else len(order) - 1
    r = coin * cum[last]
    pick = int(np.searchsorted(cum[: last + 1], r, side="right"))
    return int(order[min(pick, last)])


class Sampler:
    def __init__(self, vocab_size: int, temperature: float, topp: float, seed: int):
        self.vocab_size = vocab_size
        self.temperature = temperature
        self.topp = topp
        self.rng_state = seed & 0xFFFFFFFFFFFFFFFF

    def set_temp(self, temperature: float):
        self.temperature = temperature

    def set_seed(self, seed: int):
        self.rng_state = seed & 0xFFFFFFFFFFFFFFFF

    def sample(self, logits: np.ndarray) -> int:
        logits = np.asarray(logits, dtype=np.float32).reshape(-1)[: self.vocab_size]
        if self.temperature == 0.0:
            return int(np.argmax(logits))
        probs = softmax(logits / self.temperature)
        self.rng_state, coin = xorshift_f32(self.rng_state)
        if self.topp <= 0 or self.topp >= 1:
            return sample_mult(probs, coin)
        return sample_topp(probs, self.topp, coin)
