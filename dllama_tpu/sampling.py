"""Token sampler: greedy argmax / temperature / top-p (nucleus).

Behavior-compatible with the reference ``Sampler``
(/root/reference/src/tokenizer.cpp:294-415), including the xorshift RNG
(`utils.cpp:53-64`) so that fixed-seed runs are reproducible against the
reference.  The host path is vectorized numpy; ``sample_on_device`` is a
jit-friendly variant that keeps the vocab-size logits on the TPU and
transfers only the chosen token id per step.
"""

from __future__ import annotations

import numpy as np


def xorshift_u32(state: int) -> tuple[int, int]:
    """xorshift RNG step (utils.cpp:53-58). Returns (new_state, value)."""
    state &= 0xFFFFFFFFFFFFFFFF
    state ^= (state >> 12)
    state ^= (state << 25) & 0xFFFFFFFFFFFFFFFF
    state ^= (state >> 27)
    value = ((state * 0x2545F4914F6CDD1D) & 0xFFFFFFFFFFFFFFFF) >> 32
    return state, value


def xorshift_f32(state: int) -> tuple[int, float]:
    """Uniform [0, 1) float (utils.cpp:61-64: top 8 bits discarded / 2^24)."""
    state, value = xorshift_u32(state)
    return state, (value >> 8) / 16777216.0


def softmax(x: np.ndarray) -> np.ndarray:
    x = x - x.max()
    e = np.exp(x)
    return e / e.sum()


def sample_mult(probs: np.ndarray, coin: float) -> int:
    """Multinomial via CDF walk (tokenizer.cpp:307-318)."""
    cdf = np.cumsum(probs)
    idx = int(np.searchsorted(cdf, coin, side="right"))
    return min(idx, len(probs) - 1)


def sample_topp(probs: np.ndarray, topp: float, coin: float) -> int:
    """Nucleus sampling (tokenizer.cpp:328-369).

    Keeps candidates with p ≥ (1-topp)/(n-1), sorts descending, truncates at
    cumulative > topp, then samples within the truncated mass.
    """
    n = len(probs)
    cutoff = (1.0 - topp) / (n - 1)
    idx = np.nonzero(probs >= cutoff)[0]
    if len(idx) == 0:
        # degenerate near-uniform distribution: nothing survives the cutoff
        # (reference hits UB here, tokenizer.cpp:344-347); sample plainly
        return sample_mult(probs, coin)
    # stable sort descending by prob; ties keep index order like qsort's
    # comparator returning 0 for equals (implementation-defined but stable
    # here for determinism)
    order = idx[np.argsort(-probs[idx], kind="stable")]
    p = probs[order]
    cum = np.cumsum(p)
    over = np.nonzero(cum > topp)[0]
    last = int(over[0]) if len(over) else len(order) - 1
    r = coin * cum[last]
    pick = int(np.searchsorted(cum[: last + 1], r, side="right"))
    return int(order[min(pick, last)])


def apply_topk(logits: np.ndarray, topk: int) -> np.ndarray:
    """Keep the ``topk`` largest logits (ties at the bar all survive),
    -inf the rest.  0 (or >= n) disables.  Threshold rule (k-th largest
    value, keep ``>=``) matches the device mirror exactly so fixed-coin
    parity holds through ties."""
    n = len(logits)
    if topk <= 0 or topk >= n:
        return logits
    thresh = np.partition(logits, n - topk)[n - topk]
    return np.where(logits < thresh, -np.inf, logits)


def sample_with_coin(logits: np.ndarray, coin: float, *, temperature: float,
                     topp: float, topk: int = 0,
                     mask: np.ndarray | None = None) -> int:
    """One sampling decision from an explicit uniform ``coin`` — the host
    reference the device path (:func:`sample_on_device`) mirrors
    branch-for-branch: vocab mask → top-k filter → temperature →
    (greedy | nucleus | plain multinomial).  ``mask`` is an optional
    boolean keep-vector (the grammar seam — identity today)."""
    logits = np.asarray(logits, dtype=np.float32).reshape(-1)
    if mask is not None:
        logits = np.where(np.asarray(mask, dtype=bool).reshape(-1),
                          logits, -np.inf)
    logits = apply_topk(logits, int(topk))
    if temperature == 0.0:
        return int(np.argmax(logits))
    probs = softmax(logits / temperature)
    if topp <= 0 or topp >= 1:
        return sample_mult(probs, coin)
    return sample_topp(probs, topp, coin)


def sample_on_device(logits, coins, temps, topps, topks, mask=None):
    """Jit-friendly batched mirror of :func:`sample_with_coin`.

    ``logits`` (B, V) stay on device; ``coins``/``temps``/``topps``/
    ``topks`` are (B,) per-row parameters and ``mask`` an optional
    (V,)- or (B, V)-broadcastable boolean keep-mask.  Returns (B,) int32
    token ids.  Every branch reproduces the host reference's decision
    rule on the same f32 probabilities — descending ``top_k`` breaks
    ties by lower index exactly like the host's stable sort, the
    nucleus prefix/cutoff/renormalized-CDF walk follows
    tokenizer.cpp:328-369 — so a fixed coin picks the same token on
    both paths (the distribution-parity test contract)."""
    import jax
    import jax.numpy as jnp

    lf = logits.astype(jnp.float32)
    v = lf.shape[-1]
    if mask is not None:
        lf = jnp.where(jnp.asarray(mask).astype(bool), lf, -jnp.inf)

    def row(lr, coin, temp, topp, topk):
        # top-k: k-th largest value as threshold, ties at the bar survive
        svals = jax.lax.top_k(lr, v)[0]
        thresh = svals[jnp.clip(topk - 1, 0, v - 1)]
        lr = jnp.where((topk > 0) & (lr < thresh), -jnp.inf, lr)
        greedy_tok = jnp.argmax(lr).astype(jnp.int32)
        probs = jax.nn.softmax(lr / jnp.where(temp > 0.0, temp, 1.0))
        # plain multinomial: CDF walk = searchsorted(cdf, coin, "right")
        cdf = jnp.cumsum(probs)
        mult_tok = jnp.clip(jnp.sum(cdf <= coin), 0, v - 1).astype(jnp.int32)
        # nucleus: descending probs put every p >= cutoff in a prefix
        sp, si = jax.lax.top_k(probs, v)
        cutoff = (1.0 - topp) / (v - 1)
        cand = sp >= cutoff
        ncand = jnp.sum(cand)
        cum = jnp.cumsum(sp)
        over = (cum > topp) & cand
        last = jnp.where(jnp.any(over), jnp.argmax(over),
                         jnp.maximum(ncand - 1, 0))
        r = coin * cum[last]
        pick = jnp.sum((cum <= r) & (jnp.arange(v) <= last))
        topp_tok = si[jnp.minimum(pick, last)].astype(jnp.int32)
        use_topp = (topp > 0.0) & (topp < 1.0) & (ncand > 0)
        sampled = jnp.where(use_topp, topp_tok, mult_tok)
        return jnp.where(temp == 0.0, greedy_tok, sampled)

    return jax.vmap(row)(lf, coins, temps, topps, topks.astype(jnp.int32))


class Sampler:
    def __init__(self, vocab_size: int, temperature: float, topp: float,
                 seed: int, topk: int = 0):
        self.vocab_size = vocab_size
        self.temperature = temperature
        self.topp = topp
        self.topk = int(topk)
        self.rng_state = seed & 0xFFFFFFFFFFFFFFFF

    def set_temp(self, temperature: float):
        self.temperature = temperature

    def set_seed(self, seed: int):
        self.rng_state = seed & 0xFFFFFFFFFFFFFFFF

    def sample(self, logits: np.ndarray, mask: np.ndarray | None = None) -> int:
        logits = np.asarray(logits, dtype=np.float32).reshape(-1)[: self.vocab_size]
        if mask is not None:
            mask = np.asarray(mask, dtype=bool).reshape(-1)[: self.vocab_size]
        if self.temperature == 0.0:
            return sample_with_coin(logits, 0.0, temperature=0.0,
                                    topp=self.topp, topk=self.topk, mask=mask)
        self.rng_state, coin = xorshift_f32(self.rng_state)
        return sample_with_coin(logits, coin, temperature=self.temperature,
                                topp=self.topp, topk=self.topk, mask=mask)
