"""Subprocess environment helpers for backend selection.

The session image pins JAX to the axon TPU backend at interpreter start
(sitecustomize registers it whenever ``PALLAS_AXON_POOL_IPS`` is set), and a
backend cannot be re-selected in-process once initialized.  Anything that
needs a CPU mesh from a TPU-pinned parent — the multichip dryrun, the CLI
tests, the bench CPU fallback — must therefore spawn a child process whose
environment forces CPU *before* JAX loads.  This is the one shared copy of
that recipe.
"""

from __future__ import annotations

import os


def forced_cpu_env(n_devices: int = 1, base: dict | None = None) -> dict:
    """Environment that selects the CPU backend with ``n_devices`` virtual
    XLA devices, regardless of what the parent process's backend is.

    Any pre-existing ``--xla_force_host_platform_device_count`` flag is
    replaced (not merely appended to) so a stale count of 1 cannot shadow
    the requested mesh size.
    """
    env = dict(os.environ if base is None else base)
    env["PALLAS_AXON_POOL_IPS"] = ""   # axon sitecustomize gates on this
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env
