"""On-device Q40 weights: packed storage + fused dequant-matmul.

TPU-native replacement for the reference's production matmul path — the
Q40×Q80 NEON/AVX2 kernel (`/root/reference/src/funcs.cpp:287-386`) that
reads 4-bit weight nibbles, applies per-32-block f16 scales, and
accumulates against quantized activations.  Here the weights stay packed in
HBM and a Pallas kernel fuses nibble-unpack + scale + matmul, so decode —
which is HBM-bandwidth-bound — streams 0.5625 bytes/weight instead of 2
(bf16), a ~3.5× roofline advantage over the bf16 matvec.  (Design target;
driver-captured numbers live in BENCH_r*.json.)

Device layout (block-local, chosen so any 32-row slice is self-contained
and therefore tensor-parallel sharding on either axis never splits a
block):

* ``qpacked`` uint8 ``(..., N/2, D)`` — for block ``b`` along the input
  axis N, packed row ``16b + r`` holds logical row ``32b + r`` in its low
  nibble and logical row ``32b + 16 + r`` in its high nibble, biased +8.
  (The reference's own BlockQ40 uses the same lo/hi split within a block,
  quants.hpp:17-20.)
* ``scales`` uint16 ``(..., N/32, D)`` — the per-block f16 deltas exactly
  as the `.m` file stores them (quants.hpp:17-20), 0.0625 B/weight, held
  as raw bits because the Mosaic dialect has no f16 type; both matmul
  paths widen f16-bits→f32 exactly (subnormals included), so
  dequantization is bit-identical to the reference codec.

Two matmul implementations:

* ``pallas`` — the fused kernel.  A `pallas_call` is not auto-partitioned
  by GSPMD, so on a multi-device mesh it runs **per shard under
  ``jax.shard_map``** (see :func:`_sharded_matmul`): the caller declares the
  weight's TP slicing ``kind`` — ``"row"`` (output dim sharded, the
  reference's RowMatmulSlice, commands.cpp:8-40: no communication) or
  ``"col"`` (input dim sharded, ColMatmulSlice commands.cpp:42-70: one
  ``psum`` over ``tp`` for the partial sums, the all-reduce the reference
  hand-rolls as gather+merge+rebroadcast, llama2-tasks.cpp:115-131).  The
  block-local packed layout guarantees an even shard never splits a
  quantization block on either axis.
* ``xla``   — plain-jnp emulation (unpack → scale → dot).  Partitionable
  under GSPMD (reshapes split the sharded axis at block granularity), used
  for prefill (compute-bound anyway), CPU tests, and as the fallback when
  shapes don't divide the mesh evenly.  XLA materializes the dequantized
  operand, so it is not the fast path for decode.

Activations stay bf16 — the TPU analogue of the reference's Q80 activation
quantization (whose purpose is wire compression, tasks.cpp:124-163; on a
TPU mesh the "wire" is ICI inside the XLA program, and bf16 keeps the MXU
fed without a quantize/dequantize round trip).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from . import pallas_compat
from .. import quants
from ..obs import dispatch as obs_dispatch
from ..parallel.mesh import get_active_mesh, shard_map

# Sweet spot measured on v5e (HBM-roofline for the 4096×11008 matvec);
# shrunk automatically when N or D is smaller.  Env-overridable so
# tools/sweep_q40.py can explore the tile space on hardware without edits.
TILE_N = int(os.environ.get("DLLAMA_Q40_TILE_N", "1024"))
TILE_D = int(os.environ.get("DLLAMA_Q40_TILE_D", "1024"))
# Decode uses the Pallas kernel; past this many rows the matmul is MXU-bound
# and the XLA path (which can pipeline the dequant) is preferable.
PALLAS_MAX_ROWS = 128
# Kernel dequant variant (see _q40_kernel): classic | fma | folded | exact.
KERNEL_VARIANT = os.environ.get("DLLAMA_Q40_VARIANT", "classic")


def padded_n(n: int) -> int:
    """Storage row count: the input dim padded to a TILE_N multiple.

    Small reduction tiles destroy kernel throughput (tile_n=256 measured
    ~10× slower than 1024 on v5e), so odd input dims (e.g. Llama-2's 11008
    hidden) are padded at pack time: padded *scales are zero*, making the
    padded region contribute exactly 0 to every dot product regardless of
    the nibble bytes; ``matmul`` zero-pads the activation columns to match.
    Cost: +2.3 % HBM on llama2-7B's 11008 hidden; up to +9 % on the
    padded tensor for small zoo models (TinyLlama's 5632 → 6144), a few
    % of total model bytes."""
    if n <= TILE_N:
        return n  # a single full-axis tile is always legal
    return ((n + TILE_N - 1) // TILE_N) * TILE_N


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class QTensor:
    """A Q40 tensor of logical shape ``(..., n, d)``, packed for the MXU.

    Storage rows cover ``padded_n(n)`` input positions (see above)."""

    qpacked: jax.Array          # uint8  (..., padded_n/2, d)
    scales: jax.Array           # uint16 (..., padded_n/32, d) — f16 bits
    logical_nd: tuple[int, int] = field(metadata=dict(static=True))

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.qpacked.shape[:-2]) + self.logical_nd

    @property
    def dtype(self):  # duck-types as an array for shape/dtype introspection
        return jnp.bfloat16


def alloc_value_plane(lead: tuple, np_: int, d: int) -> np.ndarray:
    """Preallocated host value plane for ``repack_file_bytes_into`` fills
    (codec-API twin of q8.alloc_value_plane — the loader stays
    codec-agnostic): Q40 packs two rows per byte."""
    return np.zeros((*lead, np_ // 2, d), np.uint8)


Tensor = QTensor  # codec-generic alias (q8.Tensor = Q8Tensor)


def pack_planes_np(qvals: np.ndarray, scales: np.ndarray
                   ) -> tuple[np.ndarray, np.ndarray, tuple[int, int]]:
    """Pack int8 nibble values ``(..., n, d)`` in [-8, 7] + scales
    ``(..., n/32, d)`` into the block-local layout as **host numpy arrays**
    (padding the input dim to ``padded_n``; padded scales are zero).
    Returns ``(packed u8, scales f16, logical_nd)`` — the loader uses this
    to fill preallocated stacks without device round trips."""
    *lead, n, d = qvals.shape
    np_ = padded_n(n)
    b = (qvals + 8).astype(np.uint8).reshape(*lead, n // 32, 32, d)
    lo = b[..., :16, :]
    hi = b[..., 16:, :]
    packed = (lo | (hi << 4)).reshape(*lead, n // 2, d)
    if np_ != n:
        packed = np.concatenate(
            [packed, np.zeros((*lead, (np_ - n) // 2, d), np.uint8)], axis=-2)
        scales = np.concatenate(
            [scales, np.zeros((*lead, (np_ - n) // 32, d), scales.dtype)], axis=-2)
    return packed, scales.astype(np.float16), (n, d)


def pack_planes(qvals: np.ndarray, scales: np.ndarray) -> QTensor:
    """Device-array wrapper over :func:`pack_planes_np` (scales upload as
    their f16 bit pattern — see the module docstring)."""
    packed, sc, nd = pack_planes_np(qvals, scales)
    # every QTensor producer funnels through here (quantize, pack_planes_t)
    # except the raw-byte loader (pack_file_groups, same check there): a
    # block whose delta overflowed f16 must fail loudly — the in-kernel
    # bit decode has no exp==0x1F branch and would yield finite garbage
    # (ADVICE r03)
    if not np.isfinite(sc).all():
        raise ValueError(
            "Q40 scale overflowed f16 (|block amax| > 8*65504) or is NaN — "
            "quantizing these values would corrupt the packed planes")
    return QTensor(jnp.asarray(packed), jnp.asarray(sc.view(np.uint16)), nd)


def quantize(w: np.ndarray) -> QTensor:
    """Quantize a float array ``(..., n, d)`` to Q40 along the input axis
    (axis -2) — converter semantics (writer.py:29-56): ``delta = amax/-8``,
    ``q = clamp(floor(x/delta + 8.5), 0, 15)``."""
    w = np.asarray(w, np.float32)
    *lead, n, d = w.shape
    if n % quants.BLOCK_SIZE:
        raise ValueError(f"input dim {n} not divisible by {quants.BLOCK_SIZE}")
    g = w.reshape(*lead, n // 32, 32, d)
    gmax = g.max(axis=-2)
    gmin = g.min(axis=-2)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    # codec parity (quants.quantize_q40 / writer.py:29-56): q from the raw
    # f32 delta, stored scale rounded to the file's f16 precision
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = np.clip(g * inv[..., None, :] + 8.5, 0.0, 15.0).astype(np.uint8).astype(np.int8) - 8
    return pack_planes(q.reshape(*lead, n, d), deltas.astype(np.float16))


def pack_planes_t(qvals: np.ndarray, scales: np.ndarray) -> QTensor:
    """Pack file-layout planes — ``(d_out, n_in)`` values and
    ``(d_out, n_in/32)`` scales as `quants.q40_planes` returns them —
    transposing to the runtime's input-dim-first convention."""
    return pack_planes(np.ascontiguousarray(np.swapaxes(qvals, -1, -2)),
                       np.ascontiguousarray(np.swapaxes(scales, -1, -2)))


def from_q40_bytes(raw: np.ndarray, d_out: int, n_in: int) -> QTensor:
    """Build a QTensor from reference `.m`-format Q40 bytes of a row-major
    ``(d_out, n_in)`` weight (the on-disk layout, transformer.cpp:389-404)."""
    return pack_planes_t(*quants.q40_planes(raw, (d_out, n_in)))


def repack_file_bytes_into(raw: np.ndarray, d: int, n: int,
                           qp2: np.ndarray, sc2: np.ndarray, col: int = 0) -> None:
    """Repack one (d, n) tensor's `.m` Q40 bytes straight into preallocated
    runtime planes (``qp2`` u8 (padded_n/2, ld), ``sc2`` f16 (padded_n/32,
    ld)) at output-column offset ``col``.

    The file's per-block lo/hi nibble split matches the runtime layout
    (BlockQ40, quants.hpp:17-20), so this is a pure byte transpose: the
    native single-pass repacker (csrc/q40pack.cpp) when built, else a
    numpy blocked transpose — either way no dense int8 plane and no f32
    transit.  Rows past n's blocks (pack padding) are left untouched: the
    caller pre-zeroes them, and zero scales null the padding's dot-product
    contribution."""
    from ..native import have_native, q40_repack_into

    nb = n // 32
    if have_native():
        q40_repack_into(raw, d, n, qp2, sc2, col)
        return
    blocks = np.asarray(raw, np.uint8).reshape(d, nb, quants.Q40_BLOCK_BYTES)
    sc2[:nb, col:col + d] = (
        np.ascontiguousarray(blocks[:, :, :2]).view(np.float16).reshape(d, nb).T)
    nib = np.moveaxis(blocks[:, :, 2:], 0, 2)       # (nb, 16, d)
    qp2[:nb * 16, col:col + d] = nib.reshape(nb * 16, d)


def pack_file_groups(groups: list[list[tuple[np.ndarray, int, int]]],
                     stacked: bool = True) -> QTensor:
    """Layer-stacked QTensor straight from `.m` file bytes.

    ``groups[l]`` is a list of ``(raw_bytes, d_out, n_in)`` whose output
    dims concatenate into one fused weight (e.g. q|k|v).  Replaces the
    q40_planes → concat → transpose → repack pipeline with one repack per
    tensor into a preallocated stack (native csrc/q40pack.cpp when built).
    ``stacked=False`` with a single group returns the 2-D QTensor (wcls).
    """
    n = groups[0][0][2]
    d_total = sum(g[1] for g in groups[0])
    L = len(groups)
    np_ = padded_n(n)
    qp = np.zeros((L, np_ // 2, d_total), np.uint8)
    sc = np.zeros((L, np_ // 32, d_total), np.float16)
    for l, group in enumerate(groups):
        col = 0
        for raw, d, gn in group:
            if gn != n:
                raise ValueError(f"fused group mixes input dims {gn} != {n}")
            repack_file_bytes_into(raw, d, n, qp[l], sc[l], col)
            col += d
    # Corrupt or converter-overflowed files (delta > f16 max stored as inf)
    # must fail loudly here: the in-kernel f16-bit decode maps inf/NaN bit
    # patterns to large finite values (_f16_bits_to_f32 has no exp==0x1F
    # branch — codec scales never legitimately contain them), so a bad
    # scale would otherwise dequantize to a silently-wrong finite weight
    # (ADVICE r03).
    if not np.isfinite(sc).all():
        raise ValueError(
            "Q40 scale plane contains inf/NaN f16 scales — corrupt or "
            "overflowed .m tensor (delta exceeded f16 range at conversion)")
    scu = sc.view(np.uint16)
    if not stacked:
        if L != 1:
            raise ValueError("stacked=False needs exactly one group")
        return QTensor(jnp.asarray(qp[0]), jnp.asarray(scu[0]), (n, d_total))
    return QTensor(jnp.asarray(qp), jnp.asarray(scu), (n, d_total))


def split_d(qt: QTensor, sizes: list[int]) -> list[QTensor]:
    """Split a (possibly layer-stacked) QTensor along its output dim.

    Used to unfuse ``wqkv``/``w13`` for tensor-parallel placement: the
    output axis is the packed arrays' last axis, so the split is a pure
    slice (no repacking); each piece stays block-aligned on the input axis.
    """
    n = qt.logical_nd[0]
    out, off = [], 0
    for s in sizes:
        # type(qt): works for Q40 QTensor and Q80 q8.Q8Tensor alike (same
        # field layout; only the value-plane row count/dtype differ, and
        # neither is touched by an output-dim slice)
        out.append(type(qt)(qt.qpacked[..., :, off:off + s],
                            qt.scales[..., :, off:off + s], (n, s)))
        off += s
    if off != qt.logical_nd[1]:
        raise ValueError(f"split sizes {sizes} != output dim {qt.logical_nd[1]}")
    return out


def widen_scales(s: jax.Array) -> jax.Array:
    """uint16 f16-bit scales → f32 (exact); f16/f32 pass through.  XLA path
    only — inside the Pallas kernel use :func:`_f16_bits_to_f32`."""
    if s.dtype == jnp.uint16:
        s = jax.lax.bitcast_convert_type(s, jnp.float16)
    return s.astype(jnp.float32)


def _f16_bits_to_f32(u: jax.Array) -> jax.Array:
    """Widen f16 *bit patterns* (any uint dtype) to f32 with integer math —
    the Mosaic dialect has no f16 type, so the kernel rebuilds the IEEE
    fields by hand; exact for normals and subnormals (inf/nan map to large
    finite values, which codec scales never contain)."""
    u = u.astype(jnp.int32)
    sign = (u >> 15) << 31
    exp = (u >> 10) & 0x1F
    mant = u & 0x3FF
    normal = jax.lax.bitcast_convert_type(
        sign | ((exp + 112) << 23) | (mant << 13), jnp.float32)
    sub = jnp.where(sign != 0, -1.0, 1.0) * mant.astype(jnp.float32) * 2.0 ** -24
    return jnp.where(exp == 0, sub, normal)


def dequantize(qt: QTensor, dtype=jnp.float32) -> jax.Array:
    """Reconstruct the dense array (tests / the XLA matmul path)."""
    if isinstance(qt, BlockedQTensor):
        qt = unblock(qt)
    *lead, n2, d = qt.qpacked.shape
    nb = n2 // 16
    v = qt.qpacked.astype(jnp.int32).reshape(*lead, nb, 16, d)
    lo = (v & 0xF).astype(jnp.float32)
    hi = (v >> 4).astype(jnp.float32)
    w = jnp.concatenate([lo, hi], axis=-2) - 8.0          # (..., nb, 32, d)
    w = w * widen_scales(qt.scales)[..., :, None, :]
    w = w.reshape(*lead, nb * 32, d)
    n = qt.logical_nd[0]
    if n != nb * 32:
        w = w[..., :n, :]  # drop the pack-time padding rows
    return w.astype(dtype)


# ---------------------------------------------------------------------------
# Pallas fused kernel
# ---------------------------------------------------------------------------

def _q40_kernel(xlo_ref, xhi_ref, bsum_ref, qp_ref, s_ref, o_ref, acc_ref, *,
                nsteps, variant):
    """One (tile_n × tile_d) fused dequant-matmul step.

    The lo/hi nibble planes are contracted by two separate dots against the
    matching halves of x (prepared outside the kernel, where XLA fuses the
    splits), which avoids a concat-to-logical-order relayout.  VPU unpack
    work is the decode bottleneck after DMA, so four ``variant`` trade-offs
    exist between per-weight VPU ops and rounding:

    * ``classic`` — ``bf16(f32(v−8)·s)`` per weight: the reference's
      dequantization rounding (one bf16 round of the exact product,
      funcs.cpp:330-335 semantics); ~5.5 VPU ops/weight.
    * ``fma``     — same f32 math regrouped as ``v·s + (−8·s)`` with the
      per-block ``−8·s`` computed once per (block, column): saves the
      per-weight subtract if the backend emits a fused multiply-add
      (~4.5 VPU ops/weight); identical result up to one f32 rounding
      regrouping, same single bf16 round as classic.
    * ``folded``  — the −8 bias never touches the weights: with
      ``w=(v−8)·s``, ``x·w = x·(v·s) − 8·(Σ_block x)·s``, so the kernel
      feeds the MXU ``bf16(v)·bf16(s)`` and corrects with a per-block dot
      against block sums of x; ~3.5 VPU ops/weight, rounding
      ~2× classic (still an order below the codec's ±s/2).
    * ``exact``   — per-block batched dots of the *raw* nibbles (integers
      ≤15, exact in bf16), scales applied per (block, column) in f32
      afterwards; ~2.5 VPU ops/weight and *less* rounding than classic —
      but its (nb, 16, t)×(nb, 16, td) batched dots stress the MXU with
      K=16 passes, so its win is hardware-dependent.  For this variant
      the activation refs hold TRANSPOSED (tn/2, t) planes and
      ``bsum_ref`` the transposed (nb, tn/2) matrix, so every in-kernel
      reshape regroups sublanes only (the original (t, tn/2) form needed
      a lane-dim regroup — an unsupported Mosaic shape cast, which kept
      this variant interpret-only through r03).

    ``bsum_ref`` is a constant (tn/2, nb) 0/1 matrix ((nb, tn/2) for
    ``exact``; full-array block either way, so its narrow lane dim is
    legal under Mosaic's block-shape rules, which a (t, tile_n/32)
    streamed input is not); ``folded``/``exact`` recover the per-block
    activation sums with two tiny MXU dots instead of a streamed ``xs``
    operand.
    """
    i = pl.program_id(1)
    qp = qp_ref[...]                                      # (tn/2, td) uint8
    tn2, td = qp.shape[-2:]
    qp = qp.reshape(tn2, td)
    nb = tn2 // 16
    sbits = s_ref[...].reshape(nb, td)                    # uint16 f16 bits
    s32 = _f16_bits_to_f32(sbits)                         # (nb, td) f32, exact
    vi = qp.astype(jnp.int32)

    def block_sums():
        """Per-block sums of this tile's activations: (t, nb) f32 — the
        whole block's sum is the sum over its lo and hi halves."""
        b = bsum_ref[:]
        return (jnp.dot(xlo_ref[:], b, preferred_element_type=jnp.float32)
                + jnp.dot(xhi_ref[:], b, preferred_element_type=jnp.float32))

    if variant == "exact":
        # Mosaic-legal form (r04 rework; the original regrouped the LANE
        # dim of (t, tn/2) activations, an unsupported shape cast — see
        # mosaic-v5e notes): the activation operands arrive TRANSPOSED
        # (tn/2, t) from _pallas_matmul, so every reshape below splits the
        # SUBLANE dim only, and ``bsum_ref`` holds the transposed (nb,
        # tn/2) summing matrix.  The batched dot emits (nb, t, td)
        # directly — no in-kernel transpose anywhere.
        lo = (vi & 0xF).astype(jnp.bfloat16).reshape(nb, 16, td)
        hi = (vi >> 4).astype(jnp.bfloat16).reshape(nb, 16, td)
        xloT = xlo_ref[:]                                 # (tn/2, t) bf16
        xhiT = xhi_ref[:]
        dot = functools.partial(
            jax.lax.dot_general,
            dimension_numbers=(((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        tt = xloT.shape[-1]
        p = (dot(xloT.reshape(nb, 16, tt), lo)
             + dot(xhiT.reshape(nb, 16, tt), hi))         # (nb, t, td)
        bs = (jnp.dot(bsum_ref[:], xloT, preferred_element_type=jnp.float32)
              + jnp.dot(bsum_ref[:], xhiT, preferred_element_type=jnp.float32))
        corr = p - 8.0 * bs[:, :, None]                   # bs: (nb, t)
        part = jnp.sum(corr * s32[:, None, :], axis=0)    # (t, td)
    else:
        if variant == "classic":
            lo = ((vi & 0xF).astype(jnp.float32) - 8.0).reshape(nb, 16, td)
            hi = ((vi >> 4).astype(jnp.float32) - 8.0).reshape(nb, 16, td)
            lo = (lo * s32[:, None, :]).astype(jnp.bfloat16).reshape(tn2, td)
            hi = (hi * s32[:, None, :]).astype(jnp.bfloat16).reshape(tn2, td)
            bias = 0.0
        elif variant == "fma":
            m32 = -8.0 * s32                              # (nb, td), amortized /16
            lo = (vi & 0xF).astype(jnp.float32).reshape(nb, 16, td)
            hi = (vi >> 4).astype(jnp.float32).reshape(nb, 16, td)
            lo = (lo * s32[:, None, :] + m32[:, None, :]).astype(jnp.bfloat16).reshape(tn2, td)
            hi = (hi * s32[:, None, :] + m32[:, None, :]).astype(jnp.bfloat16).reshape(tn2, td)
            bias = 0.0
        else:  # folded
            sb = s32.astype(jnp.bfloat16)
            lo = (vi & 0xF).astype(jnp.bfloat16).reshape(nb, 16, td)
            hi = (vi >> 4).astype(jnp.bfloat16).reshape(nb, 16, td)
            lo = (lo * sb[:, None, :]).reshape(tn2, td)
            hi = (hi * sb[:, None, :]).reshape(tn2, td)
            bias = 8.0 * jnp.dot(block_sums().astype(jnp.bfloat16), sb,
                                 preferred_element_type=jnp.float32)
        part = (jnp.dot(xlo_ref[:], lo, preferred_element_type=jnp.float32)
                + jnp.dot(xhi_ref[:], hi, preferred_element_type=jnp.float32)
                - bias)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = part

    @pl.when(i > 0)
    def _():
        acc_ref[:] = acc_ref[:] + part

    @pl.when(i == nsteps - 1)
    def _():
        o_ref[:] = acc_ref[:]


def _stacked_q40_kernel(lidx_ref, xlo_ref, xhi_ref, bsum_ref, qp_ref, s_ref,
                        o_ref, acc_ref, *, nsteps, variant):
    del lidx_ref  # consumed by the index_maps
    _q40_kernel(xlo_ref, xhi_ref, bsum_ref, qp_ref, s_ref, o_ref, acc_ref,
                nsteps=nsteps, variant=variant)


def _x_parts(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Split activations (t, n) into the packed-row-order halves the kernel
    contracts against: ``x_lo``/``x_hi`` (t, n/2) matching the low/high
    nibble planes."""
    t, n = x.shape
    nb = n // 32
    xr = x.reshape(t, nb, 32)
    x_lo = xr[:, :, :16].reshape(t, n // 2)
    x_hi = xr[:, :, 16:].reshape(t, n // 2)
    return x_lo, x_hi


@functools.cache
def _bsum_mat(tile_n: int) -> np.ndarray:
    """Constant (tile_n/2, tile_n/32) block-summing matrix: column b is the
    indicator of packed rows [16b, 16b+16) — one half of quantization block
    b — so ``x_half @ B`` yields that half's per-block sums."""
    nb = tile_n // 32
    return np.kron(np.eye(nb, dtype=np.float32),
                   np.ones((16, 1), np.float32)).astype(jnp.bfloat16)


def _check_variant(variant: str | None) -> str:
    v = variant or KERNEL_VARIANT
    if v not in ("classic", "fma", "folded", "exact"):
        raise ValueError(f"unknown q40 kernel variant {v!r} "
                         "(expected classic | fma | folded | exact)")
    return v


def _tile_rules() -> list[tuple[int, int, int]]:
    """Width-aware tile overrides, highest d_min first: ``(d_min, tn, td)``
    applies to weights with output width ≥ d_min.

    Motivation (docs/PERF.md lever #1): a (tn/2, td) tile of the row-major
    packed plane is td contiguous bytes per row, so td sets the HBM burst
    length — and measured per-shape kernel bandwidth falls with d (wo at
    d=4096 streams ~632 GB/s, w13 at 22016 only ~354).  The rule table is
    data-driven (env ``DLLAMA_Q40_TILES_JSON``, e.g. ``[[8192,512,2048]]``)
    so the hardware sweep (tools/sweep_q40.py; bench.py probes a few tile
    configs every run) can flip defaults without a code edit; empty until
    a driver-verified measurement lands."""
    s = os.environ.get("DLLAMA_Q40_TILES_JSON", "")
    if not s:
        return []
    import json
    return sorted(((int(a), int(b), int(c)) for a, b, c in json.loads(s)),
                  reverse=True)


def _tiles(n: int, d: int, cap_elems: int = 4 * 1024 * 1024) -> tuple[int, int]:
    """Pick reduction/output tile sizes; the ragged last D tile is masked
    on store.  Pack-time padding makes n a TILE_N multiple for whole
    tensors; a TP shard's local n may be a smaller power-of-two multiple
    (padded_n/tp), so fall down the divisor ladder rather than taking the
    whole axis as one tile (which would blow VMEM at 7B shapes).

    ``cap_elems`` bounds tn·td so the working set fits VMEM and is
    codec-specific: q40's packed tile + bf16 dequant temporaries stay
    ~12 MB at the 4 Mi default, but the q8 kernel also carries an f32
    intermediate of tn·td·4 B (16 MB alone at 4 Mi), so its dispatch
    passes a 2 Mi cap — one shared ladder, two ceilings (ADVICE r04 #2)."""
    for d_min, tn, td in _tile_rules():
        # tn ≥ 256 keeps the scales operand's sublane count ≥ 8 (Mosaic);
        # td must be a positive lane-dim multiple; tn·td is capped per the
        # calling codec (see above).  Malformed rules are skipped, not
        # applied.
        if d >= d_min and tn >= 256 and tn % 32 == 0 and n % tn == 0 \
                and td >= 128 and td % 128 == 0 and tn * td <= cap_elems:
            return tn, td
    tile_n = n
    for tn in (TILE_N, TILE_N // 2, TILE_N // 4, TILE_N // 8, TILE_N // 16, 32):
        if n % tn == 0:
            tile_n = tn
            break
    tile_d = min(TILE_D, d) if d % 128 == 0 else TILE_D
    return tile_n, tile_d


@functools.partial(jax.jit, static_argnames=("interpret", "variant", "tiles"))
def _pallas_matmul(x: jax.Array, qpacked: jax.Array, scales: jax.Array,
                   interpret: bool = False, variant: str | None = None,
                   tiles: tuple[int, int] | None = None) -> jax.Array:
    """x (t, n_padded) @ packed (n_padded/2, d) → (t, d) f32.

    ``tiles`` forces a (tile_n, tile_d) choice — used by the hardware probe
    to test exactly the tile class dispatch would pick."""
    t, n = x.shape
    d = qpacked.shape[-1]
    tile_n, tile_d = tiles or _tiles(n, d)
    grid = (pl.cdiv(d, tile_d), n // tile_n)
    variant = _check_variant(variant)
    x_lo, x_hi = _x_parts(x.astype(jnp.bfloat16))
    bsum = jnp.asarray(_bsum_mat(tile_n))
    if variant == "exact":
        # transposed activation planes + transposed summing matrix: lets
        # the kernel's per-block reshapes regroup sublanes only (the lane
        # regroup of the original form does not lower under Mosaic)
        x_lo, x_hi, bsum = x_lo.T, x_hi.T, bsum.T
        xspec = pl.BlockSpec((tile_n // 2, t), lambda j, i: (i, 0),
                             memory_space=pltpu.VMEM)
    else:
        xspec = pl.BlockSpec((t, tile_n // 2), lambda j, i: (0, i),
                             memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_q40_kernel, nsteps=grid[1], variant=variant),
        grid=grid,
        in_specs=[
            xspec,
            xspec,
            pl.BlockSpec(bsum.shape, lambda j, i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n // 2, tile_d), lambda j, i: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n // 32, tile_d), lambda j, i: (i, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, tile_d), lambda j, i: (0, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((t, tile_d), jnp.float32)],
        compiler_params=pallas_compat.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x_lo, x_hi, bsum, qpacked, scales)


@functools.partial(jax.jit, static_argnames=("interpret", "variant"))
def _pallas_matmul_stacked(x: jax.Array, qpacked: jax.Array, scales: jax.Array,
                           layer: jax.Array, interpret: bool = False,
                           variant: str | None = None) -> jax.Array:
    """Layer-indexed matmul over layer-stacked packed weights.

    The layer index rides as a scalar-prefetch argument into the block
    index_maps, so the kernel DMAs tiles of layer ``layer`` straight out of
    the stacked (L, n/2, d) HBM buffer — no per-layer slice materialization
    inside the ``lax.scan`` over blocks (a sliced copy would add a full
    read+write of every layer's weights per step, measured ~20 % of decode
    step time).
    """
    t, n = x.shape
    d = qpacked.shape[-1]
    tile_n, tile_d = _tiles(n, d)
    grid = (pl.cdiv(d, tile_d), n // tile_n)
    variant = _check_variant(variant)
    x_lo, x_hi = _x_parts(x.astype(jnp.bfloat16))
    bsum = jnp.asarray(_bsum_mat(tile_n))
    if variant == "exact":  # transposed operands — see _pallas_matmul
        x_lo, x_hi, bsum = x_lo.T, x_hi.T, bsum.T
        xspec = pl.BlockSpec((tile_n // 2, t), lambda j, i, l: (i, 0))
    else:
        xspec = pl.BlockSpec((t, tile_n // 2), lambda j, i, l: (0, i))
    out = pl.pallas_call(
        functools.partial(_stacked_q40_kernel, nsteps=grid[1],
                          variant=variant),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                xspec,
                xspec,
                pl.BlockSpec(bsum.shape, lambda j, i, l: (0, 0)),
                pl.BlockSpec((1, tile_n // 2, tile_d), lambda j, i, l: (l[0], i, j)),
                pl.BlockSpec((1, tile_n // 32, tile_d), lambda j, i, l: (l[0], i, j)),
            ],
            out_specs=pl.BlockSpec((t, tile_d), lambda j, i, l: (0, j)),
            scratch_shapes=[pltpu.VMEM((t, tile_d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        compiler_params=pallas_compat.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(layer.reshape(1).astype(jnp.int32), x_lo, x_hi, bsum, qpacked, scales)
    return out


@dataclass(frozen=True)
class QLayerView:
    """A traced view of one 2-D slice of a stacked QTensor.

    Created inside the model's layer loop (the ``lax.scan`` body) so the
    fused kernel can index the stacked HBM buffer directly instead of the
    scan slicing out a per-layer copy.  ``layer`` is a **flat** index over
    the flattened leading dims — a layer for ``(L, n/2, d)`` weights, or
    ``layer·E + expert`` for ``(L, E, n/2, d)`` MoE expert stacks (the
    flatten-reshape is a free bitcast; the kernel DMAs only the selected
    expert's packed tiles, which is what bounds MoE decode reads to the
    k active experts).  Never crosses a jit boundary, so it needs no
    pytree registration.
    """

    qt: QTensor            # stacked (*lead, n/2, d)
    layer: jax.Array       # traced flat index over the flattened lead dims

    @property
    def logical_nd(self):
        return self.qt.logical_nd

    def select(self, sub: jax.Array, span: int) -> "QLayerView":
        """Narrow to a sub-slice of the next leading dim (e.g. an expert):
        flat index becomes ``layer·span + sub``."""
        return QLayerView(self.qt, self.layer * span + sub)

    def flat_planes(self) -> tuple[jax.Array, jax.Array]:
        """qpacked/scales with all leading dims flattened to one."""
        qp, s = self.qt.qpacked, self.qt.scales
        if qp.ndim > 3:
            qp = qp.reshape((-1,) + qp.shape[-2:])
            s = s.reshape((-1,) + s.shape[-2:])
        return qp, s

    def sliced(self) -> QTensor:
        qp, s = self.flat_planes()
        # type(self.qt): a view can wrap a Q40 QTensor or a Q80 q8.Q8Tensor
        # (same field layout); slicing must preserve the codec type
        return type(self.qt)(
            jax.lax.dynamic_index_in_dim(qp, self.layer, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(s, self.layer, 0, keepdims=False),
            self.qt.logical_nd)


def _pad_x(x2: jax.Array, n: int, np_: int) -> jax.Array:
    if np_ == n:
        return x2
    return jnp.pad(x2, ((0, 0), (0, np_ - n)))  # zeros meet zero pad scales


# ---------------------------------------------------------------------------
# Tile-contiguous ("blocked") storage — docs/PERF.md lever #1b
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class BlockedQTensor:
    """Layer-stacked Q40 storage re-blocked so each kernel tile is ONE
    fully-sequential HBM read.

    The row-major layout streams a (tn/2, td) tile as tn/2 separate
    td-byte bursts with a d-byte stride; the r05 xplane showed per-shape
    kernel bandwidth falling with output width d (w13 at d=22016 ~317
    GB/s vs wo at d=4096 ~632), pointing at burst length.  Here the
    packed plane lives as ``(L, n2/bn, dp/td, bn, td)`` (``bn = tn/2``,
    ``dp`` = d padded to a td multiple) so the tile DMA is ``bn·td``
    contiguous bytes.  Scales are blocked the same way.  Created from a
    row-major :class:`QTensor` at load time (:func:`to_blocked`, env
    ``DLLAMA_Q40_LAYOUT=blocked``); single-device decode only — on a
    multi-device mesh the loader keeps row-major storage, whose sharding
    semantics match the reference's splitWeights (commands.cpp:19-36).
    """

    qpacked: jax.Array          # uint8  (L, n2/bn, dp/td, bn, td)
    scales: jax.Array          # uint16 (L, n2/bn, dp/td, bn/16, td)
    logical_nd: tuple[int, int] = field(metadata=dict(static=True))
    tiles: tuple[int, int] = field(metadata=dict(static=True))  # (tn, td)
    # True when built from a 2-D (n/2, d) tensor (wcls — the widest d and
    # the worst strided-burst penalty): storage carries L=1 and unblock
    # squeezes it back out
    lead_2d: bool = field(default=False, metadata=dict(static=True))

    @property
    def shape(self) -> tuple[int, ...]:
        if self.lead_2d:
            return self.logical_nd
        return (self.qpacked.shape[0],) + self.logical_nd

    @property
    def dtype(self):
        return jnp.bfloat16


# default blocked tiles: tn=512 keeps bn·td at 512 KB per DMA with td=2048
# (well under the VMEM cap; wide td = the long sequential burst being
# probed).  Overridable until a hardware sweep bakes a measured choice.
DEFAULT_BLOCKED_TILES = (512, 2048)


def blocked_tiles_env() -> tuple[int, int]:
    """The ``DLLAMA_Q40_BLOCK_TILES`` override, parsed LAZILY at each
    :func:`to_blocked` call (an import-time parse would crash the process
    on a typo and ignore post-import env changes).  The value must be
    exactly two positive ints; anything else warns once through the
    dispatch ledger and falls back to :data:`DEFAULT_BLOCKED_TILES`."""
    spec = os.environ.get("DLLAMA_Q40_BLOCK_TILES", "")
    if not spec:
        return DEFAULT_BLOCKED_TILES
    try:
        parts = tuple(int(v) for v in spec.split(","))
        if len(parts) != 2 or parts[0] <= 0 or parts[1] <= 0:
            raise ValueError(spec)
        return parts
    except ValueError:
        obs_dispatch.record_degrade(
            "q40", "bad_block_tiles_env", warn_key=spec, spec=spec,
            fallback=DEFAULT_BLOCKED_TILES)
        return DEFAULT_BLOCKED_TILES


def to_blocked(qt: QTensor, tn: int | None = None,
               td: int | None = None) -> "BlockedQTensor":
    """Re-block a layer-stacked row-major QTensor (qpacked (L, n2, d)).

    d pads up to a td multiple with ZERO scales, so pad output columns are
    exactly 0 and callers slice ``[..., :d]``.  One-time load-cost
    transform (device-side reshape/transpose)."""
    env_tn, env_td = blocked_tiles_env()
    tn = tn or env_tn
    td = td or env_td
    lead_2d = qt.qpacked.ndim == 2
    qp0 = qt.qpacked[None] if lead_2d else qt.qpacked
    sc0 = qt.scales[None] if lead_2d else qt.scales
    if qp0.ndim != 3:
        raise ValueError("to_blocked expects a (n/2, d) or layer-stacked "
                         f"(L, n/2, d) QTensor, got {qt.qpacked.shape}")
    L, n2, d = qp0.shape
    # clamp tiles to the tensor: tn falls down the divisor ladder (tiny
    # test models; production shapes take the requested tn — note the
    # hardware kernel needs tn ≥ 256 for the scales operand's sublane
    # count, which every real model satisfies), td shrinks toward d so a
    # narrow weight doesn't pad 20× (d pads to the next td multiple)
    while tn > 32 and n2 % (tn // 2):
        tn //= 2
    td = min(td, -(-d // 128) * 128)
    bn, bnb = tn // 2, tn // 32
    if n2 % bn or tn % 32:
        raise ValueError(f"packed rows {n2} not divisible by tn/2={bn}")
    dp = -(-d // td) * td
    qp = jnp.pad(qp0, ((0, 0), (0, 0), (0, dp - d)))
    sc = jnp.pad(sc0, ((0, 0), (0, 0), (0, dp - d)))
    qb = qp.reshape(L, n2 // bn, bn, dp // td, td).transpose(0, 1, 3, 2, 4)
    sb = sc.reshape(L, n2 // bn, bnb, dp // td, td).transpose(0, 1, 3, 2, 4)
    return BlockedQTensor(qb, sb, qt.logical_nd, (tn, td), lead_2d)


def unblock(bqt: BlockedQTensor) -> QTensor:
    """Inverse of :func:`to_blocked` (drops the d padding) — the XLA/CPU
    dequant fallback path."""
    L, nI, nJ, bn, td = bqt.qpacked.shape
    d = bqt.logical_nd[1]
    qp = bqt.qpacked.transpose(0, 1, 3, 2, 4).reshape(L, nI * bn, nJ * td)
    bnb = bqt.scales.shape[3]
    sc = bqt.scales.transpose(0, 1, 3, 2, 4).reshape(L, nI * bnb, nJ * td)
    if bqt.lead_2d:
        qp, sc = qp[0], sc[0]
    return QTensor(qp[..., :d], sc[..., :d], bqt.logical_nd)


def _unblock_layer(bqt: "BlockedQTensor", layer: jax.Array) -> QTensor:
    """Un-transpose ONE layer of a blocked stack to row-major (the XLA
    fallback for per-layer calls — prefill rows past PALLAS_MAX_ROWS)."""
    qp = jax.lax.dynamic_index_in_dim(bqt.qpacked, layer, 0, keepdims=False)
    sc = jax.lax.dynamic_index_in_dim(bqt.scales, layer, 0, keepdims=False)
    nI, nJ, bn, td = qp.shape
    d = bqt.logical_nd[1]
    qp = qp.transpose(0, 2, 1, 3).reshape(nI * bn, nJ * td)[:, :d]
    bnb = sc.shape[2]
    sc = sc.transpose(0, 2, 1, 3).reshape(nI * bnb, nJ * td)[:, :d]
    return QTensor(qp, sc, bqt.logical_nd)


def _blocked_tiles_ok(bqt: "BlockedQTensor") -> bool:
    """STATIC legality of a blocked tensor's pack-time tiles: the scales
    operand needs tn/32 ≥ 8 sublanes (tn ≥ 256), td must be a lane-dim
    multiple, and the packed block must respect the VMEM cap.  Failing
    tiles degrade dispatch to the XLA path (tiny test shapes; bad env
    overrides).  This predicate cannot prove Mosaic lowerability at real
    shapes — the bench's hardware check compiles the blocked kernel once
    before trusting it (bench.py _pallas_hw_check), which is where a
    genuine lowering failure downgrades the run."""
    tn, td = bqt.tiles
    return tn >= 256 and tn % 32 == 0 and td % 128 == 0 \
        and tn * td <= 4 * 1024 * 1024


def blocked_params(params: dict) -> dict:
    """Convert every dense Q40 weight in a params pytree to the
    tile-contiguous layout (DLLAMA_Q40_LAYOUT=blocked): layer-stacked
    3-D weights and 2-D wcls (the widest d — the worst strided-burst
    penalty).  4-D MoE expert stacks keep row-major storage (the
    expert-select kernel path, _sharded_matmul_ep)."""
    def conv(v):
        if isinstance(v, QTensor) and v.qpacked.ndim in (2, 3):
            return to_blocked(v)
        return v
    return jax.tree.map(conv, params,
                        is_leaf=lambda v: isinstance(v, QTensor))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_matmul_blocked(x: jax.Array, qb: jax.Array, sb: jax.Array,
                           layer: jax.Array,
                           interpret: bool = False) -> jax.Array:
    """Layer-indexed fused matmul over tile-contiguous packed storage.

    Identical math to ``_pallas_matmul_stacked`` (classic variant); only
    the HBM layout of the weight operands differs — each grid step DMAs
    one contiguous (1,1,1,bn,td) block (the kernel's leading-singleton
    squeeze handles the rank).  Returns (t, dp); callers slice ``[:, :d]``.
    """
    t = x.shape[0]
    L, nI, nJ, bn, td = qb.shape
    tn = bn * 2
    grid = (nJ, nI)
    x_lo, x_hi = _x_parts(x.astype(jnp.bfloat16))
    bsum = jnp.asarray(_bsum_mat(tn))
    xspec = pl.BlockSpec((t, bn), lambda j, i, l: (0, i))
    return pl.pallas_call(
        functools.partial(_stacked_q40_kernel, nsteps=grid[1],
                          variant="classic"),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                xspec,
                xspec,
                pl.BlockSpec(bsum.shape, lambda j, i, l: (0, 0)),
                pl.BlockSpec((1, 1, 1, bn, td),
                             lambda j, i, l: (l[0], i, j, 0, 0)),
                pl.BlockSpec((1, 1, 1, bn // 16, td),
                             lambda j, i, l: (l[0], i, j, 0, 0)),
            ],
            out_specs=pl.BlockSpec((t, td), lambda j, i, l: (0, j)),
            scratch_shapes=[pltpu.VMEM((t, td), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((t, nJ * td), jnp.float32),
        compiler_params=pallas_compat.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(layer.reshape(1).astype(jnp.int32), x_lo, x_hi, bsum, qb, sb)


# ---------------------------------------------------------------------------
# Tensor-parallel dispatch: per-shard pallas under shard_map
# ---------------------------------------------------------------------------

def _smap_mesh():
    """The active mesh, if the fused kernel must be run per shard."""
    mesh = get_active_mesh()
    if mesh is None or mesh.size <= 1:
        return None
    return mesh


def _tp_shardable(np_: int, d: int, kind: str | None, tp: int) -> bool:
    """An even shard must not split a 32-row quantization block (col) or
    leave a ragged output chunk (row).  With tp==1 (an sp/dp-only mesh)
    the kernel runs replicated under shard_map — always legal, any kind."""
    if tp == 1:
        return True
    if kind == "row":
        return d % tp == 0
    if kind == "col":
        return np_ % (32 * tp) == 0
    return False


def _fused_reduce_ok(d: int, tp: int, interp: bool) -> bool:
    """Can the bidirectional ring reduce replace the trailing psum?

    TPU-only (the kernel is built on inter-chip RDMA,
    ``pltpu.make_async_remote_copy``); both direction halves must be
    lane-aligned so the comm buffers tile cleanly; ``DLLAMA_TP_REDUCE=psum``
    is the operator's portable opt-out (a requested path, not a degrade)."""
    if interp or tp < 2:
        return False
    if os.environ.get("DLLAMA_TP_REDUCE", "") == "psum":
        return False
    if jax.default_backend() != "tpu":
        return False
    return d % (2 * 128) == 0


def _ring_reduce_kernel(x_ref, o_ref, comm_ref, send_sem, recv_sem, *,
                        tp: int):
    """Bidirectional ring all-reduce of a (t, d) f32 partial sum over
    ``tp``.

    The output half ``[:, :d/2]`` circulates clockwise (to the right
    neighbor), the half ``[:, d/2:]`` counter-clockwise — both ICI
    directions carry traffic every step, so the reduce finishes in
    ``tp-1`` steps of ``d/2`` words instead of ``tp-1`` steps of ``d``.
    Each step's accumulate folds the chunk received the PREVIOUS step
    while the current transfer is in flight: the VPU add hides under the
    RDMA, which is the "reduce fused into the dispatch" this kernel
    exists for (the psum it replaces serializes transfer after the
    matmul).
    """
    t, d = x_ref.shape
    dh = d // 2
    my = jax.lax.axis_index("tp")
    right = jax.lax.rem(my + 1, tp)
    left = jax.lax.rem(my + tp - 1, tp)
    # the serving mesh is (dp, sp, ep, tp) with tp innermost; a neighbor
    # differs only in the tp coordinate
    base = (jax.lax.axis_index("dp"), jax.lax.axis_index("sp"),
            jax.lax.axis_index("ep"))

    # accumulator starts at the local partial; each direction's slot-0
    # payload is the local half that will circulate that way
    o_ref[...] = x_ref[...]
    comm_ref[0, 0] = x_ref[:, :dh]
    comm_ref[1, 0] = x_ref[:, dh:]

    # neighbor barrier: no RDMA may land in a peer still seeding its
    # comm buffers (guide: Local Barrier Between Neighbors)
    barrier = pltpu.get_barrier_semaphore()
    for nb in (right, left):
        pltpu.semaphore_signal(barrier, inc=1, device_id=base + (nb,),
                               device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)

    for step in range(tp - 1):
        snd, rcv = step % 2, (step + 1) % 2
        copies = []
        for dirn, nb in ((0, right), (1, left)):
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_ref.at[dirn, snd],
                dst_ref=comm_ref.at[dirn, rcv],
                send_sem=send_sem.at[dirn, snd],
                recv_sem=recv_sem.at[dirn, rcv],
                device_id=base + (nb,),
                device_id_type=pltpu.DeviceIdType.MESH)
            rdma.start()
            copies.append(rdma)
        if step > 0:
            # overlap: fold the chunk received last step (slot ``snd`` —
            # also this step's outgoing payload; both are reads) into the
            # accumulator while the transfer is in flight
            o_ref[:, :dh] += comm_ref[0, snd]
            o_ref[:, dh:] += comm_ref[1, snd]
        for rdma in copies:
            rdma.wait()
    last = (tp - 1) % 2
    o_ref[:, :dh] += comm_ref[0, last]
    o_ref[:, dh:] += comm_ref[1, last]


def _tp_ring_allreduce(x: jax.Array, tp: int) -> jax.Array:
    """All-reduce ``x`` (t, d) f32 over the ``tp`` axis with the
    bidirectional RDMA ring — called inside the ``_sharded_matmul``
    shard_map body, immediately after the per-shard matmul kernel."""
    t, d = x.shape
    return pl.pallas_call(
        functools.partial(_ring_reduce_kernel, tp=tp),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, 2, t, d // 2), jnp.float32),  # [dir, slot, ...]
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        compiler_params=pallas_compat.compiler_params(
            has_side_effects=True, collective_id=0),
    )(x)


def _sharded_matmul(x2: jax.Array, qp: jax.Array, s: jax.Array,
                    layer: jax.Array | None,
                    kind: str, mesh, interp: bool) -> jax.Array:
    """Run the fused kernel per shard under ``shard_map``.

    ``kind="row"``: weight output dim sharded on ``tp`` — each shard
    computes its slice of the output from the (replicated) input; no
    communication, matching RowMatmulSlice (commands.cpp:8-40).

    ``kind="col"``: weight input dim sharded — each shard contracts its
    input slice into a full-width partial sum, combined over ``tp``
    (ColMatmulSlice + the root merge, commands.cpp:42-70,
    llama2-tasks.cpp:125-131).  On TPU the combine is the bidirectional
    RDMA ring (:func:`_tp_ring_allreduce`) fused into the dispatch —
    partial-sum transfer overlaps the accumulate — with ``jax.lax.psum``
    kept as the portable fallback; the choice is recorded in the
    dispatch ledger (``path=tp_fused_reduce|tp_psum``).  The pack-time
    padding sits at the global end of the input axis, so activation
    columns and packed rows shard at the same logical boundaries.

    Axes other than ``tp`` (``dp``/``sp``) are unmentioned in the specs:
    shard_map treats the operands as replicated across them, which is
    exactly the activations' layout in this framework.
    """
    stacked = layer is not None
    tp = mesh.shape.get("tp", 1)
    fused = False
    if tp == 1 or kind == "row":
        # tp==1 (sp/dp-only mesh): fully replicated specs — each device runs
        # the whole kernel; shard_map only exists to keep GSPMD from trying
        # (and failing) to partition the pallas_call
        tp_ax = "tp" if kind in ("row", "col") and tp > 1 else None
        wspec = P(None, None, tp_ax) if stacked else P(None, tp_ax)
        xspec, ospec = P(None, None), P(None, tp_ax)
        kind = "row" if tp_ax else "repl"
    else:
        wspec = P(None, "tp", None) if stacked else P("tp", None)
        xspec, ospec = P(None, "tp"), P(None, None)
        d_out = qp.shape[-1]
        fused = _fused_reduce_ok(d_out, tp, interp)
        obs_dispatch.record_dispatch(
            "q40", "tp_fused_reduce" if fused else "tp_psum",
            kind="col", tp=tp, d=d_out)
        if not fused and not interp \
                and os.environ.get("DLLAMA_TP_REDUCE", "") != "psum":
            # falling off the fused collective is a degrade off the fast
            # path, same funnel as blocked_ignored_mesh (warn-once per
            # backend + width; the counter keeps the true count)
            obs_dispatch.record_degrade(
                "q40", "tp_psum",
                warn_key=(jax.default_backend(), d_out),
                backend=jax.default_backend(), tp=tp, d=d_out,
                hint="fused ring reduce needs a TPU backend and "
                     "d % 256 == 0; decode collectives run as plain psum")

    def body(x_local, qp, s, *l):
        if stacked:
            out = _pallas_matmul_stacked(x_local, qp, s, l[0], interpret=interp)
        else:
            out = _pallas_matmul(x_local, qp, s, interpret=interp)
        if kind == "col":
            if fused:
                out = _tp_ring_allreduce(out, tp)
            else:
                out = jax.lax.psum(out, "tp")
        return out

    args = [x2, qp, s] + ([layer] if stacked else [])
    in_specs = [xspec, wspec, wspec] + ([P()] if stacked else [])
    return shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                         out_specs=ospec, check_vma=False)(*args)


def _sharded_matmul_ep(x2: jax.Array, qp4: jax.Array, s4: jax.Array,
                       flat_idx: jax.Array, kind: str, mesh,
                       interp: bool) -> jax.Array:
    """Expert-parallel fused matmul on a ``(L, E, n/2, d)`` packed stack
    whose expert axis is sharded over ``ep`` (hidden axis over ``tp``).

    The reference TP-slices every expert onto every node (transformer.cpp:
    299-317), which caps the model size at nSlices ≤ nKvHeads; sharding the
    expert axis is the extra degree of freedom that lets packed Grok-1-314B
    fit a 16-chip v5e mesh (tools/memory_plan.py).  Mechanism:

    * each shard holds ``E/ep`` experts per layer; the traced flat
      ``layer·E + expert`` index (QLayerView.select) is decoded per shard
      into (layer, expert), and ONLY the owner runs the kernel on its
      local sub-stack — non-owners take the zero branch of a ``lax.cond``
      and perform **no packed-tile DMA at all** (VERDICT r04 Weak #2: the
      earlier mask-the-input variant still streamed a clamped expert's
      tiles on every shard, making per-step expert-weight HBM traffic
      ~ep× the useful bytes);
    * a psum over ``ep`` (and ``tp`` for col-sharded weights) then
      replicates the true product everywhere, so each of up/gate/down is
      independently correct and composable no matter which impl the other
      matmuls of the FFN picked (no "unreduced intermediate" contract).

    Net: weight residency AND per-step expert-read traffic both drop by
    ``ep`` (each expert's tiles are read exactly once, on their owner).
    """
    tp = mesh.shape.get("tp", 1)
    ep = mesh.shape["ep"]
    tp_ax = "tp" if tp > 1 else None
    if kind == "row":
        wspec = P(None, "ep", None, tp_ax)
        xspec, ospec = P(None, None), P(None, tp_ax)
        sum_axes: tuple = ("ep",)
    else:  # col
        wspec = P(None, "ep", tp_ax, None)
        xspec = P(None, tp_ax)
        ospec = P(None, None)
        sum_axes = ("ep", "tp") if tp_ax else ("ep",)

    def body(x_local, qp, s, flat):
        e_local = qp.shape[1]
        layer_idx = flat // (e_local * ep)
        sel = flat % (e_local * ep)
        local_sel = sel - jax.lax.axis_index("ep") * e_local
        owned = (local_sel >= 0) & (local_sel < e_local)
        lflat = layer_idx * e_local + jnp.clip(local_sel, 0, e_local - 1)
        qpf = qp.reshape((-1,) + qp.shape[-2:])
        sf = s.reshape((-1,) + s.shape[-2:])

        def run_kernel(_):
            return _pallas_matmul_stacked(x_local, qpf, sf, lflat,
                                          interpret=interp)

        def skip(_):  # non-owner: contribute zeros, touch no packed tiles
            return jnp.zeros((x_local.shape[0], qpf.shape[-1]), jnp.float32)

        out = jax.lax.cond(owned, run_kernel, skip, None)
        return jax.lax.psum(out, sum_axes)

    return shard_map(body, mesh=mesh,
                         in_specs=(xspec, wspec, wspec, P()),
                         out_specs=ospec, check_vma=False)(x2, qp4, s4, flat_idx)


@functools.cache
def _pallas_ok(tile_n: int = 64, tile_d: int = 128, t: int = 1) -> bool:
    """Hardware probe: can Mosaic lower + run the fused kernel at this tile
    class?

    Guards the ``auto`` dispatch so a lowering regression degrades to the
    XLA emulation with a warning instead of crashing decode.  Cached per
    (tile_n, tile_d, t-bucket): the probe runs a 2-step reduction over
    tiles of exactly the production size, so a VMEM/tiling failure that
    only appears at 7B shapes (e.g. tile_n=tile_d=1024) is caught here,
    not in the middle of dispatch (VERDICT r02 Weak #5).

    The fixture is RANDOM (fixed seed): with a constant fixture every block
    quantizes identically, so a nibble-order or scale-indexing bug would
    pass the probe and ship wrong numerics (VERDICT r03 Weak #2); random
    blocks make the value-vs-XLA comparison sensitive to layout bugs."""
    try:
        n = 2 * tile_n  # two reduction steps: exercises the accumulator path
        rng = np.random.RandomState(0)
        qt = quantize((rng.randn(n, tile_d) * 0.1).astype(np.float32))
        x = jnp.asarray(rng.randn(t, n).astype(np.float32), jnp.bfloat16)
        out = _pallas_matmul(x, qt.qpacked, qt.scales, tiles=(tile_n, tile_d))
        ref = x @ dequantize(qt, jnp.bfloat16)
        if not np.allclose(np.asarray(out), np.asarray(ref),
                           atol=1e-2 * float(np.abs(np.asarray(ref)).max())):
            raise AssertionError("pallas probe result mismatch")
        return True
    except Exception as e:  # Mosaic lowering/runtime failure
        obs_dispatch.record_degrade(
            "q40", "probe_failed", warn_key=(tile_n, tile_d, t),
            tile_n=tile_n, tile_d=tile_d, t=t,
            error=f"{type(e).__name__}: {str(e)[:120]}")
        return False


def _dispatch_tiles_ok(np_: int, d: int, rows: int, kind: str | None) -> bool:
    """Probe the tile class this dispatch would actually run (per-shard
    local shapes on a mesh).  Shapes that cannot take the pallas path at
    all (unshardable under the active mesh) return False without paying a
    probe compile — dispatch falls straight back to XLA."""
    mesh = _smap_mesh()
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    local_n, local_d = np_, d
    if mesh is not None:
        if not _tp_shardable(np_, d, kind, tp):
            return False
        if tp > 1 and kind == "col":
            local_n = np_ // tp
        elif tp > 1 and kind == "row":
            local_d = d // tp
    tile_n, tile_d = _tiles(local_n, local_d)
    t_bucket = 1 if rows == 1 else PALLAS_MAX_ROWS
    return _pallas_ok(tile_n, tile_d, t_bucket)


def matmul(x: jax.Array, qt: QTensor | QLayerView, impl: str = "auto",
           out_dtype=None, kind: str | None = None) -> jax.Array:
    """``x @ dequantize(qt)`` with f32 accumulation.

    x: (..., n); qt logical (n, d) — a 2-D QTensor or a QLayerView of a
    stacked one.  Returns (..., d).

    ``kind`` declares the weight's TP slicing on a multi-device mesh
    ("row" = output dim on ``tp``, "col" = input dim on ``tp``) so the
    pallas path can run per shard; without it (or when shapes don't divide
    the mesh evenly) a multi-device pallas request falls back to the
    GSPMD-partitionable XLA emulation.
    """
    n, d = qt.logical_nd
    lead = x.shape[:-1]
    rows = int(np.prod(lead)) if lead else 1
    out_dtype = out_dtype or x.dtype

    raw_qt = qt.qt if isinstance(qt, QLayerView) else qt
    blocked = isinstance(raw_qt, BlockedQTensor)

    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        if blocked:
            # blocked tiles are fixed at pack time; Mosaic-illegal tiles
            # (clamped-down tn < 256 on tiny shapes, or a bad env
            # override) degrade to the XLA path like the row-major ladder
            impl = "pallas" if (on_tpu and rows <= PALLAS_MAX_ROWS
                                and _blocked_tiles_ok(raw_qt)) else "xla"
        else:
            np_probe = raw_qt.qpacked.shape[-2] * 2
            impl = "pallas" if (on_tpu and rows <= PALLAS_MAX_ROWS
                                and _dispatch_tiles_ok(np_probe, d, rows, kind)) else "xla"

    if blocked and impl == "pallas":
        # forced-pallas callers (cfg.quant_impl) get the same degrades as
        # auto dispatch — never a Mosaic compile error mid-forward
        if not _blocked_tiles_ok(raw_qt):
            obs_dispatch.record_degrade(
                "q40", "blocked_tiles_illegal", warn_key=raw_qt.tiles,
                tiles=raw_qt.tiles,
                hint="need tn >= 256, td % 128 == 0, within the VMEM cap")
            impl = "xla"
        elif rows > PALLAS_MAX_ROWS:
            # the blocked kernel's grid is sized for decode-width row
            # counts; a forced-pallas prefill mirrors the auto-dispatch
            # rows cap instead of hitting a lowering failure mid-forward
            obs_dispatch.record_degrade(
                "q40", "rows_exceed_pallas_max",
                warn_key=("blocked", raw_qt.tiles), rows=rows,
                max_rows=PALLAS_MAX_ROWS, tiles=raw_qt.tiles)
            impl = "xla"
    if blocked and impl in ("pallas", "pallas_interpret"):
        if _smap_mesh() is not None:
            # blocked storage is single-device by construction (to_blocked
            # is only applied on 1-device meshes); a mesh here means a
            # programming error upstream
            raise ValueError("BlockedQTensor cannot run under a multi-"
                             "device mesh; load with row-major storage")
        layer = qt.layer if isinstance(qt, QLayerView) else jnp.int32(0)
        np_ = raw_qt.qpacked.shape[1] * raw_qt.tiles[0]
        x2 = _pad_x(x.reshape(rows, n), n, np_)
        obs_dispatch.record_dispatch("q40", "pallas-blocked", rows=rows,
                                     tiles=raw_qt.tiles, layout="blocked")
        out = _pallas_matmul_blocked(x2, raw_qt.qpacked, raw_qt.scales,
                                     layer, interpret=impl == "pallas_interpret")
        return out[:, :d].reshape(*lead, d).astype(out_dtype)
    if blocked:  # xla / CPU fallback: undo the layout, then the dense path
        if isinstance(qt, QLayerView):
            # slice the ONE layer first, then un-transpose it: unblocking
            # the whole (L, ...) stack inside a traced per-layer call
            # would relayout every layer's bytes L times per forward
            qt = _unblock_layer(raw_qt, qt.layer)
        else:
            qt = unblock(raw_qt)

    if impl in ("pallas", "pallas_interpret"):
        interp = impl == "pallas_interpret"
        if isinstance(qt, QLayerView):
            qp3, s3 = qt.flat_planes()
            layer = qt.layer
        else:
            if len(qt.qpacked.shape) != 2:
                raise ValueError(f"matmul needs a 2-D QTensor, got {qt.shape}")
            qp3, s3, layer = qt.qpacked, qt.scales, None
        np_ = qp3.shape[-2] * 2
        mesh = _smap_mesh()
        if mesh is not None:
            tp = mesh.shape.get("tp", 1)
            ep = mesh.shape.get("ep", 1)
            if _tp_shardable(np_, d, kind, tp):
                x2 = _pad_x(x.reshape(rows, n), n, np_)
                raw = qt.qt if isinstance(qt, QLayerView) else None
                if (ep > 1 and raw is not None and raw.qpacked.ndim == 4
                        and raw.qpacked.shape[1] % ep == 0
                        and kind in ("row", "col")):
                    # (L, E, n/2, d) expert stack on an ep mesh: the stack
                    # is expert-sharded in HBM (place_params) — decode the
                    # flat index per shard and psum the owner's product
                    out = _sharded_matmul_ep(x2, raw.qpacked, raw.scales,
                                             layer, kind, mesh, interp)
                else:
                    out = _sharded_matmul(x2, qp3, s3, layer, kind, mesh, interp)
                obs_dispatch.record_dispatch(
                    "q40", "pallas-fused", rows=rows, kind=kind,
                    tp=mesh.shape.get("tp", 1), layout="row-major")
                return out.reshape(*lead, d).astype(out_dtype)
            obs_dispatch.record_degrade(
                "q40", "unshardable", warn_key=(kind, np_, d, tp),
                shape=(np_, d), kind=kind, tp=tp)
            impl = "xla"
        else:
            x2 = _pad_x(x.reshape(rows, n), n, np_)
            obs_dispatch.record_dispatch("q40", "pallas-fused", rows=rows,
                                         kind=kind, layout="row-major")
            if layer is not None:
                out = _pallas_matmul_stacked(x2, qp3, s3, layer, interpret=interp)
            else:
                out = _pallas_matmul(x2, qp3, s3, interpret=interp)
            return out.reshape(*lead, d).astype(out_dtype)
    if impl == "xla":
        if isinstance(qt, QLayerView):
            qt = qt.sliced()
        obs_dispatch.record_dispatch("q40", "xla-dequant", rows=rows,
                                     kind=kind)
        w = dequantize(qt, dtype=jnp.bfloat16)
        return jnp.dot(x.astype(jnp.bfloat16), w,
                       preferred_element_type=jnp.float32).astype(out_dtype)
    raise ValueError(f"unknown q40 matmul impl {impl!r}")


def mm(x: jax.Array, w, impl: str = "auto", out_dtype=None,
       kind: str | None = None) -> jax.Array:
    """Generic matmul: dispatches packed tensors (Q40 or Q80, bare or as a
    layer view) to their fused path, arrays to a plain dot."""
    if not isinstance(w, (jax.Array, np.ndarray)):
        from . import q8
        base = w.qt if isinstance(w, QLayerView) else w
        if isinstance(base, q8.Q8Tensor):
            return q8.matmul(x, w, impl=impl, out_dtype=out_dtype, kind=kind)
        if isinstance(base, (QTensor, BlockedQTensor)):
            return matmul(x, w, impl=impl, out_dtype=out_dtype, kind=kind)
        raise TypeError(f"mm: unsupported weight type {type(w).__name__}")
    obs_dispatch.record_dispatch("dense", "dense",
                                 rows=int(np.prod(x.shape[:-1]) or 1))
    out = x @ w
    return out.astype(out_dtype) if out_dtype is not None else out
