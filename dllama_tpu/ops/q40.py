"""On-device Q40 weights: packed storage + fused dequant-matmul.

TPU-native replacement for the reference's production matmul path — the
Q40×Q80 NEON/AVX2 kernel (`/root/reference/src/funcs.cpp:287-386`) that
reads 4-bit weight nibbles, applies per-32-block f16 scales, and
accumulates against quantized activations.  Here the weights stay packed in
HBM and a Pallas kernel fuses nibble-unpack + scale + matmul, so decode —
which is HBM-bandwidth-bound — streams 0.5625 bytes/weight instead of 2
(bf16): measured ~810 GB/s effective weight stream on v5e, ~3.5× faster
than the bf16 matvec.

Device layout (block-local, chosen so any 32-row slice is self-contained
and therefore tensor-parallel sharding on either axis never splits a
block):

* ``qpacked`` uint8 ``(..., N/2, D)`` — for block ``b`` along the input
  axis N, packed row ``16b + r`` holds logical row ``32b + r`` in its low
  nibble and logical row ``32b + 16 + r`` in its high nibble, biased +8.
  (The reference's own BlockQ40 uses the same lo/hi split within a block,
  quants.hpp:17-20.)
* ``scales`` f32 ``(..., N/32, D)`` — the per-block f16 deltas from the
  `.m` file, widened to f32 (f16 compute is awkward on TPU; f32 scales
  cost 0.125 B/weight).

Two matmul implementations:

* ``pallas`` — the fused kernel, for single-chip decode (a `pallas_call`
  is not auto-partitioned by GSPMD, so it requires unsharded weights).
* ``xla``   — plain-jnp emulation (unpack → scale → dot).  Partitionable
  under GSPMD (reshapes split the sharded axis at block granularity), used
  for tensor-parallel execution, prefill (compute-bound anyway), and CPU
  tests.  XLA materializes the dequantized operand, so it is not the fast
  path for decode.

Activations stay bf16 — the TPU analogue of the reference's Q80 activation
quantization (whose purpose is wire compression, tasks.cpp:124-163; on a
TPU mesh the "wire" is ICI inside the XLA program, and bf16 keeps the MXU
fed without a quantize/dequantize round trip).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import quants

# Sweet spot measured on v5e (HBM-roofline for the 4096×11008 matvec);
# shrunk automatically when N or D is smaller.
TILE_N = 1024
TILE_D = 1024
# Decode uses the Pallas kernel; past this many rows the matmul is MXU-bound
# and the XLA path (which can pipeline the dequant) is preferable.
PALLAS_MAX_ROWS = 128


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class QTensor:
    """A Q40 tensor of logical shape ``(..., n, d)``, packed for the MXU."""

    qpacked: jax.Array          # uint8 (..., n/2, d)
    scales: jax.Array           # f32   (..., n/32, d)
    logical_nd: tuple[int, int] = field(metadata=dict(static=True))

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.qpacked.shape[:-2]) + self.logical_nd

    @property
    def dtype(self):  # duck-types as an array for shape/dtype introspection
        return jnp.bfloat16


def pack_planes(qvals: np.ndarray, scales: np.ndarray) -> QTensor:
    """Pack int8 nibble values ``(..., n, d)`` in [-8, 7] + scales
    ``(..., n/32, d)`` into the block-local device layout."""
    *lead, n, d = qvals.shape
    b = (qvals + 8).astype(np.uint8).reshape(*lead, n // 32, 32, d)
    lo = b[..., :16, :]
    hi = b[..., 16:, :]
    packed = (lo | (hi << 4)).reshape(*lead, n // 2, d)
    return QTensor(jnp.asarray(packed), jnp.asarray(scales.astype(np.float32)),
                   (n, d))


def quantize(w: np.ndarray) -> QTensor:
    """Quantize a float array ``(..., n, d)`` to Q40 along the input axis
    (axis -2) — converter semantics (writer.py:29-56): ``delta = amax/-8``,
    ``q = clamp(floor(x/delta + 8.5), 0, 15)``."""
    w = np.asarray(w, np.float32)
    *lead, n, d = w.shape
    if n % quants.BLOCK_SIZE:
        raise ValueError(f"input dim {n} not divisible by {quants.BLOCK_SIZE}")
    g = w.reshape(*lead, n // 32, 32, d)
    gmax = g.max(axis=-2)
    gmin = g.min(axis=-2)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    # codec parity (quants.quantize_q40 / writer.py:29-56): q from the raw
    # f32 delta, stored scale rounded to the file's f16 precision
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = np.clip(g * inv[..., None, :] + 8.5, 0.0, 15.0).astype(np.uint8).astype(np.int8) - 8
    return pack_planes(q.reshape(*lead, n, d),
                       deltas.astype(np.float16).astype(np.float32))


def pack_planes_t(qvals: np.ndarray, scales: np.ndarray) -> QTensor:
    """Pack file-layout planes — ``(d_out, n_in)`` values and
    ``(d_out, n_in/32)`` scales as `quants.q40_planes` returns them —
    transposing to the runtime's input-dim-first convention."""
    return pack_planes(np.ascontiguousarray(np.swapaxes(qvals, -1, -2)),
                       np.ascontiguousarray(np.swapaxes(scales, -1, -2)))


def from_q40_bytes(raw: np.ndarray, d_out: int, n_in: int) -> QTensor:
    """Build a QTensor from reference `.m`-format Q40 bytes of a row-major
    ``(d_out, n_in)`` weight (the on-disk layout, transformer.cpp:389-404)."""
    return pack_planes_t(*quants.q40_planes(raw, (d_out, n_in)))


def dequantize(qt: QTensor, dtype=jnp.float32) -> jax.Array:
    """Reconstruct the dense array (tests / the XLA matmul path)."""
    *lead, n2, d = qt.qpacked.shape
    nb = n2 // 16
    v = qt.qpacked.astype(jnp.int32).reshape(*lead, nb, 16, d)
    lo = (v & 0xF).astype(jnp.float32)
    hi = (v >> 4).astype(jnp.float32)
    w = jnp.concatenate([lo, hi], axis=-2) - 8.0          # (..., nb, 32, d)
    w = w * qt.scales[..., :, None, :]
    return w.reshape(*lead, nb * 32, d).astype(dtype)


# ---------------------------------------------------------------------------
# Pallas fused kernel
# ---------------------------------------------------------------------------

def _q40_kernel(x_ref, qp_ref, s_ref, o_ref, acc_ref, *, nsteps):
    i = pl.program_id(1)
    qp = qp_ref[:]                                        # (tn/2, td) uint8
    tn2, td = qp.shape
    nb = tn2 // 16
    # Mosaic has no int8 vector sub / u8→f convert; widen to i32 first.
    v = qp.reshape(nb, 16, td).astype(jnp.int32)
    lo = (v & 0xF).astype(jnp.float32)
    hi = (v >> 4).astype(jnp.float32)
    w = jnp.concatenate([lo, hi], axis=1) - 8.0           # (nb, 32, td)
    w = (w * s_ref[:][:, None, :]).astype(jnp.bfloat16).reshape(nb * 32, td)
    part = jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = part

    @pl.when(i > 0)
    def _():
        acc_ref[:] = acc_ref[:] + part

    @pl.when(i == nsteps - 1)
    def _():
        o_ref[:] = acc_ref[:]


def _n_tile(n: int, cap: int) -> int:
    """Reduction-axis tile: Mosaic needs the x block's lane dim (tile_n)
    to be a multiple of 128 and the scales block's sublane dim (tile_n/32)
    to be a multiple of 8 ⇒ tile_n ≡ 0 (mod 256) — unless the tile spans
    the whole axis, which is always legal."""
    best = 0
    t = 256
    while t <= cap:
        if n % t == 0:
            best = t
        t += 256
    return best or n


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_matmul(x: jax.Array, qpacked: jax.Array, scales: jax.Array,
                   interpret: bool = False) -> jax.Array:
    t, n = x.shape
    d = qpacked.shape[-1]
    tile_n = _n_tile(n, TILE_N)
    tile_d = min(TILE_D, d) if d % 128 == 0 else TILE_D
    grid = (pl.cdiv(d, tile_d), n // tile_n)  # ragged last D tile is masked on store
    out = pl.pallas_call(
        functools.partial(_q40_kernel, nsteps=grid[1]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((t, tile_n), lambda j, i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n // 2, tile_d), lambda j, i: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n // 32, tile_d), lambda j, i: (i, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, tile_d), lambda j, i: (0, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((t, tile_d), jnp.float32)],
        interpret=interpret,
    )(x.astype(jnp.bfloat16), qpacked, scales)
    return out


def matmul(x: jax.Array, qt: QTensor, impl: str = "auto",
           out_dtype=None) -> jax.Array:
    """``x @ dequantize(qt)`` with f32 accumulation.

    x: (..., n); qt logical (n, d) (2-D only — stacked layers are sliced by
    the ``lax.scan`` over blocks before reaching here).  Returns (..., d).
    """
    if len(qt.qpacked.shape) != 2:
        raise ValueError(f"matmul needs a 2-D QTensor, got {qt.shape}")
    n, d = qt.logical_nd
    lead = x.shape[:-1]
    rows = int(np.prod(lead)) if lead else 1
    out_dtype = out_dtype or x.dtype

    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        impl = "pallas" if (on_tpu and rows <= PALLAS_MAX_ROWS) else "xla"

    if impl in ("pallas", "pallas_interpret"):
        x2 = x.reshape(rows, n)
        out = _pallas_matmul(x2, qt.qpacked, qt.scales,
                             interpret=(impl == "pallas_interpret"))
        return out.reshape(*lead, d).astype(out_dtype)
    if impl == "xla":
        w = dequantize(qt, dtype=jnp.bfloat16)
        return jnp.dot(x.astype(jnp.bfloat16), w,
                       preferred_element_type=jnp.float32).astype(out_dtype)
    raise ValueError(f"unknown q40 matmul impl {impl!r}")


def mm(x: jax.Array, w, impl: str = "auto", out_dtype=None) -> jax.Array:
    """Generic matmul: dispatches QTensor → fused path, array → plain dot."""
    if isinstance(w, QTensor):
        return matmul(x, w, impl=impl, out_dtype=out_dtype)
    out = x @ w
    return out.astype(out_dtype) if out_dtype is not None else out
